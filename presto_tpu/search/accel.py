"""Fourier-domain F–Fdot acceleration search (accelsearch rebuilt TPU-first).

Reference call stack (SURVEY.md §3.2, src/accelsearch.c:134-221,
src/accel_utils.c): per r-block of ACCEL_USELEN half-bins —
  subharm_ffdot_plane  (accel_utils.c:879-1051): normalize amplitudes,
      spread ×2 interbin, FFT, per-z-row complex-multiply by conj
      z-response kernel, inverse FFT, |·|²/fftlen² into powers[z][r]
  inmem harmonic sums  (accel_utils.c:1160-1256): powers[z][r] +=
      plane[zind(frac,z)][round(r*frac)]
  search_ffdotpows     (accel_utils.c:1259-1298): threshold at
      powcut[stage], candidate_sigma, sorted insert.

TPU-first redesign (this module):
  * the whole spectrum's fundamental plane is built as ONE batched
    tensor program: [nblocks, fftlen] spread segments x [numz, fftlen]
    kernel bank -> batched IFFT -> [nblocks, numz, uselen] powers,
    assembled to P[numz, R] in HBM (the reference's `-inmem` plane,
    accel_utils.c:1651-1670, is the natural TPU layout);
  * harmonic summing is a z-row take plus a PHASE-DECOMPOSED column
    read (static strided views when slab starts are numharm-aligned —
    no minor-axis gather, the TPU scan-time hot spot), accumulated
    stage by stage;
  * thresholding is a segment-max (lossless under the r-dedup rule)
    followed by a top-k per stage (static K, the `omp critical` insert
    becomes host-side filtering), returned as ONE packed int32 tensor
    so the host pays a single D2H;
  * candidate sigma/powcut math runs on host in float64 (ops/stats).

All device entry points keep complex internal to jit (float32 pair
boundaries — see ops/fftpack note on the TPU complex-transfer limit).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, replace
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from functools import lru_cache, partial

from presto_tpu.ops import responses as resp
from presto_tpu.ops import stats as st
from presto_tpu.utils.psr import next2_to_n

# Search grid constants (include/accel.h:18-31)
ACCEL_NUMBETWEEN = 2
ACCEL_DR = 0.5
ACCEL_RDR = 2
ACCEL_DZ = 2
ACCEL_RDZ = 0.5
ACCEL_CLOSEST_R = 15.0
ACCEL_USELEN = 7470
DBLCORRECT = 1e-14

# One shared device-memory constant (the meminfo.h analog): every HBM
# budget in this module derives from it so independent sub-budgets
# cannot stack past the device.  Override (bytes) for parts with
# different headroom.
DEVICE_HBM_BYTES = int(os.environ.get("PRESTO_TPU_HBM_BYTES",
                                      str(16 * 2 ** 30)))
# the [chunk, numz, fftlen] complex plane-build intermediate budget
# (bigger was NOT better in clean A/Bs on v5e — HBM pressure beside
# the plane + stacked-ys residents); single source for every consumer
CHUNK_BUDGET_BYTES = int(os.environ.get("PRESTO_TPU_CHUNK_BUDGET",
                                        str(2 ** 30)))


def _nearest_int(x: float) -> int:
    """Round half away from zero — the reference's NEAREST_INT
    (prepfold.h:14), NOT Python's banker's rounding."""
    return int(np.ceil(x - 0.5)) if x < 0 else int(np.floor(x + 0.5))


def calc_required_z(harm_fract: float, zfull: float) -> float:
    """z of the subharmonic for fundamental z (accel_utils.c:53-59)."""
    return _nearest_int(ACCEL_RDZ * zfull * harm_fract) * ACCEL_DZ


def calc_required_r(harm_fract: float, rfull: float) -> float:
    """r of the subharmonic for fundamental r (accel_utils.c:60-66)."""
    return int(ACCEL_RDR * rfull * harm_fract + 0.5) * ACCEL_DR


def calc_required_w(harm_fract: float, wfull: float) -> float:
    """w of the subharmonic for fundamental w, rounded to the jerk
    grid (modern PRESTO's calc_required_w; the mounted reference
    predates the jerk search)."""
    return _nearest_int(wfull * harm_fract / ACCEL_DW) * ACCEL_DW


def index_from_z(z: float, loz: float) -> int:
    return int((z - loz) * ACCEL_RDZ + DBLCORRECT)


def calc_fftlen(numharm: int, harmnum: int, max_zfull: int,
                uselen: int = ACCEL_USELEN,
                max_wfull: int = 0) -> int:
    """FFT length for a subharmonic block (accel_utils.c:116-131;
    jerk-search banks size for the widest w kernel)."""
    harm_fract = harmnum / numharm
    bins_needed = uselen * harmnum // numharm + 2
    z_req = calc_required_z(harm_fract, max_zfull)
    hw = (resp.w_resp_halfwidth(z_req, max_wfull, resp.LOWACC)
          if max_wfull else resp.z_resp_halfwidth(z_req, resp.LOWACC))
    end_effects = 2 * ACCEL_NUMBETWEEN * hw
    return next2_to_n(bins_needed + end_effects)


ACCEL_DW = 20                    # w grid step of the jerk search


@dataclass
class AccelConfig:
    zmax: int = 200              # max |z| searched (fundamental)
    wmax: int = 0                # max |w| of the jerk search (0 = off)
    numharm: int = 8             # max harmonics summed (power of two)
    sigma: float = 2.0           # candidate sigma cutoff
    rlo: float = 0.0             # min Fourier freq searched (bins);
                                 # 0 -> flo * T at plan time
    rhi: float = 0.0             # 0 -> numbins - 1
    flo: float = 1.0             # min freq (Hz) if rlo not given
    uselen: int = ACCEL_USELEN   # half-bins of fundamental per block
    max_cands_per_stage: int = 2048   # static top-k size
    norm: str = "median"         # "median" (accel_utils.c:952-967) or
                                 # "prenorm" (spectrum already
                                 # normalized: -photon/-locpow modes
                                 # prescale on host)

    @property
    def numharmstages(self) -> int:
        return int(np.log2(self.numharm)) + 1

    @property
    def numz(self) -> int:
        return (self.zmax // ACCEL_DZ) * 2 + 1

    @property
    def ws(self) -> np.ndarray:
        """Jerk-search w grid (empty when wmax == 0)."""
        if not self.wmax:
            return np.zeros(1)
        nside = self.wmax // ACCEL_DW
        return (np.arange(2 * nside + 1) - nside) * float(ACCEL_DW)


@dataclass
class AccelKernels:
    """The z-response kernel bank for the fundamental (host-built).

    Kernels are stored TIME-DOMAIN, centered in a common kmax-tap
    window (kmax = 2*NUMBETWEEN*halfwidth of the widest kernel); the
    host uploads this compact bank and _fft_kernel_bank_c expands it to
    the FFT'd fftlen bank on device (a ~20x upload saving through the
    tunneled link; one bank per w plane in the jerk search).
    """
    fftlen: int
    halfwidth: int
    numz: int
    zlo: int
    kmax: int
    kern_pairs: np.ndarray       # [numz, kmax, 2] float32, centered

    @classmethod
    def build(cls, cfg: AccelConfig, w: float = 0.0) -> "AccelKernels":
        """Parity: init_kernel (accel_utils.c:133-151) for harm 1/1.

        One kernel per z in [-zmax, zmax] step ACCEL_DZ: the float64
        z-response (or w-response for the jerk search's w != 0 planes),
        kernels shared across all r-blocks.  All w planes of one
        search share the kmax sized for the widest kernel so the
        plane builder compiles once.
        """
        fftlen = calc_fftlen(1, 1, cfg.zmax, cfg.uselen, cfg.wmax)
        halfwidth = (resp.w_resp_halfwidth(float(cfg.zmax),
                                           float(cfg.wmax), resp.LOWACC)
                     if cfg.wmax else
                     resp.z_resp_halfwidth(float(cfg.zmax), resp.LOWACC))
        numz = cfg.numz
        kmax = 2 * ACCEL_NUMBETWEEN * halfwidth
        kerns = np.zeros((numz, kmax), dtype=np.complex128)
        zs = -cfg.zmax + np.arange(numz, dtype=np.float64) * ACCEL_DZ
        if abs(w) >= 1e-7:
            # whole-bank quadrature at full kmax taps (the centered
            # numkern sub-grids of the kmax grid coincide exactly, so
            # masking reproduces the per-z-truncated kernels); the
            # serial per-z path cost ~1-2 s/kernel — an hour per
            # wmax=300 bank set
            full = resp.gen_w_response_bank(0.0, ACCEL_NUMBETWEEN,
                                            zs, float(w), kmax)
        for i in range(numz):
            z = zs[i]
            if abs(w) < 1e-7:
                hw = resp.z_resp_halfwidth(float(z), resp.LOWACC)
                numkern = min(2 * ACCEL_NUMBETWEEN * hw, kmax)
                k = resp.gen_z_response(0.0, ACCEL_NUMBETWEEN, float(z),
                                        numkern)
                start = kmax // 2 - numkern // 2
                kerns[i, start:start + numkern] = k[:numkern]
            else:
                hw = resp.w_resp_halfwidth(float(z), float(w),
                                           resp.LOWACC)
                numkern = min(2 * ACCEL_NUMBETWEEN * hw, kmax)
                start = kmax // 2 - numkern // 2
                kerns[i, start:start + numkern] = \
                    full[i, start:start + numkern]
        pairs = np.stack([kerns.real, kerns.imag], axis=-1).astype(np.float32)
        return cls(fftlen=fftlen, halfwidth=halfwidth, numz=numz,
                   zlo=-cfg.zmax, kmax=kmax, kern_pairs=pairs)


# ----------------------------------------------------------------------
# Device: fundamental plane construction
# ----------------------------------------------------------------------

def fft_kernel_bank_np(kern: "AccelKernels") -> np.ndarray:
    """Host-side expansion of the compact time-domain bank to the
    FFT'd [numz, fftlen, 2] bank _ffdot_blocks consumes (the numpy
    twin of _fft_kernel_bank_c, for driver entry points and referee
    paths that want plain arrays).

    NOTE: this twin FFTs in complex128 then rounds, while the device's
    _fft_kernel_bank_c FFTs in complex64 — the two banks agree only to
    float32 rounding, not bit-for-bit (accel_ref's referee compares
    candidate lists, where the difference is far below threshold)."""
    kc = kern.kern_pairs[..., 0] + 1j * kern.kern_pairs[..., 1]
    half = kern.kmax // 2
    placed = np.zeros((kc.shape[0], kern.fftlen), dtype=np.complex128)
    placed[:, :half] = kc[:, half:]
    placed[:, kern.fftlen - half:] = kc[:, :half]
    k = np.fft.fft(placed, axis=-1)
    return np.stack([k.real, k.imag], axis=-1).astype(np.float32)


@partial(jax.jit, static_argnames=("fftlen",))
def _fft_kernel_bank_c(kern_tpairs, fftlen):
    """FFT'd complex64 device bank from the compact time-domain bank
    (NR wrap placement, corr_prep.c:58-80 + forward FFT) — the form
    the build hot path consumes (see the dtype note on _kern_bank_z;
    the compact time-domain bank still uploads as pairs)."""
    kc = kern_tpairs[..., 0] + 1j * kern_tpairs[..., 1]
    kmax = kc.shape[-1]
    half = kmax // 2
    numz = kc.shape[0]
    placed = jnp.zeros((numz, fftlen), dtype=jnp.complex64)
    placed = placed.at[:, :half].set(kc[:, half:])
    placed = placed.at[:, fftlen - half:].set(kc[:, :half])
    return jnp.fft.fft(placed, axis=-1)


@partial(jax.jit, static_argnames=("uselen", "fftlen", "halfwidth"))
def _ffdot_blocks(seg_pairs, kern_pairs, uselen, fftlen, halfwidth):
    """Batched f-fdot power plane for many r-blocks at once —
    the PAIRS-boundary form kept for __graft_entry__ and external
    float32-only consumers (the build hot path uses the complex
    slab engines _ffdot_slab_mxu/_ffdot_slab_fft instead).

    seg_pairs: [nblocks, fftlen//2, 2] float32 — normalized Fourier
        amplitudes for each block's read window (lobin = block_rlo -
        halfwidth, fftlen//2 whole bins).
    kern_pairs: [numz, fftlen, 2] float32 — FFT'd kernel bank as
        pairs (fft_kernel_bank_np's output).
    Returns [nblocks, numz, uselen] float32 powers.

    Parity with the per-row loop of accel_utils.c:1002-1051: spread ×2,
    forward FFT, multiply by conj(kernel), inverse FFT, take uselen
    points starting at halfwidth*NUMBETWEEN, |.|^2 / fftlen^2.
    (A direct-conv MXU formulation was benchmarked at parity with this
    on v5e at float32 precision and abandoned — batched FFTs through
    XLA already saturate the same ~25 ms/chunk.)
    """
    data = seg_pairs[..., 0] + 1j * seg_pairs[..., 1]   # [B, fftlen//2]
    kern = kern_pairs[..., 0] + 1j * kern_pairs[..., 1]  # [numz, fftlen]
    B = data.shape[0]
    spread = jnp.zeros((B, fftlen), dtype=jnp.complex64)
    spread = spread.at[:, ::ACCEL_NUMBETWEEN].set(data)
    fdata = jnp.fft.fft(spread, axis=-1)                # [B, fftlen]
    prod = fdata[:, None, :] * jnp.conj(kern)[None]     # [B, numz, fftlen]
    corr = jnp.fft.ifft(prod, axis=-1)                  # ifft = fft(-1)/n
    offset = halfwidth * ACCEL_NUMBETWEEN
    good = jax.lax.dynamic_slice_in_dim(corr, offset, uselen, axis=2)
    # reference norm: |x|^2/fftlen^2 with unnormalized inverse FFT; jnp
    # ifft divides by fftlen already, so only one factor remains... but
    # the forward FFT here is unnormalized like COMPLEXFFT, so
    # |ifft_np|^2 = |ifft_ref|^2 / fftlen^2 exactly matches ref norm.
    return (good.real ** 2 + good.imag ** 2).astype(jnp.float32)


# ----------------------------------------------------------------------
# Factored MXU-DFT correlation engine
# ----------------------------------------------------------------------
#
# XLA's TPU FFT is a multi-pass HBM-bound loop, and the correlation
# pipeline around it (spread scatter, kernel cmul, inverse FFT,
# |.|^2, then a plane-sized [B, numz, .] -> [numz, B*.] relayout)
# costs several full traversals of multi-GB complex intermediates.
# The factored engine computes the same correlation as two small DFT
# matmul stages (fftlen = n1 * 128) on the MXU, with the inverse
# written directly in z-major order ('zxic' einsum output) so the
# slab lands in plane layout with NO post-hoc transpose.  Validated
# at HIGHEST precision to the same float32 error vs a float64 FFT as
# the jnp.fft path (3.2e-7 vs 3.6e-7 max rel on the bench workload).

_DFT_N2 = 128                    # lane-width radix of stage 2

ACCEL_ENGINE = os.environ.get("PRESTO_TPU_ACCEL_ENGINE", "auto")


def _use_mxu_engine(fftlen: int) -> bool:
    """auto: factored engine on TPU (pocketfft-backed XLA FFT wins on
    CPU), when fftlen factors as n1*128 with even n1 (the spread trick
    needs n2/2 integral)."""
    ok = fftlen % (2 * _DFT_N2) == 0
    if ACCEL_ENGINE == "mxu":
        return ok
    if ACCEL_ENGINE == "fft":
        return False
    try:
        return ok and jax.devices()[0].platform == "tpu"
    except Exception:
        return False


@lru_cache(maxsize=8)
def _dft_consts_np(fftlen: int):
    """Pair-format (f32 [..., 2]) DFT stage constants — complex arrays
    cannot cross the host->device boundary on the tunneled TPU, so
    they upload as pairs and recombine under jit.

    Factorization (time i = i1*n2 + i2, freq k = k1 + n1*k2):
      fwd   Y[k1, j] = sum_i1 D1[k1, i1] x[i1*(n2/2) + j]   (spread
            data: only even i2 = 2j are nonzero, halving stage 2)
            S[k1, k2] = (Y * T2) @ D2m, tiled 2x along k2
      inv   q = P @ C2;  corr[i1, i2] = iD1 @ (q * Tb)
    """
    n2 = _DFT_N2
    n1 = fftlen // n2
    m = n2 // 2

    def pairs(c):
        return np.stack([c.real, c.imag], -1).astype(np.float32)

    k1 = np.arange(n1)
    i1 = np.arange(n1)
    j = np.arange(m)
    k2 = np.arange(n2)
    i2 = np.arange(n2)
    D1 = np.exp(-2j * np.pi * np.outer(k1, i1) / n1)
    T2 = np.exp(-2j * np.pi * np.outer(k1, 2 * j) / fftlen)
    D2m = np.exp(-2j * np.pi * np.outer(j, np.arange(m)) / m)
    C2 = np.exp(+2j * np.pi * np.outer(k2, i2) / n2)
    Tb = np.exp(+2j * np.pi * np.outer(k1, i2) / fftlen) / fftlen
    iD1 = np.exp(+2j * np.pi * np.outer(i1, k1) / n1)
    return tuple(pairs(c) for c in (D1, T2, D2m, C2, Tb, iD1))


@partial(jax.jit, static_argnames=("fftlen",))
def _kern_bank_z(kern_c, fftlen):
    """FFT'd complex bank [numz, fftlen] -> conjugated stage-layout
    bank [numz, n1, n2] (Z[k1, k2] = Kfft[k1 + n1*k2]).

    NOTE on dtypes in this module's device path: everything internal
    is complex64, NOT float32 [..., 2] pairs — a trailing dim of 2
    lands on the TPU lane axis and is padded 2 -> 128, a 64x tax on
    every byte moved (measured: the 561 window slices alone cost
    121 ms in pair layout).  Pairs appear only at host<->device
    boundaries (the axon link cannot transfer complex)."""
    n1 = fftlen // _DFT_N2
    return jnp.conj(kern_c).reshape(
        kern_c.shape[0], _DFT_N2, n1).transpose(0, 2, 1)


def _ffdot_slab_mxu(data, kz, consts, uselen, fftlen, halfwidth):
    """Factored-DFT twin of _ffdot_blocks, returning the slab in plane
    layout [numz, B*uselen] (z-major, blocks concatenated along
    columns) — same math, same normalization, no output transpose.

    data: [B, fftlen//2] complex64 block windows; kz: _kern_bank_z
    bank; consts: _dft_consts pair arrays."""
    n2 = _DFT_N2
    n1 = fftlen // n2
    B = data.shape[0]
    cx = lambda p: p[..., 0] + 1j * p[..., 1]
    C2, Tb, iD1 = (cx(c) for c in consts[3:])
    numz = kz.shape[0]
    prec = jax.lax.Precision.HIGHEST
    S = _fwd_stage_c(data, consts, fftlen)               # [B, n1, n2]
    Pm = S[:, None] * kz[None]                           # [B,numz,n1,n2]
    q = jnp.einsum("xzab,bc->xzac", Pm, C2, precision=prec)
    corr = jnp.einsum("ia,xzac->zxic", iD1, q * Tb[None, None],
                      precision=prec)                    # [numz,B,n1,n2]
    pw = (corr.real ** 2 + corr.imag ** 2).astype(jnp.float32)
    pw = pw.reshape(numz, B, fftlen)
    off = halfwidth * ACCEL_NUMBETWEEN
    pw = jax.lax.slice(pw, (0, 0, off), (numz, B, off + uselen))
    return pw.reshape(numz, B * uselen)


def _fwd_stage_c(data, consts, fftlen):
    """Forward half of the factored transform: block windows ->
    stage-layout spectra S [B, n1, n2] complex — ONE implementation
    shared by the XLA slab engine and the pallas builder, so the two
    engines cannot drift."""
    n2 = _DFT_N2
    n1 = fftlen // n2
    m = n2 // 2
    B = data.shape[0]
    cx = lambda p: p[..., 0] + 1j * p[..., 1]
    D1, T2, D2m = (cx(c) for c in consts[:3])
    prec = jax.lax.Precision.HIGHEST
    x2 = data.reshape(B, n1, m)
    Y = jnp.einsum("ab,xbj->xaj", D1, x2, precision=prec)
    Sm = jnp.einsum("xaj,jk->xak", Y * T2[None], D2m, precision=prec)
    return jnp.concatenate([Sm, Sm], axis=-1)


def _fwd_stage_mxu(data, consts, fftlen):
    """_fwd_stage_c as (re, im) float32 pairs (the pallas builder's
    input form)."""
    S = _fwd_stage_c(data, consts, fftlen)
    return (S.real.astype(jnp.float32), S.imag.astype(jnp.float32))


def _ffdot_slab_fft(data, kern_c, uselen, fftlen, halfwidth):
    """jnp.fft twin of _ffdot_slab_mxu (complex in, z-major slab out)
    — the engine used where the factored transform doesn't apply
    (CPU, or fftlen not a multiple of 256)."""
    B = data.shape[0]
    numz = kern_c.shape[0]
    spread = jnp.zeros((B, fftlen), dtype=jnp.complex64)
    spread = spread.at[:, ::ACCEL_NUMBETWEEN].set(data)
    fdata = jnp.fft.fft(spread, axis=-1)
    prod = fdata[:, None, :] * jnp.conj(kern_c)[None]
    corr = jnp.fft.ifft(prod, axis=-1)
    offset = halfwidth * ACCEL_NUMBETWEEN
    good = jax.lax.dynamic_slice_in_dim(corr, offset, uselen, axis=2)
    pw = (good.real ** 2 + good.imag ** 2).astype(jnp.float32)
    return jnp.moveaxis(pw, 0, 1).reshape(numz, B * uselen)


def _block_median_norms_c(data):
    """Old-style per-block median power normalization factors.

    norm = 1/sqrt(median(|amps|^2)/ln2) (accel_utils.c:952-967).
    data: [B, numdata] complex windows -> [B, 1] float32 scale (the
    reference scales data before correlating)."""
    pows = data.real ** 2 + data.imag ** 2
    med = jnp.maximum(jnp.median(pows, axis=-1), 1e-30)
    return (1.0 / jnp.sqrt(med / jnp.log(2.0))).astype(jnp.float32)[
        :, None]


# ----------------------------------------------------------------------
# Device: harmonic summing + thresholding over the full plane
# ----------------------------------------------------------------------

def _harm_fracs_and_zinds(cfg: AccelConfig, numz: int):
    """Host-precomputed per-stage harmonic fractions and z-row maps.

    For each stage s >= 1 and odd harm < 2^s: fraction harm/2^s and the
    z-row gather map zind[numz] (inmem_add_ffdotpows index math,
    accel_utils.c:1160-1207).  Column maps are computed on device from
    the fraction (round-half-up of absolute half-bin * frac).
    """
    out = []
    zlo = -cfg.zmax
    zs = zlo + np.arange(numz) * ACCEL_DZ
    for stage in range(1, cfg.numharmstages):
        harmtosum = 1 << stage
        stage_list = []
        for harm in range(1, harmtosum, 2):
            frac = harm / harmtosum
            zinds = np.array([index_from_z(calc_required_z(frac, z), zlo)
                              for z in zs], dtype=np.int32)
            stage_list.append((harm, harmtosum, zinds))
        out.append(stage_list)
    return out


SEARCH_SEG = 16     # columns per segment-max before top-k: 16 columns
                    # = 8 r-bins < ACCEL_CLOSEST_R, so candidates
                    # merged here are exactly those the r-dedup
                    # (insert_new_accelcand semantics) collapses anyway


def _make_search_scanner(numharmstages, fracs_zinds, powcuts, slab, k,
                         plane_numr, aligned=False,
                         pallas_reducer=None, numz=None,
                         plane_padded=False):
    """One jit'd function running the whole staged search as a lax.scan
    over slab start columns (a single device dispatch — the tunneled
    TPU pays ~0.1-0.4 s latency per call, so per-slab calls dominate
    wall time otherwise).

    Per slab: accumulate the harmonic sums, then per stage reduce each
    column to its max over z (same-column different-z cells are exact
    duplicates under the sifter's r-dedup), segment-max groups of
    SEARCH_SEG columns (duplicates under the same rule — the
    reference's own insert-time dedup, accel_utils.c:294-382, collapses
    candidates within ACCEL_CLOSEST_R=15 bins), and top-k the segments
    above powcut (TPU top-k cost scales with the input length; the
    16x shrink is the big win).  Column gather indices use exact int32
    round-half-up of (abs_halfbin * harm / htot), equal to the
    reference's (int)(rrint*frac + 0.5) double math
    (accel_utils.c:1169-1175), and each harmonic reads only its
    contiguous source window via dynamic_slice (bounded gather
    traffic).  Returns ONE packed int32 array [3, nslabs, stages, k]
    (power bits, column, zrow) so the host pays a single D2H transfer.
    """
    powcuts = jnp.asarray(powcuts, dtype=jnp.float32)
    fz = [(harm, htot, jnp.asarray(zi)) for stage in fracs_zinds
          for (harm, htot, zi) in stage]
    nseg = -(-slab // SEARCH_SEG)
    segpad = nseg * SEARCH_SEG - slab
    kk = min(k, nseg)

    def _zi_for(zinds, nrows):
        """zinds extended to a pad_rows plane (the direct-plane pallas
        builder hands the scanner ceil(numz/8)*8 rows; pad rows are
        zero-kernel rows, mapped to themselves so they stay zero in
        every harmonic accumulator and can never beat powcut)."""
        if nrows == zinds.shape[0]:
            return zinds
        return jnp.concatenate([
            zinds, jnp.arange(zinds.shape[0], nrows, dtype=jnp.int32)])

    def slab_body(planes, start_col):
        """planes: [1 + n_harm_terms] source planes — planes[0] is the
        fundamental, planes[1 + fi] the source for harmonic term fi.
        For the z-only search every entry aliases ONE buffer (free);
        the jerk search passes per-subharmonic-w planes."""
        P = planes[0]
        cols = start_col + jnp.arange(slab, dtype=jnp.int32)
        acc = jax.lax.dynamic_slice(P, (0, start_col), (P.shape[0], slab))

        def collect(acc, stage):
            colmax = acc.max(axis=0)
            colz = acc.argmax(axis=0).astype(jnp.int32)
            masked = jnp.where(colmax > powcuts[stage], colmax, 0.0)
            segs = jnp.pad(masked, (0, segpad)).reshape(nseg,
                                                        SEARCH_SEG)
            v, si = jax.lax.top_k(segs.max(axis=1), kk)
            ci = si * SEARCH_SEG + \
                jnp.take(segs.argmax(axis=1).astype(jnp.int32), si)
            # padded-segment hits have v == 0 and are filtered on host
            return v, ci, jnp.take(colz, ci, mode="clip")

        outs = [collect(acc, 0)]
        fi = 0
        for stage in range(1, numharmstages):
            for _ in range(1 << (stage - 1)):   # odd harmonics
                harm, htot, zinds = fz[fi]
                fi += 1        # planes[fi] is now THIS term's source
                               # (planes[0] is the fundamental)
                if (aligned and slab % htot == 0
                        and (slab // htot + 1) * harm <= slab):
                    # Phase-decomposed subharmonic read — NO gather.
                    # With start_col % htot == 0 (the _slab_plan
                    # alignment contract), column j = q*htot + ph maps
                    # to source column cstart + q*harm + off(ph),
                    # off(ph) = (ph*harm + htot//2)//htot <= harm: all
                    # phases are STATIC slices of a [nq+1, harm]
                    # reshape, replacing the minor-axis gather that
                    # dominated scan time on TPU (~6x the slice cost).
                    nq = slab // htot
                    cstart = (start_col // htot) * harm
                    src = jax.lax.dynamic_slice(
                        planes[fi], (0, cstart), (P.shape[0], slab))
                    sub = jnp.take(src, _zi_for(zinds, P.shape[0]),
                                   axis=0)
                    src3 = sub[:, :(nq + 1) * harm].reshape(
                        -1, nq + 1, harm)
                    pieces = []
                    for ph in range(htot):
                        off = (ph * harm + (htot >> 1)) // htot
                        if off < harm:
                            pieces.append(src3[:, :nq, off])
                        else:            # off == harm: next q, tap 0
                            pieces.append(src3[:, 1:nq + 1, 0])
                    acc = acc + jnp.stack(pieces, axis=-1).reshape(
                        acc.shape[0], slab)
                else:
                    # round-half-up of cols*harm/htot without int32
                    # overflow (split off the quotient so the multiply
                    # stays < 2^31 even for billion-bin spectra):
                    # exact for htot = 2^s.
                    rind = ((cols // htot) * harm
                            + ((cols % htot) * harm + (htot >> 1))
                            // htot)
                    cstart = jnp.minimum(
                        (start_col // htot) * harm
                        + ((start_col % htot) * harm + (htot >> 1))
                        // htot,
                        plane_numr - slab)
                    src = jax.lax.dynamic_slice(planes[fi], (0, cstart),
                                                (P.shape[0], slab))
                    sub = jnp.take(src, _zi_for(zinds, P.shape[0]),
                                   axis=0)
                    acc = acc + jnp.take(sub, rind - cstart, axis=1)
            outs.append(collect(acc, stage))
        vals = jnp.stack([o[0] for o in outs])      # [stages, k]
        cidx = jnp.stack([o[1] for o in outs])
        zrow = jnp.stack([o[2] for o in outs])
        # one int32 tensor (power bits / column / zrow) -> one D2H
        return jnp.stack([jax.lax.bitcast_convert_type(vals, jnp.int32),
                          cidx, zrow])

    nterms = len(fz)

    def _scan_planes_py(planes, start_cols):
        def body(carry, start):
            return carry, slab_body(planes, start)
        _, packed = jax.lax.scan(body, None, start_cols)
        return jnp.moveaxis(packed, 1, 0)  # [3, nslabs, stages, k]

    def _collect_from_reduced(colmax, colz):
        """Shared threshold + segment-max + top-k over the reduced
        [nslabs, stages, slab] (colmax, colz) arrays -> packed int32
        [3, nslabs, stages, k] (same packing as slab_body)."""
        nslabs = colmax.shape[0]
        masked = jnp.where(colmax > powcuts[None, :, None], colmax,
                           0.0)
        segs = masked.reshape(nslabs, numharmstages, nseg,
                              SEARCH_SEG)
        v, si = jax.lax.top_k(segs.max(-1), kk)
        ci = si * SEARCH_SEG + jnp.take_along_axis(
            segs.argmax(-1).astype(jnp.int32), si, axis=-1)
        zrow = jnp.take_along_axis(colz, ci, axis=-1)
        return jnp.stack([jax.lax.bitcast_convert_type(v, jnp.int32),
                          ci, zrow])

    def _scan_pallas_py(P, start_cols):
        """Pallas stage-reduction path: pad the plane to the kernel's
        tiling contract, reduce on-kernel, finish in XLA.  A plane
        from the direct-plane builder (plane_padded) already has
        pad_rows rows and >= PLANE_PAD trailing zero columns — no
        multi-GB pad pass."""
        from presto_tpu.search import accel_pallas as ap
        rowpad = max(0, ap.pad_rows(numz) - P.shape[0])
        colpad = 0 if plane_padded else ap.PLANE_PAD
        Ppad = jnp.pad(P, ((0, rowpad), (0, colpad))) \
            if (rowpad or colpad) else P
        colmax, colz = pallas_reducer(Ppad, start_cols)
        return _collect_from_reduced(colmax, colz)

    def _scan_all_py(P, start_cols):
        if pallas_reducer is not None:
            return _scan_pallas_py(P, start_cols)
        # z-only search: every harmonic reads the fundamental plane
        return _scan_planes_py((P,) * (1 + nterms), start_cols)

    scan_all = jax.jit(_scan_all_py)
    scan_all.body = _scan_all_py     # unjitted, for fused build+search
    # jerk search: explicit per-subharmonic-w source planes
    scan_all.planes = jax.jit(_scan_planes_py)

    @jax.jit
    def scan_many(Ps, start_cols):
        """Batched: Ps [numdms, numz, plane_numr] -> per-DM results in
        ONE device dispatch (the DM fan-out of a survey search)."""
        def per_dm(_, P):
            return None, _scan_all_py(P, start_cols)
        _, outs = jax.lax.scan(per_dm, None, Ps)
        return jnp.moveaxis(outs, 1, 0)   # [3, numdms, nslabs, stages, k]

    from functools import partial

    @partial(jax.jit, static_argnums=2)
    def scan_many_compact(Ps, start_cols, m):
        """scan_many + per-trial top-m candidate compaction in the
        SAME dispatch: the dense [3, nd, nslabs, stages, k] tensor
        never crosses to the host (compact_scan_packed — the D2H
        shrink that made the e2e share device-bound, applied to the
        library's batched path)."""
        packed = scan_many(Ps, start_cols)
        per_dm = jnp.moveaxis(packed, 1, 0)  # [nd, 3, nsl, st, k]
        return jax.vmap(
            lambda p: compact_scan_packed(p, m))(per_dm)

    scan_all.many = scan_many
    scan_all.many_compact = scan_many_compact
    return scan_all


def _unpack_scan(packed: np.ndarray):
    """Host side of the packed scanner output: float32 powers + int32
    column/zrow indices."""
    arr = np.asarray(packed)
    return arr[0].view(np.float32), arr[1], arr[2]


# compact_scan_packed meta-word layout (low to high bits)
_CMP_ZBITS = 12          # zrow: plane row index (numz + reducer pad)
_CMP_SBITS = 3           # stage: numharmstages <= 5 in practice
COMPACT_CANDS = 2048     # default top-m budget per trial


def compact_scan_packed(packed, m: int = COMPACT_CANDS):
    """Device-side compaction of one trial's scanner output.

    The scanner's packed [3, nslabs, stages, k] tensor reserves k
    top-k slots per (slab, stage) but above-powcut survivors are
    typically a few hundred per trial — over the tunneled TPU link the
    dense D2H (tens of MB per DM group) dominates the whole e2e wall
    (TARGETSCALE_r04: 153.8 of 154.0 s host-side).  This selects the
    top-m slots by power across ALL (slab, stage, slot) cells in one
    device pass, so the host transfer shrinks from nslabs*stages*k to
    m words per row.  Lossless as long as the number of positive
    (above-powcut) slots is < m; collect_compacted() raises if the
    m-th value is still positive (possible truncation) — raise m.

    Pure jnp: call it inside an enclosing jit (e.g. appended to a
    fused build+scan+compact program) so no extra dispatch is paid.
    Returns int32 [3, m]: power bits (descending), within-slab column,
    and meta = zrow | stage << _CMP_ZBITS | slab << (_CMP_ZBITS+_CMP_SBITS).
    """
    valbits, cidx, zrow = packed[0], packed[1], packed[2]
    nslabs, stages, k = valbits.shape
    assert stages < (1 << _CMP_SBITS) and nslabs < (1 << 16), \
        (nslabs, stages)
    m = min(m, nslabs * stages * k)
    si = jnp.arange(nslabs, dtype=jnp.int32)[:, None, None]
    sg = jnp.arange(stages, dtype=jnp.int32)[None, :, None]
    meta = (zrow | (sg << _CMP_ZBITS)
            | (si << (_CMP_ZBITS + _CMP_SBITS)))
    vals = jax.lax.bitcast_convert_type(valbits, jnp.float32)
    v, idx = jax.lax.top_k(vals.reshape(-1), m)
    return jnp.stack([jax.lax.bitcast_convert_type(v, jnp.int32),
                      jnp.take(cidx.reshape(-1), idx),
                      jnp.take(meta.reshape(-1), idx)])


@dataclass
class AccelCand:
    """A raw search candidate (pre-sifting). Mirrors accelcand
    (accel.h:76-86) minus the optimization fields."""
    power: float
    sigma: float
    numharm: int
    r: float           # fundamental-search r / numharm (candidate freq bin)
    z: float
    w: float = 0.0     # jerk plane of origin (0 unless wmax search)

    def freq(self, T: float) -> float:
        return self.r / T


class AccelSearch:
    """In-memory accelsearch over a packed spectrum.

    Usage:
        s = AccelSearch(cfg, T=obs_seconds)
        cands = s.search(fft_pairs)   # [numbins, 2] float32 pairs
    """

    def __init__(self, cfg: AccelConfig, T: float, numbins: int):
        # spectra shorter than one ACCEL_USELEN r-block would yield an
        # empty search (the reference's block loop, accelsearch.c:167,
        # simply assumes survey-length FFTs): shrink the block to fit
        max_uselen = max(64, 2 * (numbins - 16))
        if cfg.uselen > max_uselen or cfg.uselen % 2:
            # even uselen keeps the block grid on whole bins — the
            # uniform-hop frame builder (_frames_fn) requires an
            # integer hop = uselen/2
            cfg = replace(cfg, uselen=min(cfg.uselen & ~1, max_uselen))
        # Direct-plane pallas builder (TPU): pick an ALIGNED geometry —
        # uselen a multiple of 128 columns filling the fftlen minus a
        # 128-aligned output offset — so the build kernel stores the
        # plane layout directly (build_pallas.py docstring).  Only the
        # DEFAULT uselen is retuned; an explicit cfg.uselen is the
        # caller's choice (the reference's own ACCEL_USELEN is a CPU
        # FFT tuning knob, accel.h:10-16).
        try:
            from presto_tpu.search import accel_pallas as _ap
            _plb_ok = (_ap.pallas_available()
                       and ACCEL_ENGINE in ("auto", "plb"))
        except Exception:
            _plb_ok = False
        if _plb_ok and cfg.uselen == ACCEL_USELEN:
            fft0 = calc_fftlen(1, 1, cfg.zmax, cfg.uselen, cfg.wmax)
            hw0 = (resp.w_resp_halfwidth(float(cfg.zmax),
                                         float(cfg.wmax), resp.LOWACC)
                   if cfg.wmax else
                   resp.z_resp_halfwidth(float(cfg.zmax), resp.LOWACC))
            hw_eff0 = -(-hw0 // 64) * 64
            u_al = (fft0 - 4 * hw_eff0) & ~127
            if (1024 <= u_al <= max_uselen
                    and calc_fftlen(1, 1, cfg.zmax, u_al,
                                    cfg.wmax) == fft0):
                cfg = replace(cfg, uselen=u_al)
        self.cfg = cfg
        self.T = T
        self.numbins = numbins
        self.kern = AccelKernels.build(cfg)
        # plb engages when the ACTUAL kernel geometry satisfies the
        # alignment contract (kern built above)
        self._plb_hw_eff = None
        if _plb_ok:
            hw_eff = -(-self.kern.halfwidth // 64) * 64
            if (self.kern.fftlen % (2 * _DFT_N2) == 0
                    and cfg.uselen % _DFT_N2 == 0
                    and cfg.uselen + 4 * hw_eff <= self.kern.fftlen
                    and _use_mxu_engine(self.kern.fftlen)):
                self._plb_hw_eff = hw_eff
        self._fn_cache = {}   # compiled build/scan fns (avoid re-jit)
        self._kern_dev = None  # device copy of the kernel bank (lazy)
        self._w_banks = {0.0: self.kern}   # jerk-search kernel banks
        self.rlo = cfg.rlo if cfg.rlo > 0 else max(cfg.flo * T, 8.0)
        self.rhi = cfg.rhi if cfg.rhi > 0 else numbins - 1
        # numindep & powcut per stage (accel_utils.c:1629-1641)
        self.numindep = []
        self.powcut = []
        for ii in range(cfg.numharmstages):
            harmtosum = 1 << ii
            if cfg.numz == 1:
                ni = (self.rhi - self.rlo) / harmtosum
            else:
                ni = ((self.rhi - self.rlo) * (cfg.numz + 1) *
                      (ACCEL_DZ / 6.95) / harmtosum)
            # jerk search: each w plane is (approximately) another set
            # of independent trials
            ni *= len(cfg.ws)
            self.numindep.append(ni)
            self.powcut.append(float(st.power_for_sigma(
                cfg.sigma, harmtosum, ni)))

    # -- plane ---------------------------------------------------------

    def _plan_blocks(self):
        """r-block starts (whole bins) covering [0, rhi] — the
        reference's inmem pre-population + search loops
        (accelsearch.c:143-160) start at r=8; this grid starts at r=0
        so plane columns stay tile-aligned (col0=16 puts every concat
        joint of the plane assembly at a misaligned lane offset, a
        measured ~2x write-cost tax on v5e).  Deviation: the first
        block's median-normalization window covers [0, uselen/2)
        instead of [8, 8+uselen/2) — 8 bins of content out of 4096,
        immaterial to the robust median — and columns below rlo are
        computed but filtered at collect time (_collect_slab r0min),
        exactly like any other below-rlo column of an aligned slab."""
        blocks = []
        startr = 0.0
        step = self.cfg.uselen * ACCEL_DR
        # Only full, in-spectrum blocks are built/searched — same bound
        # as the reference loop (accelsearch.c:167): a partial block at
        # the top would be median-normalized against zero padding.
        while startr + step < self.rhi:
            blocks.append(startr)
            startr += step
        return blocks

    def build_plane(self, fft_pairs: np.ndarray,
                    kern_pairs_dev=None):
        """Fundamental F-Fdot plane P[numz, plane_numr] — a device
        array resident in HBM (host transfers of the multi-GB plane
        through the host<->TPU link would dominate the search time).

        plane column c = absolute half-bin (r = c * ACCEL_DR), starting
        at column 0 == r 0.  Block j occupies the contiguous columns
        [j*uselen, (j+1)*uselen): starts are j*uselen*DR (the r=0
        block-grid origin of _plan_blocks; columns below rlo are
        filtered at collect time), so the per-chunk slabs concatenate
        directly into the plane.
        fft_pairs: [numbins, 2] float32 (the packed .fft as pairs).
        """
        kern = self.kern
        starts = self._plan_blocks()
        if not starts:
            # spectrum too short for one full block: empty plane
            return jnp.zeros((kern.numz, 0), dtype=jnp.float32)
        if kern_pairs_dev is None:
            kern_pairs_dev = self._kern_bank_dev()
        yp = self._build_plan_ns()
        key = ("build",) + yp.key
        self._build_plan = key
        if key not in self._fn_cache:
            self._fn_cache[key] = jax.jit(yp.build_body)
        return self._fn_cache[key](self._to_dev(fft_pairs),
                                   kern_pairs_dev)

    def _kern_bank_dev(self):
        if self._kern_dev is None:   # one small upload, reused
            self._kern_dev = _fft_kernel_bank_c(
                jnp.asarray(self.kern.kern_pairs), self.kern.fftlen)
        return self._kern_dev

    @staticmethod
    def _to_dev(fft_pairs):
        if isinstance(fft_pairs, jax.Array):
            return fft_pairs             # already uploaded (jerk loop)
        return jnp.asarray(np.ascontiguousarray(fft_pairs))

    def _plane_geom(self):
        """Block/window geometry of the plane build (host-side ints),
        cached — it depends only on (cfg, numbins)."""
        if getattr(self, "_geom", None) is not None:
            return self._geom
        cfg, kern = self.cfg, self.kern
        starts = self._plan_blocks()
        if not starts:
            self._geom = False
            return False
        numdata = kern.fftlen // 2
        # plane width padded (zero columns) to a multiple of the
        # scanner's alignment so every aligned slab fits inside the
        # plane; zero columns can never exceed powcut.  On TPU the
        # pallas stage reducer wants TILE-aligned slab starts, so the
        # plane pads to that stricter grid.
        align = max(16, cfg.numharm)
        try:
            from presto_tpu.search import accel_pallas as ap
            if ap.pallas_available():
                align = max(align, ap.TILE)
        except Exception:
            pass
        # direct-plane builder geometry: the plane IS the kernel
        # output, [numz_pad, nb_pad*uselen] with >= 1 zero-padded
        # block on the right (covers the scan's PLANE_PAD contract);
        # the effective halfwidth rounds the window offset to a
        # 128-column boundary so the good region is whole n1-rows
        hw_eff = self._plb_hw_eff
        hw_use = hw_eff if hw_eff else kern.halfwidth
        nb_pad = None
        if hw_eff:
            from presto_tpu.search import build_pallas as bp
            nb_pad = -(-(len(starts) + 1) // bp.BB) * bp.BB
            plane_numr = nb_pad * cfg.uselen
        else:
            plane_numr = int(2 * int(starts[-1]) + cfg.uselen)
            plane_numr += (-plane_numr) % align
        # Chunk the block batch: the [chunk, numz, fftlen] complex
        # intermediate is the peak working memory, so bound it — the
        # HBM-ladder analog of meminfo.h.  Round down to the smallest
        # chunk keeping chunk*uselen a lane-tile multiple (aligned
        # concat joints / DUS offsets).
        chunk = max(1, int(CHUNK_BUDGET_BYTES
                           // (kern.numz * kern.fftlen * 8)))
        import math as _math
        almul = 128 // _math.gcd(cfg.uselen, 128)
        if chunk >= almul:
            chunk -= chunk % almul
        col0 = int(starts[0]) * ACCEL_RDR
        # Host uploads ONLY the raw spectrum; the per-block read
        # windows are gathered on device (the tunneled host->TPU link
        # runs ~tens of MB/s for real payloads, so shipping the ~10%-
        # overlapping window tensor costs more than the whole device
        # compute).  Window j = fft_pad[lobins[j] : +numdata]; padded
        # (beyond-nblocks) windows point at a zero region.
        nblocks = len(starts)
        chunk = min(chunk, nblocks)
        nsteps = (nblocks + chunk - 1) // chunk
        npad_blocks = nsteps * chunk - nblocks
        lobin0 = int(starts[0]) - hw_use
        pad_lo = max(0, -lobin0)
        # cover the last real window AND the frame builder's (F+P)*hop
        # base region (padded frames read zeros there)
        hop = int(cfg.uselen * ACCEL_DR)
        F = nsteps * chunk
        P = -(-numdata // hop)
        pad_hi = numdata + max(
            0, int(starts[-1]) - hw_use + numdata - self.numbins)
        pad_hi = max(pad_hi,
                     lobin0 + pad_lo + (F + P) * hop - self.numbins)
        lobins = np.asarray(
            [int(s0) - hw_use for s0 in starts]
            + [self.numbins] * npad_blocks, np.int32) + pad_lo
        from types import SimpleNamespace
        self._geom = SimpleNamespace(
            starts=starts, numdata=numdata, plane_numr=plane_numr,
            chunk=chunk, nsteps=nsteps, col0=col0, nblocks=nblocks,
            lobins=lobins, hw_use=hw_use, hw_eff=hw_eff,
            nb_pad=nb_pad,
            pads=((pad_lo, pad_hi), (0, 0)),
            body_numr=nsteps * chunk * cfg.uselen)
        return self._geom

    def _chunk_slab_fn(self, g):
        """Per-chunk slab computation: [chunk, numdata] complex block
        windows -> [numz, chunk*uselen] slab in plane (z-major)
        layout.  kern_use is an ARGUMENT (not a closure) so the jerk
        search's per-w kernel banks share one compiled function; it is
        the complex FFT'd bank for the fft engine and the stage-layout
        conj bank (_kern_bank_z) for the mxu engine."""
        cfg, kern = self.cfg, self.kern
        use_mxu = _use_mxu_engine(kern.fftlen)
        consts = _dft_consts_np(kern.fftlen) if use_mxu else None
        hw_use = g.hw_use     # effective halfwidth: plb geometry pads
                              # the output offset, and the window
                              # lobins shift with it — every engine
                              # must slice at the same offset

        def chunk_slab(data, kern_use):
            if cfg.norm == "median":
                data = data * _block_median_norms_c(data)
            if use_mxu:
                return _ffdot_slab_mxu(
                    data, kern_use, tuple(map(jnp.asarray, consts)),
                    cfg.uselen, kern.fftlen, hw_use)
            return _ffdot_slab_fft(data, kern_use, cfg.uselen,
                                   kern.fftlen, hw_use)

        chunk_slab.use_mxu = use_mxu
        return chunk_slab

    def _frames_fn(self, g):
        """All block read windows at once, from the uniform block grid
        (hop = uselen*ACCEL_DR bins): two reshapes + one concat
        instead of per-block slices (561 dynamic_slice ops measured
        ~100 ms on v5e; this is one pass over ~18 MB).  Returns
        f(fft_raw_pairs) -> [nframes, numdata] complex64, where frames
        past the real blocks read the zero padding (the padded-block
        contract of _plane_geom)."""
        kern = self.kern
        hop = int(self.cfg.uselen * ACCEL_DR)
        L = g.numdata
        F = g.nsteps * g.chunk
        lob0 = int(g.lobins[0])
        pad_lo, pad_hi = g.pads[0]
        P = -(-L // hop)              # rows each frame spans

        def frames(fft_raw):
            c = jnp.pad(fft_raw[:, 0] + 1j * fft_raw[:, 1],
                        (pad_lo, pad_hi))
            base = jax.lax.slice(c, (lob0,), (lob0 + (F + P) * hop,))
            A = base.reshape(F + P, hop)
            parts = [jax.lax.slice(A, (p, 0),
                                   (p + F, min(hop, L - p * hop)))
                     for p in range(P)]
            return jnp.concatenate(parts, axis=1) if P > 1 else parts[0]
        return frames

    def _pallas_build_body(self, g, frames_fn):
        """Direct-plane pallas build body (the default TPU engine when
        the aligned geometry holds — see __init__): forward spectra in
        XLA, correlation + |.|^2 in a VMEM pallas kernel
        (search/build_pallas.py) that writes the plane layout
        directly.  The output is [numz_pad, nb_pad*uselen]: pad z
        rows are zero (zero kernels) and padded blocks write zero
        columns, both handled by the scanner; the only post-op is a
        free reshape.  (The previous full-frame version lost ~290 ms
        to an XLA [off:off+uselen] relayout pass; kernel alone
        measured ~74 ms on the bench workload.)"""
        try:
            from presto_tpu.search import accel_pallas as ap
            if not ap.pallas_available():
                if ACCEL_ENGINE == "plb":
                    print("accel: PRESTO_TPU_ACCEL_ENGINE=plb "
                          "requested but no TPU backend — using the "
                          "default engine")
                return None
            from presto_tpu.search import build_pallas as bp
        except Exception as e:
            print("accel: pallas build unavailable (%s) — using the "
                  "default engine" % (e,))
            return None
        cfg, kern = self.cfg, self.kern
        fftlen, numz = kern.fftlen, kern.numz
        nblocks = g.nblocks
        uselen = cfg.uselen
        off_eff = g.hw_eff * ACCEL_NUMBETWEEN
        numz_pad = -(-numz // bp.ZT) * bp.ZT
        nb_pad = g.nb_pad
        assert nb_pad * uselen == g.plane_numr
        builder = bp.make_plane_builder(numz, nb_pad, fftlen, uselen,
                                        off_eff)
        consts = _dft_consts_np(fftlen)

        def build_body(fft_raw, kern_dev):
            fr = jax.lax.slice(frames_fn(fft_raw), (0, 0),
                               (nblocks, fftlen // 2))
            if cfg.norm == "median":
                fr = fr * _block_median_norms_c(fr)
            Sr, Si = _fwd_stage_mxu(
                fr, tuple(map(jnp.asarray, consts)), fftlen)
            bpad = ((0, nb_pad - nblocks), (0, 0), (0, 0))
            Sr, Si = jnp.pad(Sr, bpad), jnp.pad(Si, bpad)
            kz = _kern_bank_z(kern_dev, fftlen)
            Kr = jnp.pad(kz.real.astype(jnp.float32),
                         ((0, numz_pad - numz), (0, 0), (0, 0)))
            Ki = jnp.pad(kz.imag.astype(jnp.float32),
                         ((0, numz_pad - numz), (0, 0), (0, 0)))
            pw = builder(Sr, Si, Kr, Ki)
            # [numz_pad, nb_pad, uselen//128, 128] -> the plane, free
            return pw.reshape(numz_pad, nb_pad * uselen)
        return build_body

    # how many chunk bodies are unrolled for the concat assembly before
    # falling back to a scanned DUS carry (HLO size bound; planes that
    # big exceed single-chip HBM anyway and stream through oocfft)
    _UNROLL_CHUNKS = 48

    def _build_plan_ns(self):
        """Plane-build plan: unrolled per-chunk z-major slabs joined by
        ONE concatenate (the plane is written exactly once — both the
        stacked-ys moveaxis assembly (~350 ms) and a scanned
        dynamic_update_slice carry (~185 ms: XLA copies the carried
        plane each step) measured as the dominant cost of the round-2
        build on v5e).  Falls back to the DUS-carry scan when nsteps
        is too large to unroll."""
        g = self._plane_geom()
        if g is False:
            return None
        kern = self.kern
        if getattr(g, "build_body", None) is None:
            chunk_slab = self._chunk_slab_fn(g)
            plane_numr, col0, pads = g.plane_numr, g.col0, g.pads
            numz = kern.numz
            cw = g.chunk * self.cfg.uselen
            use_mxu = chunk_slab.use_mxu
            fftlen = kern.fftlen

            def prep_bank(kern_c):
                return _kern_bank_z(kern_c, fftlen) if use_mxu \
                    else kern_c

            frames_fn = self._frames_fn(g)
            chunk = g.chunk

            if ACCEL_ENGINE == "plb" and not g.hw_eff:
                print("accel: PRESTO_TPU_ACCEL_ENGINE=plb requested "
                      "but the aligned geometry does not hold "
                      "(explicit uselen or halfwidth too wide) — "
                      "using the default engine")
            plb = self._pallas_build_body(g, frames_fn) \
                if (use_mxu and g.hw_eff) else None
            if plb is not None:
                g.build_body = plb
                g.key = (g.chunk, g.nsteps, g.plane_numr, "plb")
                return g

            # the unrolled concat holds all slabs (~1x plane) PLUS the
            # concat output plane; when 2x plane + the chunk
            # intermediate would crowd HBM, stream through the 1x-plane
            # DUS carry instead (slower, but it fits)
            fits = (numz * (plane_numr + g.nsteps * cw) * 4
                    + CHUNK_BUDGET_BYTES) < (DEVICE_HBM_BYTES * 9) // 16

            if g.nsteps <= self._UNROLL_CHUNKS and fits:
                def build_body(fft_raw, kern_dev):
                    fr = frames_fn(fft_raw)
                    kern_use = prep_bank(kern_dev)
                    # optimization_barrier chain: unrolled chunks have
                    # no data deps between them, and XLA's scheduler
                    # will happily keep every chunk's multi-GB complex
                    # intermediates alive at once (OOM on v5e); the
                    # chain forces chunk i+1 to start after slab i
                    slabs = []
                    for i in range(g.nsteps):
                        data = jax.lax.slice(
                            fr, (i * chunk, 0),
                            ((i + 1) * chunk, fr.shape[1]))
                        slab = chunk_slab(data, kern_use)
                        if i + 1 < g.nsteps:
                            fr, slab = jax.lax.optimization_barrier(
                                (fr, slab))
                        slabs.append(slab)
                    # keep only REAL blocks' columns (a padded frame
                    # reads the spectrum tail + zero padding, so its
                    # ~zero median blows the normalization up — its
                    # output must never reach the plane), zero-fill
                    # the alignment padding, and write everything with
                    # one concatenate
                    keep = min(plane_numr - col0,
                               g.nblocks * self.cfg.uselen)
                    over = g.nsteps * cw - keep
                    if over > 0:
                        slabs[-1] = jax.lax.slice(
                            slabs[-1], (0, 0), (numz, cw - over))
                    parts = [jnp.zeros((numz, col0), jnp.float32)] \
                        if col0 else []
                    parts += slabs
                    right = plane_numr - col0 - sum(
                        s.shape[1] for s in slabs)
                    if right > 0:
                        parts.append(jnp.zeros((numz, right),
                                               jnp.float32))
                    return jnp.concatenate(parts, axis=1)
            else:
                # DUS-carry fallback: chunks of REAL blocks only, the
                # final chunk overlapping backwards (rewrites the same
                # values) so padded-frame output never lands in the
                # plane and every dispatch shares one shape
                bstarts = [min(i * chunk, g.nblocks - chunk)
                           for i in range(g.nsteps)]
                start_cols = np.asarray(
                    [col0 + b * self.cfg.uselen for b in bstarts],
                    np.int32)
                bstarts = np.asarray(bstarts, np.int32)

                def build_body(fft_raw, kern_dev):
                    fr = frames_fn(fft_raw)
                    kern_use = prep_bank(kern_dev)
                    pl = jnp.zeros((numz, plane_numr), jnp.float32)

                    def body(pl, xs):
                        b0, start_col = xs
                        data = jax.lax.dynamic_slice(
                            fr, (b0, 0), (chunk, fr.shape[1]))
                        slabv = chunk_slab(data, kern_use)
                        return jax.lax.dynamic_update_slice(
                            pl, slabv, (0, start_col)), None
                    pl, _ = jax.lax.scan(
                        body, pl, (jnp.asarray(bstarts),
                                   jnp.asarray(start_cols)))
                    return pl

            g.build_body = build_body
            g.key = (g.chunk, g.nsteps, g.plane_numr, use_mxu)
        return g

    # -- search --------------------------------------------------------

    def search(self, fft_pairs: np.ndarray,
               plane: Optional[np.ndarray] = None,
               slab: int = 1 << 20) -> List[AccelCand]:
        """Run the full staged harmonic-summing search.

        With cfg.wmax set this is the JERK search: one F-Fdot plane per
        w on the ACCEL_DW grid (each with w-response kernels), searched
        independently and merged — the reference jerk search's
        (r, z, w) volume.  Harmonic summing reads each subharmonic
        from the plane at its OWN grid w, w_sub = calc_required_w(
        harm/numharm, w) — the per-subharmonic w kernels of modern
        PRESTO's jerk search — via an HBM-budgeted device plane cache
        (planes are built in |w| order so subharmonic planes usually
        already exist; evicted ones are rebuilt).

        The plane stays resident in HBM; the search region is processed
        in `slab`-column accumulator slabs (peak extra memory ~
        numz*slab floats per gather), each slab thresholded+top-k'd per
        stage on device with candidates collected on host — bounding
        memory for arbitrarily long spectra.

        Returned candidates are PRE-COLLAPSED to at most one per ~8
        r-bins (the segment-max reduction; lossless w.r.t. the final
        list because remove_duplicates' ACCEL_CLOSEST_R=15-bin rule —
        insert_new_accelcand semantics — collapses anything closer
        anyway).  Library callers should not expect sub-segment
        multiplicity; apply remove_duplicates/eliminate_harmonics for
        the reference's final-list semantics.
        """
        cfg = self.cfg
        if plane is None and cfg.wmax:
            return self._search_jerk(fft_pairs, slab)
        if plane is None:
            cs = self._search_fused(fft_pairs, slab,
                                    self._kern_bank_dev())
            if cs is not None:
                return cs
            plane = self.build_plane(fft_pairs)
        return self._search_plane(plane, slab)

    def _harm_fracs(self):
        """Harmonic fractions in the scanner's term order — derived
        from the SAME flattened _harm_fracs_and_zinds list the scanner
        consumes, so the planes[1+fi] <-> fraction pairing cannot
        drift."""
        fz = _harm_fracs_and_zinds(self.cfg, self.cfg.numz)
        return [harm / htot
                for stage in fz for (harm, htot, _zi) in stage]

    def _collect_packed(self, packed, start_cols) -> List[AccelCand]:
        vals, cidx, zrow = _unpack_scan(packed)
        return self._dedup_sort(
            self._collect_group(vals, cidx, zrow, start_cols))

    def _search_jerk(self, fft_pairs, slab: int) -> List[AccelCand]:
        """The (r, z, w) jerk search over the ACCEL_DW w grid with
        per-subharmonic-w source planes (see search() docstring)."""
        cfg = self.cfg
        fft_pairs = self._to_dev(fft_pairs)
        fracs = self._harm_fracs()

        # host-RAM budget for cached w kernel banks (a bank is
        # numz*kmax*2 float32 ~ a few MB; a wmax=300 search uses 31
        # fundamental banks plus subharmonic-w banks, and rebuilding
        # one costs seconds of host quadrature — cache by bytes, not
        # the old count-of-8 which thrashed past wmax=140)
        bank_budget = int(os.environ.get(
            "PRESTO_TPU_WBANK_BUDGET", str(512 * 2 ** 20)))

        def bank_for(wg: float) -> AccelKernels:
            bank = self._w_banks.get(wg)
            if bank is None:
                bank = AccelKernels.build(cfg, wg)
                used = sum(b.kern_pairs.nbytes
                           for b in self._w_banks.values())
                if used + bank.kern_pairs.nbytes <= bank_budget:
                    self._w_banks[wg] = bank
            return bank

        all_cands: List[AccelCand] = []

        if not fracs:
            # numharm == 1: no subharmonic reads — take the fused
            # build+search dispatch per w (no resident plane at all)
            for w in (float(x) for x in cfg.ws):
                kern_dev = self._w_bank_dev(w, bank_for)
                cs = self._search_fused(fft_pairs, slab, kern_dev)
                if cs is None:
                    cs = self._search_plane(
                        self.build_plane(fft_pairs, kern_dev), slab)
                for c in cs:
                    c.w = w
                    all_cands.append(c)
            return self._merge_w_cands(all_cands)
        return self._search_jerk_planes(fft_pairs, slab, fracs,
                                        bank_for, all_cands)

    def _w_bank_dev(self, wg: float, bank_for):
        """Device FFT'd kernel bank for the w-plane grid value wg,
        LRU-cached ACROSS search() calls (HBM-byte-budgeted,
        PRESTO_TPU_WBANK_DEV_BUDGET, default 512 MB).  A steady-state
        jerk survey re-searches many spectra with one config; without
        this cache every search re-uploads ~1-3 MB per w bank through
        the host link and re-FFTs it — measurable against the ~200 ms
        per-w device work."""
        cache = getattr(self, "_w_banks_dev_cache", None)
        if cache is None:
            cache = self._w_banks_dev_cache = {}
        ent = cache.pop(wg, None)
        if ent is None:
            bank = bank_for(wg)
            ent = _fft_kernel_bank_c(jnp.asarray(bank.kern_pairs),
                                     bank.fftlen)
            budget = int(os.environ.get(
                "PRESTO_TPU_WBANK_DEV_BUDGET", str(512 * 2 ** 20)))
            nbytes = int(np.prod(ent.shape)) * ent.dtype.itemsize
            used = sum(int(np.prod(b.shape)) * b.dtype.itemsize
                       for b in cache.values())
            while cache and used + nbytes > budget:   # LRU: dicts
                old = next(iter(cache))               # keep insert
                used -= int(np.prod(cache[old].shape)) \
                    * cache[old].dtype.itemsize       # order
                del cache[old]
        cache[wg] = ent               # (re)insert most-recent
        return ent

    def _search_jerk_planes(self, fft_pairs, slab, fracs, bank_for,
                            all_cands):
        """The numharm>1 jerk path: per-subharmonic-w source planes
        over an HBM-budgeted LRU, with ALL w scans dispatched before
        any host collection — jax dispatches are async, so the host
        sync (the per-w np.asarray of round 4) was paying the
        tunneled link's ~120 ms dispatch+sync floor once per w plane;
        queueing every scan first and collecting afterwards pays it
        once for the whole ws ladder (same float program, identical
        candidates)."""
        cfg = self.cfg

        # Per-subharmonic-w source planes over an HBM-budgeted LRU.
        # Planes in `keep` are the current scan's working set and are
        # never evicted — at numharm=16 that is up to 5 distinct
        # planes, the irreducible footprint of per-subharmonic reads.
        plane_cache: dict = {}        # grid w -> device plane (LRU)
        g = self._plane_geom()
        plane_bytes = max(self.kern.numz * g.plane_numr * 4, 1) \
            if g else 1
        # cache budget = shared HBM constant minus the plane-build
        # working set (the concat build holds plane + the per-chunk
        # slabs + chunk intermediate concurrently — see
        # _build_plan_ns), so the two budgets cannot stack past the
        # device
        build_ws = (self.kern.numz * g.body_numr * 4
                    + CHUNK_BUDGET_BYTES) if g else 0
        cache_budget = max(DEVICE_HBM_BYTES - build_ws - 2 * 2 ** 30,
                           plane_bytes)
        max_planes = max(1, int(cache_budget // plane_bytes))

        def plane_for(wg: float, keep: set):
            pl = plane_cache.pop(wg, None)
            if pl is None:
                # evict BEFORE building so peak residency stays at
                # max_planes (+ the build's own working memory)
                while len(plane_cache) >= max_planes:
                    for old in list(plane_cache):   # LRU, spare keep
                        if old not in keep:
                            del plane_cache[old]
                            break
                    else:
                        break
                pl = self.build_plane(fft_pairs,
                                      self._w_bank_dev(wg, bank_for))
            plane_cache[wg] = pl      # (re)insert most-recent
            return pl

        # one slab plan for the whole loop: plane width is w-invariant
        # (fftlen/uselen geometry is shared by every bank)
        splan = self._slab_plan(g.plane_numr, slab) if g else None
        if splan is None:
            return []
        slab_, k, scanner, start_cols = splan
        scols = jnp.asarray(start_cols, dtype=jnp.int32)
        # Queue w scans AHEAD of collection so the device runs back-
        # to-back while the host decodes (collection = the sync that
        # otherwise pays the link's dispatch floor once per w) — but
        # with a BOUNDED in-flight window: queued executions keep
        # their input planes alive regardless of host-side LRU
        # eviction, so an unbounded queue would hold the whole ws
        # ladder's planes at once and defeat the HBM budget.  A
        # window of 2 (one collecting + one queued, the r4 e2e's
        # one-ahead pipeline) captures the overlap at a bounded
        # +1 working set of planes.
        MAX_INFLIGHT = 2

        def drain(pend, down_to):
            while len(pend) > down_to:
                w, packed = pend.pop(0)
                for c in self._collect_packed(packed, start_cols):
                    # the plane cell is the numharm-th harmonic: its
                    # (r, z, w) all scale down to the fundamental
                    c.w = w / c.numharm
                    all_cands.append(c)

        pend = []
        for w in sorted((float(x) for x in cfg.ws), key=abs):
            wsubs = [calc_required_w(f, w) for f in fracs]
            keep = set(wsubs) | {w}
            pl = plane_for(w, keep)
            subs = [plane_for(wg, keep) for wg in wsubs]
            pend.append((w, scanner.planes(tuple([pl] + subs),
                                           scols)))
            drain(pend, MAX_INFLIGHT - 1)
        drain(pend, 0)
        return self._merge_w_cands(all_cands)

    @staticmethod
    def _merge_w_cands(all_cands: List[AccelCand]) -> List[AccelCand]:
        """Same (numharm, r) found in neighboring w planes: keep the
        strongest (the volume's local max)."""
        best = {}
        for c in sorted(all_cands, key=lambda c: -c.sigma):
            key = (c.numharm, c.r)
            if key not in best:
                best[key] = c
        return sorted(best.values(), key=lambda c: (-c.sigma, c.r))

    def _search_fused(self, fft_pairs, slab: int,
                      kern_dev) -> Optional[List[AccelCand]]:
        """Plane build + staged search in ONE device dispatch (the
        plane never surfaces; saves a host<->device round trip, which
        costs ~0.2-0.4 s through the tunneled TPU link).  Returns None
        when there is no build plan (too-short spectra) — callers then
        take the two-dispatch path."""
        yp = self._build_plan_ns()
        if yp is None:
            return None
        splan = self._slab_plan(yp.plane_numr, slab)
        if splan is None:
            return []
        slab_, k, scanner, start_cols = splan
        key = ("fused",) + yp.key + (slab_, k)
        if key not in self._fn_cache:
            build_body, scan_body = yp.build_body, scanner.body

            @jax.jit
            def fused(fft_raw, kern_dev, scols):
                return scan_body(build_body(fft_raw, kern_dev), scols)
            self._fn_cache[key] = fused
        packed = self._fn_cache[key](
            self._to_dev(fft_pairs), kern_dev,
            jnp.asarray(start_cols, dtype=jnp.int32))
        return self._collect_packed(packed, start_cols)

    def _slab_plan(self, plane_numr: int, slab: int):
        """(slab, k, scanner, start_cols) for a plane width — the ONE
        source of the slab/top-k layout for single and batched paths
        (the overlap-last-slab trick keeps one jit shape)."""
        cfg = self.cfg
        r0 = int(self.rlo) * ACCEL_RDR
        self._r0min = r0          # candidates below rlo are filtered
        numr = min(int(self.rhi) * ACCEL_RDR, plane_numr) - r0
        if numr <= 0:
            return None
        top = r0 + numr
        self._rtop = top          # ... and at/above rhi (alignment
                                  # may scan a few columns past top)
        slab = min(slab, numr)
        # Alignment contract for the scanner's phase-decomposed
        # harmonic reads: every slab start (and the slab length) is a
        # multiple of numharm, so each subharmonic read is a static
        # strided view.  Aligning r0 down (and the top slab up, within
        # the align-padded plane) scans a few out-of-range columns,
        # filtered in _collect_slab via _r0min/_rtop.
        align = cfg.numharm
        # the pallas stage reducer (TPU) wants TILE-aligned starts
        # and a TILE-multiple slab; fall back to the XLA scanner when
        # the geometry is too small to align
        use_pallas = False
        ptile = None
        try:
            from presto_tpu.search import accel_pallas as ap
            fz_probe = _harm_fracs_and_zinds(cfg, self.cfg.numz)
            # plane is aligned to the MAX tile, so any smaller
            # power-of-two tile the VMEM budget picks also divides it
            ptile = ap.pick_tile(fz_probe, self.cfg.numz, slab) \
                if (ap.pallas_available() and cfg.numharm <= 16
                    and plane_numr % ap.TILE == 0) else None
            if ptile:
                # tuned engine choice: a measured harmonic_sum_layout
                # entry may prefer the XLA staged scan for this
                # geometry (candidate lists are engine-identical, so
                # this is performance-only)
                from presto_tpu import tune
                if tune.enabled():
                    lay = tune.best(
                        "harmonic_sum_layout",
                        tune.key_harm_layout(self.cfg.numz,
                                             cfg.numharm))
                    if lay and lay.get("engine") == "xla":
                        ptile = None
            if ptile:
                align = max(align, ptile)
                use_pallas = True
        except Exception:
            pass
        aligned = (slab % align == 0 or slab > 4 * align) \
            and plane_numr % align == 0
        if aligned and slab % align:
            slab -= slab % align
        use_pallas = use_pallas and aligned and slab % align == 0
        r0a = r0 - (r0 % align) if aligned else r0
        top_a = min(top + ((-top) % align), plane_numr) if aligned \
            else top
        k = min(cfg.max_cands_per_stage, slab)
        # a direct-plane build already carries the reducer's row pad
        # and >= PLANE_PAD trailing zero columns: skip the 3.4 GB pad
        plane_padded = bool(
            use_pallas and self._plb_hw_eff
            and plane_numr >= top_a + ap.PLANE_PAD)
        skey = ("scan", slab, k, plane_numr, aligned, use_pallas,
                plane_padded)
        if skey not in self._fn_cache:
            fz = _harm_fracs_and_zinds(cfg, self.cfg.numz)
            reducer = None
            if use_pallas:
                reducer = ap.make_stage_reducer(
                    cfg.numharmstages, fz, slab, self.cfg.numz,
                    plane_numr, tile=ptile)
            self._fn_cache[skey] = _make_search_scanner(
                cfg.numharmstages, fz, self.powcut, slab, k,
                plane_numr, aligned=aligned,
                pallas_reducer=reducer, numz=self.cfg.numz,
                plane_padded=plane_padded)
        start_cols = []
        off = r0a
        while True:
            if off + slab >= top_a:             # keep one jit shape:
                start_cols.append(max(top_a - slab, 0))  # overlap last
                break
            start_cols.append(off)
            off += slab
        return slab, k, self._fn_cache[skey], start_cols

    def _search_plane(self, plane, slab: int) -> List[AccelCand]:
        # top-k cost grows steeply with k on TPU: keep k fixed and
        # scale the number of slabs instead (per-slab top-k truncates
        # only the weakest noise candidates)
        numz, plane_numr = plane.shape
        plan = self._slab_plan(plane_numr, slab)
        if plan is None:
            return []
        slab, k, scanner, start_cols = plan
        dplane = jnp.asarray(plane)
        packed = scanner(dplane, jnp.asarray(start_cols,
                                             dtype=jnp.int32))
        return self._collect_packed(packed, start_cols)

    @staticmethod
    def _dedup_sort(cands: List[AccelCand]) -> List[AccelCand]:
        # overlapping the final slab can duplicate candidates: dedup on
        # exact (numharm, r, z)
        seen = set()
        uniq = []
        for c in cands:
            key = (c.numharm, c.r, c.z)
            if key not in seen:
                seen.add(key)
                uniq.append(c)
        return sorted(uniq, key=lambda c: (-c.sigma, c.r))

    def search_many(self, pairs_batch: np.ndarray,
                    slab: int = 1 << 20,
                    compact_m: int = COMPACT_CANDS,
                    mesh=None, obs=None) -> List[List[AccelCand]]:
        """Batched search over many same-length spectra — the survey's
        DM fan-out (one plane build + one scanned search dispatch per
        memory-budgeted DM group instead of per-trial dispatch storms;
        the mpiprepsubband-scale path of SURVEY §2.5).

        pairs_batch: [numdms, numbins, 2] float32 — a NumPy array or a
        DEVICE array (jax.Array): the survey's fused realfft->search
        path keeps spectra resident in HBM, skipping a host download +
        re-upload per DM trial (each direction of the tunneled link
        costs seconds per group).  Returns per-DM candidate lists
        (same semantics as search() per spectrum).

        ``mesh``: a jax Mesh whose first axis shards the DM trials —
        the sharded seam's per-device spectra search in place via
        parallel/sharded.sharded_accel_search_many (candidate lists
        are test-pinned equal to this method's); None keeps the
        single-device grouped path.

        ``obs``: an Observability handle — when enabled, the scan
        program's per-dispatch FLOP/byte unit cost is harvested once
        per geometry (obs/costmodel.probe, kind "accel_search") so
        the survey's dispatch accounting carries silicon cost.
        """
        cfg = self.cfg
        if mesh is not None and len(list(mesh.devices.flat)) > 1:
            from presto_tpu.parallel.sharded import (
                sharded_accel_search_many)
            return sharded_accel_search_many(self, pairs_batch, mesh,
                                             slab=slab,
                                             compact_m=compact_m,
                                             obs=obs)
        if isinstance(pairs_batch, jax.Array):
            batch = pairs_batch
            if batch.dtype != jnp.float32:    # same boundary cast the
                batch = batch.astype(jnp.float32)   # NumPy path gets
        else:
            batch = np.ascontiguousarray(np.asarray(pairs_batch,
                                                    np.float32))
        nd = batch.shape[0]
        if nd == 0:
            return []
        if cfg.wmax:
            # jerk searches never take the batched path: go straight
            # to the per-DM loop (no wasted priming plane build)
            return [self.search(batch[i], slab=slab)
                    for i in range(nd)]
        # first spectrum primes the caches and fixes the geometry
        p0 = self.build_plane(batch[0])
        numz, plane_numr = p0.shape
        if plane_numr == 0:
            return [[] for _ in range(nd)]
        key = self._build_plan
        build_one = self._fn_cache[key]
        mkey = ("build_many",) + key[1:]
        if mkey not in self._fn_cache:
            if "plb" in key:
                # pallas_call + vmap is unsupported; sequential map is
                # fine (each build saturates the chip on its own)
                self._fn_cache[mkey] = jax.jit(
                    lambda batch, kd: jax.lax.map(
                        lambda b: build_one(b, kd), batch))
            else:
                self._fn_cache[mkey] = jax.jit(
                    jax.vmap(build_one, in_axes=(0, None)))
        build_many = self._fn_cache[mkey]

        splan = self._slab_plan(plane_numr, slab)
        if splan is None:
            return [[] for _ in range(nd)]
        slab, k, scanner, start_cols = splan
        scols = jnp.asarray(start_cols, dtype=jnp.int32)
        self._kern_bank_dev()         # ensure the FFT'd device bank
        if obs is not None:
            from presto_tpu.obs import costmodel
            costmodel.probe(obs, "accel_search", scanner, p0, scols)

        def collect_dm(vals, cidx, zrow):
            return self._dedup_sort(
                self._collect_group(vals, cidx, zrow, start_cols))

        # the priming plane p0 serves as spectrum 0's search (no
        # discarded build)
        out: List[List[AccelCand]] = [
            collect_dm(*_unpack_scan(scanner(p0, scols)))]
        del p0
        # per-spectrum footprint in the vmapped build: plane + stacked
        # ys + the [chunk, numz, fftlen] complex FFT intermediate
        # (vmap multiplies ALL of them by the group size).  The group
        # budget is HALF the old 6 GB because up to TWO groups are now
        # in flight (the window below) — same peak residency.
        g = self._plane_geom()
        plane_bytes = numz * plane_numr * 4
        per_bytes = plane_bytes * 2 + (
            g.chunk * numz * self.kern.fftlen * 8 if g else 0)
        group = max(1, int(3 * 2 ** 30 // max(per_bytes, 1)))
        group = min(group, max(nd - 1, 1))
        # back-overlap the final group so every dispatch shares ONE jit
        # shape (the tail would otherwise retrace the two heaviest
        # compiled programs); overlapped DMs are recomputed and their
        # duplicate results skipped
        starts = list(range(1, nd, group))
        if starts and starts[-1] + group > nd:
            starts[-1] = max(nd - group, 1)
        done = 1

        def collect_group(ent):
            """The host sync for one dispatched group."""
            nonlocal done
            g0, planes, comp_dev = ent
            comp = np.asarray(comp_dev)
            dense = None
            for d in range(comp.shape[0]):
                if g0 + d < done:
                    continue               # overlap: already collected
                try:
                    cands = self.collect_compacted(
                        comp[d], start_cols, requested_m=compact_m)
                except ValueError:
                    if dense is None:
                        dense = _unpack_scan(
                            scanner.many(planes, scols))
                    vals, cidx, zrow = dense
                    cands = collect_dm(vals[d], cidx[d], zrow[d])
                out.append(cands)
                done = g0 + d + 1

        # 2-deep in-flight window (the jerk ladder's pattern, see
        # pipeline/fusion.InflightWindow): group i+1's build+scan is
        # queued on the device before group i's host collection syncs,
        # so candidate decoding overlaps device work instead of
        # paying the link's dispatch+sync floor once per group.
        # `planes` rides in the window entry because the pathological
        # dense fallback needs it alive until its group is collected.
        pend: list = []
        for g0 in starts:
            sub = jnp.asarray(batch[g0:g0 + group])
            planes = build_many(sub, self._kern_dev)
            # per-trial top-m compaction rides the scan dispatch: the
            # dense top-k tensor stays on device (compact_m slots per
            # trial cross instead — the D2H that dominated slow-link
            # surveys).  A trial overflowing the budget (pathological
            # RFI forest) falls back to the lossless dense fetch for
            # its group.
            pend.append((g0, planes,
                         scanner.many_compact(planes, scols,
                                              compact_m)))
            if len(pend) >= 2:
                collect_group(pend.pop(0))
        while pend:
            collect_group(pend.pop(0))
        return out

    def _collect_group(self, vals: np.ndarray, cidx: np.ndarray,
                       zrow: np.ndarray, start_cols) -> List[AccelCand]:
        """Vectorized host collection over [nslabs, stages, k] scanner
        output: one numpy pass for the bounds filtering and one
        batched candidate_sigma per stage, instead of a Python loop
        per (slab, stage) — the survey e2e share collects thousands of
        slabs and was host-bound on the loop (VERDICT r4 weak #1).
        Parity: search_ffdotpows (accel_utils.c:1259-1298); each
        column contributes its max-over-z cell (same-column lower-z
        cells are duplicates under the sifter's r-dedup).  Same math
        and candidate order-class as the historical per-slab loop
        (exact float op order preserved); callers dedup/sort."""
        cfg = self.cfg
        r0min = getattr(self, "_r0min", 0)
        rtop = getattr(self, "_rtop", None)
        sc = np.asarray(start_cols, dtype=np.int64)[:, None, None]
        absc = sc + cidx
        good = (vals > 0.0) & (zrow < cfg.numz)  # pad rows are zeros
        good &= absc >= r0min     # alignment searched below rlo ...
        if rtop is not None:      # ... or a few columns past rhi
            good &= absc < rtop
        stg = np.broadcast_to(
            np.arange(vals.shape[1], dtype=np.int32)[None, :, None],
            vals.shape)
        g = good.ravel()
        return self._cands_from_flat(
            vals.ravel()[g], absc.ravel()[g], zrow.ravel()[g],
            stg.ravel()[g])

    def collect_compacted(self, comp: np.ndarray, start_cols,
                          requested_m: int = None,
                          allow_truncated: bool = False
                          ) -> List[AccelCand]:
        """Host decode of compact_scan_packed output [3, m] -> the
        same candidate list _collect_packed builds from the dense
        tensor (bounds filter + sigma + dedup/sort).

        requested_m: the m the producer passed to
        compact_scan_packed, if known — an output NARROWER than the
        request means m was clamped to the dense tensor's full slot
        count (truncation impossible), so an all-positive output is
        legitimate and the budget guard is skipped.

        allow_truncated: decode a budget-exhausted output anyway
        (keeping the strongest m candidates) instead of raising —
        ONLY for consumers that explicitly tolerate a truncated tail
        (e.g. timing replays of recorded outputs where the canonical
        results came from a lossless path)."""
        cfg = self.cfg
        assert cfg.numz < (1 << _CMP_ZBITS), cfg.numz
        comp = np.asarray(comp)
        v = comp[0].view(np.float32)
        if (v.size and v[-1] > 0.0 and not allow_truncated
                and (requested_m is None or v.size >= requested_m)):
            raise ValueError(
                "compact_scan_packed budget exhausted (m=%d slots all "
                "positive): candidates may have been dropped — raise m"
                % v.size)
        cidx = comp[1]
        zrow = comp[2] & ((1 << _CMP_ZBITS) - 1)
        stg = (comp[2] >> _CMP_ZBITS) & ((1 << _CMP_SBITS) - 1)
        si = comp[2] >> (_CMP_ZBITS + _CMP_SBITS)
        absc = np.asarray(start_cols, dtype=np.int64)[si] + cidx
        r0min = getattr(self, "_r0min", 0)
        rtop = getattr(self, "_rtop", None)
        good = (v > 0.0) & (zrow < cfg.numz) & (absc >= r0min)
        if rtop is not None:
            good &= absc < rtop
        return self._dedup_sort(self._cands_from_flat(
            v[good], absc[good], zrow[good], stg[good]))

    def _cands_from_flat(self, v: np.ndarray, absc: np.ndarray,
                         zrow: np.ndarray,
                         stg: np.ndarray) -> List[AccelCand]:
        """Filtered flat hits -> AccelCands, sigma batched per stage.
        Float op order matches the historical per-slab loop:
        (col * ACCEL_DR) / numharm and (-zmax + z * ACCEL_DZ) /
        numharm in float64."""
        cfg = self.cfg
        out: List[AccelCand] = []
        for stage in np.unique(stg).tolist():
            m = stg == stage
            numharm = 1 << int(stage)
            sigmas = np.atleast_1d(st.candidate_sigma(
                v[m], numharm, self.numindep[stage]))
            rr = (absc[m] * ACCEL_DR) / numharm
            zz = (-cfg.zmax + zrow[m] * ACCEL_DZ) / numharm
            for p, s, r_, z_ in zip(v[m].tolist(), sigmas.tolist(),
                                    rr.tolist(), zz.tolist()):
                out.append(AccelCand(power=p, sigma=s,
                                     numharm=numharm, r=r_, z=z_))
        return out


# ----------------------------------------------------------------------
# Candidate post-processing (host)
# ----------------------------------------------------------------------

# The reference's fixed list of "other common harmonic ratios"
# (accel_utils.c:415-439) in addition to r*ii and r/ii, ii = 1..16.
_HARM_RATIOS = [3 / 2, 5 / 2, 2 / 3, 4 / 3, 5 / 3, 3 / 4, 5 / 4, 2 / 5,
                3 / 5, 4 / 5, 5 / 6, 2 / 7, 3 / 7, 4 / 7, 3 / 8, 5 / 8,
                2 / 9, 3 / 10, 2 / 11, 3 / 11, 2 / 13, 3 / 13, 2 / 15]


def eliminate_harmonics(cands: List[AccelCand],
                        tooclose: float = 1.5,
                        maxharm: int = 16) -> List[AccelCand]:
    """Remove less-significant harmonically-related candidates.

    Parity: eliminate_harmonics (accel_utils.c:384-460): walking the
    sigma-sorted list, a later candidate is dropped when its r lies
    within `tooclose` bins of r_strong*ii, r_strong/ii (ii<=16), or
    r_strong*ratio for the fixed rational-ratio list.
    """
    if not cands:
        return []
    cands = sorted(cands, key=lambda c: (-c.sigma, c.r))
    kept: List[AccelCand] = []
    for c in cands:
        is_harm = False
        for k in kept:
            rk, rc = k.r, c.r
            if any(abs(rk / ii - rc) < tooclose or
                   abs(rk * ii - rc) < tooclose
                   for ii in range(1, maxharm + 1)):
                is_harm = True
            elif any(abs(rk * ratio - rc) < tooclose
                     for ratio in _HARM_RATIOS):
                is_harm = True
            if is_harm:
                break
        if not is_harm:
            kept.append(c)
    return kept


def remove_duplicates(cands: List[AccelCand]) -> List[AccelCand]:
    """Collapse candidates within ACCEL_CLOSEST_R bins of a stronger one
    to the strongest, regardless of z — the exact dedup rule of
    insert_new_accelcand (accel_utils.c:294-382), which keys on r alone.
    This also makes the device search's per-column max-over-z reduction
    lossless with respect to the final candidate list."""
    kept: List[AccelCand] = []
    for c in sorted(cands, key=lambda c: (-c.sigma, c.r)):
        if all(abs(c.r - k.r) >= ACCEL_CLOSEST_R for k in kept):
            kept.append(c)
    return kept
