"""Fourier-domain F–Fdot acceleration search (accelsearch rebuilt TPU-first).

Reference call stack (SURVEY.md §3.2, src/accelsearch.c:134-221,
src/accel_utils.c): per r-block of ACCEL_USELEN half-bins —
  subharm_ffdot_plane  (accel_utils.c:879-1051): normalize amplitudes,
      spread ×2 interbin, FFT, per-z-row complex-multiply by conj
      z-response kernel, inverse FFT, |·|²/fftlen² into powers[z][r]
  inmem harmonic sums  (accel_utils.c:1160-1256): powers[z][r] +=
      plane[zind(frac,z)][round(r*frac)]
  search_ffdotpows     (accel_utils.c:1259-1298): threshold at
      powcut[stage], candidate_sigma, sorted insert.

TPU-first redesign (this module):
  * the whole spectrum's fundamental plane is built as ONE batched
    tensor program: [nblocks, fftlen] spread segments x [numz, fftlen]
    kernel bank -> batched IFFT -> [nblocks, numz, uselen] powers,
    assembled to P[numz, R] in HBM (the reference's `-inmem` plane,
    accel_utils.c:1651-1670, is the natural TPU layout);
  * harmonic summing is two chained takes (rows by zind map, columns by
    rind map) — XLA gathers, no scalar loops;
  * thresholding is a single top-k over the masked plane per stage
    (static K, the `omp critical` insert becomes host-side filtering);
  * candidate sigma/powcut math runs on host in float64 (ops/stats).

All device entry points keep complex internal to jit (float32 pair
boundaries — see ops/fftpack note on the TPU complex-transfer limit).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from functools import partial

from presto_tpu.ops import responses as resp
from presto_tpu.ops import stats as st
from presto_tpu.utils.psr import next2_to_n

# Search grid constants (include/accel.h:18-31)
ACCEL_NUMBETWEEN = 2
ACCEL_DR = 0.5
ACCEL_RDR = 2
ACCEL_DZ = 2
ACCEL_RDZ = 0.5
ACCEL_CLOSEST_R = 15.0
ACCEL_USELEN = 7470
DBLCORRECT = 1e-14


def _nearest_int(x: float) -> int:
    """Round half away from zero — the reference's NEAREST_INT
    (prepfold.h:14), NOT Python's banker's rounding."""
    return int(np.ceil(x - 0.5)) if x < 0 else int(np.floor(x + 0.5))


def calc_required_z(harm_fract: float, zfull: float) -> float:
    """z of the subharmonic for fundamental z (accel_utils.c:53-59)."""
    return _nearest_int(ACCEL_RDZ * zfull * harm_fract) * ACCEL_DZ


def calc_required_r(harm_fract: float, rfull: float) -> float:
    """r of the subharmonic for fundamental r (accel_utils.c:60-66)."""
    return int(ACCEL_RDR * rfull * harm_fract + 0.5) * ACCEL_DR


def index_from_z(z: float, loz: float) -> int:
    return int((z - loz) * ACCEL_RDZ + DBLCORRECT)


def calc_fftlen(numharm: int, harmnum: int, max_zfull: int,
                uselen: int = ACCEL_USELEN) -> int:
    """FFT length for a subharmonic block (accel_utils.c:116-131)."""
    harm_fract = harmnum / numharm
    bins_needed = uselen * harmnum // numharm + 2
    end_effects = 2 * ACCEL_NUMBETWEEN * \
        resp.z_resp_halfwidth(calc_required_z(harm_fract, max_zfull),
                              resp.LOWACC)
    return next2_to_n(bins_needed + end_effects)


@dataclass
class AccelConfig:
    zmax: int = 200              # max |z| searched (fundamental)
    numharm: int = 8             # max harmonics summed (power of two)
    sigma: float = 2.0           # candidate sigma cutoff
    rlo: float = 0.0             # min Fourier freq searched (bins);
                                 # 0 -> flo * T at plan time
    rhi: float = 0.0             # 0 -> numbins - 1
    flo: float = 1.0             # min freq (Hz) if rlo not given
    uselen: int = ACCEL_USELEN   # half-bins of fundamental per block
    max_cands_per_stage: int = 2048   # static top-k size

    @property
    def numharmstages(self) -> int:
        return int(np.log2(self.numharm)) + 1

    @property
    def numz(self) -> int:
        return (self.zmax // ACCEL_DZ) * 2 + 1


@dataclass
class AccelKernels:
    """The z-response kernel bank for the fundamental (host-built)."""
    fftlen: int
    halfwidth: int
    numz: int
    zlo: int
    kern_pairs: np.ndarray       # [numz, fftlen, 2] float32, FFT'd

    @classmethod
    def build(cls, cfg: AccelConfig) -> "AccelKernels":
        """Parity: init_kernel (accel_utils.c:133-151) for harm 1/1.

        One kernel per z in [-zmax, zmax] step ACCEL_DZ; each is the
        float64 z-response placed NR-style into an fftlen array and
        forward-FFT'd (kernels are shared across all r-blocks).
        """
        fftlen = calc_fftlen(1, 1, cfg.zmax, cfg.uselen)
        halfwidth = resp.z_resp_halfwidth(float(cfg.zmax), resp.LOWACC)
        numz = cfg.numz
        kerns = np.empty((numz, fftlen), dtype=np.complex128)
        for i in range(numz):
            z = -cfg.zmax + i * ACCEL_DZ
            hw = resp.z_resp_halfwidth(float(z), resp.LOWACC)
            numkern = 2 * ACCEL_NUMBETWEEN * hw
            k = resp.gen_z_response(0.0, ACCEL_NUMBETWEEN, float(z), numkern)
            kerns[i] = np.fft.fft(resp.place_complex_kernel(k, fftlen))
        pairs = np.stack([kerns.real, kerns.imag], axis=-1).astype(np.float32)
        return cls(fftlen=fftlen, halfwidth=halfwidth, numz=numz,
                   zlo=-cfg.zmax, kern_pairs=pairs)


# ----------------------------------------------------------------------
# Device: fundamental plane construction
# ----------------------------------------------------------------------

@partial(jax.jit, static_argnames=("uselen", "fftlen", "halfwidth"))
def _ffdot_blocks(seg_pairs, kern_pairs, uselen, fftlen, halfwidth):
    """Batched f-fdot power plane for many r-blocks at once.

    seg_pairs: [nblocks, fftlen//2, 2] float32 — normalized Fourier
        amplitudes for each block's read window (lobin = block_rlo -
        halfwidth, fftlen//2 whole bins).
    kern_pairs: [numz, fftlen, 2] float32 — FFT'd kernel bank.
    Returns [nblocks, numz, uselen] float32 powers.

    Parity with the per-row loop of accel_utils.c:1002-1051: spread ×2,
    forward FFT, multiply by conj(kernel), inverse FFT, take uselen
    points starting at halfwidth*NUMBETWEEN, |.|^2 / fftlen^2.
    """
    data = seg_pairs[..., 0] + 1j * seg_pairs[..., 1]   # [B, fftlen//2]
    kern = kern_pairs[..., 0] + 1j * kern_pairs[..., 1]  # [numz, fftlen]
    B = data.shape[0]
    spread = jnp.zeros((B, fftlen), dtype=jnp.complex64)
    spread = spread.at[:, ::ACCEL_NUMBETWEEN].set(data)
    fdata = jnp.fft.fft(spread, axis=-1)                # [B, fftlen]
    prod = fdata[:, None, :] * jnp.conj(kern)[None]     # [B, numz, fftlen]
    corr = jnp.fft.ifft(prod, axis=-1)                  # ifft = fft(-1)/n
    offset = halfwidth * ACCEL_NUMBETWEEN
    good = jax.lax.dynamic_slice_in_dim(corr, offset, uselen, axis=2)
    # reference norm: |x|^2/fftlen^2 with unnormalized inverse FFT; jnp
    # ifft divides by fftlen already, so only one factor remains... but
    # the forward FFT here is unnormalized like COMPLEXFFT, so
    # |ifft_np|^2 = |ifft_ref|^2 / fftlen^2 exactly matches ref norm.
    return (good.real ** 2 + good.imag ** 2).astype(jnp.float32)


@jax.jit
def _block_median_norms(seg_pairs):
    """Old-style per-block median power normalization factors.

    norm = 1/sqrt(median(|amps|^2)/ln2) (accel_utils.c:952-967).
    seg_pairs: [nblocks, numdata, 2] -> [nblocks, 1, 1] scale to apply
    to amplitudes (the reference scales data before correlating).
    """
    pows = seg_pairs[..., 0] ** 2 + seg_pairs[..., 1] ** 2
    med = jnp.maximum(jnp.median(pows, axis=-1), 1e-30)  # all-zero guard
    return (1.0 / jnp.sqrt(med / jnp.log(2.0)))[:, None, None]


# ----------------------------------------------------------------------
# Device: harmonic summing + thresholding over the full plane
# ----------------------------------------------------------------------

def _harm_index_maps(cfg: AccelConfig, numz: int, r0: int, numr: int,
                     plane_numr: int):
    """Host-precomputed gather maps, stage by stage.

    For each harmonic fraction j/2^s: row map zind[numz] into the plane
    and column map rind[numr] (absolute half-bin -> plane column).
    Parity: inmem_add_ffdotpows index math (accel_utils.c:1160-1207).
    """
    maps = []
    zlo = -cfg.zmax
    for stage in range(1, cfg.numharmstages):
        harmtosum = 1 << stage
        stage_maps = []
        for harm in range(1, harmtosum, 2):
            frac = harm / harmtosum
            zs = zlo + np.arange(numz) * ACCEL_DZ
            zinds = np.array([index_from_z(calc_required_z(frac, z), zlo)
                              for z in zs], dtype=np.int32)
            rr = r0 + np.arange(numr, dtype=np.int64)
            rinds = np.minimum((rr * frac + 0.5).astype(np.int64),
                               plane_numr - 1).astype(np.int32)
            stage_maps.append((zinds, rinds))
        maps.append(stage_maps)
    return maps


@partial(jax.jit, static_argnames=("k",))
def _threshold_topk(powers, powcut, k):
    """Top-k powers above cutoff: returns (vals, flat_idx) with vals
    masked to 0 where below cutoff. powers: [numz, numr]."""
    flat = powers.ravel()
    masked = jnp.where(flat > powcut, flat, 0.0)
    vals, idx = jax.lax.top_k(masked, k)
    return vals, idx


@dataclass
class AccelCand:
    """A raw search candidate (pre-sifting). Mirrors accelcand
    (accel.h:76-86) minus the optimization fields."""
    power: float
    sigma: float
    numharm: int
    r: float           # fundamental-search r / numharm (candidate freq bin)
    z: float

    def freq(self, T: float) -> float:
        return self.r / T


class AccelSearch:
    """In-memory accelsearch over a packed spectrum.

    Usage:
        s = AccelSearch(cfg, T=obs_seconds)
        cands = s.search(fft_pairs)   # [numbins, 2] float32 pairs
    """

    def __init__(self, cfg: AccelConfig, T: float, numbins: int):
        self.cfg = cfg
        self.T = T
        self.numbins = numbins
        self.kern = AccelKernels.build(cfg)
        self.rlo = cfg.rlo if cfg.rlo > 0 else max(cfg.flo * T, 8.0)
        self.rhi = cfg.rhi if cfg.rhi > 0 else numbins - 1
        # numindep & powcut per stage (accel_utils.c:1629-1641)
        self.numindep = []
        self.powcut = []
        for ii in range(cfg.numharmstages):
            harmtosum = 1 << ii
            if cfg.numz == 1:
                ni = (self.rhi - self.rlo) / harmtosum
            else:
                ni = ((self.rhi - self.rlo) * (cfg.numz + 1) *
                      (ACCEL_DZ / 6.95) / harmtosum)
            self.numindep.append(ni)
            self.powcut.append(float(st.power_for_sigma(
                cfg.sigma, harmtosum, ni)))

    # -- plane ---------------------------------------------------------

    def _plan_blocks(self):
        """r-block starts (whole bins) covering [8, rhi] like the
        reference's inmem pre-population + search loops
        (accelsearch.c:143-160)."""
        blocks = []
        startr = 8.0
        step = self.cfg.uselen * ACCEL_DR
        # Only full, in-spectrum blocks are built/searched — same bound
        # as the reference loop (accelsearch.c:167): a partial block at
        # the top would be median-normalized against zero padding.
        while startr + step < self.rhi:
            blocks.append(startr)
            startr += step
        return blocks

    def build_plane(self, fft_pairs: np.ndarray) -> np.ndarray:
        """Fundamental F-Fdot plane P[numz, plane_numr] (float32, HBM).

        plane column c = absolute half-bin (r = c * ACCEL_DR), starting
        at column 0 == r 0 (columns below 16 are zero: the search and
        pre-population start at r=8 as in accelsearch.c:144).
        fft_pairs: [numbins, 2] float32 (the packed .fft as pairs).
        """
        cfg, kern = self.cfg, self.kern
        starts = self._plan_blocks()
        numdata = kern.fftlen // 2
        segs = np.zeros((len(starts), numdata, 2), dtype=np.float32)
        for i, s0 in enumerate(starts):
            lobin = int(s0) - kern.halfwidth
            lo = max(lobin, 0)
            hi = min(lobin + numdata, self.numbins)
            if hi > lo:
                segs[i, lo - lobin:hi - lobin] = fft_pairs[lo:hi]
        if not starts:
            # spectrum too short for one full block: empty plane
            return np.zeros((kern.numz, 0), dtype=np.float32)
        kern_dev = jnp.asarray(kern.kern_pairs)
        plane_numr = int(2 * int(starts[-1]) + cfg.uselen)
        plane = np.zeros((kern.numz, plane_numr), dtype=np.float32)
        # Chunk the block batch: the [chunk, numz, fftlen] complex
        # intermediate is the peak memory, so bound it (~0.25 GB/chunk
        # at zmax=200) — the HBM-ladder analog of meminfo.h.
        chunk = max(1, int(2 ** 28 // (kern.numz * kern.fftlen * 8)))
        for c0 in range(0, len(starts), chunk):
            batch = segs[c0:c0 + chunk]
            if batch.shape[0] < chunk:     # pad to keep one jit shape
                pad = np.zeros((chunk - batch.shape[0],) + batch.shape[1:],
                               dtype=np.float32)
                pad[:, 0, 0] = 1.0         # avoid 0-median div-by-zero
                batch = np.concatenate([batch, pad], axis=0)
            bdev = jnp.asarray(batch)
            norms = _block_median_norms(bdev)
            powers = np.asarray(_ffdot_blocks(
                bdev * norms, kern_dev, cfg.uselen, kern.fftlen,
                kern.halfwidth))           # [chunk, numz, uselen]
            for j, s0 in enumerate(starts[c0:c0 + chunk]):
                col = int(s0) * ACCEL_RDR
                plane[:, col:col + cfg.uselen] = powers[j]
        return plane

    # -- search --------------------------------------------------------

    def search(self, fft_pairs: np.ndarray,
               plane: Optional[np.ndarray] = None) -> List[AccelCand]:
        """Run the full staged harmonic-summing search."""
        cfg = self.cfg
        if plane is None:
            plane = self.build_plane(fft_pairs)
        numz, plane_numr = plane.shape
        r0 = int(self.rlo) * ACCEL_RDR          # first searched column
        numr = min(int(self.rhi) * ACCEL_RDR, plane_numr) - r0
        if numr <= 0:
            return []
        maps = _harm_index_maps(cfg, numz, r0, numr, plane_numr)

        dplane = jnp.asarray(plane)
        acc = jax.lax.dynamic_slice_in_dim(dplane, r0, numr, axis=1)
        cands: List[AccelCand] = []
        self._collect(acc, 1, r0, cands)
        for stage in range(1, cfg.numharmstages):
            harmtosum = 1 << stage
            for (zinds, rinds) in maps[stage - 1]:
                sub = jnp.take(dplane, jnp.asarray(zinds), axis=0)
                sub = jnp.take(sub, jnp.asarray(rinds), axis=1)
                acc = acc + sub
            self._collect(acc, harmtosum, r0, cands)
        return sorted(cands, key=lambda c: (-c.sigma, c.r))

    def _collect(self, acc, numharm: int, r0: int,
                 out: List[AccelCand]) -> None:
        """Threshold+top-k on device; sigma + bookkeeping on host.
        Parity: search_ffdotpows (accel_utils.c:1259-1298)."""
        cfg = self.cfg
        stage = int(np.log2(numharm))
        k = min(cfg.max_cands_per_stage, int(np.prod(acc.shape)))
        vals, idx = _threshold_topk(acc, self.powcut[stage], k)
        vals = np.asarray(vals)
        idx = np.asarray(idx)
        good = vals > 0.0
        if not np.any(good):
            return
        numr = acc.shape[1]
        zi = idx[good] // numr
        ri = idx[good] % numr
        sigmas = st.candidate_sigma(vals[good], numharm,
                                    self.numindep[stage])
        for p, s, z_i, r_i in zip(vals[good], sigmas, zi, ri):
            rr = (r0 + int(r_i)) * ACCEL_DR / numharm
            zz = (-cfg.zmax + int(z_i) * ACCEL_DZ) / numharm
            out.append(AccelCand(power=float(p), sigma=float(s),
                                 numharm=numharm, r=rr, z=zz))


# ----------------------------------------------------------------------
# Candidate post-processing (host)
# ----------------------------------------------------------------------

# The reference's fixed list of "other common harmonic ratios"
# (accel_utils.c:415-439) in addition to r*ii and r/ii, ii = 1..16.
_HARM_RATIOS = [3 / 2, 5 / 2, 2 / 3, 4 / 3, 5 / 3, 3 / 4, 5 / 4, 2 / 5,
                3 / 5, 4 / 5, 5 / 6, 2 / 7, 3 / 7, 4 / 7, 3 / 8, 5 / 8,
                2 / 9, 3 / 10, 2 / 11, 3 / 11, 2 / 13, 3 / 13, 2 / 15]


def eliminate_harmonics(cands: List[AccelCand],
                        tooclose: float = 1.5,
                        maxharm: int = 16) -> List[AccelCand]:
    """Remove less-significant harmonically-related candidates.

    Parity: eliminate_harmonics (accel_utils.c:384-460): walking the
    sigma-sorted list, a later candidate is dropped when its r lies
    within `tooclose` bins of r_strong*ii, r_strong/ii (ii<=16), or
    r_strong*ratio for the fixed rational-ratio list.
    """
    if not cands:
        return []
    cands = sorted(cands, key=lambda c: (-c.sigma, c.r))
    kept: List[AccelCand] = []
    for c in cands:
        is_harm = False
        for k in kept:
            rk, rc = k.r, c.r
            if any(abs(rk / ii - rc) < tooclose or
                   abs(rk * ii - rc) < tooclose
                   for ii in range(1, maxharm + 1)):
                is_harm = True
            elif any(abs(rk * ratio - rc) < tooclose
                     for ratio in _HARM_RATIOS):
                is_harm = True
            if is_harm:
                break
        if not is_harm:
            kept.append(c)
    return kept


def remove_duplicates(cands: List[AccelCand]) -> List[AccelCand]:
    """Collapse candidates within ACCEL_CLOSEST_R/2 bins & same numharm
    family to the strongest (the sorted-insert dedup of
    insert_new_accelcand, accel_utils.c:294-382)."""
    kept: List[AccelCand] = []
    for c in sorted(cands, key=lambda c: -c.sigma):
        if all(abs(c.r - k.r) > ACCEL_CLOSEST_R / 2 or
               abs(c.z - k.z) > ACCEL_DZ * 2 for k in kept):
            kept.append(c)
    return kept
