"""NumPy/SciPy reference accelsearch — float64 referee and CPU baseline.

This is the same staged harmonic-summing F-Fdot search AccelSearch runs
on device (plane build per r-block: spread x2 interbin, forward FFT,
per-z-row multiply by conj(z-response), inverse FFT, |.|^2; then
per-stage subharmonic adds and powcut thresholding), written in plain
NumPy + scipy.fft (pocketfft) at selectable precision.  It exists for
two jobs:

* the **float64 referee** (SURVEY.md s7.3.1 north-star acceptance):
  the float32 TPU candidate list must match this path after sigma
  rounding (tests/test_referee.py);
* the **fair CPU baseline** (bench_cpu.py): the reference's hot loop
  (src/accel_utils.c:1002-1051) is multithreaded FFTW/OpenMP; this twin
  runs the identical algorithm through scipy.fft with ``workers`` set
  to every host core, so bench.py's ``vs_baseline`` compares against an
  honest all-cores CPU number rather than a single-threaded proxy.

Parity anchors: subharm_ffdot_plane (accel_utils.c:879-1051), inmem
harmonic sums (accel_utils.c:1160-1256), search_ffdotpows
(accel_utils.c:1259-1298), powcut/numindep (accel_utils.c:1629-1641).
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Tuple

import numpy as np

try:
    from scipy import fft as sfft
except Exception:                                    # pragma: no cover
    sfft = None

from presto_tpu.ops import stats as st
from presto_tpu.search.accel import (
    ACCEL_DR,
    ACCEL_DZ,
    ACCEL_NUMBETWEEN,
    ACCEL_RDR,
    AccelCand,
    AccelConfig,
    AccelKernels,
    AccelSearch,
    _harm_fracs_and_zinds,
)


def _fft(x, workers, axis=-1):
    if sfft is not None:
        return sfft.fft(x, axis=axis, workers=workers)
    return np.fft.fft(x, axis=axis)


def _ifft(x, workers, axis=-1):
    if sfft is not None:
        return sfft.ifft(x, axis=axis, workers=workers)
    return np.fft.ifft(x, axis=axis)


def kernel_bank_ref(kern: AccelKernels, cdtype=np.complex128) -> np.ndarray:
    """FFT'd [numz, fftlen] kernel bank at the requested precision.

    Same NR wrap placement as the device's _fft_kernel_bank
    (place_complex_kernel, corr_prep.c:58-80).  complex128 keeps the
    float64 referee honest; pass complex64 to reproduce the device bank
    at float32.
    """
    kc = (kern.kern_pairs[..., 0].astype(np.float64)
          + 1j * kern.kern_pairs[..., 1].astype(np.float64))
    half = kern.kmax // 2
    placed = np.zeros((kc.shape[0], kern.fftlen), dtype=np.complex128)
    placed[:, :half] = kc[:, half:]
    placed[:, kern.fftlen - half:] = kc[:, :half]
    return np.fft.fft(placed, axis=-1).astype(cdtype)


def build_plane_ref(search: AccelSearch, spectrum: np.ndarray,
                    dtype=np.float64,
                    workers: Optional[int] = None,
                    kern: Optional[AccelKernels] = None
                    ) -> Tuple[np.ndarray, int]:
    """The fundamental F-Fdot power plane, host-side.

    spectrum: [numbins] complex (or [numbins, 2] float pairs).
    Returns (plane[numz, plane_cols], col0) where column c holds the
    power at absolute half-bin col0*0 + c (i.e. r = c * ACCEL_DR), with
    columns below col0 zero — the same layout AccelSearch.build_plane
    produces on device.

    kern: an alternate kernel bank (a jerk search's w-plane bank from
    AccelKernels.build(cfg, w) — fftlen/uselen geometry is shared by
    every bank of a config); defaults to the search's z-only bank.
    """
    if spectrum.ndim == 2:
        spectrum = spectrum[..., 0] + 1j * spectrum[..., 1]
    cdtype = np.complex128 if dtype == np.float64 else np.complex64
    kern = kern if kern is not None else search.kern
    cfg = search.cfg
    bank = np.conj(kernel_bank_ref(kern, cdtype))
    starts = search._plan_blocks()
    if not starts:
        return np.zeros((kern.numz, 0), dtype=dtype), 0
    numdata = kern.fftlen // 2
    # the search's EFFECTIVE halfwidth: the direct-plane TPU builder
    # pads the window offset to a 128-column boundary, shifting every
    # block's read window and normalization window with it — the
    # referee must use the same geometry to produce the same list
    # (on CPU hw_use == kern.halfwidth and nothing changes)
    g = search._plane_geom()
    hw_use = g.hw_use if g else kern.halfwidth
    offset = hw_use * ACCEL_NUMBETWEEN
    col0 = int(starts[0]) * ACCEL_RDR
    plane_cols = col0 + len(starts) * cfg.uselen
    plane = np.zeros((kern.numz, plane_cols), dtype=dtype)
    spec = np.asarray(spectrum, dtype=cdtype)
    nbins = spec.shape[0]
    for j, s0 in enumerate(starts):
        lobin = int(s0) - hw_use
        win = np.zeros(numdata, dtype=cdtype)
        lo, hi = max(lobin, 0), min(lobin + numdata, nbins)
        win[lo - lobin:hi - lobin] = spec[lo:hi]
        # old-style per-block median normalization (accel_utils.c:952-967)
        if cfg.norm == "median":
            med = max(float(np.median(win.real ** 2 + win.imag ** 2)),
                      1e-30)
            norm = 1.0 / np.sqrt(med / np.log(2.0))
        else:
            norm = 1.0
        spread = np.zeros(kern.fftlen, dtype=cdtype)
        spread[::ACCEL_NUMBETWEEN] = win * dtype(norm)
        fdata = _fft(spread, workers)
        corr = _ifft(fdata[None, :] * bank, workers)
        good = corr[:, offset:offset + cfg.uselen]
        c = col0 + j * cfg.uselen
        plane[:, c:c + cfg.uselen] = (good.real ** 2 + good.imag ** 2)
    return plane, col0


def _accum_stages(search: AccelSearch, plane: np.ndarray):
    """Yield (stage, acc[numz, top-r0]) after each stage's subharmonic
    adds — the ONE accumulation loop both the referee search
    (search_plane_ref) and the cell-power probe (ref_cell_powers)
    consume, so they cannot desynchronize.  acc is accumulated in
    place: consumers must not mutate it."""
    cfg = search.cfg
    numz, plane_cols = plane.shape
    r0 = int(search.rlo) * ACCEL_RDR
    top = min(int(search.rhi) * ACCEL_RDR, plane_cols)
    if top <= r0:
        return
    acc = plane[:, r0:top].copy()
    fz = _harm_fracs_and_zinds(cfg, numz)
    yield 0, acc
    cols = np.arange(r0, top, dtype=np.int64)
    for stage in range(1, cfg.numharmstages):
        for (harm, htot, zinds) in fz[stage - 1]:
            # exact round-half-up of cols*harm/htot (overflow-safe),
            # as ONE int32 map per term
            rind = ((cols // htot) * harm +
                    ((cols % htot) * harm + (htot >> 1)) // htot
                    ).astype(np.int32)
            # zinds is nondecreasing with long runs of repeats (the
            # subharmonic z grid is coarser by 1/frac): gather each
            # DISTINCT source row once, then one broadcast add per run
            # — the numpy formulation closest to C-loop speed.
            zinds = np.asarray(zinds)
            runs = np.flatnonzero(np.diff(zinds)) + 1
            starts = np.concatenate([[0], runs])
            ends = np.concatenate([runs, [len(zinds)]])
            for g0, g1 in zip(starts, ends):
                acc[g0:g1] += np.take(plane[zinds[g0]], rind)[None, :]
        yield stage, acc


def search_plane_ref(search: AccelSearch, plane: np.ndarray,
                     max_cands_per_stage: int = 1 << 16) -> List[AccelCand]:
    """Staged harmonic-summing search of a host plane.

    Candidate semantics match AccelSearch: per stage, each column
    contributes its max-over-z cell when above powcut[stage] (the
    sifter's r-dedup makes same-column lower-z cells duplicates);
    callers apply remove_duplicates for the final list, exactly as the
    reference's insert_new_accelcand (accel_utils.c:294-382) does at
    insert time.
    """
    cfg = search.cfg
    r0 = int(search.rlo) * ACCEL_RDR
    cands: List[AccelCand] = []

    def collect(acc, stage):
        numharm = 1 << stage
        colmax = acc.max(axis=0)
        good = np.flatnonzero(colmax > search.powcut[stage])
        if good.size > max_cands_per_stage:       # keep the strongest
            good = good[np.argsort(colmax[good])[::-1]
                        [:max_cands_per_stage]]
        if good.size == 0:
            return
        # z row only needed for accepted columns (a full-plane argmax
        # would cost more than the harmonic sums themselves)
        colz = acc[:, good].argmax(axis=0)
        sigmas = np.atleast_1d(st.candidate_sigma(
            colmax[good], numharm, search.numindep[stage]))
        for gi, zi, sg in zip(good.tolist(), colz.tolist(),
                              sigmas.tolist()):
            rr = (r0 + gi) * ACCEL_DR / numharm
            zz = (-cfg.zmax + zi * ACCEL_DZ) / numharm
            cands.append(AccelCand(power=float(colmax[gi]), sigma=sg,
                                   numharm=numharm, r=rr, z=zz))

    for stage, acc in _accum_stages(search, plane):
        collect(acc, stage)
    return sorted(cands, key=lambda c: (-c.sigma, c.r))


def ref_cell_powers(search: AccelSearch, spectrum: np.ndarray,
                    cells, dtype=np.float32,
                    workers: Optional[int] = None) -> List[float]:
    """Harmonic-summed power of the reference path at specific cells.

    cells: list of (stage, zrow, col) in FUNDAMENTAL-plane units —
    stage = log2(numharm), col = candidate r * numharm / ACCEL_DR,
    zrow = (candidate z * numharm + zmax) / ACCEL_DZ.  Used by the
    e2e referee to explain chip candidates with no reference
    counterpart: a cell whose ref power sits just below powcut while
    the chip's float32 ordering put it just above is a legitimate
    threshold-straddle, not a missed feature (the reference's own
    -inmem vs standard split has the same texture, SURVEY §4.8).
    """
    plane, _ = build_plane_ref(search, spectrum, dtype=dtype,
                               workers=workers)
    numz = plane.shape[0]
    r0 = int(search.rlo) * ACCEL_RDR
    top = min(int(search.rhi) * ACCEL_RDR, plane.shape[1])
    out = [float("nan")] * len(cells)
    for stage, acc in _accum_stages(search, plane):
        for i, (sg, zr, col) in enumerate(cells):
            if sg == stage and 0 <= zr < numz and r0 <= col < top:
                out[i] = float(acc[int(zr), int(col) - r0])
    return out


def search_ref(fft_pairs: np.ndarray, cfg: AccelConfig, T: float,
               numbins: Optional[int] = None, dtype=np.float64,
               workers: Optional[int] = None) -> List[AccelCand]:
    """Full reference search: pairs/complex spectrum -> candidate list.

    dtype=np.float64 is the referee configuration; dtype=np.float32
    reproduces the device arithmetic on host (the CPU-baseline timing
    configuration, matching the reference's float FFTW build).
    """
    if workers is None:
        workers = os.cpu_count() or 1
    if numbins is None:
        numbins = fft_pairs.shape[0]
    search = AccelSearch(cfg, T=T, numbins=numbins)
    plane, _ = build_plane_ref(search, fft_pairs, dtype=dtype,
                               workers=workers)
    return search_plane_ref(search, plane)


def timed_search_ref(fft_pairs: np.ndarray, cfg: AccelConfig, T: float,
                     dtype=np.float32,
                     workers: Optional[int] = None):
    """(candidates, plane_seconds, search_seconds, cells) for bench_cpu."""
    if workers is None:
        workers = os.cpu_count() or 1
    numbins = fft_pairs.shape[0]
    search = AccelSearch(cfg, T=T, numbins=numbins)
    t0 = time.perf_counter()
    plane, _ = build_plane_ref(search, fft_pairs, dtype=dtype,
                               workers=workers)
    t1 = time.perf_counter()
    cands = search_plane_ref(search, plane)
    t2 = time.perf_counter()
    numr = int(search.rhi - search.rlo) * ACCEL_RDR
    cells = cfg.numz * numr
    return cands, t1 - t0, t2 - t1, cells


def timed_jerk_ref(fft_pairs: np.ndarray, cfg: AccelConfig, T: float,
                   dtype=np.float32,
                   workers: Optional[int] = None):
    """(ncands, seconds, cells) — the jerk-search CPU baseline for
    bench_cpu (VERDICT r4 weak #4: the device jerk row had no ratio).

    Per w plane: fundamental plane built with that w's kernel bank,
    then the staged harmonic-summing search.  CONSERVATIVE by
    construction: the true algorithm (the reference's -wmax path and
    the device's _search_jerk) reads each SUBHARMONIC from its own
    w-scaled plane, costing extra plane builds per w — this twin sums
    subharmonics from the same-w plane, so the measured CPU time
    UNDERESTIMATES the reference's work and any device ratio derived
    from it is a lower bound.  Kernel-bank generation is excluded from
    the timed span on both sides (the reference likewise excludes its
    'Generating correlation kernels' setup, accelsearch.c:134-160).
    """
    if workers is None:
        workers = os.cpu_count() or 1
    numbins = fft_pairs.shape[0]
    search = AccelSearch(cfg, T=T, numbins=numbins)
    ws = sorted(float(x) for x in cfg.ws)
    banks = {w: AccelKernels.build(cfg, w) for w in ws}   # untimed
    t0 = time.perf_counter()
    ncands = 0
    for w in ws:
        plane, _ = build_plane_ref(search, fft_pairs, dtype=dtype,
                                   workers=workers, kern=banks[w])
        ncands += len(search_plane_ref(search, plane))
    el = time.perf_counter() - t0
    numr = int(search.rhi - search.rlo) * ACCEL_RDR
    cells = cfg.numz * numr * len(ws)
    return ncands, el, cells
