"""Pallas TPU kernel for the accelsearch harmonic-sum stage scan.

The staged harmonic summing (SURVEY §7.2 step 9a: "Pallas kernels for
the harmonic-sum gather") is HBM-bandwidth-bound in the XLA
formulation: every subharmonic add materializes plane-sized
intermediates (z-permuted copy, phase-stacked copy, accumulator
update).  This kernel keeps one column tile of the accumulator in
VMEM, DMAs exactly the source windows each harmonic needs from the
HBM-resident plane, applies the z-row mapping AND the fractional-
stride column mapping as one-hot MXU matmuls (exact selections;
Mosaic cannot lower the interleave reshape the XLA phase trick
uses), and reduces each stage to per-column (max over z, argmax) on
the spot — the only HBM writes are the [stages, slab] reduction
outputs, ~1000x smaller than the XLA path's intermediates.

Thresholding / segment-max / top-k stay in XLA outside the kernel
(they operate on the reduced [stages, slab] arrays, which are cheap).

Alignment contract (enforced by the caller): slab starts and the slab
length are multiples of TILE, so every tile start j0 is divisible by
every htot <= 16; DMA starts are floored to 128-lane multiples with
the residual rolled away in VMEM.  The plane must be padded to
ceil(numz/8)*8 rows and carry >= PLANE_PAD columns of zero padding at
the right edge so subharmonic window DMAs never run off the array
(search/accel.py's _scan_pallas_py applies both pads).

Hardware notes discovered building this: grid-pipelined manual DMAs
into one scratch get reordered across grid steps (hence the per-term
x2-parity window banks), and pltpu.roll with a dynamic NEGATIVE
shift is miscompiled by this Mosaic version (hence the positive-
equivalent WIN - off shifts).
"""

from __future__ import annotations

from functools import partial
from typing import List, Tuple

import numpy as np
import jax
import jax.numpy as jnp

TILE = 256                   # columns per grid tile (lanes)
WIN = TILE + 128             # DMA window (lane-aligned): covers the
                             # harmonic-term span for all harm < htot <= 16
PLANE_PAD = WIN              # right-edge zero padding the plane needs


def _stage_terms(fracs_zinds):
    """Flatten the per-stage (harm, htot, zinds) lists, keeping the
    stage boundaries: returns (terms, stage_term_counts)."""
    terms = []
    counts = []
    for stage in fracs_zinds:
        counts.append(len(stage))
        for harm, htot, zinds in stage:
            terms.append((harm, htot, np.asarray(zinds)))
    return terms, counts


def make_stage_reducer(numharmstages, fracs_zinds, slab: int,
                       numz: int, plane_numr: int,
                       interpret: bool = False):
    """Build the pallas stage reducer.

    Returns f(P, start_cols) -> (colmax f32, colz i32), each
    [nslabs, numharmstages, slab]: per search column, the max over z
    of the stage-summed powers and its z row — the kernel half of the
    staged search (thresholding/top-k are done by the caller).

    Requires slab % TILE == 0, start_cols % TILE == 0, and P padded
    to ceil(numz/8)*8 rows (zero rows below; `pad_rows` below).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    terms, counts = _stage_terms(fracs_zinds)
    nterms = len(terms)
    ntiles = slab // TILE
    nstages = numharmstages
    # sublane tiling: the kernel works on a plane padded to 8-row
    # multiples (zero rows; they never win the argmax since powers
    # are >= 0 and ties resolve to the lowest row index)
    numz_pad = -(-numz // 8) * 8

    # one-hot z-permutation matrices: perm[t] @ src == src[zinds_t]
    onehots = np.zeros((max(nterms, 1), numz_pad, numz_pad),
                       np.float32)
    for i, (_h, _t, zinds) in enumerate(terms):
        onehots[i, np.arange(numz), zinds] = 1.0

    # one-hot column-selection matrices: (src @ colsel[t])[z, j] ==
    # src[z, (j*harm + htot//2) // htot] of the ROLLED window (max
    # needed row < TILE for every harm < htot) — Mosaic cannot lower
    # the phase-interleave reshape the XLA path uses, so the
    # fractional-stride column map runs on the MXU too (exact:
    # selectors are 0/1, so the decomposed-f32 passes recover each
    # power bit-for-bit)
    colsels = np.zeros((max(nterms, 1), TILE, TILE), np.float32)
    j = np.arange(TILE)
    for i, (harm, htot, _z) in enumerate(terms):
        colsels[i, (j * harm + (htot >> 1)) // htot, j] = 1.0

    def kernel(start_cols_ref, P_ref, onehot_ref, colsel_ref,
               colmax_ref, colz_ref, acc_ref, src_ref, sems):
        s = pl.program_id(0)
        t = pl.program_id(1)
        j0 = start_cols_ref[s] + t * TILE

        # One DMA buffer + semaphore PER window (fundamental + each
        # harmonic term) x2 grid-step parity banks: Mosaic pipelines
        # grid iterations, so the next step's DMAs race this step's
        # reads unless they land in the other bank; the fan-out also
        # overlaps all fetches with compute.
        bank = ((s * ntiles + t) % 2) * (1 + nterms)

        def start_dma(slot, cstart):
            slot = slot + bank
            pltpu.make_async_copy(
                P_ref.at[:, pl.ds(cstart, WIN)],
                src_ref.at[slot], sems.at[slot]).start()

        def wait_dma(slot, cstart):
            slot = slot + bank
            pltpu.make_async_copy(
                P_ref.at[:, pl.ds(cstart, WIN)],
                src_ref.at[slot], sems.at[slot]).wait()

        def term_start(fi):
            harm, htot, _z = terms[fi]
            cs = (j0 // htot) * harm
            # DMA starts must be 128-lane-aligned: fetch from the
            # floor; the residual (0/32/64/96) is rolled away at use
            off = cs % 128
            return pl.multiple_of(cs - off, 128), off

        fund_start = pl.multiple_of(j0, 128)
        start_dma(0, fund_start)
        for fi in range(nterms):
            start_dma(1 + fi, term_start(fi)[0])

        wait_dma(0, fund_start)
        acc_ref[:, :] = src_ref[bank, :, :TILE]

        def collect(stage):
            a = acc_ref[:, :]
            m = jnp.max(a, axis=0)
            iota = jax.lax.broadcasted_iota(jnp.int32, a.shape, 0)
            z = jnp.min(jnp.where(a == m[None, :], iota, numz_pad),
                        axis=0).astype(jnp.int32)
            colmax_ref[0, stage, :] = m
            colz_ref[0, stage, :] = z

        collect(0)
        fi = 0
        for stage in range(1, nstages):
            for _ in range(counts[stage - 1]):
                cstart, off = term_start(fi)
                wait_dma(1 + fi, cstart)
                # positive-equivalent shift: dynamic NEGATIVE rolls
                # are miscompiled by this Mosaic version (off by a
                # lane tile); WIN - off rolls the residual away
                src = pltpu.roll(src_ref[bank + 1 + fi],
                                 shift=WIN - off, axis=1)[:, :TILE]
                # column map then z-row map, both as one-hot MXU
                # matmuls (exact selections, see colsels note)
                cols = jax.lax.dot_general(
                    src, colsel_ref[fi],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)
                add = jax.lax.dot_general(
                    onehot_ref[fi], cols,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)
                acc_ref[:, :] = acc_ref[:, :] + add
                fi += 1
            collect(stage)

    onehots_j = jnp.asarray(onehots)
    colsels_j = jnp.asarray(colsels)

    @jax.jit
    def reduce_stages(P, start_cols):
        nslabs = start_cols.shape[0]
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nslabs, ntiles),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),   # P (HBM)
                pl.BlockSpec(memory_space=pltpu.VMEM),  # onehots
                pl.BlockSpec(memory_space=pltpu.VMEM),  # colsels
            ],
            out_specs=[
                pl.BlockSpec((1, nstages, TILE),
                             lambda s, t, *_: (s, 0, t)),
                pl.BlockSpec((1, nstages, TILE),
                             lambda s, t, *_: (s, 0, t)),
            ],
            scratch_shapes=[
                pltpu.VMEM((numz_pad, TILE), jnp.float32),   # acc
                pltpu.VMEM((2 * (1 + nterms), numz_pad, WIN),
                           jnp.float32),                     # windows
                pltpu.SemaphoreType.DMA((2 * (1 + nterms),)),
            ],
        )
        return pl.pallas_call(
            kernel,
            grid_spec=gs,
            out_shape=[
                jax.ShapeDtypeStruct((nslabs, nstages, slab),
                                     jnp.float32),
                jax.ShapeDtypeStruct((nslabs, nstages, slab),
                                     jnp.int32),
            ],
            interpret=interpret,
        )(start_cols, P, onehots_j, colsels_j)

    return reduce_stages


def pad_rows(numz: int) -> int:
    """Rows the kernel-ready plane must have (8-sublane tiling)."""
    return -(-numz // 8) * 8


def pallas_available() -> bool:
    """True when the default jax backend can run the TPU kernel."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False
