"""Pallas TPU kernel for the accelsearch harmonic-sum stage scan.

The staged harmonic summing (SURVEY §7.2 step 9a: "Pallas kernels for
the harmonic-sum gather") is HBM-bandwidth-bound in the XLA
formulation: every subharmonic add materializes plane-sized
intermediates (z-permuted copy, phase-stacked copy, accumulator
update).  This kernel keeps one column tile of the accumulator in
VMEM, DMAs exactly the source window each harmonic needs from the
HBM-resident plane (only the z rows the term's zinds map can touch —
~frac*numz of them), applies the fractional-stride column mapping as
single-vreg lane gathers (tpu.dynamic_gather, decomposed over 128-lane
source/output chunks; the dynamic DMA-alignment residual folds into
the gather indices, so no vector rolls at all), applies the z-row
mapping as ONE exact bf16x3 one-hot matmul (hi/mid/lo split of the
f32 values stacked along the contraction — each output element is a
single selected bf16 triplet, reconstructing the float32 bit-for-bit
at full-bf16 MXU rate instead of a 6-pass HIGHEST f32 matmul), and
reduces each stage to per-column (max over z, argmax) on the spot —
the only HBM writes are the [stages, slab] reduction outputs.

v1 of this kernel (one fixed-size window per term + pltpu.roll + two
HIGHEST-precision one-hot matmuls) measured 336 ms on the bench
workload; the selection matmuls were ~200 ms of it and the
DMA+collect floor 135 ms.  v2 cuts both: ~45% less DMA (row-shrunk
windows), no rolls, and ~3x cheaper exact selection.

Thresholding / segment-max / top-k stay in XLA outside the kernel
(they operate on the reduced [stages, slab] arrays, which are cheap).

Alignment contract (enforced by the caller): slab starts and the slab
length are multiples of TILE, so every tile start j0 is divisible by
every htot <= 16; DMA starts are floored to 128-lane multiples with
the residual added to the gather indices.  The plane must be padded
to ceil(numz/8)*8 rows and carry >= PLANE_PAD columns of zero padding
at the right edge so subharmonic window DMAs never run off the array
(search/accel.py's _scan_pallas_py applies both pads).

Hardware notes (discovered building v1/v2): grid-pipelined manual
DMAs into one scratch get reordered across grid steps (hence the
per-term x2-parity window banks); tpu.dynamic_gather handles ONE
source vreg along the gathered dim, so lane gathers decompose into
128-lane chunks combined with predicated selects.
"""

from __future__ import annotations


import numpy as np
import jax
import jax.numpy as jnp

TILE = 1024                  # columns per grid tile (lanes): fewer
                             # per-tile DMAs/collects win — 256/512/
                             # 1024 measured 194/164/151 ms on the
                             # bench workload (VMEM bounds going
                             # further)
PLANE_PAD = 1152             # right-edge zero padding the plane needs
                             # (largest per-term DMA window)


def _stage_terms(fracs_zinds):
    """Flatten the per-stage (harm, htot, zinds) lists, keeping the
    stage boundaries: returns (terms, stage_term_counts)."""
    terms = []
    counts = []
    for stage in fracs_zinds:
        counts.append(len(stage))
        for harm, htot, zinds in stage:
            terms.append((harm, htot, np.asarray(zinds)))
    return terms, counts


def _term_geom(harm: int, htot: int, zinds: np.ndarray,
               tile: int = None):
    """Static per-term window geometry: rows the zinds map can touch
    (8-padded) and the 128-multiple DMA window width covering the
    column map's span from any 128-aligned floor.  The residual
    off = ((j0//htot)*harm) % 128 is a multiple of (TILE*harm/htot)
    mod 128 — at TILE=1024 only {0, 64}, but the sizing keeps the
    worst case over ANY TILE >= 128 (112, reached at TILE=256 for
    htot=16; an earlier 96-based window undersized that term by one
    lane chunk and silently zeroed 8 of every 2048 columns)."""
    tile = tile or TILE
    rows = -(-(int(zinds.max()) + 1) // 8) * 8
    cspan = ((tile - 1) * harm + (htot >> 1)) // htot + 2
    win = -(-(112 + cspan) // 128) * 128
    return rows, win


def make_stage_reducer(numharmstages, fracs_zinds, slab: int,
                       numz: int, plane_numr: int,
                       interpret: bool = False, tile: int = None):
    """Build the pallas stage reducer.

    Returns f(P, start_cols) -> (colmax f32, colz i32), each
    [nslabs, numharmstages, slab]: per search column, the max over z
    of the stage-summed powers and its z row — the kernel half of the
    staged search (thresholding/top-k are done by the caller).

    Requires slab % tile == 0, start_cols % tile == 0, and P padded
    to ceil(numz/8)*8 rows (zero rows below; `pad_rows` below).

    `tile` (default TILE) is threaded explicitly through the whole
    build — module state is never consulted or mutated, so concurrent
    plans with different tiles cannot race.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tile = int(tile or TILE)
    if tile < 128 or tile % 128 or slab % tile:
        raise ValueError("tile must be a 128-multiple dividing the "
                         "slab (tile=%d, slab=%d)" % (tile, slab))
    terms, counts = _stage_terms(fracs_zinds)
    nterms = len(terms)
    ntiles = slab // tile
    nstages = numharmstages
    numz_pad = -(-numz // 8) * 8
    geom = [_term_geom(h, t, zi, tile) for (h, t, zi) in terms]

    # bf16x3 stacked one-hot z-permutation: oh3[t] is [numz_pad,
    # 3*rows] with the same one-hot block repeated for the hi/mid/lo
    # value planes — (oh3 @ [hi;mid;lo]) selects and reconstructs each
    # float32 exactly in ONE bf16 matmul (see module docstring)
    onehots = []
    for i, (_h, _t, zinds) in enumerate(terms):
        rows = geom[i][0]
        oh = np.zeros((numz_pad, rows), np.float32)
        oh[np.arange(numz), zinds] = 1.0
        onehots.append(jnp.asarray(
            np.concatenate([oh, oh, oh], axis=1).astype(jnp.bfloat16)))

    def kernel(start_cols_ref, P_ref, *refs):
        oh_refs = refs[:nterms]
        colmax_ref, colz_ref = refs[nterms], refs[nterms + 1]
        acc_ref = refs[nterms + 2]
        win_refs = refs[nterms + 3:nterms + 3 + (1 + nterms)]
        sems = refs[-1]

        s = pl.program_id(0)
        t = pl.program_id(1)
        j0 = start_cols_ref[s] + t * tile

        # x2 grid-step parity banks: Mosaic pipelines grid iterations,
        # so the next step's DMAs race this step's reads unless they
        # land in the other bank; the fan-out also overlaps fetches
        # with compute.
        bank = (s * ntiles + t) % 2

        def fund_dma():
            return pltpu.make_async_copy(
                P_ref.at[:, pl.ds(pl.multiple_of(j0, 128), tile)],
                win_refs[0].at[bank], sems.at[0, bank])

        def term_dma(fi):
            harm, htot, _z = terms[fi]
            rows, win = geom[fi]
            cs = (j0 // htot) * harm
            off = cs % 128
            return pltpu.make_async_copy(
                P_ref.at[pl.ds(0, rows),
                         pl.ds(pl.multiple_of(cs - off, 128), win)],
                win_refs[1 + fi].at[bank], sems.at[1 + fi, bank]), off

        fund_dma().start()
        for fi in range(nterms):
            term_dma(fi)[0].start()

        fund_dma().wait()
        acc_ref[:, :] = win_refs[0][bank]

        def collect(stage):
            a = acc_ref[:, :]
            m = jnp.max(a, axis=0)
            iota = jax.lax.broadcasted_iota(jnp.int32, a.shape, 0)
            z = jnp.min(jnp.where(a == m[None, :], iota, numz_pad),
                        axis=0).astype(jnp.int32)
            colmax_ref[0, stage, :] = m
            colz_ref[0, stage, :] = z

        collect(0)
        fi = 0
        for stage in range(1, nstages):
            for _ in range(counts[stage - 1]):
                harm, htot, _z = terms[fi]
                rows, win = geom[fi]
                dma, off = term_dma(fi)
                dma.wait()
                src = win_refs[1 + fi][bank]      # [rows, win]
                # fractional-stride column map as chunked lane
                # gathers; the DMA-floor residual `off` rides in the
                # indices (no roll)
                sel_cols = []
                nchunks = win // 128
                for c2 in range(tile // 128):
                    jj = jax.lax.broadcasted_iota(
                        jnp.int32, (rows, 128), 1) + c2 * 128
                    idx = off + (jj * harm + (htot >> 1)) // htot
                    out = jnp.zeros((rows, 128), jnp.float32)
                    for c in range(nchunks):
                        g = jnp.take_along_axis(
                            src[:, c * 128:(c + 1) * 128],
                            jnp.clip(idx - c * 128, 0, 127), axis=1)
                        out = jnp.where(idx // 128 == c, g, out)
                    sel_cols.append(out)
                sel = jnp.concatenate(sel_cols, axis=1)  # [rows, tile]
                # exact bf16x3 split: hi+mid+lo == x bit-for-bit
                hi = sel.astype(jnp.bfloat16)
                r1 = sel - hi.astype(jnp.float32)
                mid = r1.astype(jnp.bfloat16)
                lo = (r1 - mid.astype(jnp.float32)).astype(jnp.bfloat16)
                stacked = jnp.concatenate([hi, mid, lo], axis=0)
                add = jax.lax.dot_general(
                    oh_refs[fi][...], stacked,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                acc_ref[:, :] = acc_ref[:, :] + add
                fi += 1
            collect(stage)

    @jax.jit
    def reduce_stages(P, start_cols):
        nslabs = start_cols.shape[0]
        gs = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nslabs, ntiles),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] +   # P (HBM)
                     [pl.BlockSpec(memory_space=pltpu.VMEM)] * nterms,
            out_specs=[
                pl.BlockSpec((1, nstages, tile),
                             lambda s, t, *_: (s, 0, t)),
                pl.BlockSpec((1, nstages, tile),
                             lambda s, t, *_: (s, 0, t)),
            ],
            scratch_shapes=[
                pltpu.VMEM((numz_pad, tile), jnp.float32),       # acc
                pltpu.VMEM((2, numz_pad, tile), jnp.float32),    # fund
            ] + [
                pltpu.VMEM((2, geom[i][0], geom[i][1]), jnp.float32)
                for i in range(nterms)
            ] + [
                pltpu.SemaphoreType.DMA((1 + nterms, 2)),
            ],
        )
        return pl.pallas_call(
            kernel,
            grid_spec=gs,
            out_shape=[
                jax.ShapeDtypeStruct((nslabs, nstages, slab),
                                     jnp.float32),
                jax.ShapeDtypeStruct((nslabs, nstages, slab),
                                     jnp.int32),
            ],
            interpret=interpret,
        )(start_cols, P, *onehots)

    return reduce_stages


def pad_rows(numz: int) -> int:
    """Rows the kernel-ready plane must have (8-sublane tiling)."""
    return -(-numz // 8) * 8


def scratch_bytes(fracs_zinds, numz: int, tile: int = None) -> int:
    """Static VMEM scratch estimate for make_stage_reducer (acc + the
    x2-parity window banks + the bf16 one-hot inputs) — callers gate
    on this instead of discovering a Mosaic scratch-allocation error
    at dispatch time (scratch scales with TILE and numz)."""
    tile = tile or TILE
    terms, _ = _stage_terms(fracs_zinds)
    numz_pad = pad_rows(numz)
    total = numz_pad * tile * 4                 # acc
    total += 2 * numz_pad * tile * 4            # fundamental banks
    for (h, t, zi) in terms:
        rows, win = _term_geom(h, t, zi, tile)
        total += 2 * rows * win * 4             # term window banks
        total += numz_pad * 3 * rows * 2        # oh3 (bf16, VMEM in)
    return total


# the TPU's scoped-vmem stack limit is 16 MB (measured: a 19.6 MB
# scratch set fails kernel compile); leave spill headroom
VMEM_BUDGET = 14 * 2 ** 20


def _tile_ok(fracs_zinds, numz: int, slab: int, t: int) -> bool:
    return (128 <= t <= slab and t % 128 == 0 and slab % t == 0
            and scratch_bytes(fracs_zinds, numz, t) <= VMEM_BUDGET)


def pick_tile(fracs_zinds, numz: int, slab: int):
    """The column tile for this kernel geometry.

    When tuning is active (SurveyConfig.tune / PRESTO_TPU_TUNE=1) a
    measured tile from the tuning DB wins, provided it still honors
    the alignment and scoped-VMEM contracts — a stale DB entry (new
    kernel source changes the fingerprint, but defend anyway) can
    degrade performance, never correctness.  Otherwise: the largest
    default tile whose scratch fits the budget (None when even the
    smallest doesn't — caller falls back to XLA)."""
    from presto_tpu import tune
    if tune.enabled():
        numharm = 1 << len(fracs_zinds)
        cfg = tune.best("accel_pallas_tile",
                        tune.key_accel_tile(numz, numharm, slab))
        if cfg:
            try:
                t = int(cfg.get("tile", 0))
            except (TypeError, ValueError):
                t = 0
            if _tile_ok(fracs_zinds, numz, slab, t):
                return t
    for t in (TILE, 512, 256):
        if _tile_ok(fracs_zinds, numz, slab, t):
            return t
    return None


def pallas_available() -> bool:
    """True when the default jax backend can run the TPU kernel."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False
