"""Batched Fourier-domain candidate refinement (the device polish).

The reference refines accelsearch candidates ONE AT A TIME on the host
(optimize_accelcand accel_utils.c:465-525 -> amoeba simplex
maximize_rz.c:22-140), every power evaluation building a fresh Fresnel
z-response kernel (rzinterp.c:144).  At survey sigma cutoffs that
serial loop dominates the whole low-zmax pass (the production
workhorse config): thousands of candidates x ~150 simplex evaluations
x a kernel build each.

TPU-first redesign — no Fresnel integrals, no per-candidate loop:

The z-response kernel is exactly the continuous matched filter

    R(d; z) = integral_0^1 exp(2 pi i (-d u + z (u^2 - u)/2)) du

(validated against ops/responses.gen_z_response to quadrature
accuracy; the (u^2-u)/2 form is the mid-observation-centered chirp of
responses.c:257's startr = roffset - z/2).  Therefore the interpolated
amplitude a candidate polish maximizes,

    A(r, z) = sum_m X[m] conj(R(m - r; z)),

is identically the time-domain dot product

    A(r, z) = integral_0^1 w(u) exp(-2 pi i (fr u + z (u^2-u)/2)) du,
    w(u)    = sum_|d|<W/2 X[rint + d] e^{2 pi i d u},   fr = r - rint.

w(u) — the band-limited chunk of the original time series carrying
the candidate — is computed ONCE per (candidate, harmonic) pair for
the whole batch (one complex matmul, MXU), after which every
refinement evaluation is an elementwise chirp multiply + mean over
npts quadrature points: fully batched over candidates, harmonics, and
trial (r, z) grids.

The optimizer itself is a fixed-shape coarse-to-fine grid descent
(jit-friendly: no data-dependent control flow): a (2G+1)^2 grid of
(r, z) steps scaled 1/numharm per candidate, re-centered on the joint
harmonic-sum argmax and shrunk 3x per stage.  Candidates whose coarse
stage pins to the grid boundary even after the re-center walk are
flagged; with PRESTO_TPU_POLISH_FALLBACK=1 (and a host complex
spectrum) they are re-polished one by one with the scipy simplex.
The fallback is OFF by default: boundary-pinned seeds are nearly
always noise candidates whose wander the reference's simplex shares,
and at survey scale the per-candidate referee costs more than the
whole batched polish.

Numerical note: A evaluated this way uses ALL W window taps for every
z, where the reference truncates the kernel at 2*hw(z) taps.  On a
candidate peak the difference is far inside the Fourier error bars
(tests pin |dr| <~ 0.01 bins vs the scipy path); it is a deliberate
accuracy upgrade, not drift.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from presto_tpu.ops import responses as resp
from presto_tpu.ops import stats as st
from presto_tpu.search.optimize import (FourierProps, OptimizedCand,
                                        RDerivs, calc_props,
                                        optimize_accelcand)

GRID_G = 3              # grid half-extent: (2G+1)^2 = 49 points/stage
GRID_GW = 2             # jerk descent: (2G+1)^2*(2GW+1) = 245/stage
N_STAGES = 5            # stage s step = step0 / 3^s
SHRINK = 3.0
STEP0_W = 5.0           # w step (fund bins; seed error <= ACCEL_DW/2)
# stage-0 steps in FUNDAMENTAL bins (scaled 1/numharm per candidate):
# the search grid quantizes r to 0.5/nh and z to 2/nh, so the true
# peak lies within (0.25, 1.0)/nh of the seed; G*step0 must cover it
STEP0_R = 0.12
STEP0_Z = 0.5
PAIR_CHUNK = 512        # pairs per lax.map slice of the grid evals


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


# ----------------------------------------------------------------------
# Device kernels
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("W", "npts"))
def _windows_to_wmat(amp_pairs, rints, W, npts, spec_of=None):
    """Gather each pair's W-tap spectral window and inverse-transform
    it to w(u) on the npts-point midpoint grid: ONE complex matmul
    for the whole batch.  Out-of-spectrum taps read zero (the same
    zero-fill as optimize.rz_interp's seg).

    amp_pairs [n, 2] for a single spectrum, or [ns, n, 2] with
    spec_of [P] selecting each pair's spectrum — the ONLY place the
    spectrum enters the polish pipeline, so the cross-trial batched
    path (optimize_accelcands_batched) differs from the single-trial
    path by this gather alone."""
    n = amp_pairs.shape[-2]
    dl = jnp.arange(W, dtype=jnp.int32) - W // 2
    idx = rints[:, None] + dl[None]
    ok = (idx >= 0) & (idx < n)
    cidx = jnp.clip(idx, 0, n - 1)
    if amp_pairs.ndim == 3:
        seg = amp_pairs[spec_of[:, None], cidx]     # [P, W, 2]
    else:
        seg = amp_pairs[cidx]                       # [P, W, 2]
    segc = jnp.where(ok, seg[..., 0] + 1j * seg[..., 1], 0.0)
    u = (jnp.arange(npts, dtype=jnp.float32) + 0.5) / npts
    F = jnp.exp(2j * jnp.pi * jnp.outer(dl.astype(jnp.float32), u))
    return jnp.matmul(segc, F,
                      precision=jax.lax.Precision.HIGHEST)  # [P, npts]


def _eval_A(wmat, fr, zh, wh=None):
    """A at (fr, z[, w]) per pair and grid point: wmat [P, npts]
    complex, fr/zh[/wh] [P, G] -> [P, G] complex64 (chirp multiply +
    mean).  The w term is the jerk phase w*(u^3/6 - u^2/4 + u/12) —
    the time-domain twin of gen_w_response's cubic phase model
    (validated against ops/responses to the same window-truncation
    tolerance as the z term)."""
    npts = wmat.shape[-1]
    u = (jnp.arange(npts, dtype=jnp.float32) + 0.5) / npts
    cu = 0.5 * (u * u - u)
    phase = fr[..., None] * u + zh[..., None] * cu
    if wh is not None:
        p3 = u * u * u / 6.0 - u * u / 4.0 + u / 12.0
        phase = phase + wh[..., None] * p3
    ph = jnp.exp(-2j * jnp.pi * phase)
    return jnp.mean(wmat[:, None, :] * ph, axis=-1)


def _eval_A_chunked(wmat, fr, zh, wh=None):
    """_eval_A with the pair axis chunked through lax.map (bounds the
    [P, G, npts] phase intermediate)."""
    P = wmat.shape[0]
    if P <= PAIR_CHUNK:
        return _eval_A(wmat, fr, zh, wh)
    pad = _round_up(P, PAIR_CHUNK) - P
    nch = (P + pad) // PAIR_CHUNK

    def prep(a):
        return jnp.pad(a, ((0, pad), (0, 0))).reshape(
            nch, PAIR_CHUNK, -1)

    if wh is None:
        out = jax.lax.map(
            lambda args: _eval_A(*args),
            (prep(wmat), prep(fr), prep(zh)))
    else:
        out = jax.lax.map(
            lambda args: _eval_A(*args),
            (prep(wmat), prep(fr), prep(zh), prep(wh)))
    return out.reshape(nch * PAIR_CHUNK, -1)[:P]


@partial(jax.jit, static_argnames=("ncand",))
def _refine_stages(wmat, cand_of, hh, frac0, zseed, inv_lp,
                   obj_w, step0_r, step0_z, ncand):
    """The coarse-to-fine joint-harmonic grid descent, entirely in
    OFFSET space: the device never sees an absolute r (float32 spacing
    at survey-scale r*h ~ 1e8 is several BINS — all absolute
    reconstruction happens on host in float64).

    wmat [P, npts]; cand_of [P] pair->candidate; hh [P] harmonic
    number; frac0 [P] = seed_r*h - rint (float64 residual, cast f32);
    zseed [ncand]; inv_lp [P] 1/locpow objective weights; obj_w [P]
    0/1 mask (harmpolish=False keeps only the fundamental in the
    objective); step0_* [ncand].

    Returns (dr, dz) [ncand] fundamental offsets from the seed and a
    boundary flag [ncand] (stage-0 argmax pinned to the grid edge
    after the re-center walk).
    """
    G = GRID_G
    g1 = jnp.arange(-G, G + 1, dtype=jnp.float32)
    gi = jnp.repeat(g1, 2 * G + 1)        # r offsets
    gj = jnp.tile(g1, 2 * G + 1)          # z offsets

    def stage_argmax(dr, dz, sr, sz):
        # trial offset grids per candidate -> per pair fr/z
        rs = dr[:, None] + sr[:, None] * gi[None]   # [ncand, ngrid2]
        zs = dz[:, None] + sz[:, None] * gj[None]
        frp = frac0[:, None] + rs[cand_of] * hh[:, None]
        zhp = (zseed[cand_of][:, None] + zs[cand_of]) * hh[:, None]
        A = _eval_A_chunked(wmat, frp, zhp)
        P2 = (A.real ** 2 + A.imag ** 2) * (inv_lp * obj_w)[:, None]
        obj = jax.ops.segment_sum(P2, cand_of, num_segments=ncand)
        best = jnp.argmax(obj, axis=-1)
        return (rs[jnp.arange(ncand), best],
                zs[jnp.arange(ncand), best], best)

    dr = jnp.zeros(ncand, jnp.float32)
    dz = jnp.zeros(ncand, jnp.float32)
    # stage-0 walk: re-center twice at the coarse step so a seed near
    # the cell edge still captures its peak
    edge = jnp.zeros(ncand, dtype=bool)
    for _ in range(2):
        dr, dz, best = stage_argmax(dr, dz, step0_r, step0_z)
        bi, bj = best // (2 * G + 1), best % (2 * G + 1)
        edge = (bi == 0) | (bi == 2 * G) | (bj == 0) | (bj == 2 * G)
    for s in range(1, N_STAGES):
        sr = step0_r / (SHRINK ** s)
        sz = step0_z / (SHRINK ** s)
        dr, dz, _ = stage_argmax(dr, dz, sr, sz)
    return dr, dz, edge


@jax.jit
def _final_measures(wmat, fr, zh):
    """Per-pair measurements at the refined peak, one dispatch:
    columns = [raw amp, d/dr stencil lo/hi, locpow offsets].
    Returns (A [P, 3] complex for (mid, lo, hi), locpow [P])."""
    H = resp.NUMLOCPOWAVG // 2
    offs = np.concatenate([[0.0, -0.05, 0.05],
                           -(resp.DELTAAVGBINS + np.arange(H)),
                           (resp.DELTAAVGBINS + np.arange(H))]
                          ).astype(np.float32)
    frg = fr[:, None] + jnp.asarray(offs)[None]
    zhg = jnp.broadcast_to(zh[:, None], frg.shape)
    A = _eval_A_chunked(wmat, frg, zhg)
    pows = A.real ** 2 + A.imag ** 2
    locpow = jnp.maximum(jnp.mean(pows[:, 3:], axis=-1), 1e-30)
    # pairs at the boundary: complex cannot cross host<->device here
    return jnp.stack([A[:, :3].real, A[:, :3].imag], -1), locpow


# ----------------------------------------------------------------------
# Host driver
# ----------------------------------------------------------------------


def _geometry(zmax_pairs: float):
    """(W, npts) for a batch whose largest per-harmonic |z| (including
    grid drift) is zmax_pairs: the window spans the widest kernel plus
    the locpow offsets, quadrature resolves W/2 + z/2 + 1 cycles."""
    hw = resp.z_resp_halfwidth(float(zmax_pairs), resp.HIGHACC)
    W = _round_up(2 * hw + 2 * (resp.DELTAAVGBINS
                                + resp.NUMLOCPOWAVG // 2) + 16, 128)
    need = W // 2 + zmax_pairs / 2 + 2
    npts = 128
    while npts < 2 * need:
        npts *= 2
    return W, int(npts)


def optimize_accelcands(amps: np.ndarray, cands, T: float,
                        numindep: Sequence[float],
                        harmpolish: bool = True,
                        with_props: bool = True,
                        spec_of=None) -> List[OptimizedCand]:
    """Batched twin of optimize_accelcand over a candidate list.

    amps: complex spectrum (numpy, any float/complex dtype) or a
    device [n, 2] float32 pairs array (the survey's resident spectra)
    — or a STACK of spectra [ns, n, 2] with spec_of [len(cands)]
    selecting each candidate's spectrum (the cross-trial batched
    regime; use optimize_accelcands_batched for the list-of-lists
    API).  Returns OptimizedCand per input candidate, in input order;
    scipy fallback per candidate where the grid descent flags a
    boundary (single-spectrum host input only).
    (optimize_jerk_cands mirrors this driver with a w dimension —
    keep shared-logic fixes in sync.)
    """
    if not cands:
        return []
    amps_host = None        # complex host spectrum (scipy fallback)
    if isinstance(amps, jax.Array):
        amp_pairs = amps
    else:
        amps = np.asarray(amps)
        if amps.dtype.kind == "c":
            amp_pairs = np.stack([amps.real, amps.imag],
                                 -1).astype(np.float32)
            if spec_of is None:
                amps_host = amps
        else:
            amp_pairs = np.asarray(amps, np.float32)
        amp_pairs = jnp.asarray(amp_pairs)
    assert (spec_of is None) == (amp_pairs.ndim == 2), \
        "spec_of required iff amps is a [ns, n, 2] stack"

    nc = len(cands)
    nh = np.asarray([c.numharm for c in cands], np.int32)
    seed_r = np.asarray([c.r for c in cands], np.float64)
    seed_z = np.asarray([c.z for c in cands], np.float64)

    # pair expansion (candidate, harmonic)
    cand_of = np.repeat(np.arange(nc, dtype=np.int32), nh)
    hh = np.concatenate([np.arange(1, n + 1) for n in nh]
                        ).astype(np.float32)
    rint = np.floor(seed_r[cand_of] * hh).astype(np.int32)
    P = cand_of.shape[0]

    step0_r = (STEP0_R / nh).astype(np.float32)
    step0_z = (STEP0_Z / nh).astype(np.float32)
    zmax_b = float(np.abs(seed_z[cand_of] * hh).max()
                   + STEP0_Z * GRID_G + 1.0)
    W, npts = _geometry(zmax_b)

    # pad pairs/cands to bucket shapes (bounded recompile count)
    Pp = max(64, 1 << int(np.ceil(np.log2(P))))
    ncp = max(32, 1 << int(np.ceil(np.log2(nc))))
    pad_p, pad_c = Pp - P, ncp - nc

    def padp(a, fill=0):
        return np.concatenate([a, np.full((pad_p,) + a.shape[1:], fill,
                                          a.dtype)]) if pad_p else a

    def padc(a, fill=0):
        return np.concatenate([a, np.full((pad_c,) + a.shape[1:], fill,
                                          a.dtype)]) if pad_c else a

    cand_ofp = padp(cand_of, nc)          # dummy pairs -> pad segment
    cand_ofp = np.where(cand_ofp >= ncp, ncp - 1, cand_ofp)
    hhp, rintp = padp(hh, 1.0), padp(rint, 0)
    spec_p = None
    if spec_of is not None:
        spec_p = jnp.asarray(padp(
            np.asarray(spec_of, np.int32)[cand_of], 0))
    # float64 residual of the absolute frequency: everything the
    # device sees is seed-relative (float32 cannot hold survey-scale
    # absolute r*h to bin precision)
    frac0 = (seed_r[cand_of] * hh.astype(np.float64)
             - rint).astype(np.float32)
    frac0p = padp(frac0, 0.5)
    seed_zp = padc(seed_z.astype(np.float32), 0.0)
    s0rp, s0zp = padc(step0_r, STEP0_R), padc(step0_z, STEP0_Z)

    wmat = _windows_to_wmat(amp_pairs, jnp.asarray(rintp), W, npts,
                            spec_of=spec_p)

    # seed local powers -> objective weights (fixed during descent,
    # like the scipy path's pre-refinement locpows)
    fr0 = jnp.asarray(frac0p)
    zh0 = jnp.asarray(seed_zp[cand_ofp] * hhp)
    _, lp0 = _final_measures(wmat, fr0, zh0)
    obj_w = padp(np.ones(P, np.float32)) if harmpolish else \
        padp((hh == 1.0).astype(np.float32))

    drc, dzc, edge = _refine_stages(
        wmat, jnp.asarray(cand_ofp), jnp.asarray(hhp),
        jnp.asarray(frac0p), jnp.asarray(seed_zp),
        1.0 / lp0, jnp.asarray(obj_w), jnp.asarray(s0rp),
        jnp.asarray(s0zp), ncp)

    drp = np.asarray(drc, np.float64)
    dzp = np.asarray(dzc, np.float64)
    rr = seed_r + drp[:nc]                # float64 reconstruction
    zz = seed_z + dzp[:nc]
    edge = np.asarray(edge)[:nc]

    # final measurements at the refined peak (padded shapes; the
    # fractional part is computed in float64 then cast)
    rrp = np.concatenate([rr, np.full(pad_c, 8.0)]) if pad_c else rr
    zzp = np.concatenate([zz, np.zeros(pad_c)]) if pad_c else zz
    frf = jnp.asarray((rrp[cand_ofp] * hhp.astype(np.float64)
                       - rintp).astype(np.float32))
    zhf = jnp.asarray((zzp[cand_ofp] * hhp).astype(np.float32))
    A3p, lpf = _final_measures(wmat, frf, zhf)
    A3p = np.asarray(A3p)[:P]
    A3 = A3p[..., 0].astype(np.complex128) + 1j * A3p[..., 1]
    lpf = np.asarray(lpf, np.float64)[:P]
    rawp = (A3[:, 0].real ** 2 + A3[:, 0].imag ** 2).astype(np.float64)
    hpow = rawp / lpf

    out: List[Optional[OptimizedCand]] = [None] * nc
    tot = np.zeros(nc)
    np.add.at(tot, cand_of, hpow)
    stages = np.log2(nh).astype(int)
    sig = np.empty(nc, np.float64)
    for s_ in np.unique(stages):      # one vectorized call per stage
        m = stages == s_
        sig[m] = np.atleast_1d(st.candidate_sigma(
            tot[m], 1 << int(s_), numindep[int(s_)]))

    # Edge-pinned candidates (stage-0 argmax on the grid boundary even
    # after the re-center walk) are almost always NOISE seeds whose
    # local max sits outside the quantization error bounds — the
    # reference's simplex wanders on those too, and they die in
    # sifting.  The scipy referee per edge candidate is therefore
    # opt-in (PRESTO_TPU_POLISH_FALLBACK=1): at survey scale it costs
    # ~70 ms x thousands of noise candidates for no list change.
    import os as _os
    fb_requested = _os.environ.get("PRESTO_TPU_POLISH_FALLBACK",
                                   "0") == "1"
    use_fb = fb_requested and amps_host is not None
    if fb_requested and amps_host is None and np.any(edge):
        # the requested scipy referee NEEDS the host spectrum: with a
        # device-resident pairs array it cannot run — say so rather
        # than silently skipping the opt-in (ADVICE r4)
        import warnings
        warnings.warn(
            "PRESTO_TPU_POLISH_FALLBACK=1 but the spectrum is device-"
            "resident (no host amps): %d edge-pinned candidate(s) "
            "keep their batched-grid values; pass a NumPy spectrum "
            "to enable the scipy referee" % int(np.sum(edge)))

    pair_lo = np.concatenate([[0], np.cumsum(nh)])
    for i in range(nc):
        if use_fb and edge[i]:
            out[i] = optimize_accelcand(amps_host, cands[i], T,
                                        numindep,
                                        harmpolish=harmpolish)
            continue
        sl = slice(pair_lo[i], pair_lo[i + 1])
        props: List[FourierProps] = []
        if with_props:
            for j in range(pair_lo[i], pair_lo[i + 1]):
                h = hh[j]
                pw = lambda a: (a.real ** 2 + a.imag ** 2) / lpf[j]
                amid, alo, ahi = A3[j]
                pm, pl, ph_ = pw(amid), pw(alo), pw(ahi)
                phm = float(np.angle(amid))
                phl = phm + float(np.angle(alo * np.conj(amid)))
                phh = phm + float(np.angle(ahi * np.conj(amid)))
                hstep = 0.05
                d = RDerivs(
                    pow=pm, phs=phm,
                    dpow=(ph_ - pl) / (2 * hstep),
                    dphs=(phh - phl) / (2 * hstep),
                    d2pow=(ph_ - 2 * pm + pl) / hstep ** 2,
                    d2phs=(phh - 2 * phm + phl) / hstep ** 2,
                    locpow=lpf[j])
                props.append(calc_props(d, rr[i] * h, zz[i] * h))
        out[i] = OptimizedCand(
            r=float(rr[i]), z=float(zz[i]), power=float(tot[i]),
            sigma=float(sig[i]), numharm=int(nh[i]),
            hpows=list(hpow[sl]), props=props)
    return out


# ----------------------------------------------------------------------
# Jerk (r, z, w) polish
# ----------------------------------------------------------------------


def optimize_accelcands_batched(amps_batch, cands_lists, T: float,
                                numindep: Sequence[float],
                                harmpolish: bool = True,
                                with_props: bool = False
                                ) -> List[List[OptimizedCand]]:
    """Cross-TRIAL batched polish: every trial's candidates refined
    against its OWN spectrum in ONE device pipeline (VERDICT r4 weak
    #3: per-trial polish calls each pay the link's ~120 ms dispatch
    floor, which dominated the survey's amortized per-trial cost —
    the spectrum index rides the window gather, everything downstream
    is already candidate-batched).

    amps_batch: [ns, numbins, 2] float32 (device or numpy — same-
    length spectra, the survey DM fan-out).  cands_lists: per-trial
    candidate lists.  Returns per-trial OptimizedCand lists.
    Equal to per-trial optimize_accelcands calls whenever the pooled
    window geometry lands in the same (W, npts) bucket as each trial
    alone would pick (_geometry buckets on max |z*h| — true for the
    homogeneous z ranges of a survey fan-out, pinned by
    tests/test_polish.py); a trial whose own z range is far below the
    pool's may get a wider window, which is a still-valid refinement
    with slightly different rounding."""
    all_cands = [c for cl in cands_lists for c in cl]
    if not all_cands:
        return [[] for _ in cands_lists]
    if not isinstance(amps_batch, jax.Array):
        amps_batch = jnp.asarray(np.asarray(amps_batch, np.float32))
    spec_of = np.concatenate(
        [np.full(len(cl), i, np.int32)
         for i, cl in enumerate(cands_lists)])
    ocs = optimize_accelcands(amps_batch, all_cands, T, numindep,
                              harmpolish=harmpolish,
                              with_props=with_props, spec_of=spec_of)
    out, k = [], 0
    for cl in cands_lists:
        out.append(ocs[k:k + len(cl)])
        k += len(cl)
    return out


@jax.jit
def _eval_A_rzw_pairs(wmat, fr, zh, wh):
    """Jitted (re, im)-pair boundary around _eval_A_chunked for the
    eager final-measure call: standalone eager complex ops fail to
    compile on the axon backend (complex must stay INSIDE jit)."""
    A = _eval_A_chunked(wmat, fr, zh, wh)
    return jnp.stack([A.real, A.imag], -1)


@partial(jax.jit, static_argnames=("ncand",))
def _refine_stages_rzw(wmat, cand_of, hh, frac0, zseed, wseed, inv_lp,
                       obj_w, step0_r, step0_z, step0_w, ncand):
    """3-D twin of _refine_stages: coarse-to-fine (r, z, w) grid
    descent in offset space.  The w seed is the jerk plane of origin
    (ACCEL_DW grid), so the stage-0 w radius only needs to cover half
    a plane step."""
    G, GW = GRID_G, GRID_GW
    g1 = jnp.arange(-G, G + 1, dtype=jnp.float32)
    gw = jnp.arange(-GW, GW + 1, dtype=jnp.float32)
    n2d = (2 * G + 1) ** 2
    gi = jnp.tile(jnp.repeat(g1, 2 * G + 1), 2 * GW + 1)
    gj = jnp.tile(jnp.tile(g1, 2 * G + 1), 2 * GW + 1)
    gk = jnp.repeat(gw, n2d)

    def stage_argmax(dr, dz, dw, sr, sz, sw):
        rs = dr[:, None] + sr[:, None] * gi[None]
        zs = dz[:, None] + sz[:, None] * gj[None]
        ws = dw[:, None] + sw[:, None] * gk[None]
        frp = frac0[:, None] + rs[cand_of] * hh[:, None]
        zhp = (zseed[cand_of][:, None] + zs[cand_of]) * hh[:, None]
        whp = (wseed[cand_of][:, None] + ws[cand_of]) * hh[:, None]
        A = _eval_A_chunked(wmat, frp, zhp, whp)
        P2 = (A.real ** 2 + A.imag ** 2) * (inv_lp * obj_w)[:, None]
        obj = jax.ops.segment_sum(P2, cand_of, num_segments=ncand)
        best = jnp.argmax(obj, axis=-1)
        ar = jnp.arange(ncand)
        return rs[ar, best], zs[ar, best], ws[ar, best]

    dr = jnp.zeros(ncand, jnp.float32)
    dz = jnp.zeros(ncand, jnp.float32)
    dw = jnp.zeros(ncand, jnp.float32)
    for _ in range(2):                       # stage-0 re-center walk
        dr, dz, dw = stage_argmax(dr, dz, dw, step0_r, step0_z,
                                  step0_w)
    for s in range(1, N_STAGES):
        dr, dz, dw = stage_argmax(
            dr, dz, dw, step0_r / (SHRINK ** s),
            step0_z / (SHRINK ** s), step0_w / (SHRINK ** s))
    return dr, dz, dw


def optimize_jerk_cands(amps, cands, T: float,
                        numindep: Sequence[float],
                        harmpolish: bool = True
                        ) -> List[OptimizedCand]:
    """Batched (r, z, w) refinement for jerk-search candidates — the
    device twin of the max_rzw_arr per-candidate simplex, whose every
    power evaluation rebuilds a w-response quadrature (~0.2-0.5 s per
    EVALUATION on host: minutes per candidate).  Seeds come from the
    search (w = the jerk plane of origin, fundamental-scaled);
    per-harmonic local powers follow the scipy acceptance convention
    (measured at w=0, refine_and_write's jerk branch).  Returns
    OptimizedCand per input, in order, with .w set.

    MAINTENANCE NOTE: the host driver below (pairs conversion, pair
    expansion, bucket padding, sigma loop) intentionally mirrors
    optimize_accelcands' — a fix to the shared logic there (padding
    collisions, locpow convention, float64 offset bookkeeping) must
    be applied HERE too."""
    if not cands:
        return []
    if isinstance(amps, jax.Array):
        amp_pairs = amps
    else:
        amps = np.asarray(amps)
        if amps.dtype.kind == "c":
            amp_pairs = np.stack([amps.real, amps.imag],
                                 -1).astype(np.float32)
        else:
            amp_pairs = np.asarray(amps, np.float32)
        amp_pairs = jnp.asarray(amp_pairs)

    nc = len(cands)
    nh = np.asarray([c.numharm for c in cands], np.int32)
    seed_r = np.asarray([c.r for c in cands], np.float64)
    seed_z = np.asarray([c.z for c in cands], np.float64)
    seed_w = np.asarray([getattr(c, "w", 0.0) for c in cands],
                        np.float64)
    cand_of = np.repeat(np.arange(nc, dtype=np.int32), nh)
    hh = np.concatenate([np.arange(1, n + 1) for n in nh]
                        ).astype(np.float32)
    rint = np.floor(seed_r[cand_of] * hh).astype(np.int32)
    P = cand_of.shape[0]
    step0_r = (STEP0_R / nh).astype(np.float32)
    step0_z = (STEP0_Z / nh).astype(np.float32)
    step0_w = (STEP0_W / nh).astype(np.float32)

    # window geometry must cover the widest (z, w) kernel in the batch
    zmax_b = float(np.abs(seed_z[cand_of] * hh).max()
                   + STEP0_Z * GRID_G + 1.0)
    wmax_b = float(np.abs(seed_w[cand_of] * hh).max()
                   + STEP0_W * GRID_GW + 1.0)
    hw = resp.w_resp_halfwidth(zmax_b, wmax_b, resp.HIGHACC)
    W = _round_up(2 * hw + 2 * (resp.DELTAAVGBINS
                                + resp.NUMLOCPOWAVG // 2) + 16, 128)
    need = W // 2 + zmax_b / 2 + wmax_b / 12.0 + 2
    npts = 128
    while npts < 2 * need:
        npts *= 2

    Pp = max(64, 1 << int(np.ceil(np.log2(P))))
    ncp = max(32, 1 << int(np.ceil(np.log2(nc))))
    pad_p, pad_c = Pp - P, ncp - nc

    def padp(a, fill=0):
        return np.concatenate([a, np.full((pad_p,) + a.shape[1:],
                                          fill, a.dtype)]) \
            if pad_p else a

    def padc(a, fill=0):
        return np.concatenate([a, np.full((pad_c,) + a.shape[1:],
                                          fill, a.dtype)]) \
            if pad_c else a

    cand_ofp = padp(cand_of, nc)
    cand_ofp = np.where(cand_ofp >= ncp, ncp - 1, cand_ofp)
    hhp, rintp = padp(hh, 1.0), padp(rint, 0)
    frac0 = (seed_r[cand_of] * hh.astype(np.float64)
             - rint).astype(np.float32)
    frac0p = padp(frac0, 0.5)
    seed_zp = padc(seed_z.astype(np.float32), 0.0)
    seed_wp = padc(seed_w.astype(np.float32), 0.0)
    s0rp = padc(step0_r, STEP0_R)
    s0zp = padc(step0_z, STEP0_Z)
    s0wp = padc(step0_w, STEP0_W)

    wmat = _windows_to_wmat(amp_pairs, jnp.asarray(rintp), W, npts)
    # locpow at the seed, w=0 (the jerk acceptance convention)
    _, lp0 = _final_measures(
        wmat, jnp.asarray(frac0p),
        jnp.asarray(seed_zp[cand_ofp] * hhp))
    obj_w = padp(np.ones(P, np.float32)) if harmpolish else \
        padp((hh == 1.0).astype(np.float32))

    drc, dzc, dwc = _refine_stages_rzw(
        wmat, jnp.asarray(cand_ofp), jnp.asarray(hhp),
        jnp.asarray(frac0p), jnp.asarray(seed_zp),
        jnp.asarray(seed_wp), 1.0 / lp0, jnp.asarray(obj_w),
        jnp.asarray(s0rp), jnp.asarray(s0zp), jnp.asarray(s0wp), ncp)

    rr = seed_r + np.asarray(drc, np.float64)[:nc]
    zz = seed_z + np.asarray(dzc, np.float64)[:nc]
    ww = seed_w + np.asarray(dwc, np.float64)[:nc]

    # raw powers at the refined (r, z, w); locpow at (r, z), w=0
    rrp = np.concatenate([rr, np.full(pad_c, 8.0)]) if pad_c else rr
    zzp = np.concatenate([zz, np.zeros(pad_c)]) if pad_c else zz
    wwp = np.concatenate([ww, np.zeros(pad_c)]) if pad_c else ww
    frf = jnp.asarray((rrp[cand_ofp] * hhp.astype(np.float64)
                       - rintp).astype(np.float32))
    zhf = jnp.asarray((zzp[cand_ofp] * hhp).astype(np.float32))
    whf = jnp.asarray((wwp[cand_ofp] * hhp).astype(np.float32))
    Afp = np.asarray(_eval_A_rzw_pairs(
        wmat, frf[:, None], zhf[:, None], whf[:, None]))
    rawp = (Afp[..., 0] ** 2 + Afp[..., 1] ** 2)[:P, 0].astype(
        np.float64)
    _, lpf = _final_measures(wmat, frf, zhf)
    lpf = np.asarray(lpf, np.float64)[:P]
    hpow = rawp / lpf

    tot = np.zeros(nc)
    np.add.at(tot, cand_of, hpow)
    stages = np.log2(nh).astype(int)
    sig = np.empty(nc, np.float64)
    for s_ in np.unique(stages):
        m = stages == s_
        sig[m] = np.atleast_1d(st.candidate_sigma(
            tot[m], 1 << int(s_), numindep[int(s_)]))

    pair_lo = np.concatenate([[0], np.cumsum(nh)])
    return [OptimizedCand(
        r=float(rr[i]), z=float(zz[i]), power=float(tot[i]),
        sigma=float(sig[i]), numharm=int(nh[i]),
        hpows=list(hpow[pair_lo[i]:pair_lo[i + 1]]), w=float(ww[i]))
        for i in range(nc)]
