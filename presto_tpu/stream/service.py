"""presto-stream: the live FRB/single-pulse trigger service.

Glues the streaming stack to the serving layer so one resident
process carries BOTH workload classes: batch survey jobs ride the
serve scheduler's throughput lane exactly as before, while the live
feed's blocks are processed by *deadline-lane* tick jobs that always
pop first — a queued backlog of surveys can no longer starve the
trigger path (serve/queue.Lanes; there is no preemption, so the
deadline SLO floor is the longest single survey stage).

Data path:  producer (socket / file tail)  ->  RingBlockSource
(bounded, drop-accounted, quarantine via io/quality)  ->  StreamSearch
(rolling dedispersion + incremental single-pulse search)  ->  triggers
on serve's /events feed (monotonic cursor, heartbeat — a dropped
subscriber resumes with ?since=<cursor> losing nothing).

Every trigger observes `stream_latency_seconds`: wall time from the
arrival of the block that *enabled* the trigger (the newest samples
its finalization needed, queue wait included) to the event emission —
the end-to-end number the latency budget in docs/STREAMING.md is
written against.
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import threading
import time
from collections import deque
from typing import List, Optional

from presto_tpu.stream.rolling import StreamConfig, StreamSearch
from presto_tpu.stream.source import (FileTailProducer,
                                      RingBlockSource, SocketProducer,
                                      StreamBlock)

#: stream_latency_seconds buckets: trigger paths live in the
#: 10ms..10s decades, not the default request-latency spread
LATENCY_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0)


class StreamService:
    """One live feed attached to a SearchService.

    A pump thread moves blocks from the ring into an inbox and keeps
    at most ONE deadline-lane tick job outstanding; the tick (on the
    scheduler thread, where all device work lives) drains the inbox,
    runs the rolling search, and emits triggers.  The single
    outstanding tick is what lets force-submission bypass the queue
    depth bound without unbounded growth.
    """

    def __init__(self, service, source: RingBlockSource,
                 cfg: StreamConfig, stream_id: str = "stream-0"):
        self.service = service
        self.source = source
        self.cfg = cfg
        self.stream_id = stream_id
        self.obs = service.obs
        self.events = service.events
        self.engine: Optional[StreamSearch] = None
        self._inbox: deque = deque()
        self._inbox_lock = threading.Lock()
        self._tick_out = False          # a tick job is outstanding
        self._tick_ids = itertools.count(1)
        self._pump: Optional[threading.Thread] = None
        self._done = threading.Event()
        self._failed: Optional[BaseException] = None
        self._quar_seen = 0             # quality spectra already routed
        self._drops_seen = 0
        self._cands_seen = 0
        self._routed: set = set()       # quarantine intervals routed
        reg = self.obs.metrics
        self._c_blocks = reg.counter(
            "stream_blocks_total", "Live-feed blocks processed")
        self._c_cands = reg.counter(
            "stream_candidates_total",
            "Finalized single-pulse candidates (pre-dedup)")
        self._c_trigs = reg.counter(
            "stream_triggers_total", "Deduplicated triggers emitted")
        self._c_drops = reg.counter(
            "stream_drops_total",
            "Blocks shed under ring backpressure (all quarantined)")
        self._c_gap = reg.counter(
            "stream_gap_spectra_total",
            "Spectra quarantined on the live feed (drops, stalls, "
            "truncation, zero fill)")
        self._g_backlog = reg.gauge(
            "stream_backlog_blocks", "Ring blocks awaiting the search")
        # `beam` label: "-" for a single-beam stream; the beam
        # multiplexer (stream/beams.py) shares this family with one
        # series per beam so latency is attributable per beam
        self._h_latency = reg.histogram(
            "stream_latency_seconds",
            "Sample arrival -> trigger emitted", ("stream", "beam"),
            buckets=LATENCY_BUCKETS)

    # ---- lifecycle ----------------------------------------------------

    def start(self) -> "StreamService":
        self._pump = threading.Thread(
            target=self._pump_loop, name="presto-stream-pump",
            daemon=True)
        self._pump.start()
        return self

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the stream is fully processed (EOF + flush)."""
        return self._done.wait(timeout)

    @property
    def failed(self) -> Optional[BaseException]:
        return self._failed

    # ---- pump thread --------------------------------------------------

    def _pump_loop(self) -> None:
        try:
            hdr = self.source.wait_header()
            if hdr is None:             # producer died before header
                raise RuntimeError("stream ended before a header")
            self.engine = StreamSearch(hdr, self.cfg, obs=self.obs)
            self.source.configure(self.engine.blocklen)
            self.events.emit(
                "stream-start", stream=self.stream_id,
                nchan=hdr.nchans, tsamp=hdr.tsamp,
                blocklen=self.engine.blocklen,
                numdms=self.cfg.numdms, maxd=self.engine.maxd)
            while True:
                blk = self.source.next_block(timeout=0.25)
                self._g_backlog.set(self.source.backlog)
                if blk is None:
                    if self.source.at_eof:
                        break
                    continue
                self._enqueue(blk)
            self._enqueue(None)         # EOF sentinel
        except BaseException as e:
            self._failed = e
            self._done.set()

    def _enqueue(self, item: Optional[StreamBlock]) -> None:
        with self._inbox_lock:
            self._inbox.append(item)
            if self._tick_out:
                return
            self._tick_out = True
        self.service.submit_callable(
            self._tick, lane="deadline",
            job_id="%s-tick-%06d" % (self.stream_id,
                                     next(self._tick_ids)),
            bucket=("stream", self.stream_id))

    # ---- tick (scheduler thread) --------------------------------------

    def _tick(self, job) -> dict:
        """Drain the inbox: all pending blocks (and possibly the EOF
        flush) in one deadline-lane execution."""
        processed = 0
        triggers = 0
        while True:
            with self._inbox_lock:
                if not self._inbox:
                    # clearing the flag under the same lock _enqueue
                    # takes closes the strand race: a block arriving
                    # after release sees _tick_out False and submits
                    self._tick_out = False
                    break
                item = self._inbox.popleft()
            if item is None:
                triggers += self._finish()
                continue
            span = self.obs.span("stream:block", stream=self.stream_id,
                                 seq=item.seq)
            try:
                self._route_quarantine(item)
                trigs = self.engine.feed_block(item.data, item.nreal)
                self._c_blocks.inc()
                processed += 1
                triggers += self._emit(trigs, item.t_arrival)
            finally:
                span.finish()
        return {"stream": self.stream_id, "blocks": processed,
                "triggers": triggers}

    def _route_quarantine(self, blk: StreamBlock) -> None:
        """Ring drops arrive as synthesized zero blocks carrying their
        interval; everything else (stall fill, truncation, NaN scrub,
        zero runs) lands in the source's quality ledger — route both
        into the engine's offregions and the stream counters."""
        for reason, lo, hi in blk.quarantined:
            self.engine.note_quarantine(lo, hi)
        stats = self.source.stats()
        if stats["dropped_blocks"] > self._drops_seen:
            delta = stats["dropped_blocks"] - self._drops_seen
            self._drops_seen = stats["dropped_blocks"]
            self._c_drops.inc(delta)
            self.events.emit("stream-drop", stream=self.stream_id,
                             blocks=delta,
                             total=stats["dropped_blocks"])
        q = self.source.quality
        if q is None:
            return
        frontier = (blk.seq + 1) * self.engine.blocklen
        fresh = {}
        for iv in q.intervals:
            key = (iv.start, iv.stop, iv.reason)
            if iv.start < frontier and key not in self._routed:
                self._routed.add(key)
                self.engine.note_quarantine(iv.start,
                                            min(iv.stop, frontier))
                fresh[iv.reason] = fresh.get(iv.reason, 0) \
                    + min(iv.stop, frontier) - iv.start
        bad = q.bad_spectra()
        if bad > self._quar_seen:
            self._c_gap.inc(bad - self._quar_seen)
            self._quar_seen = bad
        if fresh:
            self.events.emit("stream-quarantine",
                             stream=self.stream_id, intervals=fresh)

    def _emit(self, trigs: List, t_arrival: float) -> int:
        now = time.time()
        for tr in trigs:
            tr.latency_s = max(now - t_arrival, 0.0)
            self._h_latency.labels(stream=self.stream_id,
                                   beam="-").observe(tr.latency_s)
            self._c_trigs.inc()
            self.events.emit("trigger", stream=self.stream_id,
                             **tr.to_json())
        new = self.engine.candidates - self._cands_seen
        if new > 0:
            self._c_cands.inc(new)
            self._cands_seen = self.engine.candidates
        return len(trigs)

    def _finish(self) -> int:
        t_eof = time.time()
        trigs = self.engine.finish()
        n = self._emit(trigs, t_eof)
        self.events.emit("stream-eof", stream=self.stream_id,
                         **self.engine.summary())
        self._done.set()
        return n

    # ---- views --------------------------------------------------------

    def summary(self) -> dict:
        out = {
            "stream": self.stream_id,
            "source": self.source.stats(),
        }
        if self.engine is not None:
            out["engine"] = self.engine.summary()
            out["latency"] = self._h_latency.labels(
                stream=self.stream_id,
                beam="-").percentiles((50, 90, 99))
        return out


# ----------------------------------------------------------------------
# presto-stream CLI
# ----------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="presto-stream",
        description="Real-time streaming single-pulse trigger service")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("-listen", type=str, metavar="HOST:PORT",
                     help="Accept one live filterbank feed here "
                          "(SIGPROC header + packed spectra)")
    src.add_argument("-tail", type=str, metavar="FILE.fil",
                     help="Tail a (possibly growing) filterbank file")
    p.add_argument("-lodm", type=float, default=0.0)
    p.add_argument("-dmstep", type=float, default=1.0)
    p.add_argument("-numdms", type=int, default=8)
    p.add_argument("-nsub", type=int, default=32)
    p.add_argument("-downsamp", type=int, default=1)
    p.add_argument("-thresh", type=float, default=6.0,
                   help="Trigger threshold (sigma)")
    p.add_argument("-blocklen", type=int, default=0,
                   help="Ring block length in spectra (0 = auto)")
    p.add_argument("-ring", type=int, default=16,
                   help="Ring capacity in blocks (drop-oldest beyond)")
    p.add_argument("-stall-timeout", dest="stall_timeout", type=float,
                   default=None,
                   help="Seconds without bytes before zero fill is "
                        "inserted (quarantined) to hold cadence")
    p.add_argument("-dedup", type=float, default=0.25,
                   help="Trigger dedup window in seconds")
    p.add_argument("-port", type=int, default=0,
                   help="Also serve the HTTP API (/events, /metrics) "
                        "on this port (0 = off)")
    p.add_argument("-workdir", type=str, default="stream_work")
    p.add_argument("-events", type=str, default=None,
                   help="Append structured JSON events to this file")
    p.add_argument("-heartbeat", type=float, default=2.0,
                   help="Heartbeat event cadence on /events (0 = off)")
    p.add_argument("-json", dest="json_out", type=str, default=None,
                   help="Write the run summary JSON here")
    p.add_argument("-timeout", type=float, default=None,
                   help="Give up after this many seconds")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from presto_tpu.apps.common import ensure_backend
    ensure_backend()
    from presto_tpu.serve.server import SearchService, start_http
    cfg = StreamConfig(lodm=args.lodm, dmstep=args.dmstep,
                       numdms=args.numdms, nsub=args.nsub,
                       downsamp=args.downsamp, threshold=args.thresh,
                       blocklen=args.blocklen or None,
                       trigger_dedup_s=args.dedup,
                       ring_capacity=args.ring,
                       stall_timeout_s=args.stall_timeout)
    service = SearchService(args.workdir, events_path=args.events,
                            heartbeat_s=args.heartbeat)
    service.start()
    source = RingBlockSource(capacity=cfg.ring_capacity,
                             policy=cfg.ring_policy,
                             stall_timeout_s=cfg.stall_timeout_s)
    if args.listen:
        host, _, port = args.listen.rpartition(":")
        producer = SocketProducer(source, host or "127.0.0.1",
                                  int(port)).start()
        print("presto-stream: listening for a feed on %s:%d"
              % producer.address)
    else:
        producer = FileTailProducer(source, args.tail,
                                    idle_eof_s=1.0).start()
        print("presto-stream: tailing %s" % args.tail)
    httpd = None
    if args.port:
        httpd = start_http(service, port=args.port)
        print("presto-stream: HTTP on http://%s:%d (/events, /metrics)"
              % httpd.server_address[:2])
    stream = StreamService(service, source, cfg).start()
    ok = stream.wait(args.timeout)
    summary = stream.summary()
    summary["ok"] = bool(ok and stream.failed is None)
    if stream.failed is not None:
        summary["error"] = "%s: %s" % (type(stream.failed).__name__,
                                       stream.failed)
    print(json.dumps(summary, sort_keys=True))
    if args.json_out:
        from presto_tpu.io.atomic import atomic_write_text
        atomic_write_text(args.json_out,
                          json.dumps(summary, indent=1,
                                     sort_keys=True) + "\n")
    if httpd is not None:
        httpd.shutdown()
    service.stop()
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
