"""Bounded ring-buffer block source for live beam feeds.

The streaming analog of io/sigproc.FilterbankFile: a producer thread
(socket receiver or file tailer) parses a standard SIGPROC filterbank
header off the wire, decodes packed spectra with the SAME decode
sequence the file reader uses (io/sigproc.decode_spectra_block), and
assembles them into fixed-length channel-ascending blocks in a bounded
ring.  The consumer (stream/rolling.py via stream/service.py) pops
blocks with the same [blocklen, nchan] float32 contract
FilterbankFile.stream_blocks delivers — the reader seam is unchanged,
only the bytes now arrive over time instead of at rest.

Because a live feed cannot be paused, overload and damage become
explicit, *accounted* states instead of crashes:

  * backpressure — the ring is bounded; when the consumer falls
    behind, the oldest undelivered block is shed ("drop-oldest": the
    newest data is the data a trigger search needs) and the gap is
    zero-filled and quarantined as "ring-drop" in a
    io/quality.DataQualityReport, so every dropped spectrum is
    visible in both the quality ledger and the drop counters — zero
    *unaccounted* drops, ever.
  * producer stalls — when no bytes arrive for `stall_timeout_s`
    while mid-stream, zero-fill spectra are inserted to hold the
    real-time cadence and quarantined as "stall"; when the feed
    resumes, an equal number of (now stale) spectra are discarded to
    re-synchronize the stream position with the wall clock.
  * truncation — a connection dying mid-spectrum quarantines the
    partial spectrum as "truncated" and zero-pads it, exactly like
    the file reader's short-read handling.

EOF (producer close) is a normal event: the final partial block is
zero-padded without quarantine, mirroring read_spectra's EOF padding.
"""

from __future__ import annotations

import socket
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from presto_tpu.io.quality import (DataQualityReport, record_zero_runs,
                                   scrub_nonfinite)
from presto_tpu.io.sigproc import (FilterbankHeader,
                                   decode_spectra_block,
                                   read_filterbank_header)


@dataclass
class StreamBlock:
    """One ring slot: a fixed-length block of decoded spectra."""
    seq: int                    # block index in the stream (0-based)
    start: int                  # absolute first spectrum index
    data: np.ndarray            # [blocklen, nchan] float32 ascending
    nreal: int                  # spectra actually received (rest pad)
    t_arrival: float            # wall clock when the block completed
    quarantined: List = field(default_factory=list)  # BadInterval-ish


class RingBlockSource:
    """Bounded producer/consumer ring of decoded spectra blocks.

    Lifecycle: a producer calls set_header() once, then push_spectra()
    repeatedly and eof() at stream end; the consumer calls
    wait_header(), configure(blocklen) (the block geometry depends on
    the DM plan, which needs the header), then next_block() until
    at_eof.  push_spectra blocks until configure() runs — the
    producer cannot outpace the handshake.
    """

    def __init__(self, capacity: int = 16,
                 policy: str = "drop-oldest",
                 stall_timeout_s: Optional[float] = None):
        if policy not in ("drop-oldest", "block"):
            raise ValueError("policy must be drop-oldest|block")
        self.capacity = int(capacity)
        self.policy = policy
        self.stall_timeout_s = stall_timeout_s
        self.header: Optional[FilterbankHeader] = None
        self.blocklen: Optional[int] = None
        self.quality: Optional[DataQualityReport] = None
        self._lock = threading.Lock()
        self._have_header = threading.Event()
        self._configured = threading.Event()
        self._cond = threading.Condition(self._lock)
        self._ring: deque = deque()
        self._partial: Optional[np.ndarray] = None   # [<blocklen, C]
        self._partial_fill = 0
        self._pushed = 0            # spectra accepted from producer
        self._delivered_start = 0   # next spectrum index the consumer
                                    # expects (gap => synthesized)
        self._seq = 0               # blocks completed by the producer
        self._next_seq = 0          # next seq the consumer expects
        self._dropped_blocks = 0
        self._dropped_spectra = 0
        self._stall_spectra = 0
        self._stall_debt = 0        # stale spectra owed after a stall
        self._eof = False
        self._error: Optional[BaseException] = None

    # ---- producer side ----------------------------------------------

    def set_header(self, hdr: FilterbankHeader) -> None:
        self.header = hdr
        self.quality = DataQualityReport(path="<stream>",
                                         nchan=hdr.nchans)
        self._have_header.set()

    def configure(self, blocklen: int) -> None:
        """Fix the block geometry (consumer side, after planning)."""
        if blocklen < 1:
            raise ValueError("blocklen must be >= 1")
        self.blocklen = int(blocklen)
        self._configured.set()

    def push_spectra(self, arr: np.ndarray,
                     quarantine: Optional[str] = None) -> None:
        """Append decoded spectra [n, nchan]; assembles full blocks
        into the ring.  `quarantine` marks the whole span as a bad
        interval of that reason (stall fill, ring-drop synthesis).
        Scrubs NaN/Inf and records zero runs like the file reader."""
        self._configured.wait()
        arr = np.asarray(arr, np.float32)
        if arr.ndim != 2 or arr.shape[1] != self.header.nchans:
            raise ValueError("push_spectra expects [n, nchan]")
        with self._lock:
            start = self._pushed
            if quarantine is not None:
                self.quality.add(start, start + len(arr), quarantine)
            else:
                arr = scrub_nonfinite(arr, start, self.quality)
                record_zero_runs(arr, start, self.quality)
            self._pushed += len(arr)
            self.quality.nspectra = self._pushed
            off = 0
            while off < len(arr):
                if self._partial is None:
                    self._partial = np.zeros(
                        (self.blocklen, self.header.nchans),
                        np.float32)
                    self._partial_fill = 0
                take = min(self.blocklen - self._partial_fill,
                           len(arr) - off)
                self._partial[self._partial_fill:
                              self._partial_fill + take] = \
                    arr[off:off + take]
                self._partial_fill += take
                off += take
                if self._partial_fill == self.blocklen:
                    self._commit_block_locked(self.blocklen)

    def _commit_block_locked(self, nreal: int) -> None:
        blk = StreamBlock(
            seq=self._seq,
            start=self._seq * self.blocklen,
            data=self._partial, nreal=nreal,
            t_arrival=time.time())
        self._partial = None
        self._partial_fill = 0
        self._seq += 1
        while len(self._ring) >= self.capacity:
            if self.policy == "block":
                self._cond.wait()
                continue
            shed = self._ring.popleft()
            self._dropped_blocks += 1
            self._dropped_spectra += shed.nreal
            self.quality.add(shed.start, shed.start + self.blocklen,
                             "ring-drop")
        self._ring.append(blk)
        self._cond.notify_all()

    def note_stall_fill(self, n: int) -> None:
        """Producer inserted `n` zero-fill spectra to hold cadence
        through a stall: count them and remember the debt so the SAME
        producer's late data is discarded on resume.  The debt lives
        on the source — with many feeds in one process, one stalled
        beam must never re-sync the wall clock (drop spectra) for
        healthy feeds."""
        with self._lock:
            self._stall_spectra += n
            self._stall_debt += n

    def settle_stall_debt(self, navail: int) -> int:
        """How many of `navail` just-arrived spectra are stale (their
        slots were already zero-filled during this source's stall) and
        must be discarded; decrements the debt by that amount."""
        with self._lock:
            drop = min(self._stall_debt, int(navail))
            self._stall_debt -= drop
            return drop

    def eof(self) -> None:
        """Producer is done: flush the partial block (zero-padded, the
        normal EOF pad — not quarantined) and wake the consumer."""
        with self._lock:
            if self._partial is not None and self._partial_fill:
                self._commit_block_locked(self._partial_fill)
            self._eof = True
            self._cond.notify_all()
        self._have_header.set()     # unblock a header-less consumer
        self._configured.set()

    def fail(self, exc: BaseException) -> None:
        """Producer died un-cleanly; the consumer re-raises."""
        with self._lock:
            self._error = exc
            self._eof = True
            self._cond.notify_all()
        self._have_header.set()
        self._configured.set()

    # ---- consumer side ----------------------------------------------

    def wait_header(self, timeout: Optional[float] = None) \
            -> Optional[FilterbankHeader]:
        self._have_header.wait(timeout)
        if self._error is not None:
            raise self._error
        return self.header

    def next_block(self,
                   timeout: Optional[float] = None
                   ) -> Optional[StreamBlock]:
        """Pop the next block in stream order, synthesizing zero-filled
        quarantined blocks for any ring-drop gap so the consumer's
        two-block dedispersion carry never sees a discontinuity.
        Returns None when nothing is available within `timeout` — check
        `at_eof` to distinguish starvation from end of stream."""
        with self._cond:
            while not self._ring and not self._eof:
                if not self._cond.wait(timeout):
                    return None
            if self._error is not None:
                raise self._error
            if not self._ring:
                return None                       # EOF and drained
            head = self._ring[0]
            if head.seq > self._next_seq:
                # the gap a shed block left: deliver zeros in its
                # place (the quality ledger already recorded it)
                blk = StreamBlock(
                    seq=self._next_seq,
                    start=self._next_seq * self.blocklen,
                    data=np.zeros((self.blocklen,
                                   self.header.nchans), np.float32),
                    nreal=0, t_arrival=head.t_arrival,
                    quarantined=[("ring-drop",
                                  self._next_seq * self.blocklen,
                                  (self._next_seq + 1)
                                  * self.blocklen)])
                self._next_seq += 1
                return blk
            self._ring.popleft()
            self._cond.notify_all()
            self._next_seq = head.seq + 1
            return head

    @property
    def at_eof(self) -> bool:
        with self._lock:
            return self._eof and not self._ring

    @property
    def backlog(self) -> int:
        with self._lock:
            return len(self._ring)

    def stats(self) -> dict:
        with self._lock:
            return {
                "pushed_spectra": self._pushed,
                "dropped_blocks": self._dropped_blocks,
                "dropped_spectra": self._dropped_spectra,
                "stall_spectra": self._stall_spectra,
                "stall_debt": self._stall_debt,
                "backlog_blocks": len(self._ring),
                "eof": self._eof,
            }


# ----------------------------------------------------------------------
# Producers
# ----------------------------------------------------------------------

class _SpectraDecoder:
    """Incremental packed-bytes -> spectra decoder: holds the partial
    trailing spectrum between reads (a socket delivers bytes, not
    spectrum-aligned records)."""

    def __init__(self, hdr: FilterbankHeader):
        self.hdr = hdr
        self.bps = hdr.bytes_per_spectrum
        self._buf = b""

    def feed(self, data: bytes) -> np.ndarray:
        buf = self._buf + data
        nspec = len(buf) // self.bps
        self._buf = buf[nspec * self.bps:]
        if nspec == 0:
            return np.zeros((0, self.hdr.nchans), np.float32)
        raw = np.frombuffer(buf[:nspec * self.bps], dtype=np.uint8)
        return decode_spectra_block(self.hdr, raw, nspec)

    @property
    def partial_bytes(self) -> int:
        return len(self._buf)


class _SockFile:
    """Minimal file-face over a connected socket.

    read(n) is exact-n (loops recv; what the header parser needs);
    read1(n) is one recv — whatever is available, None on a read
    timeout (how feed_stream tells a stall from EOF's b"")."""

    def __init__(self, conn: socket.socket):
        self._sock = conn
        self._pos = 0

    def read(self, n: int = -1) -> bytes:
        bufs, got = [], 0
        while got < n:
            chunk = self._sock.recv(n - got)
            if not chunk:
                break
            bufs.append(chunk)
            got += len(chunk)
        self._pos += got
        return b"".join(bufs)

    def read1(self, n: int) -> Optional[bytes]:
        try:
            data = self._sock.recv(n)
        except (socket.timeout, TimeoutError):
            return None
        self._pos += len(data)
        return data

    def tell(self) -> int:
        return self._pos

    def seek(self, *a):
        raise OSError("socket streams are not seekable")


def feed_stream(source: RingBlockSource, fileobj,
                read_size: int = 1 << 16,
                faults: Optional[Callable] = None) -> None:
    """Drive a RingBlockSource from any binary stream (socket adapter,
    pipe, file): parse the SIGPROC header, then decode and push
    spectra until EOF.  A trailing partial spectrum is quarantined as
    "truncated" and zero-padded — a producer dying mid-spectrum must
    not lose the spectra before it.

    A None read (only the socket adapter produces one, on its read
    timeout) is a producer stall: zero fill is inserted to hold the
    real-time cadence, quarantined as "stall", and the equal count of
    late spectra is discarded when the feed resumes so the stream
    position stays aligned with the wall clock.  The debt is tracked
    PER SOURCE (RingBlockSource.note_stall_fill / settle_stall_debt),
    never in shared state: one stalled feed re-syncing the clock for
    every healthy feed in the process would skew their gap synthesis.

    `faults` is the chaos seam (testing/chaos.StreamFaults): called as
    faults(spectra_so_far) before every read; it may sleep (stall),
    raise, or close the stream underneath us.
    """
    try:
        hdr = read_filterbank_header(fileobj, "<stream>")
        source.set_header(hdr)
        dec = _SpectraDecoder(hdr)
        reader = (fileobj.read1 if hasattr(fileobj, "read1")
                  else fileobj.read)
        pushed = 0
        while True:
            if faults is not None:
                faults(pushed)
            try:
                data = reader(read_size)
            except (socket.timeout, TimeoutError):
                data = None
            if data is None:
                if source.stall_timeout_s is None:
                    break
                n = max(int(source.stall_timeout_s
                            / max(hdr.tsamp, 1e-9)), 1)
                source.push_spectra(
                    np.zeros((n, hdr.nchans), np.float32),
                    quarantine="stall")
                source.note_stall_fill(n)
                pushed += n
                continue
            if not data:
                break
            spectra = dec.feed(data)
            if len(spectra):
                drop = source.settle_stall_debt(len(spectra))
                if drop:
                    spectra = spectra[drop:]
            if len(spectra):
                source.push_spectra(spectra)
                pushed += len(spectra)
        if dec.partial_bytes:
            # mid-spectrum truncation: quarantine + zero-pad one
            # spectrum so the stream position stays spectrum-aligned
            source.push_spectra(
                np.zeros((1, hdr.nchans), np.float32),
                quarantine="truncated")
        source.eof()
    except BaseException as e:
        source.fail(e)
        raise


class SocketProducer:
    """Listen for ONE live feed connection and pump it into a source.

    Binds host:port (port=0 picks a free one, the test/loadgen
    pattern), accepts a single producer, and runs feed_stream on a
    daemon thread.  `stall_timeout_s` on the source doubles as the
    socket read timeout that makes stall detection possible.
    """

    def __init__(self, source: RingBlockSource,
                 host: str = "127.0.0.1", port: int = 0):
        self.source = source
        self._srv = socket.create_server((host, port))
        self.address = self._srv.getsockname()[:2]
        self._thread = threading.Thread(
            target=self._run, name="presto-stream-recv", daemon=True)

    def start(self) -> "SocketProducer":
        self._thread.start()
        return self

    def _run(self) -> None:
        try:
            conn, _ = self._srv.accept()
        except OSError:
            self.source.eof()
            return
        try:
            if self.source.stall_timeout_s is not None:
                conn.settimeout(self.source.stall_timeout_s)
            feed_stream(self.source, _SockFile(conn))
        except BaseException:
            pass                        # source.fail already recorded
        finally:
            try:
                conn.close()
            except OSError:
                pass
            self._srv.close()

    def close(self) -> None:
        try:
            self._srv.close()
        except OSError:
            pass

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)


class FileTailProducer:
    """Tail a (possibly still growing) filterbank file into a source.

    Reads whatever exists, then polls for growth every `poll_s`; ends
    the stream after `idle_eof_s` seconds without growth (None = only
    stop() ends it).  The offline replay / "file-at-rest as a feed"
    producer, and the zero-dependency path for tests.
    """

    def __init__(self, source: RingBlockSource, path: str,
                 poll_s: float = 0.05,
                 idle_eof_s: Optional[float] = 0.5,
                 faults: Optional[Callable] = None):
        self.source = source
        self.path = path
        self.poll_s = poll_s
        self.idle_eof_s = idle_eof_s
        self.faults = faults
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="presto-stream-tail", daemon=True)

    def start(self) -> "FileTailProducer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    def _run(self) -> None:
        try:
            with open(self.path, "rb") as f:
                hdr = read_filterbank_header(f, self.path)
                self.source.set_header(hdr)
                dec = _SpectraDecoder(hdr)
                idle = 0.0
                pushed = 0
                while not self._stop.is_set():
                    if self.faults is not None:
                        self.faults(pushed)
                    data = f.read(1 << 16)
                    if data:
                        idle = 0.0
                        spectra = dec.feed(data)
                        if len(spectra):
                            self.source.push_spectra(spectra)
                            pushed += len(spectra)
                        continue
                    if self.idle_eof_s is not None \
                            and idle >= self.idle_eof_s:
                        break
                    time.sleep(self.poll_s)
                    idle += self.poll_s
                if dec.partial_bytes:
                    self.source.push_spectra(
                        np.zeros((1, hdr.nchans), np.float32),
                        quarantine="truncated")
            self.source.eof()
        except BaseException as e:
            self.source.fail(e)
