"""Rolling dedispersion + incremental single-pulse triggering.

The online composition of two existing engines:

  * ops/dedispersion's explicit two-block carry
    (dedisp_subbands_block -> float_dedisp_many_block), driven block
    by block exactly like apps/prepsubband's streaming loop — same
    delay plan (apps.prepsubband.plan_delays), same priming, same two
    zero flush blocks, same valid-length trim.  Because every output
    sample's accumulation order is channel-then-subband ascending
    regardless of where block boundaries fall, the dedispersed series
    is byte-identical to the batch driver's whatever block length the
    live feed uses.
  * search/singlepulse's incremental carry (SinglePulseStream), one
    per DM trial, fed only *valid* dedispersed samples: the last
    `maxd` samples are held back until newer raw data proves them
    uncontaminated by flush padding — the streaming analog of the
    batch driver trimming to (N - maxd) before writing .dat files.

Candidates across the DM fan-out are deduplicated into *triggers*: a
physical pulse peaks in several adjacent DM trials and boxcar widths,
so finalized candidates are clustered by arrival time and the
strongest candidate of each cluster is emitted exactly once.
"""

from __future__ import annotations

from dataclasses import dataclass
from types import SimpleNamespace
from typing import List, Optional

import numpy as np
import jax.numpy as jnp

from presto_tpu.ops import dedispersion as dd
from presto_tpu.search.singlepulse import (SinglePulseSearch,
                                           SinglePulseStream,
                                           SPCandidate)


@dataclass
class StreamConfig:
    """Streaming search parameters (wire-safe plain values)."""
    lodm: float = 0.0
    dmstep: float = 1.0
    numdms: int = 8
    nsub: int = 32
    downsamp: int = 1
    subdm: Optional[float] = None
    #: spectra per ring block; None resolves via
    #: apps.common.stream_blocklen (the batch streaming bound)
    blocklen: Optional[int] = None
    threshold: float = 6.0
    #: matched-filter geometry: smaller chunks than the batch default
    #: (8000/8192) bound the trigger holdback — a chunk is searchable
    #: only one whole chunk behind the normalization frontier
    chunklen: int = 1920
    fftlen: int = 2048
    detrendlen: int = 1000
    topk: int = 256
    max_pulse_width_s: float = 0.0       # 0 -> MAX_DOWNFACT bin cap
    #: candidates within this many seconds of an emitted trigger are
    #: the same physical event (adjacent DM trials / widths)
    trigger_dedup_s: float = 0.25
    #: ring capacity (blocks) and overload policy
    ring_capacity: int = 16
    ring_policy: str = "drop-oldest"
    #: socket read timeout that converts a producer stall into
    #: quarantined zero fill (None = wait forever)
    stall_timeout_s: Optional[float] = None


@dataclass
class Trigger:
    """One emitted single-pulse trigger (the deduplicated event).
    `time` is the pulse's top-of-band arrival (per-trial dispersion
    offset added back), directly comparable across DM trials."""
    time: float                 # top-of-band arrival, s from start
    dm: float
    sigma: float
    downfact: int
    bin: int                    # downsampled dedispersed sample index
    members: int = 1            # candidates merged into this trigger
    latency_s: float = 0.0      # sample-arrival -> trigger-emitted

    def to_json(self) -> dict:
        return {"time": round(self.time, 6), "dm": self.dm,
                "sigma": round(float(self.sigma), 3),
                "downfact": int(self.downfact), "bin": int(self.bin),
                "members": int(self.members),
                "latency_s": round(self.latency_s, 4)}


class RollingDedisp:
    """The two-block dedispersion carry as an object.

    feed() mirrors one iteration of the batch streaming loop
    (apps/prepsubband.run): block j primes the raw carry, j+1 primes
    the subband carry, every later block yields one dedispersed
    series block covering the window two blocks back.  flush() pushes
    the batch driver's two zero blocks through the carry.
    """

    def __init__(self, chan_bins: np.ndarray, dm_bins: np.ndarray,
                 nsub: int, downsamp: int = 1):
        self.nsub = int(nsub)
        self.downsamp = int(downsamp)
        self._chan_bins = jnp.asarray(np.asarray(chan_bins, np.int32))
        # host np: float_dedisp_many_block's static fast path
        self._dm_bins = np.asarray(dm_bins, np.int32)
        self._prev_raw = None
        self._prev_sub = None
        self.blocks_in = 0

    def feed(self, block_tc: np.ndarray) -> Optional[np.ndarray]:
        """block_tc: [blocklen, nchan] float32 ascending.  Returns the
        next [numdms, blocklen // downsamp] series block, or None
        while the carry is still priming."""
        cur = jnp.asarray(np.ascontiguousarray(block_tc.T))
        out = None
        if self._prev_raw is not None:
            sub = dd.dedisp_subbands_block(self._prev_raw, cur,
                                           self._chan_bins, self.nsub)
            if self._prev_sub is not None:
                series = dd.float_dedisp_many_block(self._prev_sub,
                                                    sub, self._dm_bins)
                series = dd.downsample_block(series, self.downsamp)
                out = np.asarray(series)
            self._prev_sub = sub
        self._prev_raw = cur
        self.blocks_in += 1
        return out

    def flush(self, blocklen: int, nchan: int) -> List[np.ndarray]:
        """The batch loop's two zero flush blocks: drains the carry,
        returning the final series blocks."""
        outs = []
        zero = np.zeros((blocklen, nchan), np.float32)
        for _ in range(2):
            out = self.feed(zero)
            if out is not None:
                outs.append(out)
        return outs


def plan_stream(hdr, cfg: StreamConfig):
    """DM-grid delay plan for a live header — the SAME plan the batch
    prepsubband builds (apps.prepsubband.plan_delays with the
    topocentric frame; a live feed has no barycentric plan), so the
    rolling series is comparable byte-for-byte."""
    from presto_tpu.apps.prepsubband import plan_delays
    args = SimpleNamespace(lodm=cfg.lodm, dmstep=cfg.dmstep,
                           numdms=cfg.numdms, nsub=cfg.nsub,
                           subdm=cfg.subdm)
    dms, chan_bins, dm_bins = plan_delays(hdr, args, avgvoverc=0.0)
    maxd = int(chan_bins.max()) + int(dm_bins.max())
    return dms, chan_bins, dm_bins, maxd


def resolve_blocklen(hdr, cfg: StreamConfig, maxd: int,
                     chan_bins, dm_bins) -> int:
    """The ring block length: explicit config, else the batch
    streaming bound (stream_blocklen) — always larger than any delay
    so the two-block window algebra holds, and a multiple of the
    downsample factor like the batch driver rounds."""
    from presto_tpu.apps.common import stream_blocklen
    stage_max = max(int(np.max(chan_bins)), int(np.max(dm_bins)))
    blocklen = (int(cfg.blocklen) if cfg.blocklen
                else stream_blocklen(hdr.nchans, stage_max))
    if blocklen <= stage_max:
        raise ValueError(
            "blocklen %d <= max per-stage delay %d: the two-block "
            "carry needs every delay inside one block"
            % (blocklen, stage_max))
    if blocklen % cfg.downsamp:
        blocklen += cfg.downsamp - blocklen % cfg.downsamp
    return blocklen


class StreamSearch:
    """The full rolling pipeline for one beam: raw blocks in, triggers
    out.  Owns the dedispersion carry, one SinglePulseStream per DM
    trial, the valid-sample holdback, quarantine -> offregion mapping,
    and cross-DM trigger dedup."""

    def __init__(self, hdr, cfg: StreamConfig,
                 blocklen: Optional[int] = None, obs=None):
        self.hdr = hdr
        self.cfg = cfg
        self.obs = obs              # Observability | None
        self.dt = float(hdr.tsamp)
        self.dms, self._chan_bins, self._dm_bins, self.maxd = \
            plan_stream(hdr, cfg)
        self.blocklen = (int(blocklen) if blocklen else
                         resolve_blocklen(hdr, cfg, self.maxd,
                                          self._chan_bins,
                                          self._dm_bins))
        self.rolling = RollingDedisp(self._chan_bins, self._dm_bins,
                                     cfg.nsub, cfg.downsamp)
        sp = SinglePulseSearch(threshold=cfg.threshold,
                               maxwidth=cfg.max_pulse_width_s,
                               detrendlen=cfg.detrendlen,
                               badblocks=False,
                               chunklen=cfg.chunklen,
                               fftlen=cfg.fftlen, topk=cfg.topk)
        self.sp = sp
        self.dt_ds = self.dt * cfg.downsamp
        self.streams = [SinglePulseStream(sp, self.dt_ds, dm=float(dm))
                        for dm in self.dms]
        # per-trial arrival alignment: trial d's series lags the
        # top-of-band arrival by its highest-frequency subband offset
        # (dm_bins are globally min-normalized), so candidates from
        # different DM trials of the SAME pulse cluster only after
        # adding each trial's min delay back — in seconds, the
        # residual dispersion sweep across the grid can exceed any
        # reasonable dedup window
        self._shift_s = {float(dm): float(self._dm_bins[d].min())
                         * self.dt
                         for d, dm in enumerate(self.dms)}
        self._nreal = 0             # real spectra fed (no flush pad)
        self._produced = 0          # downsampled series samples out
        self._sp_fed = 0            # series samples handed to search
        self._lag = np.zeros((cfg.numdms, 0), np.float32)
        # holdback (downsampled samples): series closer than maxd raw
        # samples to the frontier may still change (flush padding)
        self._hold = -(-self.maxd // cfg.downsamp)
        self._finished = False
        self.candidates = 0         # finalized candidates (pre-dedup)
        self.triggers: List[Trigger] = []
        self._open: List[Trigger] = []      # clusters still refining
        self._recent: List[Trigger] = []    # emitted (absorb-only)

    # -- quarantine routing -------------------------------------------
    def note_quarantine(self, lo: int, hi: int) -> None:
        """Raw spectra [lo, hi) are damaged/synthetic: any dedispersed
        sample whose accumulation window touches them becomes an
        offregion for border pruning in every DM trial (the streaming
        analog of the batch .inf onoff regions).  One extra detrend
        block of guard on each side: the data/damage edge perturbs the
        whole detrend block it lands in, and edge discontinuities
        would otherwise read as spurious wide-boxcar triggers."""
        ds = self.cfg.downsamp
        guard = self.cfg.detrendlen
        lo_ds = max(max(lo - self.maxd, 0) // ds - guard, 0)
        hi_ds = -(-hi // ds) + guard
        for s in self.streams:
            s.add_offregion(lo_ds, hi_ds)

    # -- feeding ------------------------------------------------------
    def feed_block(self, data: np.ndarray,
                   nreal: int) -> List[Trigger]:
        """One ring block ([blocklen, nchan], `nreal` real spectra —
        the rest is EOF padding).  Returns triggers finalized by this
        block."""
        if self._finished:
            raise RuntimeError("stream already finished")
        self._nreal += int(nreal)
        span = (self.obs.span("stream:dedisp", block=self.rolling.
                              blocks_in) if self.obs else None)
        series = self.rolling.feed(data)
        if span is not None:
            span.finish()
        span = (self.obs.span("stream:search") if self.obs else None)
        out = self._dedup(self._advance(series))
        if span is not None:
            span.finish()
        return out

    def finish(self) -> List[Trigger]:
        """End of stream: flush the dedispersion carry, trim to the
        valid length ((N - maxd) // downsamp, the batch trim), flush
        every DM search, emit remaining triggers."""
        if self._finished:
            return []
        return self.finish_series(
            self.rolling.flush(self.blocklen, self.hdr.nchans))

    # -- external-dedispersion entry points ---------------------------
    # The beam multiplexer (stream/beams.py) computes the rolling
    # series for many beams in ONE stacked jit step and hands each
    # beam's slice back here, so the trigger logic — holdback, valid
    # trim, offregions, dedup — is literally this class's code and
    # per-beam triggers stay byte-equal to an independent stream.

    def feed_series(self, series: Optional[np.ndarray],
                    nreal: int) -> List[Trigger]:
        """Account `nreal` real spectra and absorb one externally
        dedispersed series block ([numdms, blocklen // downsamp], or
        None while the external carry is still priming).  Equivalent
        to feed_block when `series` is what rolling.feed would have
        produced for the same raw block."""
        if self._finished:
            raise RuntimeError("stream already finished")
        self._nreal += int(nreal)
        self.rolling.blocks_in += 1     # keep summary()/spans honest
        return self._dedup(self._advance(series))

    def finish_series(self,
                      flush_series: List[np.ndarray]) -> List[Trigger]:
        """finish() with externally computed flush blocks (what
        rolling.flush would have produced from two zero blocks)."""
        if self._finished:
            return []
        self._finished = True
        cands: List[SPCandidate] = []
        for series in flush_series:
            cands.extend(self._advance(series))
        cands.extend(self._advance(None))   # drain the lag to `valid`
        for s in self.streams:
            cands.extend(s.flush())
        return self._dedup(cands, final=True)

    def _advance(self,
                 series: Optional[np.ndarray]) -> List[SPCandidate]:
        """Append a produced series block to the lag buffer and feed
        every sample that can no longer change to the per-DM searches:
        mid-stream that is (produced - holdback); once finished the
        exact batch trim ((N - maxd) // downsamp) applies — series
        past it is flush-padding-contaminated and the batch driver
        never searches it either."""
        cands: List[SPCandidate] = []
        if series is not None:
            self._produced += series.shape[1]
            self._lag = (np.concatenate([self._lag, series], axis=1)
                         if self._lag.shape[1] else series)
        if self._finished:
            valid = max((self._nreal - self.maxd)
                        // self.cfg.downsamp, 0)
            feed_to = min(valid, self._produced)
        else:
            feed_to = self._produced - self._hold
        if feed_to > self._sp_fed:
            take = feed_to - self._sp_fed
            for d, s in enumerate(self.streams):
                cands.extend(s.feed(self._lag[d, :take]))
            self._lag = self._lag[:, take:]
            self._sp_fed = feed_to
        return cands

    # -- trigger dedup ------------------------------------------------
    def _frontier_time(self) -> float:
        """Aligned arrival time no future candidate can precede: each
        DM trial's emission floor shifted into the common top-of-band
        frame, minimized over trials.  Clusters older than this (minus
        the dedup window) are complete and safe to emit with their
        best member's DM/sigma."""
        return min(
            s.emission_floor() * self.dt_ds
            + self._shift_s[float(dm)]
            for dm, s in zip(self.dms, self.streams))

    def _dedup(self, cands: List[SPCandidate],
               final: bool = False) -> List[Trigger]:
        """Cluster finalized candidates (all DM trials) by aligned
        arrival time.  A cluster stays open — absorbing members and
        refining its leader to the strongest candidate — until every
        trial's emission frontier has passed it (the residual
        dispersion sweep across the grid: the price of emitting the
        *best* DM exactly once instead of the first DM early)."""
        self.candidates += len(cands)
        win = self.cfg.trigger_dedup_s
        for c in sorted(cands, key=lambda c: -c.sigma):
            t = c.time + self._shift_s.get(c.dm, 0.0)
            home = None
            for trig in self._open + self._recent:
                if abs(trig.time - t) <= win:
                    home = trig
                    break
            if home is None:
                self._open.append(Trigger(time=t, dm=c.dm,
                                          sigma=c.sigma,
                                          downfact=c.downfact,
                                          bin=c.bin))
            else:
                home.members += 1
                if any(home is tr for tr in self._open) \
                        and c.sigma > home.sigma:
                    home.time, home.dm = t, c.dm
                    home.sigma = c.sigma
                    home.downfact, home.bin = c.downfact, c.bin
        if final:
            out, self._open = self._open, []
        else:
            ft = self._frontier_time()
            out = [tr for tr in self._open if tr.time + win < ft]
            self._open = [tr for tr in self._open
                          if tr.time + win >= ft]
        # emit in arrival order: clusters are *created* in sigma order
        # within a batch, and the frontier already guarantees batch k's
        # emissions all precede batch k+1's, so an in-batch sort makes
        # the whole trigger stream time-monotonic
        out.sort(key=lambda tr: tr.time)
        # emitted history: a pathological late straggler is absorbed
        # (counted, never re-emitted) instead of double-triggering
        self._recent = (self._recent + out)[-64:]
        self.triggers.extend(out)
        return out

    # -- views --------------------------------------------------------
    @property
    def spectra_fed(self) -> int:
        return self._nreal

    def summary(self) -> dict:
        return {
            "spectra": self._nreal,
            "blocks": self.rolling.blocks_in,
            "numdms": self.cfg.numdms,
            "maxd": self.maxd,
            "blocklen": self.blocklen,
            "candidates": self.candidates,
            "triggers": len(self.triggers),
        }
