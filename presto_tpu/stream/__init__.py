"""presto_tpu.stream — real-time streaming search (live-telescope
scenario).

Turns the file-at-rest pipeline into a live FRB/single-pulse trigger
engine with a bounded latency budget:

  * source.py  — bounded ring-buffer block source behind the reader
    seam, fed by a socket or file-tail producer; backpressure with
    drop accounting, dropout quarantine via io/quality.
  * rolling.py — rolling dedispersion over the DM grid using the
    two-block carry from ops/dedispersion, plus incremental
    single-pulse triggering (search/singlepulse.SinglePulseStream)
    that matches the batch search on the same bytes.
  * service.py — the presto-stream CLI and the deadline-lane glue
    into the serve scheduler; triggers stream on serve's /events.
  * beams.py   — the presto-beams multiplexer: N same-geometry beam
    feeds stacked into ONE jitted rolling-dedispersion chain per
    deadline tick, with per-beam QoS degradation, a cross-beam
    coincidence veto, and lease/fence beam hand-off across replicas.

See docs/STREAMING.md for the architecture and the latency budget.
"""

from presto_tpu.stream.rolling import (RollingDedisp, StreamConfig,
                                       StreamSearch, Trigger)
from presto_tpu.stream.source import (FileTailProducer,
                                      RingBlockSource, SocketProducer,
                                      StreamBlock, feed_stream)
from presto_tpu.stream.service import StreamService
from presto_tpu.stream.beams import (BeamLedger, BeamMultiplexer,
                                     CoincidenceVeto,
                                     StackedRollingDedisp,
                                     make_beam_block_step)

__all__ = [
    "RollingDedisp", "StreamConfig", "StreamSearch", "Trigger",
    "FileTailProducer", "RingBlockSource", "SocketProducer",
    "StreamBlock", "feed_stream", "StreamService",
    "BeamLedger", "BeamMultiplexer", "CoincidenceVeto",
    "StackedRollingDedisp", "make_beam_block_step",
]
