"""Beam multiplexer: N live beam feeds as ONE stacked device chain.

Modern arrays deliver hundreds of coherent beams at once; one
`presto-stream` per beam means N sockets, N rolling-dedisp carries and
N deadline ticks fighting one queue.  This module multiplexes N
same-geometry beam feeds (sockets or tailed files) into a single
resident pipeline:

  * **Stacking** — per-beam `RingBlockSource` fronts are assembled
    tick-aligned into one ``[beams, nchan, blocklen]`` device array
    and pushed through ONE jitted rolling-dedispersion step per stack
    group (`make_beam_block_step`): 64 beams cost one dispatch chain,
    not 64.  Each beam's subgraph inside the stacked jit is exactly
    `ops.dedispersion.make_block_step`'s composed graph, so every
    beam's dedispersed series — and therefore its trigger set, which
    is produced by feeding the per-beam slice back through the SAME
    `StreamSearch` trigger logic an independent `presto-stream` runs —
    is byte-identical to N independent instances.
  * **QoS / degradation** — the deadline tick never waits on a
    straggler: a beam whose next block has not arrived `qos_wait_s`
    after the first beam's has degrades to a zero gap block,
    quarantined as "stall" in that beam's own `DataQualityReport`
    (the per-beam dimension of the existing quality reasons) and
    counted on ``stream_beam_stalled_total{beam=}``.  The late real
    block is discarded on arrival (``stream_beam_dropped_total``) so
    the beam stays wall-clock aligned — per-beam stall debt, never
    shared (see stream/source.py).
  * **Cross-beam coincidence veto** — a real pulse is localized on
    the sky; broadband RFI is not.  Triggers landing in >= K distinct
    beams within `window_s` (and `dm_tol` when set) are vetoed as one
    cluster, emitting the decision AND the per-beam evidence
    (`beam-veto` event, ``stream_beam_vetoed_total{beam=}``).  With
    the veto off every per-beam trigger is emitted exactly as an
    independent stream would.
  * **Beam hand-off** — with a fleet directory, every beam is a
    leased item in a `BeamLedger` (pipeline/leaseledger.py: lease /
    heartbeat / epoch fencing).  Each tick commits newly emitted
    triggers and the emission frontier to the ledger *before* the
    events go out; when a replica dies mid-observation a successor
    reaps, re-leases, replays the (replayable) feeds and suppresses
    the already-committed triggers — zero lost, zero duplicated.

The tick runs on the serve scheduler's deadline lane exactly like
stream/service.StreamService (single outstanding tick; force
submission bypasses the depth bound without unbounded growth).
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from presto_tpu.io.quality import DataQualityReport
from presto_tpu.obs import jaxtel
from presto_tpu.ops import dedispersion as dd
from presto_tpu.pipeline.leaseledger import LeaseLedger
from presto_tpu.stream.rolling import (StreamConfig, StreamSearch,
                                       Trigger)
from presto_tpu.stream.service import LATENCY_BUCKETS
from presto_tpu.stream.source import (FileTailProducer,
                                      RingBlockSource,
                                      SocketProducer)

#: chaos seam names the multiplexer fires (testing/chaos.FaultInjector
#: substring match); the taxonomy copy is obs/taxonomy.BEAM_KILL_POINTS
#: and testing/chaos.py re-exports them for trial authors
BEAM_KILL_POINTS = ("beam-tick", "beam-commit", "beam-handoff")


# ----------------------------------------------------------------------
# Stacked rolling dedispersion: one jit step for a whole beam group
# ----------------------------------------------------------------------

def make_beam_block_step(chan_delays, dm_delays, numsubbands: int,
                         downsamp: int = 1):
    """Build the stacked two-block rolling step: ``(prime, step)``
    jitted callables over ``[beams, nchan, blocklen]`` carries.

    Each beam's subgraph is EXACTLY ops.dedispersion.make_block_step's
    composition (subbands -> many-DM shift-add with host-np delays on
    the static-slice fast path -> downsample), unrolled over the beam
    axis inside one jit and stacked at the end.  XLA preserves each
    independent subgraph's accumulation order, so beam b's series is
    bit-identical to a per-beam RollingDedisp fed the same blocks —
    the whole group costs ONE dispatch per tick instead of `beams`.
    """
    chan_dev = jnp.asarray(np.asarray(chan_delays), jnp.int32)
    dm_delays_np = np.asarray(dm_delays, np.int32)
    nsub = int(numsubbands)
    ds = int(downsamp)

    @jax.jit
    def prime(prev_raw, cur):
        """First carry transition: subbands only (no series yet)."""
        return jnp.stack([
            dd.dedisp_subbands_block(prev_raw[b], cur[b], chan_dev,
                                     nsub)
            for b in range(prev_raw.shape[0])])

    @jax.jit
    def step(prev_raw, cur, prev_sub):
        subs, series = [], []
        for b in range(cur.shape[0]):
            sub = dd.dedisp_subbands_block(prev_raw[b], cur[b],
                                           chan_dev, nsub)
            ser = dd.float_dedisp_many_block(prev_sub[b], sub,
                                             dm_delays_np)
            subs.append(sub)
            series.append(dd.downsample_block(ser, ds))
        return jnp.stack(subs), jnp.stack(series)

    return prime, step


class StackedRollingDedisp:
    """RollingDedisp's two-block carry lifted over a beam axis: same
    priming state machine (block 0 primes the raw carry, block 1 the
    subband carry, every later block yields one stacked series block),
    one device dispatch per fed block once primed."""

    def __init__(self, chan_bins, dm_bins, nsub: int,
                 downsamp: int = 1):
        self._prime, self._step = make_beam_block_step(
            chan_bins, dm_bins, nsub, downsamp)
        self._prev_raw = None
        self._prev_sub = None
        self.blocks_in = 0

    def feed(self, stack_tc: np.ndarray
             ) -> Tuple[Optional[np.ndarray], int]:
        """stack_tc: [beams, blocklen, nchan] float32.  Returns
        (series [beams, numdms, blocklen // downsamp] or None while
        priming, device dispatches issued)."""
        cur = jnp.asarray(np.ascontiguousarray(
            stack_tc.transpose(0, 2, 1)))
        out, dispatched = None, 0
        if self._prev_raw is not None:
            if self._prev_sub is None:
                self._prev_sub = self._prime(self._prev_raw, cur)
            else:
                self._prev_sub, series = self._step(
                    self._prev_raw, cur, self._prev_sub)
                out = np.asarray(series)
            dispatched = 1
        self._prev_raw = cur
        self.blocks_in += 1
        return out, dispatched


# ----------------------------------------------------------------------
# Cross-beam coincidence veto
# ----------------------------------------------------------------------

@dataclass
class VetoDecision:
    """One vetoed coincidence cluster with its per-beam evidence."""
    time: float                       # strongest member's arrival
    nbeams: int                       # distinct beams hit
    evidence: Dict[str, dict]         # beam id -> strongest trigger

    def to_json(self) -> dict:
        return {"time": round(self.time, 6), "nbeams": self.nbeams,
                "evidence": self.evidence}


class CoincidenceVeto:
    """Buffer per-beam triggers until every live beam's emission
    frontier has passed them, then cluster by arrival time (and DM
    when `dm_tol` is set): a cluster hitting >= `k` distinct beams is
    broadband RFI and is vetoed whole; everything else is released
    for emission.  `k` <= 1 disables buffering entirely (the
    byte-equality mode: triggers flow through untouched)."""

    def __init__(self, k: int, window_s: float = 0.1,
                 dm_tol: Optional[float] = None):
        self.k = int(k)
        self.window_s = float(window_s)
        self.dm_tol = dm_tol
        self._pending: List[Tuple[str, Trigger]] = []

    @property
    def enabled(self) -> bool:
        return self.k > 1

    def add(self, beam: str, trig: Trigger) -> None:
        self._pending.append((beam, trig))

    def _same_cluster(self, a: Trigger, b: Trigger) -> bool:
        if abs(a.time - b.time) > self.window_s:
            return False
        if self.dm_tol is not None \
                and abs(a.dm - b.dm) > self.dm_tol:
            return False
        return True

    def drain(self, frontier_s: float, final: bool = False
              ) -> Tuple[List[Tuple[str, Trigger]],
                         List[VetoDecision]]:
        """Release every pending trigger no future candidate can join
        (its window is fully behind every beam's frontier), clustered;
        returns (emit list, veto decisions)."""
        if final:
            ripe, self._pending = self._pending, []
        else:
            ripe = [p for p in self._pending
                    if p[1].time + self.window_s < frontier_s]
            self._pending = [p for p in self._pending
                             if p[1].time + self.window_s
                             >= frontier_s]
        clusters: List[List[Tuple[str, Trigger]]] = []
        for beam, trig in sorted(ripe, key=lambda p: p[1].time):
            for cl in clusters:
                if self._same_cluster(cl[0][1], trig):
                    cl.append((beam, trig))
                    break
            else:
                clusters.append([(beam, trig)])
        emit: List[Tuple[str, Trigger]] = []
        vetoes: List[VetoDecision] = []
        for cl in clusters:
            beams = {b for b, _ in cl}
            if len(beams) >= self.k:
                best = max(cl, key=lambda p: p[1].sigma)[1]
                ev: Dict[str, dict] = {}
                for b, t in cl:
                    if b not in ev or t.sigma > ev[b]["sigma"]:
                        ev[b] = {"time": round(t.time, 6),
                                 "dm": t.dm,
                                 "sigma": round(float(t.sigma), 3)}
                vetoes.append(VetoDecision(time=best.time,
                                           nbeams=len(beams),
                                           evidence=ev))
            else:
                emit.extend(cl)
        emit.sort(key=lambda p: p[1].time)
        return emit, vetoes


# ----------------------------------------------------------------------
# Beam ledger: lease / fence / exactly-once commit per beam
# ----------------------------------------------------------------------

class BeamLedgerError(Exception):
    pass


class StaleBeamWrite(BeamLedgerError):
    def __init__(self, item_id, host, epoch, current_epoch, why):
        self.item_id, self.host = item_id, host
        self.epoch, self.current_epoch = epoch, current_epoch
        self.why = why
        super().__init__(
            "stale beam write rejected: %r by %r under epoch %d "
            "(cluster epoch %d): %s"
            % (item_id, host, epoch, current_epoch, why))


class BeamLedger(LeaseLedger):
    """One leased item per beam inside a fleet directory.  The row's
    ``triggers`` list is the authoritative emitted set: `advance`
    commits new triggers (and the emission frontier) under the ledger
    lock with the full fence check BEFORE any event leaves the
    process, so a successor replaying the observation after a replica
    death suppresses exactly the committed set — zero lost, zero
    duplicated across the hand-off."""

    LEDGER_NAME = "beams.json"
    ITEMS_KEY = "beams"
    ERROR = BeamLedgerError
    STALE = StaleBeamWrite
    EV_LEASE = "beam-lease"
    EV_DONE = "beam-done"
    EV_REDO = "beam-redo"
    EV_STALE = "beam-stale-write"
    EV_HOST_DEAD = "beam-replica-dead"
    EV_EPOCH_BUMP = "beam-epoch-bump"

    def advance(self, leases: Dict[str, "ItemLease"], host: str,
                updates: Dict[str, dict], ttl: float,
                now: Optional[float] = None) -> None:
        """One transaction for the whole tick: for every beam in
        `updates` ({beam id: {"triggers": [...json...],
        "frontier_s": float, "vetoed": int}}) append the new
        triggers, advance the frontier and renew the lease.  Any
        fenced beam raises STALE (after recording the event) — a
        zombie replica must stop, not partially write."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            items = self._items(state)
            for iid in sorted(updates):
                lease = leases[iid]
                row = items.get(iid)
                why = self._fence_why(row, lease, host)
                if why is not None:
                    self._reject_stale(state, lease, host, {}, why)
                up = updates[iid]
                row.setdefault("triggers", []).extend(
                    up.get("triggers", ()))
                row["frontier_s"] = max(
                    float(row.get("frontier_s", 0.0)),
                    float(up.get("frontier_s", 0.0)))
                row["vetoed"] = int(row.get("vetoed", 0)) \
                    + int(up.get("vetoed", 0))
                row["lease_expires"] = now + ttl
            self._save(state)


# ----------------------------------------------------------------------
# Per-beam lane state
# ----------------------------------------------------------------------

class BeamLane:
    """One beam inside the multiplexer: its ring source, its OWN
    StreamSearch trigger engine (internal rolling carry bypassed —
    the stacked step hands each tick's series slice back through
    feed_series, so the trigger logic is literally the independent
    stream's code), and the per-beam accounting dimension."""

    LIVE, FLUSHING, DONE = "live", "flushing", "done"

    def __init__(self, beam_id: str, source: RingBlockSource,
                 engine: StreamSearch):
        self.beam_id = beam_id
        self.source = source
        self.engine = engine
        # two independent state machines, one per thread: the
        # ASSEMBLER advances feed_state (LIVE -> FLUSHING) when the
        # reader drains, and the TICK thread advances state
        # (LIVE -> FLUSHING -> DONE) from the pad ordinals carried in
        # each bundle — the tick thread may run many bundles behind
        # the assembler (burst feeds, compile stalls), so it must
        # never read the assembler's clock
        self.state = self.LIVE
        self.feed_state = self.LIVE
        self.inbox: deque = deque()       # blocks from the reader
        self.lock = threading.Lock()
        self.feed_eof = False             # reader saw source EOF
        self.ticks = 0                    # stacked ticks consumed
        self.flush_series: List[np.ndarray] = []
        self.flush_ticks = 0
        self.pad_issued = 0               # assembler-side flush pads
        self.last_t_arrival = time.time()
        # mux-side quarantine (straggler gap fill) — the `beam`
        # dimension of the existing quality reasons
        self.quality = DataQualityReport(
            path="<%s>" % beam_id, nchan=engine.hdr.nchans)
        self.stalled_spectra = 0
        self.dropped_spectra = 0
        self.vetoed = 0
        self.emitted = 0
        self.replayed = 0
        self.handoff = False
        self.committed: set = set()       # canonical trigger keys
        self._routed: set = set()         # quality intervals routed
        self._quar_seen = 0
        self.lease = None

    # canonical trigger identity: every deterministic field (latency
    # is wall clock and excluded — replay reproduces everything else)
    @staticmethod
    def trigger_key(tj: dict) -> str:
        return json.dumps({k: v for k, v in sorted(tj.items())
                           if k != "latency_s"}, sort_keys=True)

    def route_quarantine(self, frontier: int) -> int:
        """Route this beam's quality intervals (source ledger: ring
        drops, stalls, truncation, NaN scrub, zero runs; plus the
        mux's own straggler fills) into the engine's offregions.
        Returns newly quarantined spectra."""
        fresh = 0
        for q in (self.source.quality, self.quality):
            if q is None:
                continue
            for iv in q.intervals:
                key = (iv.start, iv.stop, iv.reason)
                if iv.start < frontier and key not in self._routed:
                    self._routed.add(key)
                    self.engine.note_quarantine(
                        iv.start, min(iv.stop, frontier))
                    fresh += min(iv.stop, frontier) - iv.start
        return fresh

    def health(self) -> dict:
        eng = self.engine.summary()
        return {
            "beam": self.beam_id,
            "state": self.state,
            "spectra": eng["spectra"],
            "blocks": self.ticks,
            "triggers": self.emitted,
            "vetoed": self.vetoed,
            "stalled_spectra": self.stalled_spectra,
            "dropped_spectra": self.dropped_spectra,
            "replayed": self.replayed,
            "handoff": self.handoff,
            "source": self.source.stats(),
            "quarantine": dict(self.source.quality.counts()
                               if self.source.quality else {},
                               **self.quality.counts()),
        }


# ----------------------------------------------------------------------
# The multiplexer
# ----------------------------------------------------------------------

class BeamMultiplexer:
    """N same-geometry beam feeds -> one stacked deadline-lane chain.

    An assembler thread aligns per-beam blocks into stacked tick
    bundles (QoS: stragglers degrade to quarantined gap fill after
    `qos_wait_s`, the tick is never stalled); a single outstanding
    deadline-lane tick job runs the stacked dedispersion step(s),
    feeds each beam's series slice to its own StreamSearch, applies
    the cross-beam coincidence veto, commits to the beam ledger and
    emits triggers.
    """

    def __init__(self, service, sources: List[RingBlockSource],
                 cfg: StreamConfig, mux_id: str = "beams-0",
                 beam_ids: Optional[List[str]] = None,
                 coincidence_k: int = 0, veto_window_s: float = 0.1,
                 dm_tol: Optional[float] = None,
                 stack: int = 0, qos_wait_s: float = 0.25,
                 fleet_dir: Optional[str] = None,
                 host: str = "replica-0", lease_ttl: float = 30.0,
                 heartbeat_ttl: float = 10.0, adopt: bool = False,
                 faults=None):
        if not sources:
            raise ValueError("need at least one beam source")
        self.service = service
        self.sources = list(sources)
        self.cfg = cfg
        self.mux_id = mux_id
        self.beam_ids = (list(beam_ids) if beam_ids else
                         ["beam-%d" % i
                          for i in range(len(sources))])
        if len(self.beam_ids) != len(sources):
            raise ValueError("beam_ids/sources length mismatch")
        self.veto = CoincidenceVeto(coincidence_k, veto_window_s,
                                    dm_tol)
        self.stack = int(stack)
        self.qos_wait_s = float(qos_wait_s)
        self.fleet_dir = fleet_dir
        self.host = host
        self.lease_ttl = float(lease_ttl)
        self.heartbeat_ttl = float(heartbeat_ttl)
        self.adopt = adopt
        self.faults = faults
        self.obs = service.obs
        self.events = service.events
        self.lanes: List[BeamLane] = []
        self.groups: List[Tuple[StackedRollingDedisp,
                                List[int]]] = []
        self.ledger: Optional[BeamLedger] = None
        self.epoch = 0
        self.blocklen = 0
        self._inbox: deque = deque()
        self._inbox_lock = threading.Lock()
        self._tick_out = False
        self._tick_ids = itertools.count(1)
        self._threads: List[threading.Thread] = []
        self._done = threading.Event()
        self._failed: Optional[BaseException] = None
        reg = self.obs.metrics
        self._g_beams = reg.gauge(
            "stream_beams", "Live beams in the multiplexer")
        self._c_stalled = reg.counter(
            "stream_beam_stalled_total",
            "Spectra gap-filled for a straggler beam (quarantined)",
            ("beam",))
        self._c_dropped = reg.counter(
            "stream_beam_dropped_total",
            "Late straggler spectra discarded to stay wall-clock "
            "aligned", ("beam",))
        self._c_vetoed = reg.counter(
            "stream_beam_vetoed_total",
            "Triggers vetoed by cross-beam coincidence", ("beam",))
        self._c_handoffs = reg.counter(
            "stream_beam_handoffs_total",
            "Beams adopted from a dead replica via the beam ledger",
            ("beam",))
        self._c_trigs = reg.counter(
            "stream_triggers_total", "Deduplicated triggers emitted")
        self._c_blocks = reg.counter(
            "stream_blocks_total", "Live-feed blocks processed")
        self._h_latency = reg.histogram(
            "stream_latency_seconds",
            "Sample arrival -> trigger emitted", ("stream", "beam"),
            buckets=LATENCY_BUCKETS)

    # ---- chaos seam ---------------------------------------------------

    def _point(self, name: str) -> None:
        if self.faults is not None:
            self.faults.point(name)

    # ---- lifecycle ----------------------------------------------------

    def start(self) -> "BeamMultiplexer":
        t = threading.Thread(target=self._run,
                             name="presto-beams-assemble",
                             daemon=True)
        self._threads.append(t)
        t.start()
        return self

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def failed(self) -> Optional[BaseException]:
        return self._failed

    # ---- setup (assembler thread) -------------------------------------

    def _resolve_stack(self, nbeams: int) -> int:
        if self.stack > 0:
            return min(self.stack, nbeams)
        try:
            from presto_tpu import tune
            if tune.enabled():
                best = tune.best("beam_stack_size", tune.GLOBAL_KEY)
                if best and int(best.get("stack", 0)) > 0:
                    return min(int(best["stack"]), nbeams)
        except Exception:
            pass
        return min(nbeams, 64)

    def _setup(self) -> None:
        hdrs = [s.wait_header() for s in self.sources]
        for h in hdrs:
            if h is None:
                raise RuntimeError("a beam feed ended before its "
                                   "header")
            geom = (h.nchans, h.tsamp, h.nbits, h.fch1, h.foff)
            if geom != (hdrs[0].nchans, hdrs[0].tsamp,
                        hdrs[0].nbits, hdrs[0].fch1, hdrs[0].foff):
                raise ValueError(
                    "beam geometry mismatch: %r vs %r"
                    % (geom, (hdrs[0].nchans, hdrs[0].tsamp,
                              hdrs[0].nbits, hdrs[0].fch1,
                              hdrs[0].foff)))
        first = StreamSearch(hdrs[0], self.cfg)
        self.blocklen = first.blocklen
        engines = [first] + [
            StreamSearch(h, self.cfg, blocklen=self.blocklen)
            for h in hdrs[1:]]
        self.lanes = [BeamLane(bid, src, eng)
                      for bid, src, eng in zip(self.beam_ids,
                                               self.sources,
                                               engines)]
        for src in self.sources:
            src.configure(self.blocklen)
        stack = self._resolve_stack(len(self.lanes))
        for lo in range(0, len(self.lanes), stack):
            idxs = list(range(lo, min(lo + stack,
                                      len(self.lanes))))
            self.groups.append((StackedRollingDedisp(
                first._chan_bins, first._dm_bins, self.cfg.nsub,
                self.cfg.downsamp), idxs))
        self._attach_ledger()
        self._g_beams.set(len(self.lanes))
        self.events.emit("beam-start", stream=self.mux_id,
                         nbeams=len(self.lanes),
                         blocklen=self.blocklen,
                         numdms=self.cfg.numdms,
                         stack=stack, groups=len(self.groups),
                         coincidence_k=self.veto.k, host=self.host)

    def _attach_ledger(self) -> None:
        if self.fleet_dir is None:
            return
        self.ledger = BeamLedger(self.fleet_dir, obs=self.obs)
        self.epoch = self.ledger.join(self.host)
        if self.adopt:
            self.ledger.reap(self.heartbeat_ttl)
        self.ledger.ensure_items(
            [(lane.beam_id, {"triggers": [], "frontier_s": 0.0,
                             "vetoed": 0})
             for lane in self.lanes], meta={"mux": self.mux_id})
        by_id = {lane.beam_id: lane for lane in self.lanes}
        while True:
            lease = self.ledger.lease(self.host, self.lease_ttl)
            if lease is None:
                break
            lane = by_id.get(lease.item_id)
            if lane is None:
                self.ledger.fail(lease, self.host)
                continue
            lane.lease = lease
            prior = lease.data.get("triggers") or []
            if prior or float(lease.data.get("frontier_s", 0.0)) > 0:
                # a predecessor replica got this far: replay and
                # suppress its committed set
                lane.handoff = True
                lane.committed = {BeamLane.trigger_key(tj)
                                  for tj in prior}
                self._c_handoffs.labels(beam=lane.beam_id).inc()
                self._point("beam-handoff")
                self.events.emit("beam-handoff",
                                 stream=self.mux_id,
                                 beam=lane.beam_id, host=self.host,
                                 committed=len(lane.committed),
                                 frontier_s=lease.data.get(
                                     "frontier_s", 0.0))
        unleased = [lane.beam_id for lane in self.lanes
                    if lane.lease is None]
        if unleased:
            raise BeamLedgerError(
                "beams %s are leased elsewhere or terminal"
                % ",".join(unleased))
        self.ledger.heartbeat(self.host, self.epoch)

    # ---- reader threads -----------------------------------------------

    #: reader-side inbox depth bound: past this the reader leaves
    #: blocks in the source ring (bounded, with explicit ring-drop
    #: accounting) instead of buffering unboundedly in the lane
    INBOX_DEPTH = 8

    def _read_loop(self, lane: BeamLane) -> None:
        try:
            while True:
                while self._failed is None:
                    with lane.lock:
                        depth = len(lane.inbox)
                    if depth < self.INBOX_DEPTH:
                        break
                    time.sleep(0.005)
                blk = lane.source.next_block(timeout=0.25)
                if blk is None:
                    if lane.source.at_eof:
                        break
                    continue
                with lane.lock:
                    lane.inbox.append(blk)
        except BaseException as e:
            self._failed = self._failed or e
        finally:
            lane.feed_eof = True

    # ---- assembler ----------------------------------------------------

    def _run(self) -> None:
        try:
            self._setup()
            for lane in self.lanes:
                t = threading.Thread(
                    target=self._read_loop, args=(lane,),
                    name="presto-beams-read-%s" % lane.beam_id,
                    daemon=True)
                self._threads.append(t)
                t.start()
            tick = 0
            # every lane needs its real blocks plus two flush pads
            # (the two zero blocks the independent finish() feeds);
            # pad_issued bounds the pipeline against the tick thread
            # lagging the assembler
            while any(lane.feed_state == BeamLane.LIVE
                      or lane.pad_issued < 2
                      for lane in self.lanes):
                bundle = self._assemble(tick)
                if bundle is None:        # reader failure
                    break
                self._enqueue(bundle)
                tick += 1
            if self._failed is None:
                self._enqueue(None)       # EOF sentinel
            else:
                self._done.set()
        except BaseException as e:
            self._failed = e
            self._done.set()

    def _assemble(self, tick: int) -> Optional[dict]:
        """Align every non-done lane's next block into one stacked
        tick.  A lane at feed EOF (or already flushing) contributes a
        zero pad block; a straggler past `qos_wait_s` degrades to a
        quarantined zero gap block (and its late block is discarded
        on arrival)."""
        nchan = self.lanes[0].engine.hdr.nchans
        deadline: Optional[float] = None
        while True:
            if self._failed is not None:
                return None
            waiting = False
            any_ready = False
            for lane in self.lanes:
                if lane.feed_state != BeamLane.LIVE:
                    continue
                with lane.lock:
                    has = bool(lane.inbox)
                if has or lane.feed_eof:
                    any_ready = True
                else:
                    waiting = True
            if not waiting:
                break
            now = time.time()
            if any_ready and deadline is None:
                deadline = now + self.qos_wait_s
            if deadline is not None and now >= deadline:
                break
            time.sleep(0.005)

        data = np.zeros((len(self.lanes), self.blocklen, nchan),
                        np.float32)
        nreal = [0] * len(self.lanes)
        arrivals = [time.time()] * len(self.lanes)
        synth = [False] * len(self.lanes)
        pads = [0] * len(self.lanes)      # 0 = live slice, n = nth pad
        for i, lane in enumerate(self.lanes):
            if lane.feed_state != BeamLane.LIVE:
                lane.pad_issued += 1      # flushing: zero pad
                pads[i] = lane.pad_issued
                continue
            blk = None
            with lane.lock:
                # a block older than this tick is a straggler whose
                # slot was already gap-filled: discard, stay aligned
                while lane.inbox and lane.inbox[0].seq < tick:
                    late = lane.inbox.popleft()
                    lane.dropped_spectra += late.nreal
                    self._c_dropped.labels(
                        beam=lane.beam_id).inc(late.nreal)
                    self.events.emit("beam-drop",
                                     stream=self.mux_id,
                                     beam=lane.beam_id,
                                     seq=late.seq,
                                     spectra=late.nreal)
                if lane.inbox:
                    blk = lane.inbox.popleft()
            if blk is not None:
                data[i] = blk.data
                nreal[i] = blk.nreal
                arrivals[i] = blk.t_arrival
            elif lane.feed_eof:
                # last real block consumed: this tick starts the
                # lane's two-block flush
                lane.feed_state = BeamLane.FLUSHING
                lane.pad_issued = 1
                pads[i] = 1
            else:
                # straggler: degrade to quarantined gap fill
                synth[i] = True
                lo = tick * self.blocklen
                lane.quality.add(lo, lo + self.blocklen, "stall")
                lane.stalled_spectra += self.blocklen
                self._c_stalled.labels(
                    beam=lane.beam_id).inc(self.blocklen)
                self.events.emit("beam-stall", stream=self.mux_id,
                                 beam=lane.beam_id, tick=tick,
                                 spectra=self.blocklen)
            lane.ticks = tick + 1
        return {"tick": tick, "data": data, "nreal": nreal,
                "arrivals": arrivals, "synth": synth, "pads": pads}

    # ---- deadline tick ------------------------------------------------

    #: assembler -> tick-thread bundle backlog bound: the assembler
    #: blocks here when the device chain lags (compile, slow tick), so
    #: backpressure reaches the source rings instead of heap bundles
    TICK_BACKLOG = 4

    def _enqueue(self, bundle: Optional[dict]) -> None:
        while bundle is not None:
            with self._inbox_lock:
                if len(self._inbox) < self.TICK_BACKLOG:
                    break
            if self._failed is not None or self._done.is_set():
                return
            time.sleep(0.005)
        with self._inbox_lock:
            self._inbox.append(bundle)
            if self._tick_out:
                return
            self._tick_out = True
        self.service.submit_callable(
            self._tick, lane="deadline",
            job_id="%s-tick-%06d" % (self.mux_id,
                                     next(self._tick_ids)),
            bucket=("stream", self.mux_id))

    def _tick(self, job) -> dict:
        processed = 0
        emitted = 0
        while True:
            with self._inbox_lock:
                if not self._inbox:
                    self._tick_out = False
                    break
                bundle = self._inbox.popleft()
            if bundle is None:
                emitted += self._finish()
                continue
            self._point("beam-tick")
            span = self.obs.span("stream:beam-tick",
                                 stream=self.mux_id,
                                 tick=bundle["tick"])
            try:
                emitted += self._process(bundle)
                processed += 1
            finally:
                span.finish()
        return {"stream": self.mux_id, "ticks": processed,
                "triggers": emitted}

    def _process(self, bundle: dict) -> int:
        tick = bundle["tick"]
        # ONE stacked dispatch chain per group, O(1) in beam count
        series_by_lane: Dict[int, Optional[np.ndarray]] = {}
        for rolling, idxs in self.groups:
            out, dispatched = rolling.feed(bundle["data"][idxs])
            if dispatched:
                jaxtel.note_dispatch(self.obs, "beam_dedisp",
                                     dispatched)
            for j, i in enumerate(idxs):
                series_by_lane[i] = (out[j] if out is not None
                                     else None)
        self._c_blocks.inc()
        pending: List[Tuple[BeamLane, Trigger]] = []
        for i, lane in enumerate(self.lanes):
            if lane.state == BeamLane.DONE:
                continue
            frontier = (tick + 1) * self.blocklen
            lane.route_quarantine(frontier)
            series = series_by_lane.get(i)
            padn = bundle["pads"][i]
            if padn == 0:                 # live slice (real or synth)
                if bundle["nreal"][i]:
                    # stamped here (tick thread), not the assembler:
                    # trigger latency reads this and the assembler can
                    # run many bundles ahead
                    lane.last_t_arrival = bundle["arrivals"][i]
                trigs = lane.engine.feed_series(
                    series, bundle["nreal"][i])
            else:                         # assembler-issued flush pad
                lane.state = BeamLane.FLUSHING
                if series is not None and padn <= 2:
                    lane.flush_series.append(series)
                lane.flush_ticks += 1
                trigs = []
                if padn >= 2:
                    trigs = lane.engine.finish_series(
                        lane.flush_series)
                    lane.state = BeamLane.DONE
            pending.extend((lane, tr) for tr in trigs)
            if lane.state == BeamLane.DONE:
                self.events.emit("beam-eof", stream=self.mux_id,
                                 beam=lane.beam_id,
                                 **lane.engine.summary())
        live = sum(1 for lane in self.lanes
                   if lane.state != BeamLane.DONE)
        self._g_beams.set(live)
        return self._emit_pending(pending, final=(live == 0))

    def _frontier_s(self) -> float:
        fronts = [lane.engine._frontier_time()
                  for lane in self.lanes
                  if lane.state != BeamLane.DONE]
        return min(fronts) if fronts else float("inf")

    def _emit_pending(self,
                      pending: List[Tuple[BeamLane, Trigger]],
                      final: bool = False) -> int:
        """Veto -> ledger commit -> event emission, in that order:
        the ledger row is the authoritative emitted set, so a kill
        between commit and emission is recovered (never duplicated)
        by the successor's replay suppression."""
        now = time.time()
        if self.veto.enabled:
            for lane, tr in pending:
                self.veto.add(lane.beam_id, tr)
            ripe, vetoes = self.veto.drain(self._frontier_s(),
                                           final=final)
        else:
            ripe = [(lane.beam_id, tr) for lane, tr in pending]
            vetoes = []
        by_id = {lane.beam_id: lane for lane in self.lanes}
        veto_counts: Dict[str, int] = {}
        for v in vetoes:
            for beam in v.evidence:
                veto_counts[beam] = veto_counts.get(beam, 0) + 1
                by_id[beam].vetoed += 1
                self._c_vetoed.labels(beam=beam).inc()
        out: List[Tuple[BeamLane, Trigger, dict]] = []
        updates: Dict[str, dict] = {}
        for beam, tr in ripe:
            lane = by_id[beam]
            tr.latency_s = max(now - lane.last_t_arrival, 0.0)
            tj = tr.to_json()
            key = BeamLane.trigger_key(tj)
            if key in lane.committed:
                lane.replayed += 1        # predecessor emitted it
                continue
            lane.committed.add(key)
            out.append((lane, tr, tj))
            updates.setdefault(beam, {"triggers": [],
                                      "vetoed": 0})[
                "triggers"].append(
                {k: v for k, v in tj.items() if k != "latency_s"})
        for beam, n in veto_counts.items():
            updates.setdefault(beam, {"triggers": []})["vetoed"] = n
        self._point("beam-commit")
        self._commit(updates)
        for lane, tr, tj in out:
            lane.emitted += 1
            self._c_trigs.inc()
            self._h_latency.labels(stream=self.mux_id,
                                   beam=lane.beam_id).observe(
                tr.latency_s)
            self.events.emit("trigger", stream=self.mux_id,
                             beam=lane.beam_id, **tj)
        for v in vetoes:
            self.events.emit("beam-veto", stream=self.mux_id,
                             **v.to_json())
        return len(out)

    def _commit(self, updates: Dict[str, dict]) -> None:
        if self.ledger is None:
            return
        frontier = self._frontier_s()
        full: Dict[str, dict] = {}
        leases: Dict[str, object] = {}
        for lane in self.lanes:
            # a DONE lane still holds its lease until _finish
            # completes it — its flush-stage triggers commit here
            if lane.lease is None:
                continue
            up = dict(updates.get(lane.beam_id,
                                  {"triggers": [], "vetoed": 0}))
            up["frontier_s"] = (frontier
                                if np.isfinite(frontier) else 0.0)
            full[lane.beam_id] = up
            leases[lane.beam_id] = lane.lease
        if full:
            self.ledger.advance(leases, self.host, full,
                                self.lease_ttl)
        self.ledger.heartbeat(self.host, self.epoch)

    def _finish(self) -> int:
        # final veto drain (pending triggers whose window never
        # closed mid-stream) — all lanes are DONE by now
        ripe_pending: List[Tuple[BeamLane, Trigger]] = []
        n = self._emit_pending(ripe_pending, final=True)
        if self.ledger is not None:
            for lane in self.lanes:
                if lane.lease is None:
                    continue
                if lane.state == BeamLane.DONE:
                    self.ledger.complete(
                        lane.lease, self.host, {},
                        extra={"summary": lane.engine.summary()})
                else:                     # feed died: let another
                    self.ledger.fail(lane.lease, self.host)  # retry
                lane.lease = None
            self.ledger.tombstone(self.host)
        self.events.emit("stream-eof", stream=self.mux_id,
                         **self.summary_totals())
        workdir = getattr(self.service, "workroot", None)
        if workdir:
            try:
                self.write_health(os.path.join(workdir,
                                               "beams.json"))
            except OSError:
                pass
        self._done.set()
        return n

    # ---- views --------------------------------------------------------

    def summary_totals(self) -> dict:
        return {
            "beams": len(self.lanes),
            "triggers": sum(l.emitted for l in self.lanes),
            "vetoed": sum(l.vetoed for l in self.lanes),
            "stalled_spectra": sum(l.stalled_spectra
                                   for l in self.lanes),
            "dropped_spectra": sum(l.dropped_spectra
                                   for l in self.lanes),
            "replayed": sum(l.replayed for l in self.lanes),
            "handoffs": sum(1 for l in self.lanes if l.handoff),
        }

    def summary(self) -> dict:
        out = {"stream": self.mux_id, "host": self.host}
        out.update(self.summary_totals())
        out["per_beam"] = [lane.health() for lane in self.lanes]
        lat = {}
        for lane in self.lanes:
            h = self._h_latency.labels(stream=self.mux_id,
                                       beam=lane.beam_id)
            if h.count:
                lat[lane.beam_id] = h.percentiles((50, 90, 99))
        out["latency"] = lat
        return out

    def write_health(self, path: str) -> None:
        from presto_tpu.io.atomic import atomic_write_text
        atomic_write_text(path, json.dumps(
            self.summary(), indent=1, sort_keys=True) + "\n")


# ----------------------------------------------------------------------
# presto-beams CLI
# ----------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="presto-beams",
        description="Multiplex N same-geometry beam feeds into one "
                    "stacked real-time trigger chain")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("-tails", type=str, nargs="+",
                     metavar="FILE.fil",
                     help="Tail these filterbank files, one beam "
                          "each (replayable: required for hand-off)")
    src.add_argument("-listen", type=str, metavar="HOST:PORT",
                     help="Accept -beams feeds on consecutive ports "
                          "starting here")
    p.add_argument("-beams", type=int, default=0,
                   help="Beam count for -listen mode")
    p.add_argument("-lodm", type=float, default=0.0)
    p.add_argument("-dmstep", type=float, default=1.0)
    p.add_argument("-numdms", type=int, default=8)
    p.add_argument("-nsub", type=int, default=32)
    p.add_argument("-downsamp", type=int, default=1)
    p.add_argument("-thresh", type=float, default=6.0)
    p.add_argument("-blocklen", type=int, default=0)
    p.add_argument("-ring", type=int, default=16)
    p.add_argument("-stall-timeout", dest="stall_timeout",
                   type=float, default=None)
    p.add_argument("-dedup", type=float, default=0.25)
    p.add_argument("-coincidence", type=int, default=0,
                   help="Veto triggers hitting >= K beams at the "
                        "same time/DM (0/1 = off)")
    p.add_argument("-veto-window", dest="veto_window", type=float,
                   default=0.1,
                   help="Coincidence clustering window (seconds)")
    p.add_argument("-dm-tol", dest="dm_tol", type=float,
                   default=None,
                   help="Also require |dDM| <= this to cluster "
                        "(default: any DM)")
    p.add_argument("-stack", type=int, default=0,
                   help="Beams per stacked device step (0 = tuned "
                        "beam_stack_size, else min(beams, 64))")
    p.add_argument("-qos-wait", dest="qos_wait", type=float,
                   default=0.25,
                   help="Seconds a tick waits for a straggler beam "
                        "before degrading it to gap fill")
    p.add_argument("-fleet", type=str, default=None,
                   help="Fleet directory holding the beam ledger "
                        "(enables lease/fence + hand-off)")
    p.add_argument("-host", type=str, default="replica-0",
                   help="Replica name in the beam ledger")
    p.add_argument("-adopt", action="store_true",
                   help="Reap dead replicas before leasing (the "
                        "successor side of a hand-off)")
    p.add_argument("-lease-ttl", dest="lease_ttl", type=float,
                   default=30.0)
    p.add_argument("-hb-ttl", dest="hb_ttl", type=float,
                   default=10.0)
    p.add_argument("-port", type=int, default=0,
                   help="Serve the HTTP API (/events, /metrics) "
                        "here (0 = off)")
    p.add_argument("-workdir", type=str, default="beams_work")
    p.add_argument("-events", type=str, default=None)
    p.add_argument("-heartbeat", type=float, default=2.0)
    p.add_argument("-json", dest="json_out", type=str, default=None)
    p.add_argument("-timeout", type=float, default=None)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from presto_tpu.apps.common import ensure_backend
    ensure_backend()
    from presto_tpu.serve.server import SearchService, start_http
    cfg = StreamConfig(lodm=args.lodm, dmstep=args.dmstep,
                       numdms=args.numdms, nsub=args.nsub,
                       downsamp=args.downsamp, threshold=args.thresh,
                       blocklen=args.blocklen or None,
                       trigger_dedup_s=args.dedup,
                       ring_capacity=args.ring,
                       stall_timeout_s=args.stall_timeout)
    service = SearchService(args.workdir, events_path=args.events,
                            heartbeat_s=args.heartbeat)
    service.start()
    sources, producers = [], []
    if args.tails:
        for path in args.tails:
            src = RingBlockSource(capacity=cfg.ring_capacity,
                                  policy=cfg.ring_policy,
                                  stall_timeout_s=cfg.stall_timeout_s)
            sources.append(src)
            producers.append(FileTailProducer(src, path,
                                              idle_eof_s=1.0).start())
        print("presto-beams: tailing %d beams" % len(sources))
    else:
        if args.beams < 1:
            print("presto-beams: -listen needs -beams N",
                  file=sys.stderr)
            return 2
        host, _, port = args.listen.rpartition(":")
        for i in range(args.beams):
            src = RingBlockSource(capacity=cfg.ring_capacity,
                                  policy=cfg.ring_policy,
                                  stall_timeout_s=cfg.stall_timeout_s)
            sources.append(src)
            producers.append(SocketProducer(
                src, host or "127.0.0.1", int(port) + i).start())
        print("presto-beams: listening for %d beams on %s:%d.."
              % (args.beams, host or "127.0.0.1", int(port)))
    httpd = None
    if args.port:
        httpd = start_http(service, port=args.port)
        print("presto-beams: HTTP on http://%s:%d"
              % httpd.server_address[:2])
    mux = BeamMultiplexer(
        service, sources, cfg,
        coincidence_k=args.coincidence,
        veto_window_s=args.veto_window, dm_tol=args.dm_tol,
        stack=args.stack, qos_wait_s=args.qos_wait,
        fleet_dir=args.fleet, host=args.host, adopt=args.adopt,
        lease_ttl=args.lease_ttl,
        heartbeat_ttl=args.hb_ttl).start()
    ok = mux.wait(args.timeout)
    summary = mux.summary()
    summary["ok"] = bool(ok and mux.failed is None)
    if mux.failed is not None:
        summary["error"] = "%s: %s" % (type(mux.failed).__name__,
                                       mux.failed)
    print(json.dumps(summary, sort_keys=True))
    if args.json_out:
        from presto_tpu.io.atomic import atomic_write_text
        atomic_write_text(args.json_out,
                          json.dumps(summary, indent=1,
                                     sort_keys=True) + "\n")
    for prod in producers:
        close = getattr(prod, "close", None)
        if close:
            close()
    if httpd is not None:
        httpd.shutdown()
    service.stop()
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
