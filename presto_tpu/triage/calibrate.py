"""Calibration: label candidates for free against injected ground
truth, train the ranker, and report recall-at-budget.

The labeling trick (the whole reason triage can be trusted at all):
`models/inject.py` writes a ground-truth sidecar
(``<out>_injected.json``) beside every injected file, so any survey
or campaign that processed injected data carries its own eval set —
a sifted candidate matching an injected pulsar's (period, DM) within
tolerance (any harmonic) is a positive, everything else a negative.
``presto-triage`` rides that loop: featurize -> label -> seeded
train -> recall-at-budget report, continuously, with no human
labels.

The acceptance artifact (TRIAGE_r20.json) is produced by
`synthetic_campaign` + `acceptance_report`: a seeded multi-
observation campaign of noise + injected candidates, trained on a
held-out prefix, evaluated on the rest — >=99% recall at >=5x fold
reduction, deterministic under the seed (tests/test_triage.py runs
the same function and pins the thresholds).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.triage.features import featurize
from presto_tpu.triage.model import TriageModel, train_model

#: harmonic ratios a matched candidate may sit at relative to the
#: injected spin frequency (ACCEL candidates routinely lock onto
#: harmonics and subharmonics)
_MAX_HARM = 16


def truth_matches(cands: Sequence, truth: Sequence[dict],
                  f_tol: float = 0.02, dm_tol: float = 3.0) \
        -> List[Optional[int]]:
    """Per-candidate index into ``truth`` (None = unmatched): the
    candidate's frequency sits within ``f_tol`` (fractional) of
    k*f_true or f_true/k for some harmonic k, and its DM within
    ``dm_tol`` of the injected DM."""
    out: List[Optional[int]] = []
    for c in cands:
        hit = None
        for ti, rec in enumerate(truth):
            ft = float(rec.get("f") or 0.0)
            if ft <= 0:
                p = float(rec.get("period") or 0.0)
                if p <= 0:
                    continue
                ft = 1.0 / p
            if abs(float(c.DM) - float(rec.get("dm", 0.0))) > dm_tol:
                continue
            for k in range(1, _MAX_HARM + 1):
                for f_h in (ft * k, ft / k):
                    if abs(float(c.f) - f_h) <= f_tol * f_h:
                        hit = ti
                        break
                if hit is not None:
                    break
            if hit is not None:
                break
        out.append(hit)
    return out


def label_candidates(cands: Sequence, truth: Sequence[dict],
                     f_tol: float = 0.02, dm_tol: float = 3.0) \
        -> np.ndarray:
    """[n] 0/1 labels: 1 where the candidate matches an injected
    pulsar."""
    m = truth_matches(cands, truth, f_tol=f_tol, dm_tol=dm_tol)
    return np.array([0.0 if x is None else 1.0 for x in m])


def recall_at_budget(cands: Sequence, scores: np.ndarray,
                     truth: Sequence[dict], budget: int,
                     f_tol: float = 0.02, dm_tol: float = 3.0) \
        -> Dict[str, float]:
    """Fraction of injected pulsars matched by at least one candidate
    inside the top-``budget`` by score (a pulsar recovered by ANY of
    its harmonics counts once)."""
    if not truth:
        return {"recall": 1.0, "budget": int(budget), "truth": 0}
    order = np.argsort(-np.asarray(scores, np.float64),
                       kind="stable")[:max(int(budget), 0)]
    kept = [cands[i] for i in order]
    matched = {m for m in truth_matches(kept, truth, f_tol=f_tol,
                                        dm_tol=dm_tol)
               if m is not None}
    return {"recall": len(matched) / len(truth),
            "budget": int(budget), "truth": len(truth),
            "recovered": len(matched)}


def train_on_observations(obs_sets: Sequence[Tuple[Sequence, Sequence[dict]]],
                          seed: int = 0, obs=None) -> TriageModel:
    """Train one model over many (candidates, truth) observation
    pairs — the calibration loop's core.  Fully seeded; emits the
    ``triage-calibrate`` event when an obs context is provided."""
    Xs, ys = [], []
    for cands, truth in obs_sets:
        if not cands:
            continue
        Xs.append(featurize(cands))
        ys.append(label_candidates(cands, truth))
    if not Xs:
        raise ValueError("no candidates to train on")
    X = np.concatenate(Xs, axis=0)
    y = np.concatenate(ys, axis=0)
    model = train_model(X, y, seed=seed)
    if obs is not None:
        obs.events.emit("triage-calibrate", observations=len(obs_sets),
                        candidates=int(X.shape[0]),
                        positives=int(y.sum()), seed=int(seed))
    return model


# ----------------------------------------------------------------------
# synthetic campaign (the acceptance rig)
# ----------------------------------------------------------------------

def synthetic_observation(rng, n_noise: int = 400, n_psr: int = 2,
                          T: float = 120.0):
    """(candidates, truth): one synthetic observation's sifted
    survivors — a noise population whose sigma tail overlaps the
    injected pulsars', so a bare sigma cut cannot reach high recall
    at a tight budget, while DM-trial support / harmonic structure /
    power concentration separate the classes the way they do on real
    ACCEL tables."""
    from presto_tpu.pipeline.sifting import Candidate
    cands, truth = [], []

    def _mk(num, sigma, numharm, ipow, cpow, r, z, dm, hits):
        c = Candidate(candnum=num, sigma=round(sigma, 2),
                      numharm=numharm, ipow_det=round(ipow, 2),
                      cpow=round(cpow, 2), r=round(r, 2),
                      z=round(z, 2), DMstr="%.2f" % dm,
                      filename="synth_DM%.2f_ACCEL_0" % dm, T=T)
        c.snr = float(np.sqrt(max(ipow - numharm, 0.0)))
        c.hits = hits
        return c

    num = 1
    for _ in range(n_noise):
        sigma = float(rng.gamma(2.0, 1.4) + 4.0)      # tail past 12
        dm = float(rng.uniform(2.0, 95.0))
        ipow = float(rng.gamma(2.0, 4.0) + 4.0)
        nh = int(rng.choice([1, 1, 1, 2, 2, 4]))
        # real ACCEL semantics: a single-harmonic candidate has
        # cpow == ipow (frac 1.0); incoherent summing only dilutes
        cpow = ipow if nh == 1 \
            else ipow * float(rng.uniform(0.35, 0.8))
        hits = [(dm, np.sqrt(max(ipow - nh, 0.0)), sigma)]
        for _extra in range(int(rng.poisson(0.3))):
            hits.append((dm + float(rng.normal(0, 1.0)),
                         float(rng.uniform(2, 4)),
                         sigma * float(rng.uniform(0.5, 0.9))))
        cands.append(_mk(num, sigma, nh, ipow, cpow,
                         float(rng.uniform(50, 5e4)),
                         float(rng.normal(0, 40.0)), dm,
                         sorted(hits)))
        num += 1
    for _ in range(n_psr):
        f = float(rng.uniform(0.8, 40.0))
        dm = float(rng.uniform(10.0, 80.0))
        sigma = float(rng.uniform(6.0, 60.0))
        nh = int(rng.choice([4, 8, 8, 16]))
        ipow = float(sigma ** 2 * rng.uniform(1.2, 1.8) + nh)
        nhits = int(rng.integers(6, 14))
        hits = sorted(
            (dm + float(rng.normal(0, 0.8)),
             float(np.sqrt(ipow) * rng.uniform(0.5, 1.0)),
             sigma * float(rng.uniform(0.6, 1.0)))
            for _h in range(nhits))
        # harmonic summing: the coherent (fundamental) power is a
        # ~1/nh slice of the summed power, a bit more for peaked
        # profiles — frac WELL BELOW a single-harmonic noise cand's
        cpow = ipow / nh * float(rng.uniform(1.0, 2.0))
        cands.append(_mk(num, sigma, nh, ipow,
                         min(cpow, ipow), f * T,
                         float(rng.normal(0, 6.0)), dm, hits))
        truth.append({"t": 0.0, "dm": dm, "f": f, "period": 1.0 / f,
                      "snr": sigma})
        num += 1
    return cands, truth


def synthetic_campaign(seed: int = 20, n_obs: int = 12, **kw):
    """[(candidates, truth)] for ``n_obs`` seeded observations."""
    rng = np.random.default_rng(int(seed))
    return [synthetic_observation(rng, **kw) for _ in range(n_obs)]


def acceptance_report(seed: int = 20, n_obs: int = 12,
                      train_frac: float = 0.5,
                      reduction: float = 5.0) -> dict:
    """The TRIAGE_r20.json payload: train on the first
    ``train_frac`` observations, evaluate recall on the rest at a
    fold budget ``reduction``x smaller than the heuristic
    selection's, and report both numbers plus determinism evidence
    (the eval ranking hashed twice from two independent scoring
    passes)."""
    import hashlib
    campaign = synthetic_campaign(seed=seed, n_obs=n_obs)
    n_train = max(int(n_obs * train_frac), 1)
    model = train_on_observations(campaign[:n_train], seed=seed)
    per_obs, rank_hashes = [], []
    deterministic = True
    tot_truth = tot_recovered = tot_heur = tot_folds = 0
    for cands, truth in campaign[n_train:]:
        scores = model.score_candidates(cands)
        scores2 = model.score_candidates(cands)
        order = np.argsort(-scores, kind="stable")
        rank_hashes.append(hashlib.sha256(
            (",".join(str(int(i)) for i in order)).encode())
            .hexdigest())
        deterministic &= np.array_equal(
            order, np.argsort(-scores2, kind="stable"))
        budget = max(int(len(cands) // reduction), 1)
        r = recall_at_budget(cands, scores, truth, budget)
        per_obs.append({"candidates": len(cands), **r})
        tot_truth += r["truth"]
        tot_recovered += r["recovered"]
        tot_heur += len(cands)
        tot_folds += budget
    return {
        "schema": 1,
        "seed": int(seed),
        "observations": {"total": n_obs, "train": n_train,
                         "eval": n_obs - n_train},
        "trained_on": int(model.trained_on),
        "recall": (tot_recovered / tot_truth) if tot_truth else 1.0,
        "injected": tot_truth,
        "recovered": tot_recovered,
        "heuristic_folds": tot_heur,
        "triage_folds": tot_folds,
        "fold_reduction": (tot_heur / tot_folds) if tot_folds else 0.0,
        "folds_avoided": tot_heur - tot_folds,
        "deterministic_ranking": bool(deterministic),
        "rank_hashes": rank_hashes,
        "per_observation": per_obs,
    }


# ----------------------------------------------------------------------
# sidecar discovery
# ----------------------------------------------------------------------

def find_truth_sidecars(paths: Sequence[str]) -> List[str]:
    """Existing ``*_injected.json`` sidecars for a list of data
    files (the DAG/campaign auto-discovery: plan_dag stamps these
    into the triage node spec so recall rides real traffic)."""
    from presto_tpu.models.inject import truth_sidecar_path
    out = []
    for p in paths:
        side = truth_sidecar_path(p)
        if os.path.exists(side):
            out.append(side)
    return out


def load_truth(path: str) -> List[dict]:
    """Records from one sidecar (empty on any structural problem —
    recall reporting degrades, selection never breaks)."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return []
    recs = raw.get("injected") if isinstance(raw, dict) else None
    return [r for r in recs or [] if isinstance(r, dict)]
