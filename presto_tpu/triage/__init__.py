"""Learned candidate triage: score sift survivors, fold only the
ones that matter.

At campaign scale the fold stage is O(candidates) device work for
O(few) pulsars, and the shared fold-selection policy
(pipeline/sifting.select_fold_candidates) is a blunt sigma rank.
This package is the AutoTVM-shaped answer the tune layer already
uses for kernels (PAPERS.md: Chen et al. 2018): cheap *measured*
features per candidate (triage/features.py), a small learned ranker
persisted in a schema-versioned weights file (triage/model.py, the
tune/db.py durability rules — atomic writes, corrupted-load degrades
to the heuristic), and continuous calibration against injected
ground truth riding real traffic (triage/calibrate.py +
presto-triage).

Triage is POLICY, never data path: it chooses *which* folds run,
so every fold artifact stays byte-equal to an untriaged run of the
same selection, and the heuristic sigma rank remains the byte-stable
default whenever triage is off, unconfigured, or its weights file is
unloadable.  See docs/TRIAGE.md.
"""

from presto_tpu.triage.features import (FEATURE_NAMES, featurize,
                                        fold_profile_features)
from presto_tpu.triage.model import (ENV_WEIGHTS, SCHEMA_VERSION,
                                     WEIGHTS_BASENAME, TriageModel,
                                     TriagePolicy,
                                     default_weights_path,
                                     load_model, train_model)

__all__ = [
    "FEATURE_NAMES", "featurize", "fold_profile_features",
    "TriageModel", "TriagePolicy", "SCHEMA_VERSION",
    "WEIGHTS_BASENAME", "ENV_WEIGHTS", "default_weights_path",
    "load_model", "train_model",
]
