"""The triage ranker: a small pure-JAX logistic scorer with a
schema-versioned, atomically-written weights file.

The durability rules are tune/db.py's, because the failure economics
are the same — a learned artifact must never be trusted over ground
truth, and a bad file on disk must never take the pipeline down:

  * loads are *defensive*: a missing, corrupted, stale-schema, or
    feature-layout-mismatched weights file degrades to ``None`` with
    a warning (callers then run the heuristic sigma rank, byte-equal
    to an untriaged run — pinned by tests/test_triage.py);
  * saves go through ``io/atomic`` (the lint atomic-write family
    covers presto_tpu/triage/, and lint/fence.py flags any write of
    the weights basename outside this module);
  * training is fully seeded (`jax.random.PRNGKey` init, full-batch
    deterministic gradient descent), so the same labeled set and
    seed produce bit-identical weights — and therefore identical
    rankings — on every host.

Scoring is ONE jitted device call per candidate batch: standardize,
affine, sigmoid.  A million sift survivors score in a single
dispatch.
"""

from __future__ import annotations

import json
import os
import warnings
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu.triage.features import (FEATURE_NAMES,
                                        FOLD_FEATURE_NAMES,
                                        featurize,
                                        fold_profile_features)

SCHEMA_VERSION = 1

#: the weights file's basename — lint/fence.py pins writes of this
#: name to this module, the way ledger-owned files pin to the ledger
WEIGHTS_BASENAME = "triage_weights.json"

#: env override for the weights location (CLI/-policy paths win)
ENV_WEIGHTS = "PRESTO_TPU_TRIAGE_WEIGHTS"


def default_weights_path() -> str:
    """$PRESTO_TPU_TRIAGE_WEIGHTS, else
    ~/.cache/presto_tpu/triage_weights.json."""
    env = os.environ.get(ENV_WEIGHTS, "")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "presto_tpu", WEIGHTS_BASENAME)


@dataclass
class TriageModel:
    """Logistic scorer over the featurize() columns (plus optional
    measured fold-feature columns for borderline rescoring)."""

    w: List[float]
    b: float
    mean: List[float]
    scale: List[float]
    feature_names: Tuple[str, ...] = FEATURE_NAMES
    #: weights for the borderline fold features (empty -> the model
    #: never consults measured fold features)
    fold_w: List[float] = field(default_factory=list)
    seed: int = 0
    trained_on: int = 0

    # -- scoring -------------------------------------------------------

    def score(self, X: np.ndarray) -> np.ndarray:
        """[n] scores in (0, 1) for an [n, F] feature matrix — one
        jitted device call for the whole batch."""
        X = np.asarray(X, np.float64)
        if X.ndim != 2 or X.shape[1] != len(self.w):
            raise ValueError("feature matrix is %r for %d weights"
                             % (X.shape, len(self.w)))
        if X.shape[0] == 0:
            return np.zeros(0)
        return np.asarray(_score_jit(
            _jnp(X), _jnp(self.w), _jnp(self.b), _jnp(self.mean),
            _jnp(self.scale)), np.float64)

    def score_candidates(self, cands: Sequence) -> np.ndarray:
        return self.score(featurize(cands))

    def fold_adjust(self, scores: np.ndarray,
                    fold_feats: np.ndarray) -> np.ndarray:
        """Rescore with the measured fold features folded in (only
        meaningful for the borderline rows fold_feats was computed
        for; rows of zeros are adjusted by exactly 0)."""
        if not self.fold_w:
            return scores
        adj = np.asarray(fold_feats, np.float64) @ np.asarray(
            self.fold_w[:fold_feats.shape[1]], np.float64)
        return np.clip(scores + adj, 0.0, 1.0)

    # -- persistence ---------------------------------------------------

    def to_doc(self) -> dict:
        return {
            "schema": SCHEMA_VERSION,
            "kind": "triage-logistic",
            "feature_names": list(self.feature_names),
            "fold_feature_names": list(
                FOLD_FEATURE_NAMES[:len(self.fold_w)]),
            "w": [float(x) for x in self.w],
            "b": float(self.b),
            "mean": [float(x) for x in self.mean],
            "scale": [float(x) for x in self.scale],
            "fold_w": [float(x) for x in self.fold_w],
            "seed": int(self.seed),
            "trained_on": int(self.trained_on),
        }

    def save(self, path: str) -> None:
        from presto_tpu.io.atomic import atomic_write_text
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        atomic_write_text(path, json.dumps(self.to_doc(), indent=1,
                                           sort_keys=True))


def load_model(path: str) \
        -> Tuple[Optional[TriageModel], Optional[str]]:
    """Defensive load: ``(model, None)`` on success, ``(None, why)``
    on any structural problem (missing file is ``(None, None)`` —
    absent is not an error, just unconfigured).  A poisoned or stale
    weights file must degrade the selection to the heuristic sigma
    rank, never crash it (docs/ROBUSTNESS.md)."""
    if not os.path.exists(path):
        return None, None
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        warnings.warn(
            "triage weights %s are unreadable (%s) — falling back to "
            "the heuristic fold selection" % (path, e),
            RuntimeWarning, stacklevel=2)
        return None, "unreadable: %s" % e
    why = _doc_why(raw)
    if why is not None:
        warnings.warn(
            "triage weights %s rejected (%s) — falling back to the "
            "heuristic fold selection" % (path, why),
            RuntimeWarning, stacklevel=2)
        return None, why
    return TriageModel(
        w=[float(x) for x in raw["w"]], b=float(raw["b"]),
        mean=[float(x) for x in raw["mean"]],
        scale=[float(x) for x in raw["scale"]],
        feature_names=tuple(raw["feature_names"]),
        fold_w=[float(x) for x in raw.get("fold_w") or []],
        seed=int(raw.get("seed", 0)),
        trained_on=int(raw.get("trained_on", 0))), None


def _doc_why(raw) -> Optional[str]:
    if not isinstance(raw, dict):
        return "not a JSON object"
    if raw.get("schema") != SCHEMA_VERSION:
        return "stale schema: %r" % (raw.get("schema"),)
    names = raw.get("feature_names")
    if tuple(names or ()) != FEATURE_NAMES:
        return "feature layout mismatch"
    for key in ("w", "mean", "scale"):
        v = raw.get(key)
        if not isinstance(v, list) or len(v) != len(FEATURE_NAMES) \
                or not all(isinstance(x, (int, float)) for x in v):
            return "malformed %r" % key
    if not isinstance(raw.get("b"), (int, float)):
        return "malformed 'b'"
    return None


# ----------------------------------------------------------------------
# pure-JAX score + seeded training
# ----------------------------------------------------------------------

def _jnp(x):
    import jax.numpy as jnp
    return jnp.asarray(x, jnp.float32)


_SCORE_CACHE: dict = {}


#: standardized features are clamped to +/- this many training-set
#: sigmas at score time: a candidate far outside the training
#: distribution (a 60-sigma pulsar scored by a model trained on
#: 6-14-sigma injections) saturates a feature's pull instead of
#: letting one wild column swamp every other signal
Z_CLIP = 8.0


def _score_jit(X, w, b, mean, scale):
    import jax
    import jax.numpy as jnp
    fn = _SCORE_CACHE.get("score")
    if fn is None:
        def _score(X, w, b, mean, scale):
            Z = (X - mean[None, :]) / scale[None, :]
            Z = jnp.clip(Z, -Z_CLIP, Z_CLIP)
            return jax.nn.sigmoid(Z @ w + b)
        fn = _SCORE_CACHE["score"] = jax.jit(_score)
    return fn(X, w, b, mean, scale)


def train_model(X: np.ndarray, y: np.ndarray, seed: int = 0,
                epochs: int = 300, lr: float = 0.5,
                l2: float = 1e-3) -> TriageModel:
    """Seeded full-batch logistic regression.  Deterministic by
    construction: PRNGKey(seed) init, fixed epoch count, no
    minibatching, float64 host-side standardization — the same
    labeled set and seed yield bit-identical weights everywhere."""
    import jax
    import jax.numpy as jnp
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    if X.ndim != 2 or X.shape[0] != y.shape[0] or X.shape[0] == 0:
        raise ValueError("bad training set: X %r, y %r"
                         % (X.shape, y.shape))
    mean = X.mean(axis=0)
    scale = X.std(axis=0)
    scale[scale <= 0] = 1.0
    Z = _jnp((X - mean[None, :]) / scale[None, :])
    yj = _jnp(y)
    key = jax.random.PRNGKey(int(seed))
    w = 0.01 * jax.random.normal(key, (X.shape[1],), Z.dtype)
    b = jnp.zeros((), Z.dtype)

    def loss(w, b):
        logits = Z @ w + b
        nll = jnp.mean(jnp.logaddexp(0.0, logits) - yj * logits)
        return nll + l2 * jnp.sum(w * w)

    grad = jax.jit(jax.grad(loss, argnums=(0, 1)))
    for _ in range(int(epochs)):
        gw, gb = grad(w, b)
        w = w - lr * gw
        b = b - lr * gb
    return TriageModel(
        w=[float(x) for x in np.asarray(w)], b=float(b),
        mean=[float(x) for x in mean],
        scale=[float(x) for x in scale],
        seed=int(seed), trained_on=int(X.shape[0]))


# ----------------------------------------------------------------------
# the policy seam
# ----------------------------------------------------------------------

@dataclass
class TriagePolicy:
    """The opt-in fold-selection policy: rank the heuristic
    selection's candidates by learned score and keep the top
    ``budget``.

    Plugs into `pipeline/sifting.select_fold_candidates(policy=...)`,
    so the batch survey and the DAG triage node triage the SAME
    candidates.  Contract: the policy only ever *reorders and
    truncates* the heuristic selection — a selected candidate folds
    with exactly the parameters the heuristic path would have used,
    which is why fold artifacts stay byte-equal to an untriaged run
    of the same selection."""

    weights_path: Optional[str] = None     # None -> default_weights_path
    budget: Optional[int] = None           # absolute fold budget
    budget_frac: Optional[float] = None    # else fraction of heuristic
    #: fraction of the budget boundary (each side) that gets measured
    #: fold features before the final cut; 0 disables the fold pass
    borderline_frac: float = 0.25
    #: resolved parent dir of .dat trials (the DAG node sets this);
    #: None -> cheap features only
    datdir: Optional[str] = None

    def resolve_budget(self, n: int) -> int:
        if self.budget is not None:
            return max(min(int(self.budget), n), 0)
        if self.budget_frac is not None:
            return max(min(int(np.ceil(n * float(self.budget_frac))),
                           n), 1 if n else 0)
        return n

    def __call__(self, heuristic: Sequence, cl=None,
                 accounting: Optional[dict] = None) -> List:
        selected, acct = self.select(heuristic)
        if accounting is not None:
            accounting.setdefault("triage", acct)
        return selected

    def select(self, heuristic: Sequence, obs=None) \
            -> Tuple[List, dict]:
        """(selected, accounting).  Heuristic fallback on any weights
        problem returns the input list UNCHANGED (same objects, same
        order) — the byte-stable default."""
        heuristic = list(heuristic)
        acct = {"mode": "heuristic", "scored": 0,
                "selected": len(heuristic), "folds_avoided": 0,
                "budget": None, "load_error": None}
        path = self.weights_path or default_weights_path()
        model, load_error = load_model(path)
        acct["load_error"] = load_error
        if model is None or not heuristic:
            return heuristic, acct
        scores = model.score_candidates(heuristic)
        budget = self.resolve_budget(len(heuristic))
        order = _rank(heuristic, scores)
        if model.fold_w and self.datdir and 0 < budget < len(order):
            scores = self._borderline_rescore(
                heuristic, scores, order, budget, model, obs=obs)
            order = _rank(heuristic, scores)
        keep = set(order[:budget])
        # keep the heuristic's (sigma-rank) order among survivors so
        # fold numbering — and therefore artifact bytes — match an
        # untriaged run of the same selection
        selected = [c for i, c in enumerate(heuristic) if i in keep]
        acct.update(mode="triage", scored=len(heuristic),
                    selected=len(selected), budget=budget,
                    folds_avoided=len(heuristic) - len(selected),
                    scores=[round(float(s), 6) for s in scores])
        return selected, acct

    def _borderline_rescore(self, heuristic, scores, order, budget,
                            model, obs=None) -> np.ndarray:
        """Measured fold features for the candidates straddling the
        budget cut (one stacked dispatch), folded into their scores."""
        half = max(int(np.ceil(budget * self.borderline_frac)), 1)
        lo = max(budget - half, 0)
        hi = min(budget + half, len(order))
        border = order[lo:hi]
        items = []
        for i in border:
            c = heuristic[i]
            base = os.path.join(self.datdir, c.filename)
            datbase = base.split("_ACCEL_")[0]
            items.append((datbase + ".dat", float(c.f), 0.0))
        feats = fold_profile_features(items, obs=obs)
        out = np.array(scores, np.float64)
        out[border] = model.fold_adjust(out[border], feats)
        return out


def _rank(cands: Sequence, scores: np.ndarray) -> List[int]:
    """Indices by (score desc, sigma desc, filename, candnum) — the
    trailing keys make exact ties deterministic across filesystems."""
    return sorted(
        range(len(cands)),
        key=lambda i: (-float(scores[i]), -float(cands[i].sigma),
                       str(cands[i].filename),
                       int(cands[i].candnum)))
