"""Deterministic candidate featurization for triage scoring.

Every feature is derived from state the sift stage already holds —
the ACCEL/.cand table fields on `pipeline/sifting.Candidate` (sigma,
powers, harmonic count, r/z), the cross-DM-trial hit list the
duplicate sift accumulated, and the pass provenance encoded in the
ACCEL filename — so featurizing a million sift survivors is pure
host arithmetic, no device work and no file reads.

For *borderline* candidates only, `fold_profile_features` adds two
measured features (folded-profile reduced chi^2 and peak/RMS) through
the existing stacked fold kernels (`search/prepfold.fold_series_batch`
-> `ops/fold.fold_data_batch`): the whole borderline set folds as ONE
batched drizzle dispatch per stack geometry, the same coalescing the
DAG fold stage rides.

Determinism contract: `featurize` is a pure function of the candidate
list (same candidates in the same order => the same float64 matrix on
any host), which is what makes a seeded model's ranking reproducible
across runs and filesystems (tests/test_triage.py).
"""

from __future__ import annotations

import re
from typing import Sequence, Tuple

import numpy as np

#: column names of the featurize() matrix, in order (persisted into
#: the weights file so a stale model never silently scores a
#: different feature layout)
FEATURE_NAMES = (
    "sigma",            # sift sigma (the heuristic's whole story)
    "log_ipow",         # log1p incoherent summed power
    "log_cpow",         # log1p coherent power
    "cpow_frac",        # cpow / ipow: power concentration
    "log2_numharm",     # harmonic structure
    "snr",              # sqrt(ipow - numharm)
    "dm",               # trial DM
    "abs_z",            # |z|: accel provenance
    "log_f",            # log10 spin frequency
    "n_hits",           # DM-trial support (dedup'd hit count)
    "hit_sigma_span",   # max-min sigma across the DM hits
    "hit_snr_max",      # strongest single-trial SNR
    "hit_dm_span",      # DM extent of the support
    "pass_z",           # zmax of the accel pass that found it
)

_PASS_RE = re.compile(r"_ACCEL_(\d+)$")


def _pass_zmax(filename: str) -> float:
    m = _PASS_RE.search(filename or "")
    return float(m.group(1)) if m else -1.0


def featurize(cands: Sequence) -> np.ndarray:
    """[n, len(FEATURE_NAMES)] float64 feature matrix for a list of
    `pipeline/sifting.Candidate` rows.  Pure, order-preserving, and
    deterministic — no RNG, no file or device access."""
    out = np.zeros((len(cands), len(FEATURE_NAMES)), np.float64)
    for i, c in enumerate(cands):
        hits = list(c.hits or ())
        hsig = [float(h[2]) for h in hits]
        hsnr = [float(h[1]) for h in hits]
        hdm = [float(h[0]) for h in hits]
        ipow = max(float(c.ipow_det), 0.0)
        cpow = max(float(c.cpow), 0.0)
        out[i] = (
            float(c.sigma),
            np.log1p(ipow),
            np.log1p(cpow),
            cpow / ipow if ipow > 0 else 0.0,
            np.log2(max(int(c.numharm), 1)),
            float(c.snr),
            float(c.DM),
            abs(float(c.z)),
            np.log10(max(float(c.f), 1e-12)),
            float(len(hits)),
            (max(hsig) - min(hsig)) if hsig else 0.0,
            max(hsnr) if hsnr else 0.0,
            (max(hdm) - min(hdm)) if hdm else 0.0,
            _pass_zmax(c.filename),
        )
    return out


# ----------------------------------------------------------------------
# borderline fold features (one batched dispatch per geometry)
# ----------------------------------------------------------------------

#: names of the measured fold-feature columns appended for borderline
#: candidates (zeros + the absent flag when not computed)
FOLD_FEATURE_NAMES = ("fold_redchi", "fold_peak_rms")


def fold_profile_features(items: Sequence[Tuple[str, float, float]],
                          obs=None) -> np.ndarray:
    """[n, 2] measured fold features for ``items`` of
    ``(datfile, f0, fd0)``: the -nosearch folded profile's reduced
    chi^2 and its (peak-mean)/RMS.

    Items are grouped by the fold stack signature
    (`apps/prepfold.fold_stack_key`) and each group folds through
    `fold_series_batch` as ONE stacked drizzle dispatch — for a
    single-search borderline set (shared N/dt) that is one dispatch
    for the whole set, the coalescing the issue's budget math counts
    on.  Failures degrade per item to zeros (a candidate the folder
    cannot read scores on its cheap features alone; triage must never
    take the selection down)."""
    from presto_tpu.apps.prepfold import (fold_geometry,
                                          fold_stack_key)
    from presto_tpu.io.datfft import read_dat_with_inf
    from presto_tpu.search.prepfold import (FoldConfig,
                                            finish_fold_nosearch,
                                            fold_series_batch)
    out = np.zeros((len(items), 2), np.float64)
    groups: dict = {}
    for idx, (datfile, f0, fd0) in enumerate(items):
        try:
            N, dt, proflen, subdiv = fold_geometry(datfile, f0, fd0)
        except Exception:
            continue
        key = fold_stack_key(N, dt, proflen, 64, subdiv)
        groups.setdefault(key, []).append(
            (idx, datfile, f0, fd0, proflen))
    for key in sorted(groups):
        rows = groups[key]
        batch, kept = [], []
        for idx, datfile, f0, fd0, proflen in rows:
            try:
                series, info = read_dat_with_inf(datfile)
            except Exception:
                continue
            cfg = FoldConfig(proflen=proflen, npart=64, nsub=1,
                             search_p=False, search_pd=False,
                             search_dm=False)
            batch.append((series, float(info.dt), f0, fd0, 0.0,
                          cfg, 0.0, 0.0))
            kept.append(idx)
        if not batch:
            continue
        try:
            results = finish_fold_nosearch(
                fold_series_batch(batch, obs=obs), obs=obs)
        except Exception:
            continue
        for idx, res in zip(kept, results):
            prof = np.asarray(res.best_prof, np.float64)
            rms = float(prof.std())
            peak = (float(prof.max() - prof.mean()) / rms
                    if rms > 0 else 0.0)
            out[idx] = (float(res.best_redchi), peak)
    return out
