"""Synthetic pulsar data generation — the makedata/injectpsr analog.

The reference's makedata (src/makedata.c + src/com.c) generates .dat
time series from closed-form signal parameters (pulse shape, f/fdot/
fdotdot, amplitude, phase, binary orbit, noise) described by .mak files;
its test suite builds on exact knowledge of the injected signal
(SURVEY.md §4.2).  This module provides the same ground-truth role:
every search stage is validated against data whose answer is known in
closed form.

All generation is float64 numpy on the host (it is setup/test code, not
a hot path).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from presto_tpu.io.infodata import InfoData, ARTIFICIAL_TELESCOPE
from presto_tpu.io.sigproc import FilterbankHeader, write_filterbank
from presto_tpu.ops.dedispersion import delay_from_dm


def pulse_shape(phases: np.ndarray, shape: str = "sine",
                width: float = 0.1) -> np.ndarray:
    """Pulse amplitude at fractional phases in [0,1).

    Shapes follow makedata's menu (src/com.c): 'sine', 'gauss' (fwhm =
    `width` in phase units), 'crab' (fast-rise exponential-decay-ish).
    All normalized to peak 1.
    """
    ph = np.mod(phases, 1.0)
    if shape == "sine":
        return 0.5 * (1.0 + np.sin(2 * np.pi * ph))
    if shape == "gauss":
        sigma = width / 2.35482
        return np.exp(-0.5 * ((ph - 0.5) / sigma) ** 2)
    if shape == "crab":
        return np.exp(-np.minimum(ph, 1 - ph) / width)
    raise ValueError("unknown pulse shape %r" % shape)


@dataclass
class FakeSignal:
    """Closed-form signal description (the .mak analog)."""
    f: float = 1.0               # Hz at t=0
    fdot: float = 0.0            # Hz/s
    fdotdot: float = 0.0         # Hz/s^2
    amp: float = 1.0
    phase0: float = 0.0          # turns
    shape: str = "gauss"
    width: float = 0.1           # fractional pulse width (gauss fwhm)
    dm: float = 0.0

    def phase(self, t: np.ndarray) -> np.ndarray:
        """Integrated phase in turns at times t (s): f t + fd t²/2 + fdd t³/6."""
        return (self.phase0 + self.f * t + 0.5 * self.fdot * t * t
                + self.fdotdot * t ** 3 / 6.0)


def fake_timeseries(N: int, dt: float, signal: FakeSignal,
                    noise_sigma: float = 0.0,
                    seed: Optional[int] = 42) -> np.ndarray:
    """Noise + pulsed signal sampled at bin centers."""
    t = (np.arange(N) + 0.5) * dt
    data = signal.amp * pulse_shape(signal.phase(t), signal.shape,
                                    signal.width)
    if noise_sigma > 0:
        rng = np.random.default_rng(seed)
        data = data + rng.normal(0.0, noise_sigma, N)
    return data.astype(np.float32)


def fake_filterbank_data(N: int, dt: float, nchan: int, lofreq: float,
                         chanwidth: float, signal: FakeSignal,
                         noise_sigma: float = 0.0,
                         baseline: float = 10.0,
                         seed: Optional[int] = 42) -> np.ndarray:
    """[N, nchan] float32, ascending frequency, with the pulsar's pulses
    arriving later in lower-frequency channels per the cold-plasma delay
    (delay_from_dm).  The highest channel has zero extra delay offset —
    matching how dedispersion references delays to the band."""
    freqs = lofreq + np.arange(nchan) * chanwidth
    delays = delay_from_dm(signal.dm, freqs)
    delays = delays - delays.min()       # highest channel ~ zero delay
    t = (np.arange(N) + 0.5) * dt
    out = np.empty((N, nchan), dtype=np.float32)
    for c in range(nchan):
        ph = signal.phase(t - delays[c])
        out[:, c] = signal.amp * pulse_shape(ph, signal.shape, signal.width)
    out += baseline
    if noise_sigma > 0:
        rng = np.random.default_rng(seed)
        out += rng.normal(0.0, noise_sigma, out.shape).astype(np.float32)
    return out


def fake_filterbank_file(path: str, N: int, dt: float, nchan: int,
                         lofreq: float, chanwidth: float,
                         signal: FakeSignal, noise_sigma: float = 0.0,
                         nbits: int = 8, tstart_mjd: float = 59000.0,
                         seed: Optional[int] = 42) -> FilterbankHeader:
    """Write a synthetic 8-bit .fil with an injected pulsar."""
    data = fake_filterbank_data(N, dt, nchan, lofreq, chanwidth, signal,
                                noise_sigma, baseline=32.0, seed=seed)
    if nbits == 8:
        q = np.clip(np.round(data * 4.0), 0, 255).astype(np.uint8)
    elif nbits == 32:
        q = data
    else:
        maxv = (1 << nbits) - 1
        q = np.clip(np.round(data * maxv / data.max()), 0, maxv).astype(
            np.uint16 if nbits == 16 else np.uint8)
    hdr = FilterbankHeader(
        # GBT + a real sky position (the Crab) so the default
        # barycentering path in the prep tools is exercised end-to-end
        source_name="FAKEPSR", machine_id=10, telescope_id=6,
        src_raj=53431.97, src_dej=220052.1,
        fch1=lofreq + (nchan - 1) * chanwidth, foff=-chanwidth,
        nchans=nchan, nbits=nbits, tstart=tstart_mjd, tsamp=dt, nifs=1,
        rawdatafile=path.split("/")[-1])
    write_filterbank(path, hdr, q)
    return hdr


def artificial_inf(name: str, N: int, dt: float, dm: float = 0.0,
                   **kw) -> InfoData:
    return InfoData(name=name, telescope=ARTIFICIAL_TELESCOPE,
                    N=float(N), dt=dt, dm=dm, **kw)
