"""Inject synthetic pulsars into existing filterbank data
(bin/injectpsr.py analog — the reference's fault-injection tool,
SURVEY.md §5.3).

Adds a parameterized pulsar signal on top of REAL (or synthetic) data:
per-channel cold-plasma delays, intra-channel DM smearing (the profile
convolved with the channel's smearing boxcar), an optional exponential
scattering tail (tau scaled per channel as tau ~ nu^-4, the injectpsr
scattering model), optional binary-orbit phase modulation
(ops/orbit.orbit_delays), and either a fixed amplitude or a target
folded S/N.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from presto_tpu.models.synth import pulse_shape
from presto_tpu.ops.dedispersion import delay_from_dm
from presto_tpu.ops.orbit import OrbitParams, orbit_delays

_NFINE = 4096


@dataclass
class InjectParams:
    f: float = 1.0                 # spin frequency, Hz (at t=0)
    fdot: float = 0.0
    phase0: float = 0.0            # turns
    dm: float = 0.0
    amp: float = 1.0               # peak amplitude, data units/sample
    shape: str = "gauss"
    width: float = 0.05            # FWHM in rotations (gauss)
    profile: Optional[np.ndarray] = field(default=None)  # custom, any len
    orbit: Optional[OrbitParams] = None
    # interstellar scattering: one-sided exponential tail of timescale
    # tau (seconds) at tau_ref_mhz (0 -> the highest channel), scaled
    # per channel as tau * (nu/nu_ref)**tau_index (thin-screen
    # Kolmogorov-ish default -4, bin/injectpsr.py's model)
    tau: float = 0.0
    tau_ref_mhz: float = 0.0
    tau_index: float = -4.0


def _base_profile(params: InjectParams) -> np.ndarray:
    """Unit-peak profile sampled on the fine phase grid."""
    ph = np.arange(_NFINE) / _NFINE
    if params.profile is not None:
        prof = np.asarray(params.profile, float)
        peak = np.abs(prof).max()
        if peak > 0:
            prof = prof / peak          # unit peak: amp semantics hold
        x = np.arange(len(prof)) / len(prof)
        return np.interp(ph, x, prof, period=1.0)
    # pulse_shape centers gauss at 0.5; shift so peak sits at phase 0
    return pulse_shape(ph + 0.5, params.shape, params.width)


def scattering_taus(params: InjectParams,
                    freqs: np.ndarray) -> np.ndarray:
    """Per-channel scattering timescales (seconds): tau at the
    reference frequency scaled by (nu/nu_ref)**tau_index."""
    freqs = np.asarray(freqs, float)
    if params.tau <= 0.0:
        return np.zeros(len(freqs))
    nu_ref = params.tau_ref_mhz or float(freqs.max())
    return params.tau * (np.maximum(freqs, 1e-3)
                         / nu_ref) ** params.tau_index


def _smeared_profiles(params: InjectParams, freqs: np.ndarray,
                      chanwidth: float, dt: float) -> np.ndarray:
    """[nchan, _NFINE] profiles convolved with each channel's DM
    smearing boxcar + the sampling boxcar (injectpsr.py applies both)
    and, when params.tau > 0, the channel's one-sided exponential
    scattering tail."""
    base = _base_profile(params)
    F = np.fft.rfft(base)
    k = np.arange(F.size)
    # smear time across one channel: d(delay)/d(f) * chanwidth
    lo = freqs - 0.5 * chanwidth
    hi = freqs + 0.5 * chanwidth
    smear_sec = np.abs(delay_from_dm(params.dm, np.maximum(lo, 1e-3))
                       - delay_from_dm(params.dm, hi))
    taus = scattering_taus(params, freqs)
    out = np.empty((len(freqs), _NFINE))
    for c, sm in enumerate(smear_sec):
        width = np.hypot(sm, dt) * params.f     # rotations
        width = min(max(width, 0.0), 1.0)
        # boxcar of `width` rotations in the Fourier domain: sinc
        resp = np.sinc(k * width).astype(complex)
        if taus[c] > 0.0:
            # unit-area one-sided exponential exp(-t/tau)/tau has
            # harmonic response 1/(1 + 2*pi*i*k*tau_rot); periodic
            # wrap-around comes free in the harmonic domain.  Flux is
            # conserved (k=0 untouched) so the peak DROPS as the tail
            # grows — the physical behavior, and why a target-S/N
            # injection should set amp via amp_for_snr on the
            # unscattered profile then expect the scattered S/N loss.
            tau_rot = taus[c] * params.f        # rotations
            resp = resp / (1.0 + 2j * np.pi * k * tau_rot)
        out[c] = np.fft.irfft(F * resp, _NFINE)
    return out


def inject_pulsar(data: np.ndarray, dt: float, freqs: np.ndarray,
                  params: InjectParams,
                  start_sec: float = 0.0) -> np.ndarray:
    """Return data + injected pulsar.

    data: [N, nchan] float, channels ASCENDING to match `freqs` (MHz).
    start_sec: observation time of data[0] (for chunked injection).
    The highest channel carries zero dispersive offset, matching the
    convention of the dedispersion ops (delays referenced to band top).
    """
    data = np.asarray(data, np.float32)
    N, nchan = data.shape
    if len(freqs) != nchan:
        raise ValueError("freqs length != nchan")
    chanwidth = float(np.median(np.diff(freqs))) if nchan > 1 else 1.0
    profs = _smeared_profiles(params, np.asarray(freqs, float),
                              abs(chanwidth), dt)
    delays = delay_from_dm(params.dm, np.asarray(freqs, float))
    delays = delays - delays.min()
    t = start_sec + (np.arange(N) + 0.5) * dt
    out = data.copy()
    for c in range(nchan):
        tc = t - delays[c]
        if params.orbit is not None:
            tc = tc - np.asarray(orbit_delays(tc, params.orbit))
        ph = (params.phase0 + params.f * tc
              + 0.5 * params.fdot * tc * tc)
        idx = np.mod((ph % 1.0) * _NFINE, _NFINE).astype(np.int64)
        out[:, c] += (params.amp * profs[c, idx]).astype(np.float32)
    return out


def amp_for_snr(snr: float, params: InjectParams, N: int,
                noise_sigma: float, nchan: int) -> float:
    """Peak amplitude per channel-sample for a target matched-filter
    S/N over the whole observation: a unit-peak periodic signal p(t)
    in nchan channels of per-sample noise sigma has
    S/N = A*sqrt(N*nchan*<p^2>)/sigma (mean-subtracted profile)."""
    prof = _base_profile(params)
    prof = prof - prof.mean()
    p2 = float(np.mean(prof ** 2))
    return float(snr * noise_sigma / np.sqrt(N * nchan * p2))


def truth_record(params: InjectParams, t: float = 0.0,
                 snr: Optional[float] = None) -> dict:
    """One injected pulsar as a ground-truth sidecar record.  This is
    the single schema every producer (injectpsr, the stream loadgen,
    synthetic campaigns) shares, so triage calibration can label
    candidates against any of them."""
    f = float(params.f)
    return {
        "t": float(t),
        "dm": float(params.dm),
        "f": f,
        "period": (1.0 / f) if f > 0 else 0.0,
        "fdot": float(params.fdot),
        "snr": float(snr) if snr is not None else None,
        "amp": float(params.amp),
        "width": float(params.width),
    }


def truth_sidecar_path(datapath: str) -> str:
    """``<out>_injected.json`` beside an injected data file."""
    import os
    return os.path.splitext(datapath)[0] + "_injected.json"


def write_truth_sidecar(datapath: str, records: list,
                        truth_out: Optional[str] = None) -> str:
    """Atomically write the ground-truth sidecar for an injected
    file; returns the path written."""
    import json

    from presto_tpu.io.atomic import atomic_write_text

    path = truth_out or truth_sidecar_path(datapath)
    atomic_write_text(path, json.dumps(
        {"schema": 1, "datafile": datapath,
         "injected": list(records)}, indent=1, sort_keys=True) + "\n")
    return path


def inject_into_filterbank(inpath: str, outpath: str,
                           params: InjectParams,
                           block: int = 1 << 14,
                           truth_out: Optional[str] = None,
                           write_truth: bool = True) -> None:
    """Stream a .fil through the injector (chunked; constant memory).

    Unless ``write_truth`` is False, a ground-truth sidecar
    (``<out>_injected.json``, or ``truth_out``) records what was
    injected — downstream triage calibration labels its candidates
    against this for free."""
    from presto_tpu.io import sigproc

    with sigproc.FilterbankFile(inpath) as fb:
        hdr = fb.header
        if hdr.nifs != 1:
            raise ValueError("injection into multi-IF files is lossy "
                             "(reader sums IFs); split pols first")
        freqs = hdr.lofreq + np.arange(hdr.nchans) * abs(hdr.foff)
        maxval = (1 << min(hdr.nbits, 16)) - 1 if hdr.nbits <= 16 \
            else None
        with open(outpath, "wb") as f:
            sigproc.write_filterbank_header(hdr, f)
            for start in range(0, hdr.N, block):
                n = min(block, hdr.N - start)
                blk = fb.read_spectra(start, n)
                blk = inject_pulsar(blk, hdr.tsamp, freqs, params,
                                    start_sec=start * hdr.tsamp)
                if maxval is not None:
                    blk = np.clip(np.round(blk), 0, maxval)
                arr = blk[:, ::-1] if hdr.foff < 0 else blk
                packed = sigproc.pack_bits(
                    arr.reshape(-1), hdr.nbits)
                packed.tofile(f)
    if write_truth:
        write_truth_sidecar(outpath, [truth_record(params)],
                            truth_out=truth_out)
