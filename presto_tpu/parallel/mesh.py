"""Device mesh construction for the search pipeline.

The reference's only distributed axis is DM trials over MPI ranks
(mpiprepsubband, SURVEY.md §2.5/§3.5: rank 0 reads + broadcasts raw
blocks, workers each own numdms/(numprocs-1) DM trials, no worker-to-
worker traffic).  TPU-native mapping: one logical jit program over a
`jax.sharding.Mesh` whose axes are

  'dm'  — DM trials (pure data parallel; the MPI_Bcast becomes a
          replicated-input sharding, the per-rank .dat writes become a
          DM-sharded output array)
  'seq' — time/frequency samples (sequence parallel for huge FFTs:
          the six-step transpose becomes an ICI all-to-all)

Search stages reuse the same mesh: the F-Fdot plane shards its z-rows
or r-blocks over 'dm' (both embarrassingly parallel) and candidate
top-k reduces device-locally before one host gather, mirroring the
reference's "no inter-worker traffic" property.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> int:
    """Multi-host (DCN) initialization — the mpirun analog.

    On a TPU pod slice with default env plumbing, call with no
    arguments (jax.distributed auto-discovers the coordinator); on
    manual clusters pass coordinator host:port and the process grid.
    After this, jax.devices() spans every host's chips and make_mesh
    builds one global mesh: the DM fan-out then scales across hosts
    with the raw-block replication riding DCN exactly where
    mpiprepsubband's MPI_Bcast did (mpiprepsubband.c:988-991).
    Returns the process count.  Safe to call once per process.
    """
    manual = (coordinator_address, num_processes, process_id)
    if any(v is not None for v in manual) and \
            not all(v is not None for v in manual):
        raise ValueError(
            "init_distributed: pass ALL of coordinator_address/"
            "num_processes/process_id for a manual cluster, or none "
            "for auto-discovery (got %r)" % (manual,))
    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    else:
        jax.distributed.initialize()
    return jax.process_count()


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Sequence[str] = ("dm",),
              shape: Optional[Sequence[int]] = None) -> Mesh:
    """Build a mesh over the first n_devices (default: all)."""
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    devs = devs[:n_devices]
    if shape is None:
        shape = (n_devices,) + (1,) * (len(axis_names) - 1)
    arr = np.array(devs).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def dm_sharding(mesh: Mesh, ndim: int = 2, dm_axis: int = 0):
    """NamedSharding placing the DM-trial axis across the 'dm' mesh
    axis; remaining dims replicated."""
    spec = [None] * ndim
    spec[dm_axis] = "dm"
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def shard_row_ranges(mesh: Mesh, nrows: int):
    """Contiguous [lo, hi) row ranges of a `nrows`-long leading axis
    sharded over the mesh's first axis, in mesh device order — the
    host-side twin of dm_sharding's partition (each range is the slice
    `NamedSharding.addressable_devices_indices_map` would assign to
    that device).  `nrows` must divide evenly; callers pad first."""
    devs = list(mesh.devices.flat)
    if nrows % len(devs):
        raise ValueError(
            "shard_row_ranges: %d rows do not divide over %d devices"
            % (nrows, len(devs)))
    per = nrows // len(devs)
    return [(k * per, (k + 1) * per) for k in range(len(devs))]


def batch_sharding(mesh: Mesh, ndim: int = 2, batch_axis: int = 0):
    """NamedSharding for a stacked micro-batch (serve layer): the
    leading batch axis — coalesced same-bucket jobs, or a job's DM
    fan-out — spreads across the mesh's first axis ('dm' on the
    standard search mesh); remaining dims replicated.  The serving
    analog of dm_sharding: batch placement rides the same axis the
    DM trials do, so a batched device call spans every chip."""
    spec = [None] * ndim
    spec[batch_axis] = mesh.axis_names[0]
    return NamedSharding(mesh, P(*spec))
