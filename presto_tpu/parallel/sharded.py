"""DM-sharded dedispersion + search pipeline steps (pjit over a Mesh).

The mpiprepsubband invariant (SURVEY.md §4.8): sharded output must
equal unsharded output for the same DMs.  Tests enforce this on an
8-device virtual CPU mesh; the driver's dryrun validates compile+run.

Sharding layout (mirrors mpiprepsubband.c:288-297's DM partition):
  raw blocks      [C, T]            replicated  (the MPI_Bcast analog)
  chan delays     [C]               replicated
  per-DM delays   [numdms, nsub]    sharded on 'dm'
  output series   [numdms, T]       sharded on 'dm'
No inter-device communication is needed after the input replication —
XLA sees the gather/sum is elementwise in the sharded axis.
"""

from __future__ import annotations

from functools import partial
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from presto_tpu.ops.dedispersion import (dedisp_subbands_block,
                                         float_dedisp_many_block,
                                         downsample_block)
from presto_tpu.parallel.mesh import (dm_sharding, replicated,
                                      shard_row_ranges)

# jax.shard_map moved in/out of the top-level namespace across jax
# releases (top-level in >=0.5/0.7, jax.experimental.shard_map before);
# resolve once so the sharded paths run on whichever is installed.
try:
    _shard_map = jax.shard_map            # newer jax
except AttributeError:                     # 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_dm_array(arr, mesh: Mesh):
    """Place [numdms, ...] array with the DM axis across mesh 'dm'."""
    return jax.device_put(arr, dm_sharding(mesh, np.ndim(arr)))


def make_sharded_dedisperse_step(mesh: Mesh, numsubbands: int,
                                 downsamp: int = 1):
    """jit-compiled (prev_raw, raw, prev_sub, chan_delays, dm_delays) ->
    (sub, series[numdms, T//downsamp]) with DM-sharded output.

    One streaming step of the prepsubband pipeline: channels->subbands
    on replicated data, then the DM fan-out sharded over devices.
    """
    out_shardings = (replicated(mesh), dm_sharding(mesh, 2))

    @partial(jax.jit, out_shardings=out_shardings)
    def step(prev_raw, raw, prev_sub, chan_delays, dm_delays):
        sub = dedisp_subbands_block(prev_raw, raw, chan_delays, numsubbands)
        series = float_dedisp_many_block(prev_sub, sub, dm_delays)
        return sub, downsample_block(series, downsamp)

    return step


def sharded_dedisperse_stream(blocks, chan_delays, dm_delays, mesh: Mesh,
                              numsubbands: int, downsamp: int = 1):
    """Dedisperse a [nblocks, C, T] stream at [numdms, nsub] delays with
    the DM axis sharded over `mesh`.  Returns [numdms, (nblocks-2)*T].

    Host-driven block loop (the real pipeline streams from disk); the
    carry logic matches ops.dedispersion.dedisperse_scan.
    """
    step = make_sharded_dedisperse_step(mesh, numsubbands, downsamp)
    chan_delays = jnp.asarray(chan_delays, dtype=jnp.int32)
    dm_delays = shard_dm_array(jnp.asarray(dm_delays, dtype=jnp.int32), mesh)
    prev_raw = jnp.asarray(blocks[0])
    raw = jnp.asarray(blocks[1])
    prev_sub = dedisp_subbands_block(prev_raw, raw, chan_delays,
                                     numsubbands)
    outs = []
    for i in range(2, len(blocks)):
        cur = jnp.asarray(blocks[i])
        sub, series = step(raw, cur, prev_sub, chan_delays, dm_delays)
        outs.append(series)
        prev_sub, raw = sub, cur
    return jnp.concatenate(outs, axis=1)


# ----------------------------------------------------------------------
# Static-delay DM-sharded dedispersion (per-device compiled plans)
# ----------------------------------------------------------------------

def _device_block_step(chan_delays: np.ndarray, dm_chunk: np.ndarray,
                       numsubbands: int, downsamp: int):
    """One device's composed streaming step with its DM sub-range's
    delays embedded as STATIC constants: the per-device twin of
    ops.dedispersion.make_block_step.  Both delay operands stay host
    NumPy so float_dedisp_many_block takes the static-slice fast path
    (and its `dedisp_dm_batch` tuning-DB bound) and the channel plan
    folds into the trace — nothing here pins the program to a device;
    it runs wherever its inputs are committed."""
    chan_np = np.ascontiguousarray(chan_delays, dtype=np.int32)
    dm_np = np.ascontiguousarray(dm_chunk, dtype=np.int32)

    @jax.jit
    def step(prev_raw, cur, prev_sub):
        sub = dedisp_subbands_block(prev_raw, cur, chan_np,
                                    numsubbands)
        series = float_dedisp_many_block(prev_sub, sub, dm_np)
        return sub, downsample_block(series, downsamp)

    return step


class ShardedDedispPlan:
    """DM-sharded streaming dedispersion with STATIC per-device delay
    plans — the mpiprepsubband partition as per-device (MPMD)
    dispatches instead of one traced-delay SPMD program.

    make_sharded_dedisperse_step passes the [numdms, nsub] delay table
    as a traced, device-sharded operand, which forces the vmap-of-
    dynamic-slice dedispersion path (the PR 5 caveat: the
    `dedisp_dm_batch` tune family never drove the multi-device path).
    Here each device gets its own compiled program with its DM
    sub-range's delays embedded as constants — the same static-slice
    fast path (and tuned unroll bound) the single-device loop uses,
    bit-identical output by the float_dedisp_many_block contract.
    Dispatches are issued per device back-to-back (async), so devices
    compute concurrently; the per-device outputs assemble into ONE
    global dm-sharded jax.Array with `concat()` — no host round-trip,
    which is exactly the hand-off the fused pipeline's sharded seam
    (pipeline/fusion.ShardedSeamBlock) consumes in place.

    Single-process only: the per-device dispatch model has no
    cross-process collective, so multi-host (-coordinator) runs keep
    the traced shard_map step.
    """

    def __init__(self, mesh: Mesh, numsubbands: int, downsamp: int,
                 chan_delays: np.ndarray, dm_delays: np.ndarray):
        self.mesh = mesh
        self.devices = list(mesh.devices.flat)
        self.numdms = int(dm_delays.shape[0])
        self.row_ranges = shard_row_ranges(mesh, self.numdms)
        self.numsubbands = int(numsubbands)
        self._chan_np = np.ascontiguousarray(chan_delays,
                                             dtype=np.int32)
        dm_np = np.asarray(dm_delays, dtype=np.int32)
        self.steps = [
            _device_block_step(self._chan_np, dm_np[lo:hi],
                               numsubbands, downsamp)
            for (lo, hi) in self.row_ranges]

    def put_block(self, blockT: np.ndarray):
        """Replicate one host channel-major block onto every mesh
        device (the MPI_Bcast analog) as per-device committed arrays."""
        return [jax.device_put(blockT, d) for d in self.devices]

    def prime(self, prev_raw, cur):
        """First-window subband carry, per device (the two-buffer SWAP
        priming of the reference's streaming loop)."""
        return [dedisp_subbands_block(pr, cu, self._chan_np,
                                      self.numsubbands)
                for pr, cu in zip(prev_raw, cur)]

    def step(self, prev_raw, cur, prev_sub):
        """One streaming step on every device: returns (subs, series)
        as per-device lists; all dispatches are queued before any
        result is awaited, so the mesh computes concurrently."""
        subs, series = [], []
        for st, pr, cu, ps in zip(self.steps, prev_raw, cur, prev_sub):
            sub, ser = st(pr, cu, ps)
            subs.append(sub)
            series.append(ser)
        return subs, series

    def concat(self, outs):
        """[per-block list of per-device series] -> ONE global
        [numdms, T] jax.Array sharded on the mesh 'dm' axis, each
        shard living on the device that computed it."""
        parts = [jnp.concatenate([blk[k] for blk in outs], axis=1)
                 for k in range(len(self.devices))]
        shape = (self.numdms, int(parts[0].shape[1]))
        return jax.make_array_from_single_device_arrays(
            shape, dm_sharding(self.mesh, 2), parts)


# ----------------------------------------------------------------------
# Sequence-sharded six-step FFT (the out-of-core / huge-FFT analog)
# ----------------------------------------------------------------------

def sixstep_fft(x, rows: int):
    """Complex DFT of x (length N = rows*cols) via the six-step
    decomposition (reference fastffts.c:38-195 / twopass_real_fwd.c:10):
      view x as [rows, cols] row-major -> FFT columns (length rows)
      -> twiddle W_N^(j2*k1) -> FFT rows (length cols) -> transpose.
    Shards naturally: with the row axis sharded over 'seq', the column
    FFT is local, the twiddle is elementwise, and the final transpose
    is XLA's all-to-all — the disk-transpose of the reference's
    out-of-core FFT becomes ICI traffic.

    Returns X[k] == jnp.fft.fft(x) (validated in tests).
    """
    N = x.shape[-1]
    cols = N // rows
    # x[j1*cols + j2] -> A[j1, j2]
    A = x.reshape(rows, cols)
    # sum over j1: FFT along axis 0 (length rows) for each j2 -> B[k1, j2]
    B = jnp.fft.fft(A, axis=0)
    # twiddle W_N^(j2*k1)
    k1 = jnp.arange(rows)[:, None]
    j2 = jnp.arange(cols)[None, :]
    tw = jnp.exp(-2j * jnp.pi * (k1 * j2) / N).astype(B.dtype)
    C = B * tw
    # sum over j2: FFT along axis 1 (length cols) -> D[k1, k2]
    D = jnp.fft.fft(C, axis=1)
    # X[k1 + rows*k2] = D[k1, k2] -> transpose then ravel
    return D.T.reshape(-1)


def make_sharded_sixstep_fft(mesh: Mesh, rows: int):
    """jit'd sequence-sharded FFT: input pairs [N,2] float32 sharded on
    'seq' (if present, else 'dm'), output pairs sharded the same way.

    The intermediate [rows, cols] matrix is sharded on the row axis;
    jnp.fft.fft along the sharded axis forces XLA to insert the
    all-to-all — exactly the six-step communication pattern.
    """
    axis = "seq" if "seq" in mesh.axis_names else mesh.axis_names[0]
    io_sharding = NamedSharding(mesh, P(axis, None))

    @partial(jax.jit, out_shardings=io_sharding)
    def fft_pairs(xp):
        x = xp[..., 0] + 1j * xp[..., 1]
        X = sixstep_fft(x, rows)
        return jnp.stack([X.real, X.imag], axis=-1).astype(jnp.float32)

    return fft_pairs


# ----------------------------------------------------------------------
# DM-batch-sharded accelsearch (the search-stage mpiprepsubband analog)
# ----------------------------------------------------------------------


def sharded_accel_search_many(searcher, pairs_batch, mesh: Mesh,
                              slab: int = 1 << 20,
                              compact_m: int = None, obs=None):
    """Accelsearch over a DM fan-out with the trial axis sharded over
    `mesh` — the search-stage application of the mpiprepsubband
    invariant (SURVEY §4.8; mpiprepsubband.c:288-297's DM partition):
    each device owns numdms/n trials and runs the IDENTICAL fused
    build+scan program on its shard sequentially (one plane resident
    per device at a time), with no cross-device communication at all.
    Each trial's candidates COMPACT on-shard before the gather
    (accel.compact_scan_packed: the dense per-stage top-k tensors are
    the dominant cross-device traffic of a sharded survey — ~100 MB
    per 512 trials over ICI/DCN vs ~12 MB compacted); host collection
    decodes to lists byte-identical to the single-device path — tests
    pin sharded lists == single-device lists — with a lossless dense
    re-gather fallback for trials that overflow the budget.

    searcher: an AccelSearch whose geometry matches pairs_batch's
    numbins.  pairs_batch: [numdms, numbins, 2] float32 (host).
    Returns per-DM candidate lists (search_many semantics).
    """
    from presto_tpu.search.accel import COMPACT_CANDS
    if compact_m is None:
        compact_m = COMPACT_CANDS
    cfg = searcher.cfg
    if cfg.wmax:
        # jerk searches keep the per-w plane-cache loop (no sharded
        # variant yet) — same results, device-serial
        return searcher.search_many(pairs_batch, slab=slab)
    if isinstance(pairs_batch, jax.Array):
        batch = pairs_batch          # device-resident: never round-
        if batch.dtype != jnp.float32:    # trip through the host
            batch = batch.astype(jnp.float32)
    else:
        batch = np.ascontiguousarray(np.asarray(pairs_batch,
                                                np.float32))
    nd = int(batch.shape[0])
    if nd == 0:
        return []
    n = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    axis = mesh.axis_names[0]
    g = searcher._build_plan_ns()
    if g is None:
        return [[] for _ in range(nd)]
    splan = searcher._slab_plan(g.plane_numr, slab)
    if splan is None:
        return [[] for _ in range(nd)]
    slab_, k, scanner, start_cols = splan
    kern_dev = searcher._kern_bank_dev()
    build_body, scan_body = g.build_body, scanner.body
    # pad the DM axis to a mesh multiple (padded trials re-search the
    # last spectrum; their results are dropped)
    pad = (-nd) % n
    if pad:
        xp = jnp if isinstance(batch, jax.Array) else np
        batch = xp.concatenate([batch] + [batch[-1:]] * pad)
    scols = jnp.asarray(np.asarray(start_cols, np.int32))

    # cache the compiled programs on the searcher (jax.jit caches on
    # function identity; a fresh closure per call would re-trace the
    # fused build+scan every survey group)
    from presto_tpu.search.accel import compact_scan_packed

    fkey = ("sharded_search_c", mesh, g.key, slab_, k, batch.shape,
            compact_m)
    fn = searcher._fn_cache.get(fkey)
    if fn is None:
        def per_shard(local, kern, sc):
            def per_dm(_, x):
                packed = scan_body(build_body(x, kern), sc)
                return None, compact_scan_packed(packed, compact_m)
            _, comp = jax.lax.scan(per_dm, None, local)
            return comp                      # [nd_loc, 3, m]

        fn = jax.jit(_shard_map(
            per_shard, mesh=mesh,
            in_specs=(P(axis), P(), P()),
            out_specs=P(axis)))
        searcher._fn_cache[fkey] = fn
    if obs is not None:
        from presto_tpu.obs import costmodel
        costmodel.probe(obs, "accel_search", fn, jnp.asarray(batch),
                        kern_dev, scols)
    comp = np.asarray(fn(jnp.asarray(batch), kern_dev, scols))
    dense = None
    out = []
    for d in range(nd):
        try:
            out.append(searcher.collect_compacted(
                comp[d], start_cols, requested_m=compact_m))
        except ValueError:
            # budget overflow (pathological trial): lossless dense
            # re-gather, compiled only when needed
            if dense is None:
                dkey = ("sharded_search", mesh, g.key, slab_, k,
                        batch.shape)
                dfn = searcher._fn_cache.get(dkey)
                if dfn is None:
                    def per_shard_dense(local, kern, sc):
                        def per_dm(_, x):
                            return None, scan_body(
                                build_body(x, kern), sc)
                        _, packed = jax.lax.scan(per_dm, None, local)
                        return jnp.moveaxis(packed, 1, 0)
                    dfn = jax.jit(_shard_map(
                        per_shard_dense, mesh=mesh,
                        in_specs=(P(axis), P(), P()),
                        out_specs=P(None, axis)))
                    searcher._fn_cache[dkey] = dfn
                from presto_tpu.search.accel import _unpack_scan
                dense = _unpack_scan(np.asarray(
                    dfn(jnp.asarray(batch), kern_dev, scols)))
            vals, cidx, zrow = dense
            out.append(searcher._dedup_sort(searcher._collect_group(
                vals[d], cidx[d], zrow[d], start_cols)))
    return out
