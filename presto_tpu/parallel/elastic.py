"""Elastic multi-host execution for the DM-sharded pipeline.

The reference's mpiprepsubband statically partitions the DM axis over
MPI ranks; one lost rank stalls the collective and loses its DM rows
forever (ROADMAP "multi-host worker loss").  This module replaces the
static partition with **leased shards** from a filesystem ledger
(pipeline/shardledger.py) so a `prepsubband -coordinator` cluster
keeps making progress when members die:

  * every host runs the same loop: lease a pending DM shard, compute
    it on **local** devices (no cross-host collective in the compute
    path — the ledger is the only coordination), stage the outputs,
    and commit them under the ledger's epoch fence;
  * hosts heartbeat through the coordinator workdir (one small atomic
    file per host); a missed heartbeat or an expired lease triggers a
    reap: survivors bump the cluster epoch and re-admit the dead
    member's unverified shards;
  * every cross-host collective that *is* issued (join rendezvous,
    global-mesh init, final sync) runs under a **barrier timeout**
    (`timed_call`) instead of stalling forever; on timeout the
    cluster degrades to independent per-host meshes and the ledger
    carries the run to completion;
  * after an epoch bump the survivors attempt to re-form a smaller
    jax.distributed mesh (best effort — re-initialization is runtime-
    dependent); when re-init is impossible they continue on their
    local devices, which the compute path uses anyway.

Why the communicator is per-host by default: on the current XLA
runtime a jax.distributed member does not merely stall when a peer
dies — the coordination-service client *terminates the surviving
process* (coordination_service_agent polls the peer error and the
default missed-heartbeat handler calls LOG(FATAL); installing a
custom callback aborts in the status marshalling instead).  Joining
the global runtime would therefore make every member share the
victim's fate, which is the opposite of elastic.  So by default
`join()` performs a *ledger rendezvous* (wait for the expected host
count under the barrier timeout) and never touches jax.distributed;
`ElasticConfig.global_mesh=True` opts back into a real
`mesh.init_distributed` join for runtimes that can survive peer
loss, and `_reform()` then re-initializes the smaller grid after a
bump — falling back to per-host meshes whenever any step times out
or fails.

The invariant the tests pin: a run that lost a member produces
artifacts byte-equal to a run that never failed, because any host
computes any shard with the identical deterministic program.
"""

from __future__ import annotations

import contextlib
import glob
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from presto_tpu.pipeline.shardledger import (Lease, ShardLedger,
                                             ShardLedgerError,
                                             StaleEpochError)

#: staged-output prefix; a host sweeps ITS OWN leftovers at join (a
#: peer's staged files are never touched — they may be mid-commit)
STAGE_PREFIX = ".shard-stage."

#: env seam for subprocess chaos harnesses:
#:   PRESTO_TPU_ELASTIC_KILL="<point>[:<nth>[:<mode>[:<stall_s>]]]"
#: mode is exit|raise|stall (testing/chaos.FaultInjector modes)
KILL_ENV = "PRESTO_TPU_ELASTIC_KILL"


class BarrierTimeout(RuntimeError):
    """A cross-host collective exceeded its configured timeout."""

    def __init__(self, name: str, timeout: float):
        self.name = name
        self.timeout = timeout
        super().__init__("collective %r stalled past %.1fs barrier "
                         "timeout" % (name, timeout))


def timed_call(fn: Callable, timeout: float, name: str = "barrier"):
    """Run `fn` (a possibly-stalling collective) in a worker thread
    and give up after `timeout` seconds.  The caller's thread never
    blocks unboundedly; a stalled collective is abandoned to its
    daemon thread and BarrierTimeout raised so the survivors can
    reform instead of hanging the whole cluster."""
    box: dict = {}
    done = threading.Event()

    def runner():
        try:
            box["value"] = fn()
        except BaseException as e:      # noqa: BLE001 — re-raised
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=runner, daemon=True,
                         name="timed-%s" % name)
    t.start()
    if not done.wait(timeout):
        raise BarrierTimeout(name, timeout)
    if "error" in box:
        raise box["error"]
    return box.get("value")


@dataclass
class ElasticConfig:
    """Knobs for the elastic shard loop (wire-safe plain values)."""
    #: upper bound on any cross-host collective (join, sync, shutdown)
    barrier_timeout: float = 60.0
    #: a shard lease not completed/renewed within this window is
    #: re-admitted — the stalled-worker bound
    lease_ttl: float = 120.0
    #: heartbeat write cadence
    heartbeat_interval: float = 2.0
    #: a host silent for this long is declared dead (default: 4x the
    #: heartbeat interval)
    heartbeat_timeout: Optional[float] = None
    #: DM rows per shard; 0 = auto (aim for ~2 shards per host)
    shard_rows: int = 0
    #: sleep while every pending shard is leased elsewhere
    idle_poll: float = 0.25
    #: join the real jax.distributed runtime (cross-host mesh).  OFF
    #: by default: the current XLA coordination client TERMINATES a
    #: surviving process when a peer dies, so a global-mesh member
    #: cannot outlive a worker loss; the default ledger-rendezvous
    #: mode keeps the communicator per-host and survives.  Enable
    #: only on runtimes verified to tolerate peer loss.
    global_mesh: bool = False

    @property
    def hb_timeout(self) -> float:
        return (self.heartbeat_timeout
                if self.heartbeat_timeout is not None
                else 4.0 * self.heartbeat_interval)


def default_host_id(procid: Optional[int] = None) -> str:
    """Stable-ish identity for the ledger: explicit process id when a
    cluster grid was given, else host+pid."""
    if procid is not None:
        return "proc%d" % int(procid)
    return "%s-%d" % (socket.gethostname(), os.getpid())


def stage_path(final: str, host: str, epoch: int) -> str:
    """Per-epoch staged name for an artifact a worker is computing —
    committed onto `final` only if the ledger accepts the lease."""
    d, b = os.path.split(os.path.abspath(final))
    return os.path.join(d, "%s%s.%s.e%d" % (STAGE_PREFIX, b, host,
                                            int(epoch)))


def sweep_stale_stage(workdir: str, host: str) -> int:
    """Remove THIS host's leftover staged files (a previous
    incarnation died mid-compute).  Peers' staged files are left
    alone — they may be one ledger-lock away from committing."""
    n = 0
    pat = os.path.join(workdir, STAGE_PREFIX + "*.%s.e*" % host)
    for p in glob.glob(pat):
        with contextlib.suppress(OSError):
            os.remove(p)
            n += 1
    return n


# ----------------------------------------------------------------------
# process-level seams (CLI entry points can't take objects via argv)
# ----------------------------------------------------------------------

_process_injector = None
_process_obs = None


def set_process_injector(injector) -> None:
    """Thread a chaos FaultInjector into elastic runs started through
    a CLI main() in this process (the survey driver uses this)."""
    global _process_injector
    _process_injector = injector


def set_process_obs(obs) -> None:
    global _process_obs
    _process_obs = obs


def _injector_from_env():
    """Build a FaultInjector from PRESTO_TPU_ELASTIC_KILL — the seam
    subprocess harnesses (tools/multihost_chaos.py) use to kill or
    stall one real cluster member at a named point."""
    spec = os.environ.get(KILL_ENV, "")
    if not spec:
        return None
    from presto_tpu.testing.chaos import FaultInjector
    parts = spec.split(":")
    point = parts[0]
    nth = int(parts[1]) if len(parts) > 1 and parts[1] else 1
    mode = parts[2] if len(parts) > 2 and parts[2] else "exit"
    stall = float(parts[3]) if len(parts) > 3 and parts[3] else 3600.0
    return FaultInjector(kill_at=point, kill_after=nth, mode=mode,
                         stall_seconds=stall)


def process_injector():
    """The active injector: explicit seam first, then the env spec."""
    return (_process_injector if _process_injector is not None
            else _injector_from_env())


# ----------------------------------------------------------------------
# the cluster
# ----------------------------------------------------------------------

class ElasticCluster:
    """One host's membership in an elastic DM-shard run.

    Lifecycle::

        cluster = ElasticCluster(workdir, host, cfg)
        cluster.join(coordinator, nproc, procid)   # timed, may degrade
        done = cluster.run(shard_specs, compute_fn)
        cluster.close()
    """

    def __init__(self, workdir: str, host: str,
                 cfg: Optional[ElasticConfig] = None, obs=None,
                 fault_injector=None, ledger_name: Optional[str] = None):
        from presto_tpu.obs import get_obs
        self.workdir = os.path.abspath(workdir)
        self.host = host
        self.cfg = cfg or ElasticConfig()
        self.obs = obs if obs is not None else (
            _process_obs if _process_obs is not None else get_obs())
        self.fault_injector = (fault_injector
                               if fault_injector is not None
                               else process_injector())
        os.makedirs(self.workdir, exist_ok=True)
        kw = {} if ledger_name is None else {"name": ledger_name}
        self.ledger = ShardLedger(self.workdir, obs=self.obs, **kw)
        self.epoch = 0
        self.distributed = False
        self.coordinator: Optional[str] = None
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._last_reap = 0.0
        reg = self.obs.metrics
        self.m_epoch = reg.gauge(
            "cluster_epoch", "Current elastic-cluster epoch")
        self.m_alive = reg.gauge(
            "cluster_alive_hosts", "Hosts with fresh heartbeats")
        self.m_done = reg.counter(
            "cluster_shards_done_total", "DM shards committed")
        self.m_redo = reg.counter(
            "cluster_shard_redos_total",
            "DM shards re-admitted after loss/expiry/verify failure")
        self.m_bumps = reg.counter(
            "cluster_epoch_bumps_total", "Cluster epoch bumps")
        self.m_barrier_to = reg.counter(
            "cluster_barrier_timeouts_total",
            "Collectives abandoned at the barrier timeout")
        self.m_stale = reg.counter(
            "cluster_stale_writes_total",
            "Epoch-fenced (zombie) shard commits rejected")
        self.m_hb = reg.counter(
            "cluster_heartbeats_total", "Heartbeats written")

    # -- chaos / events ----------------------------------------------
    def _point(self, name: str) -> None:
        """Chaos kill point: flight-recorded first so a kill here
        names itself in the dump (the survey._chaos contract)."""
        if self.obs.enabled:
            self.obs.event("chaos-point", point=name, host=self.host)
        if self.fault_injector is not None:
            self.fault_injector.point(name)

    # -- membership ---------------------------------------------------
    def join(self, coordinator: Optional[str] = None,
             nproc: Optional[int] = None,
             procid: Optional[int] = None) -> int:
        """Join the cluster: ledger registration, heartbeat thread,
        and a bounded rendezvous.  Never stalls: with the default
        per-host communicator (cfg.global_mesh=False) the rendezvous
        is a ledger poll for the expected host count; with
        global_mesh=True a real jax.distributed init runs under the
        barrier timeout.  Either way a timeout degrades to an
        independent per-host mesh — the compute path only ever uses
        local devices, so that is a visibility downgrade, not a
        correctness one.  Returns the epoch joined under."""
        sweep_stale_stage(self.workdir, self.host)
        self.coordinator = coordinator
        if self.cfg.global_mesh and (coordinator
                                     or nproc is not None):
            from presto_tpu.parallel.mesh import init_distributed
            try:
                timed_call(
                    lambda: init_distributed(coordinator, nproc,
                                             procid),
                    self.cfg.barrier_timeout, "init-distributed")
                self.distributed = True
            except BarrierTimeout:
                self.m_barrier_to.inc()
                if self.obs.enabled:
                    self.obs.event("barrier-timeout",
                                   name="init-distributed",
                                   timeout=self.cfg.barrier_timeout)
                print("elastic: cluster join timed out after %.1fs — "
                      "continuing on the local mesh"
                      % self.cfg.barrier_timeout)
            except Exception as e:
                print("elastic: cluster join failed (%s: %s) — "
                      "continuing on the local mesh"
                      % (type(e).__name__, e))
        self.epoch = self.ledger.join(self.host, addr=coordinator)
        self._readmit_own_leases()
        self.ledger.heartbeat(self.host, self.epoch)
        self.m_hb.inc()
        self.m_epoch.set(self.epoch)
        if self.obs.enabled:
            self.obs.event("cluster-join", host=self.host,
                           epoch=self.epoch,
                           distributed=self.distributed)
        self._hb_thread = threading.Thread(
            target=self._hb_loop, daemon=True,
            name="elastic-hb-%s" % self.host)
        self._hb_thread.start()
        if not self.distributed and nproc is not None and nproc > 1:
            self._rendezvous(int(nproc))
        return self.epoch

    def _rendezvous(self, expected: int) -> bool:
        """Ledger-based join barrier: wait (bounded by the barrier
        timeout) until `expected` hosts heartbeat, so a run starts
        with its full cluster when everyone shows up — but a member
        that never arrives only costs the timeout, not the run."""
        deadline = time.time() + self.cfg.barrier_timeout
        while time.time() < deadline:
            alive = self.ledger.alive_hosts(ttl=self.cfg.hb_timeout)
            self.m_alive.set(len(alive))
            if len(alive) >= expected:
                return True
            time.sleep(min(0.05, self.cfg.idle_poll))
        self.m_barrier_to.inc()
        if self.obs.enabled:
            self.obs.event("barrier-timeout", name="join-rendezvous",
                           timeout=self.cfg.barrier_timeout,
                           expected=expected)
        print("elastic: join rendezvous timed out (%d host(s) "
              "expected) — proceeding with the survivors" % expected)
        return False

    def _readmit_own_leases(self) -> None:
        """A restarting host cannot have in-flight work: any lease the
        ledger still shows under this host's name belongs to a dead
        incarnation.  Expire it now rather than waiting out the TTL."""
        redone = self.ledger.readmit_owned(self.host)
        if redone:
            self.epoch = self.ledger.epoch
            self.m_epoch.set(self.epoch)
            self.m_bumps.inc()
            self.m_redo.inc(len(redone))

    def _hb_loop(self) -> None:
        while not self._hb_stop.wait(self.cfg.heartbeat_interval):
            try:
                self.ledger.heartbeat(self.host, self.epoch)
                self.m_hb.inc()
            except OSError:
                pass                       # workdir vanished: dying

    def close(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)

    # -- failure detection + reform -----------------------------------
    def _note_reap(self, report) -> None:
        if report.bumped:
            self.epoch = report.epoch
            self.m_epoch.set(self.epoch)
            self.m_bumps.inc()
            self.m_redo.inc(len(report.redone))

    def maybe_reap(self, now: Optional[float] = None) -> bool:
        """Periodic failure detection; returns True when membership
        changed (epoch bumped) and a mesh reform was attempted."""
        now = time.time() if now is None else now
        if now - self._last_reap < self.cfg.heartbeat_interval:
            return False
        self._last_reap = now
        report = self.ledger.reap(self.cfg.hb_timeout, now=now)
        alive = self.ledger.alive_hosts(now=now,
                                        ttl=self.cfg.hb_timeout)
        self.m_alive.set(len(alive))
        if not report.bumped:
            if self.epoch < report.epoch:   # a peer bumped it
                self.epoch = report.epoch
                self.m_epoch.set(self.epoch)
            return False
        self._note_reap(report)
        self._reform(alive)
        self._point("post-epoch-bump")
        return True

    def _reform(self, alive: List[str]) -> None:
        """Re-form the communicator over the survivors.  Best effort:
        tear down the stalled runtime under the barrier timeout and
        try a fresh jax.distributed grid agreed through the ledger
        (rank = index among sorted survivors, coordinator port offset
        by epoch).  When any step fails — the common case on runtimes
        that cannot re-initialize in-process — degrade to independent
        per-host meshes; the compute path is local-only either way."""
        if not self.distributed:
            return
        import jax
        with contextlib.suppress(BaseException):
            timed_call(jax.distributed.shutdown,
                       self.cfg.barrier_timeout,
                       "distributed-shutdown")
        ok = False
        coord = self._reform_coordinator(alive)
        if coord is not None and self.host in alive:
            try:
                timed_call(
                    lambda: jax.distributed.initialize(
                        coordinator_address=coord,
                        num_processes=len(alive),
                        process_id=sorted(alive).index(self.host)),
                    self.cfg.barrier_timeout, "reform")
                ok = jax.process_count() == len(alive)
            except BarrierTimeout:
                self.m_barrier_to.inc()
                if self.obs.enabled:
                    self.obs.event("barrier-timeout", name="reform",
                                   timeout=self.cfg.barrier_timeout)
            except Exception:
                ok = False
        if not ok:
            self.distributed = False
        if self.obs.enabled:
            self.obs.event("mesh-reform",
                           mode="cluster" if ok else "local",
                           survivors=sorted(alive),
                           epoch=self.epoch)
        print("elastic: epoch %d — %s mesh over %d survivor(s)"
              % (self.epoch, "re-formed" if ok else "per-host",
                 max(len(alive), 1)))

    def _reform_coordinator(self, alive: List[str]) -> Optional[str]:
        if not alive or self.coordinator is None:
            return None
        host, _, port = self.coordinator.rpartition(":")
        try:
            return "%s:%d" % (host, int(port) + self.epoch)
        except ValueError:
            return None

    def barrier(self, name: str = "sync") -> bool:
        """Timed cross-host sync; False (never a stall) on timeout."""
        if not self.distributed:
            return True
        try:
            from jax.experimental import multihost_utils
            timed_call(
                lambda: multihost_utils.sync_global_devices(name),
                self.cfg.barrier_timeout, name)
            return True
        except BarrierTimeout:
            self.m_barrier_to.inc()
            if self.obs.enabled:
                self.obs.event("barrier-timeout", name=name,
                               timeout=self.cfg.barrier_timeout)
            return False
        except Exception:
            return False

    # -- the shard loop -----------------------------------------------
    def run(self, specs: Sequence[Tuple[str, int, int]],
            compute_fn: Callable[[Lease], Dict[str, str]],
            meta: Optional[dict] = None) -> int:
        """Drive the elastic loop until every shard is done.

        `compute_fn(lease)` computes the lease's DM rows and returns
        {final_path: staged_path}; this loop owns lease/commit/fence
        handling and failure detection.  Returns the number of shards
        THIS host committed."""
        self.ledger.ensure_shards(specs, meta=meta)
        self.ledger.verify_done()
        committed = 0
        while True:
            self.maybe_reap()
            if self.ledger.all_done():
                break
            lease = self.ledger.lease(self.host, self.cfg.lease_ttl)
            if lease is None:
                # every pending shard is leased elsewhere: wait for a
                # peer commit, or for reap to re-admit a lost lease
                time.sleep(self.cfg.idle_poll)
                continue
            if self.epoch < lease.epoch:
                self.epoch = lease.epoch
                self.m_epoch.set(self.epoch)
            self._point("shard-leased")
            try:
                staged = compute_fn(lease)
            except Exception:
                # a compute error on this host: release the lease so a
                # peer (possibly differently configured) can try, then
                # surface the error — it is a bug, not a membership
                # event
                self.ledger.fail(lease, self.host)
                raise
            self._point("shard-computed")
            self._point("pre-shard-commit")
            try:
                self.ledger.complete(lease, self.host, staged)
                committed += 1
                self.m_done.inc()
            except StaleEpochError:
                # fenced: our lease was re-admitted while we computed
                # (we were presumed dead, or the lease expired).  The
                # staged files are gone; the shard belongs to whoever
                # re-leased it.
                self.m_stale.inc()
                continue
            except ShardLedgerError as e:
                print("elastic: commit of %s failed (%s) — shard "
                      "re-admitted" % (lease.shard_id, e))
                continue
            self._point("post-shard-commit")
        self.barrier("elastic-done")
        return committed


def run_elastic(workdir: str, host: str,
                specs: Sequence[Tuple[str, int, int]],
                compute_fn: Callable[[Lease], Dict[str, str]],
                cfg: Optional[ElasticConfig] = None,
                coordinator: Optional[str] = None,
                nproc: Optional[int] = None,
                procid: Optional[int] = None, obs=None,
                fault_injector=None, meta: Optional[dict] = None) -> int:
    """One-call wrapper: join, run every shard, leave.  Returns the
    number of shards this host committed."""
    cluster = ElasticCluster(workdir, host, cfg, obs=obs,
                             fault_injector=fault_injector)
    cluster.join(coordinator, nproc, procid)
    try:
        return cluster.run(specs, compute_fn, meta=meta)
    finally:
        cluster.close()
