"""Range-string parsing: '0:3,10,15:17' -> [0,1,2,3,10,15,16,17].

Parity: ranges_to_ivect (src/range_parse.c) — PRESTO accepts both
'lo:hi' and 'lo-hi' with comma separation; ranges are inclusive.
"""

from __future__ import annotations

from typing import List


def parse_ranges(s: str) -> List[int]:
    out: List[int] = []
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        for sep in (":", "-"):
            if sep in part:
                lo, hi = part.split(sep, 1)
                out.extend(range(int(lo), int(hi) + 1))
                break
        else:
            out.append(int(part))
    return sorted(set(out))
