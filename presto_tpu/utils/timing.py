"""Timing, progress, and profiling instrumentation (SURVEY §5.1).

Every long-running reference tool prints user/system/total times via
times() (accelsearch.c:56,301-308; realfft.c:62) and a percent-
complete meter (accelsearch.c:22-41, prepsubband.c:1026-1040).  This
module provides those behaviors plus the TPU-era additions the rebuild
plan calls for: named per-stage wall-clock accounting and an optional
JAX profiler trace (set PRESTO_TPU_PROFILE=<dir> to capture a trace
viewable in TensorBoard/Perfetto).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Optional


def print_percent_complete(current: int, total: int,
                           last: int = -1) -> int:
    """Throttled percent meter (print_percent_complete,
    accelsearch.c:22-41): prints at most once per whole percent.
    Returns the new 'last' value; pass it back on the next call."""
    pct = int(100.0 * current / max(total, 1))
    if pct != last:
        sys.stdout.write("\rAmount complete = %3d%%" % pct)
        if pct >= 100:
            sys.stdout.write("\n")
        sys.stdout.flush()
    return pct


class LatencyStats:
    """Per-name latency samples with percentile accounting — the
    serving layer's /metrics backbone.  Each name keeps a bounded
    window of recent samples (deque; old samples age out) plus
    lifetime count/total, and reports p50/p90/p99 over the window.
    Thread-safe: the service records from scheduler and HTTP threads.
    """

    def __init__(self, window: int = 2048):
        self._lock = threading.Lock()
        self._window = window
        self._samples: Dict[str, deque] = {}
        self._count: Dict[str, int] = {}
        self._total: Dict[str, float] = {}

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            if name not in self._samples:
                self._samples[name] = deque(maxlen=self._window)
                self._count[name] = 0
                self._total[name] = 0.0
            self._samples[name].append(float(seconds))
            self._count[name] += 1
            self._total[name] += float(seconds)

    def percentiles(self, name: str,
                    qs=(50, 90, 99)) -> Dict[str, float]:
        """Nearest-rank percentiles over the sample window."""
        with self._lock:
            xs = sorted(self._samples.get(name, ()))
        if not xs:
            return {"p%d" % q: 0.0 for q in qs}
        n = len(xs)
        return {"p%d" % q: xs[min(n - 1, max(0, (n * q + 99) // 100 - 1))]
                for q in qs}

    def snapshot(self) -> Dict[str, dict]:
        """{name: {count, mean_s, p50_s, p90_s, p99_s, max_s}} for
        every recorded stage (the /metrics `latency` block)."""
        with self._lock:
            names = list(self._samples)
        out = {}
        for name in names:
            with self._lock:
                xs = list(self._samples[name])
                count = self._count[name]
                total = self._total[name]
            if not xs:
                continue
            pcts = self.percentiles(name)
            out[name] = {
                "count": count,
                "mean_s": round(total / count, 6),
                "p50_s": round(pcts["p50"], 6),
                "p90_s": round(pcts["p90"], 6),
                "p99_s": round(pcts["p99"], 6),
                "max_s": round(max(xs), 6),
            }
        return out


class StageTimer:
    """Accumulates named per-stage wall times; prints a summary table.
    The pipeline-driver analog of the reference's per-tool timing.
    With `stats` (a LatencyStats), every closed stage also records a
    latency sample, so a resident service accumulates per-stage
    percentiles across jobs."""

    def __init__(self, stats: Optional[LatencyStats] = None):
        self.stages: Dict[str, float] = {}
        self._t0 = time.time()
        self._cur: Optional[tuple] = None
        self._stats = stats

    def _close(self, name: str, dt: float) -> None:
        self.stages[name] = self.stages.get(name, 0.0) + dt
        if self._stats is not None:
            self._stats.record(name, dt)

    def mark(self, name: Optional[str]) -> None:
        """Sequential accounting: close the current stage (if any) and
        open `name` (None = just close).  Lighter to wire into an
        existing driver than the context manager."""
        now = time.time()
        if self._cur is not None:
            cname, t0 = self._cur
            self._close(cname, now - t0)
        self._cur = (name, now) if name else None

    @contextmanager
    def stage(self, name: str):
        t0 = time.time()
        try:
            yield
        finally:
            self._close(name, time.time() - t0)

    def report(self, file=None) -> str:
        total = time.time() - self._t0
        lines = ["Per-stage wall times:"]
        for name, dt in self.stages.items():
            lines.append("  %-24s %8.2f s  (%4.1f%%)"
                         % (name, dt, 100.0 * dt / max(total, 1e-9)))
        lines.append("  %-24s %8.2f s" % ("TOTAL", total))
        text = "\n".join(lines)
        print(text, file=file or sys.stdout)
        return text


@contextmanager
def app_timer(prog: str):
    """Wrap an app main: on exit print the reference's closing block
    (user/system/total CPU + wall time, accelsearch.c:301-308), and
    honor PRESTO_TPU_PROFILE=<dir> with a JAX profiler trace."""
    profile_dir = os.environ.get("PRESTO_TPU_PROFILE")
    tracing = False
    if profile_dir:
        try:
            import jax
            jax.profiler.start_trace(profile_dir)
            tracing = True
        except Exception as e:           # profiling must never break
            print("%s: profiler unavailable (%s)" % (prog, e),
                  file=sys.stderr)
    t0 = time.time()
    c0 = os.times()
    try:
        yield
    finally:
        wall = time.time() - t0
        c1 = os.times()
        if tracing:
            try:
                import jax
                jax.profiler.stop_trace()
                print("%s: JAX profile trace -> %s" % (prog,
                                                       profile_dir))
            except Exception:
                pass
        print("%s: user %.1f s, system %.1f s, wall %.1f s"
              % (prog, c1.user - c0.user, c1.system - c0.system,
                 wall))
