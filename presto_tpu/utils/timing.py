"""Timing, progress, and profiling instrumentation (SURVEY §5.1).

Every long-running reference tool prints user/system/total times via
times() (accelsearch.c:56,301-308; realfft.c:62) and a percent-
complete meter (accelsearch.c:22-41, prepsubband.c:1026-1040).  This
module provides those behaviors plus the TPU-era additions the rebuild
plan calls for: named per-stage wall-clock accounting and an optional
JAX profiler trace (set PRESTO_TPU_PROFILE=<dir> to capture a trace
viewable in TensorBoard/Perfetto).
"""

from __future__ import annotations

import os
import sys
import time
from contextlib import contextmanager
from typing import Dict, Optional


def print_percent_complete(current: int, total: int,
                           last: int = -1) -> int:
    """Throttled percent meter (print_percent_complete,
    accelsearch.c:22-41): prints at most once per whole percent.
    Returns the new 'last' value; pass it back on the next call."""
    pct = int(100.0 * current / max(total, 1))
    if pct != last:
        sys.stdout.write("\rAmount complete = %3d%%" % pct)
        if pct >= 100:
            sys.stdout.write("\n")
        sys.stdout.flush()
    return pct


class StageTimer:
    """Accumulates named per-stage wall times; prints a summary table.
    The pipeline-driver analog of the reference's per-tool timing."""

    def __init__(self):
        self.stages: Dict[str, float] = {}
        self._t0 = time.time()
        self._cur: Optional[tuple] = None

    def mark(self, name: Optional[str]) -> None:
        """Sequential accounting: close the current stage (if any) and
        open `name` (None = just close).  Lighter to wire into an
        existing driver than the context manager."""
        now = time.time()
        if self._cur is not None:
            cname, t0 = self._cur
            self.stages[cname] = self.stages.get(cname, 0.0) + now - t0
        self._cur = (name, now) if name else None

    @contextmanager
    def stage(self, name: str):
        t0 = time.time()
        try:
            yield
        finally:
            self.stages[name] = self.stages.get(name, 0.0) + \
                (time.time() - t0)

    def report(self, file=None) -> str:
        total = time.time() - self._t0
        lines = ["Per-stage wall times:"]
        for name, dt in self.stages.items():
            lines.append("  %-24s %8.2f s  (%4.1f%%)"
                         % (name, dt, 100.0 * dt / max(total, 1e-9)))
        lines.append("  %-24s %8.2f s" % ("TOTAL", total))
        text = "\n".join(lines)
        print(text, file=file or sys.stdout)
        return text


@contextmanager
def app_timer(prog: str):
    """Wrap an app main: on exit print the reference's closing block
    (user/system/total CPU + wall time, accelsearch.c:301-308), and
    honor PRESTO_TPU_PROFILE=<dir> with a JAX profiler trace."""
    profile_dir = os.environ.get("PRESTO_TPU_PROFILE")
    tracing = False
    if profile_dir:
        try:
            import jax
            jax.profiler.start_trace(profile_dir)
            tracing = True
        except Exception as e:           # profiling must never break
            print("%s: profiler unavailable (%s)" % (prog, e),
                  file=sys.stderr)
    t0 = time.time()
    c0 = os.times()
    try:
        yield
    finally:
        wall = time.time() - t0
        c1 = os.times()
        if tracing:
            try:
                import jax
                jax.profiler.stop_trace()
                print("%s: JAX profile trace -> %s" % (prog,
                                                       profile_dir))
            except Exception:
                pass
        print("%s: user %.1f s, system %.1f s, wall %.1f s"
              % (prog, c1.user - c0.user, c1.system - c0.system,
                 wall))
