"""Timing, progress, and profiling instrumentation (SURVEY §5.1).

Every long-running reference tool prints user/system/total times via
times() (accelsearch.c:56,301-308; realfft.c:62) and a percent-
complete meter (accelsearch.c:22-41, prepsubband.c:1026-1040).  This
module provides those behaviors plus the TPU-era additions the rebuild
plan calls for: named per-stage wall-clock accounting and an optional
JAX profiler trace (set PRESTO_TPU_PROFILE=<dir> to capture a trace
viewable in TensorBoard/Perfetto).

Since the obs layer landed, the latency accounting here is a *view*
over the shared metrics registry (presto_tpu/obs/metrics.py) rather
than a private sample store: LatencyStats keeps its exact API and
nearest-rank percentile semantics, but every sample it records lands
in a registry histogram (`latency_seconds{name=...}`), so the serve
layer's /metrics JSON and the Prometheus exposition read the same
numbers — one source of truth.
"""

from __future__ import annotations

import os
import sys
import time
from contextlib import contextmanager
from typing import Dict, Optional

#: env override for the \r percent meter: "1" forces it on (even when
#: stdout is piped), "0" forces it off.  Unset -> isatty() decides.
METER_ENV = "PRESTO_TPU_METER"


def _meter_enabled() -> bool:
    """Should the in-place \r meter run?  Interactive terminals only —
    a piped stdout (batch logs, the serve event log) must not be
    spammed with carriage returns."""
    env = os.environ.get(METER_ENV)
    if env is not None:
        return env not in ("", "0")
    try:
        return sys.stdout.isatty()
    except (AttributeError, ValueError):
        return False


def print_percent_complete(current: int, total: int,
                           last: int = -1) -> int:
    """Throttled percent meter (print_percent_complete,
    accelsearch.c:22-41): prints at most once per whole percent.
    Returns the new 'last' value; pass it back on the next call.

    On a non-TTY stdout the running \r meter is suppressed (only the
    final 100% line is printed) so piped logs stay one-line-per-event;
    set PRESTO_TPU_METER=1/0 to force it on/off."""
    pct = int(100.0 * current / max(total, 1))
    if pct != last:
        meter = _meter_enabled()
        if meter and pct < 100:
            sys.stdout.write("\rAmount complete = %3d%%" % pct)
            sys.stdout.flush()
        elif pct >= 100:
            sys.stdout.write("\rAmount complete = %3d%%\n" % pct
                             if meter
                             else "Amount complete = 100%\n")
            sys.stdout.flush()
    return pct


class LatencyStats:
    """Per-name latency samples with percentile accounting — the
    serving layer's /metrics backbone.  Each name is one child of a
    shared registry histogram (`latency_seconds{name=...}`): lifetime
    count/sum plus a bounded window of recent samples for p50/p90/p99
    (nearest-rank, old samples age out).  Thread-safe: the service
    records from scheduler and HTTP threads.

    Pass `registry` (obs MetricsRegistry) to share the serve layer's
    registry; by default a private always-enabled registry backs the
    instance, preserving the historical standalone behavior."""

    METRIC = "latency_seconds"

    def __init__(self, window: int = 2048, registry=None):
        if registry is None:
            from presto_tpu.obs.metrics import MetricsRegistry
            registry = MetricsRegistry(enabled=True)
        self.registry = registry
        self._hist = registry.histogram(
            self.METRIC, "Recorded latency samples by name",
            ("name",), window=window)

    def record(self, name: str, seconds: float) -> None:
        self._hist.labels(name=name).observe(float(seconds))

    def percentiles(self, name: str,
                    qs=(50, 90, 99)) -> Dict[str, float]:
        """Nearest-rank percentiles over the sample window."""
        return self._hist.labels(name=name).percentiles(qs)

    def snapshot(self) -> Dict[str, dict]:
        """{name: {count, mean_s, p50_s, p90_s, p99_s, max_s}} for
        every recorded stage (the /metrics `latency` block)."""
        out = {}
        for labels, child in self._hist.children():
            count = child.count
            xs = child.samples()
            if not count or not xs:
                continue
            pcts = child.percentiles()
            out[dict(labels)["name"]] = {
                "count": count,
                "mean_s": round(child.sum / count, 6),
                "p50_s": round(pcts["p50"], 6),
                "p90_s": round(pcts["p90"], 6),
                "p99_s": round(pcts["p99"], 6),
                "max_s": round(max(xs), 6),
            }
        return out


class StageTimer:
    """Accumulates named per-stage wall times; prints a summary table.
    The pipeline-driver analog of the reference's per-tool timing.
    With `stats` (a LatencyStats), every closed stage also records a
    latency sample, so a resident service accumulates per-stage
    percentiles across jobs.  With `obs` (an Observability), every
    stage additionally becomes a span and a
    `survey_stage_seconds{stage=...}` histogram sample."""

    def __init__(self, stats: Optional[LatencyStats] = None,
                 obs=None):
        self.stages: Dict[str, float] = {}
        self._t0 = time.time()
        self._cur: Optional[tuple] = None
        self._stats = stats
        self._obs = obs if (obs is not None
                            and getattr(obs, "enabled", False)) \
            else None
        self._span = None

    def _close(self, name: str, dt: float) -> None:
        self.stages[name] = self.stages.get(name, 0.0) + dt
        if self._stats is not None:
            self._stats.record(name, dt)
        if self._obs is not None:
            self._obs.metrics.histogram(
                "survey_stage_seconds",
                "Survey stage wall time",
                ("stage",)).labels(stage=name).observe(dt)

    def mark(self, name: Optional[str]) -> None:
        """Sequential accounting: close the current stage (if any) and
        open `name` (None = just close).  Lighter to wire into an
        existing driver than the context manager."""
        now = time.time()
        if self._cur is not None:
            cname, t0 = self._cur
            self._close(cname, now - t0)
        if self._span is not None:
            self._span.finish()
            self._span = None
        self._cur = (name, now) if name else None
        if name and self._obs is not None:
            self._span = self._obs.span("stage:" + name, stage=name)

    @contextmanager
    def stage(self, name: str):
        t0 = time.time()
        span = (self._obs.span("stage:" + name, stage=name)
                if self._obs is not None else None)
        try:
            yield
        finally:
            if span is not None:
                span.finish()
            self._close(name, time.time() - t0)

    def report(self, file=None) -> str:
        total = time.time() - self._t0
        lines = ["Per-stage wall times:"]
        for name, dt in self.stages.items():
            lines.append("  %-24s %8.2f s  (%4.1f%%)"
                         % (name, dt, 100.0 * dt / max(total, 1e-9)))
        lines.append("  %-24s %8.2f s" % ("TOTAL", total))
        text = "\n".join(lines)
        print(text, file=file or sys.stdout)
        return text


@contextmanager
def app_timer(prog: str):
    """Wrap an app main: on exit print the reference's closing block
    (user/system/total CPU + wall time, accelsearch.c:301-308), and
    honor PRESTO_TPU_PROFILE=<dir> with a JAX profiler trace."""
    profile_dir = os.environ.get("PRESTO_TPU_PROFILE")
    tracing = False
    if profile_dir:
        try:
            import jax
            jax.profiler.start_trace(profile_dir)
            tracing = True
        except Exception as e:           # profiling must never break
            print("%s: profiler unavailable (%s)" % (prog, e),
                  file=sys.stderr)
    t0 = time.time()
    c0 = os.times()
    try:
        yield
    finally:
        wall = time.time() - t0
        c1 = os.times()
        if tracing:
            try:
                import jax
                jax.profiler.stop_trace()
                print("%s: JAX profile trace -> %s" % (prog,
                                                       profile_dir))
            except Exception:
                pass
        print("%s: user %.1f s, system %.1f s, wall %.1f s"
              % (prog, c1.user - c0.user, c1.system - c0.system,
                 wall))
