"""Pulsar catalog: lookup of known-pulsar parameters at an epoch.

Parity targets:
  src/database.c — get_psr_at_epoch (:167-230, spin/orbit advance to
    the observation epoch), psr_number_from_name lookup;
  lib/python/pypsrcat.py — parser for the ATNF psrcat "Short with
    errors" text export (lib/psr_catalog.txt format);
  python/presto_src/__init__.py:62 psrepoch();
  src/responses.c:92-140 binary_velocity().

The reference ships a snapshot of the ATNF catalog (lib/psr_catalog.txt,
3033 pulsars).  Here a small built-in catalog of bright/famous pulsars
covers tests and offline use; a full ATNF text export can be dropped in
via load_catalog(path) or $PRESTO_TPU_CATALOG — the parser reads the
same column layout the reference's pypsrcat.py consumes.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from presto_tpu.ops.orbit import OrbitParams, keplers_eqn, E_to_v, SOL

SECPERDAY = 86400.0
TWOPI = 2.0 * math.pi


@dataclass
class PsrParams:
    """Spin/astrometric/orbit parameters of a catalog pulsar
    (include/database.h:42-66 psrparams)."""
    jname: str = ""
    bname: str = ""
    ra2000: float = 0.0          # radians
    dec2000: float = 0.0         # radians
    ra_str: str = ""
    dec_str: str = ""
    p: float = 0.0               # s
    pd: float = 0.0
    pdd: float = 0.0
    f: float = 0.0               # Hz
    fd: float = 0.0
    fdd: float = 0.0
    dm: float = 0.0
    timepoch: float = 0.0        # MJD of p/f values
    orb: Optional[OrbitParams] = None   # orb.p in SECONDS once at-epoch

    @property
    def name(self) -> str:
        return self.jname or self.bname


from presto_tpu.astro.bary import parse_ra as _hms_to_rad
from presto_tpu.astro.bary import parse_dec as _dms_to_rad


# Built-in mini-catalog.  Public astronomical facts (ATNF psrcat
# values); enough pulsars for zap lists, tests, and demos.  Fields:
# PB days, A1 lt-s, OM deg, T0 MJD.
_BUILTIN: List[dict] = [
    dict(bname="B0329+54", jname="J0332+5434", raj="03:32:59.4",
         decj="+54:34:43.6", p0=0.714519699726, p1=2.04961e-15,
         pepoch=46473.0, dm=26.7641),
    dict(bname="B0531+21", jname="J0534+2200", raj="05:34:31.97",
         decj="+22:00:52.06", p0=0.0333924123, p1=4.20972e-13,
         pepoch=40000.0, dm=56.771),
    dict(bname="B0833-45", jname="J0835-4510", raj="08:35:20.61",
         decj="-45:10:34.88", p0=0.089328385024, p1=1.25008e-13,
         pepoch=51559.319, dm=67.99),
    dict(bname="B1937+21", jname="J1939+2134", raj="19:39:38.56",
         decj="+21:34:59.14", p0=0.00155780644887275,
         p1=1.051193e-19, pepoch=52601.0, dm=71.0151),
    dict(bname="B0950+08", jname="J0953+0755", raj="09:53:09.31",
         decj="+07:55:35.75", p0=0.2530651649482, p1=2.29758e-16,
         pepoch=46375.0, dm=2.97),
    dict(bname="B1919+21", jname="J1921+2153", raj="19:21:44.815",
         decj="+21:53:02.25", p0=1.3373021601895, p1=1.34809e-15,
         pepoch=48999.0, dm=12.4309),
    dict(jname="J0437-4715", raj="04:37:15.88", decj="-47:15:09.11",
         p0=0.005757451936712637, p1=5.729e-20, pepoch=54500.0,
         dm=2.64476, pb=5.7410459, a1=3.36669157, ecc=1.918e-5,
         om=1.22, t0=54501.4671),
    dict(bname="B1913+16", jname="J1915+1606", raj="19:15:27.99",
         decj="+16:06:27.38", p0=0.059030003217813, p1=8.6183e-18,
         pepoch=52984.0, dm=168.77, pb=0.322997448918,
         a1=2.341782, ecc=0.6171338, om=292.54450, t0=52144.90097844),
    dict(bname="B1957+20", jname="J1959+2048", raj="19:59:36.77",
         decj="+20:48:15.12", p0=0.00160740168480632, p1=1.685e-20,
         pepoch=48196.0, dm=29.1168, pb=0.38196748742,
         a1=0.0892253, ecc=0.0, om=0.0, t0=48196.0635242),
    dict(jname="J0737-3039A", raj="07:37:51.25", decj="-30:39:40.71",
         p0=0.0226993785996239, p1=1.75993e-18, pepoch=53156.0,
         dm=48.920, pb=0.10225156248, a1=1.415032, ecc=0.0877775,
         om=87.0331, t0=53155.9074280),
    dict(bname="B1821-24", jname="J1824-2452A", raj="18:24:32.008",
         decj="-24:52:10.8", p0=0.0030542120468132, p1=1.61857e-18,
         pepoch=54500.0, dm=120.502),
    dict(bname="B0656+14", jname="J0659+1414", raj="06:59:48.13",
         decj="+14:14:21.5", p0=0.384891195054, p1=5.50130e-14,
         pepoch=49721.0, dm=13.977),
]


class Catalog:
    """Name -> PsrParams lookup over a list of catalog records."""

    def __init__(self, records: List[dict]):
        self.records = records
        self._index: Dict[str, int] = {}
        for i, r in enumerate(records):
            for key in (r.get("jname"), r.get("bname")):
                if key:
                    self._index.setdefault(key.lstrip("JB").upper(), i)
                    self._index.setdefault(key.upper(), i)

    def __len__(self):
        return len(self.records)

    def lookup(self, name: str) -> Optional[dict]:
        """Find a record by J/B name, with or without the prefix
        (psr_number_from_name database.c:118-150 strips J/B/PSR)."""
        name = name.upper()
        for cand in (name, name.lstrip("JB"),
                     "J" + name, "B" + name):
            if cand in self._index:
                return self.records[self._index[cand]]
        return None

    def params(self, name: str) -> Optional[PsrParams]:
        r = self.lookup(name)
        if r is None:
            return None
        p0 = r.get("p0", 0.0)
        p1 = r.get("p1", 0.0)
        f = 1.0 / p0 if p0 else 0.0
        fd = -p1 * f * f if p0 else 0.0
        orb = None
        if r.get("pb"):
            orb = OrbitParams(p=r["pb"],        # days until psrepoch()
                              x=r.get("a1", 0.0), e=r.get("ecc", 0.0),
                              w=r.get("om", 0.0), t=r.get("t0", 0.0))
        return PsrParams(
            jname=r.get("jname", ""), bname=r.get("bname", ""),
            ra_str=r.get("raj", ""), dec_str=r.get("decj", ""),
            ra2000=_hms_to_rad(r["raj"]) if r.get("raj") else 0.0,
            dec2000=_dms_to_rad(r["decj"]) if r.get("decj") else 0.0,
            p=p0, pd=p1, f=f, fd=fd, fdd=r.get("f2", 0.0),
            dm=r.get("dm", 0.0), timepoch=r.get("pepoch", 51000.0),
            orb=orb)


# ATNF "Short with errors" column order (pypsrcat.py:14-18); columns in
# ERR_PARAMS are followed by an error token.
_PARAMS = ["NAME", "PSRJ", "RAJ", "DECJ", "PMRA", "PMDEC", "PX",
           "POSEPOCH", "GL", "GB", "P0", "P1", "F2", "F3", "PEPOCH",
           "DM", "DM1", "S400", "S1400", "BINARY", "T0", "PB", "A1",
           "OM", "ECC", "TASC", "EPS1", "EPS2", "DIST", "ASSOC",
           "SURVEY", "PSR"]
_ERR_PARAMS = {"RAJ", "DECJ", "PMRA", "PMDEC", "PX", "P0", "P1", "F2",
               "F3", "DM", "DM1", "S400", "S1400", "T0", "PB", "A1",
               "OM", "ECC", "TASC", "EPS1", "EPS2"}


def parse_atnf_catalog(path: str) -> List[dict]:
    """Parse an ATNF psrcat text export in the reference's
    lib/psr_catalog.txt layout (leading index column, '*' for missing,
    value+error token pairs for measured quantities)."""
    records = []
    with open(path) as fh:
        for line in fh:
            if not line.strip() or line.startswith(("#", "-")):
                continue
            parts = line.split()[1:]       # drop the index column
            vals = {}
            pi = 0
            for param in _PARAMS:
                if pi >= len(parts):
                    break
                tok = parts[pi]
                if tok != "*":
                    vals[param] = tok
                pi += 1
                if param in _ERR_PARAMS:
                    pi += 1    # value+error token pairs ('* 0' when
                               # missing) — pypsrcat.py part_index += 1
            rec = {}
            if "NAME" in vals and vals["NAME"].startswith("B"):
                rec["bname"] = vals["NAME"]
            if "PSRJ" in vals:
                rec["jname"] = vals["PSRJ"]
            if "RAJ" in vals:
                rec["raj"] = vals["RAJ"]
            if "DECJ" in vals:
                rec["decj"] = vals["DECJ"]
            for src, dst in (("P0", "p0"), ("P1", "p1"), ("F2", "f2"),
                             ("PEPOCH", "pepoch"), ("DM", "dm"),
                             ("PB", "pb"), ("A1", "a1"), ("OM", "om"),
                             ("ECC", "ecc"), ("T0", "t0"),
                             ("TASC", "tasc"), ("EPS1", "eps1"),
                             ("EPS2", "eps2")):
                if src in vals:
                    try:
                        rec[dst] = float(vals[src])
                    except ValueError:
                        pass
            # ELL1 binaries: (TASC, EPS1, EPS2) -> (T0, ECC, OM)
            if "tasc" in rec and "t0" not in rec:
                from presto_tpu.ops.orbit import ell1_to_keplerian
                ecc, om, t0 = ell1_to_keplerian(
                    rec.get("eps1", 0.0), rec.get("eps2", 0.0),
                    rec["tasc"], rec.get("pb", 0.0))
                rec["ecc"], rec["om"] = ecc, om
                if rec.get("pb"):
                    rec["t0"] = t0
            if rec.get("jname") or rec.get("bname"):
                records.append(rec)
    return records


def parse_compact_catalog(path: str) -> List[dict]:
    """Parse the shipped compact TSV catalog
    (presto_tpu/data/pulsars.psrcat, written by tools/make_catalog.py:
    header line naming the fields, '*' for missing)."""
    records = []
    fields = None
    with open(path) as fh:
        for line in fh:
            if line.startswith("#"):
                if "\t" in line:       # the field-name header
                    fields = line[1:].split()
                continue
            if not line.strip() or fields is None:
                continue
            rec = {}
            for k, tok in zip(fields, line.rstrip("\n").split("\t")):
                if tok == "*" or not tok:
                    continue
                if k in ("bname", "jname", "raj", "decj"):
                    rec[k] = tok
                else:
                    try:
                        rec[k] = float(tok)
                    except ValueError:
                        pass
            if rec.get("jname") or rec.get("bname"):
                records.append(rec)
    return records


def shipped_catalog_path() -> Optional[str]:
    """The catalog file shipped with the package (the lib/pulsars.cat
    analog, src/database.c:676), or None if absent."""
    p = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "data", "pulsars.psrcat")
    return p if os.path.exists(p) else None


def default_birds_path() -> Optional[str]:
    """The shipped default birdie list (the lib/parkes_birds.txt
    analog): power-mains harmonics."""
    p = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "data", "default_birds.txt")
    return p if os.path.exists(p) else None


_default: Optional[Catalog] = None


def default_catalog() -> Catalog:
    """The shipped ~1000-pulsar catalog (+ builtin mini list),
    extended by $PRESTO_TPU_CATALOG (path to an ATNF text export)
    when set."""
    global _default
    if _default is None:
        records = list(_BUILTIN)
        shipped = shipped_catalog_path()
        if shipped:
            records = records + parse_compact_catalog(shipped)
        path = os.environ.get("PRESTO_TPU_CATALOG")
        if path and os.path.exists(path):
            records = parse_atnf_catalog(path) + records
        _default = Catalog(records)
    return _default


def load_catalog(path: str) -> Catalog:
    return Catalog(parse_atnf_catalog(path))


def psrepoch(psrname: str, epoch: float,
             catalog: Optional[Catalog] = None) -> PsrParams:
    """Catalog parameters advanced to `epoch` (MJD): spin frequency by
    its derivatives, orbital period to seconds, orb.t to seconds since
    the last periastron (get_psr_at_epoch database.c:167-230)."""
    cat = catalog or default_catalog()
    psr = cat.params(psrname)
    if psr is None:
        raise KeyError("PSR %s not found in catalog" % psrname)
    difft = SECPERDAY * (epoch - psr.timepoch)
    f, fd = psr.f, psr.fd
    psr.f = f + fd * difft + 0.5 * psr.fdd * difft * difft
    psr.fd = fd + psr.fdd * difft
    psr.p = 1.0 / psr.f
    psr.pd = -psr.fd * psr.p * psr.p
    # note: the reference evaluates pdd with the PRE-advance f/fd
    # (database.c:199); here the advanced values are used so p/pd/pdd
    # are all consistent at the returned timepoch
    psr.pdd = ((2.0 * psr.fd * psr.fd / psr.f - psr.fdd)
               / (psr.f * psr.f)) if psr.f else 0.0
    psr.timepoch = epoch
    if psr.orb is not None and psr.orb.p:
        difft = SECPERDAY * (epoch - psr.orb.t)   # orb.t held T0 (MJD)
        psr.orb.p = psr.orb.p * SECPERDAY + psr.orb.pd * difft
        psr.orb.t = math.fmod(difft, psr.orb.p)
        if psr.orb.t < 0.0:
            psr.orb.t += psr.orb.p
        psr.orb.w = psr.orb.w + psr.orb.wd * (difft / (SECPERDAY * 365.25))
    return psr


def binary_velocity(T: float, orb: OrbitParams):
    """(min, max) pulsar radial velocity (v/c) during an observation of
    length T seconds (binary_velocity responses.c:92-140).  orb.p in
    seconds, orb.t seconds since periastron at obs start."""
    if T >= orb.p:
        c1 = TWOPI * orb.x / (orb.p * math.sqrt(1.0 - orb.e ** 2))
        c2 = orb.e * math.cos(math.radians(orb.w))
        return c1 * (c2 - 1.0), c1 * (c2 + 1.0)
    t = orb.t + np.linspace(0.0, T, 1025)
    E = keplers_eqn(t, orb.p, orb.e)
    v = E_to_v(E, orb) * 1000.0 / SOL     # km/s -> v/c
    return float(np.min(v)), float(np.max(v))
