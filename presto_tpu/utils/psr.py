"""Pulsar unit conversions and planning helpers (host-side, float64 numpy).

Parity targets in the reference: lib/python/psr_utils.py and
src/misc_utils.c (next2_to_n), src/dispersion.c (smearing formulas),
src/barycenter.c:3 (doppler).  All planning math runs in float64 on the
host; only bulk per-sample compute goes to the device in float32.
"""

from __future__ import annotations

import numpy as np

# Speed of light (m/s), seconds per day.
SOL = 299792458.0
SECPERDAY = 86400.0
# PRESTO's dispersion constant appears as delay = DM / (0.000241 f^2)
# (reference src/dispersion.c:30-39).  Keep the literal for parity.
DM_CONST_INV = 0.000241  # MHz^-2 cm^3 pc^-1 s^-1


def doppler(freq_observed, voverc):
    """Frequency emitted given observed frequency and radial v/c.

    Parity: reference src/barycenter.c:3-10.
    """
    return freq_observed * (1.0 + voverc)


def next2_to_n(x: float) -> int:
    """Smallest power of 2 >= x (reference src/misc_utils.c next2_to_n)."""
    n = 1
    while n < x:
        n <<= 1
    return n


def _is_smooth(n: int, primes=(2, 3, 5, 7)) -> bool:
    for p in primes:
        while n % p == 0:
            n //= p
    return n == 1


def good_fft_size(n: int, multiple_of: int = 16) -> int:
    """Smallest 7-smooth integer >= n divisible by `multiple_of`.

    The analog of psr_utils.choose_N (reference lib/python/psr_utils.py:33):
    a highly-factorable series length, divisible by max_downsample*2 = 16,
    friendly to both XLA's FFT and downsampling.
    """
    n = int(n)
    m = ((n + multiple_of - 1) // multiple_of) * multiple_of
    while not _is_smooth(m):
        m += multiple_of
    return m


def choose_N(orig_N: int) -> int:
    """Pick a highly-factorable series length >= orig_N, divisible by 16.

    Behavioral parity with psr_utils.choose_N: returns 0 for N < 10000.
    """
    if orig_N < 10000:
        return 0
    return good_fft_size(orig_N, multiple_of=16)


# --- frequency/period/acceleration conversions (psr_utils.py:387-407) ---

def z_to_accel(z, T, freq):
    """Convert Fourier f-dot drift z (bins) to acceleration (m/s^2).

    z = f_dot * T^2;  accel = z * c / (T^2 * f).
    """
    return z * SOL / (T * T * freq)


def accel_to_z(accel, T, freq):
    """Inverse of z_to_accel."""
    return accel * T * T * freq / SOL


def p_to_f(p, pd=0.0, pdd=None):
    """Period (+derivatives) -> frequency (+derivatives).

    Parity: psr_utils.p_to_f / src/characteristics.c switch_f_and_p.
    """
    f = 1.0 / p
    fd = -pd / (p * p)
    if pdd is None:
        return f, fd
    if pdd == 0.0:
        fdd = 0.0
    else:
        fdd = 2.0 * pd * pd / (p ** 3) - pdd / (p * p)
    return f, fd, fdd


def f_to_p(f, fd=0.0, fdd=None):
    """Frequency (+derivatives) -> period (+derivatives) (same formula)."""
    return p_to_f(f, fd, fdd)


# --- dispersion smearing (src/dispersion.c:3-27) ---

def smearing_from_bw(dm, center_freq, bandwidth):
    """Dispersion smearing (s) across `bandwidth` MHz at `center_freq` MHz."""
    cf = np.asarray(center_freq, dtype=np.float64)
    out = dm * bandwidth / (0.0001205 * cf * cf * cf)
    return np.where(cf == 0.0, 0.0, out)


def dm_smear(dm, bw_mhz, center_freq_mhz):
    """Alias matching psr_utils.dm_smear."""
    return smearing_from_bw(dm, center_freq_mhz, bw_mhz)


def rad_to_hms(rad: float):
    """Radians -> (hours, minutes, seconds) of right ascension."""
    rad = rad % (2 * np.pi)
    hours = rad * 12.0 / np.pi
    h = int(hours)
    minutes = (hours - h) * 60.0
    m = int(minutes)
    s = (minutes - m) * 60.0
    return h, m, s


def rad_to_dms(rad: float):
    """Radians -> (degrees, minutes, seconds) of declination."""
    sign = -1 if rad < 0 else 1
    rad = abs(rad)
    deg = rad * 180.0 / np.pi
    d = int(deg)
    minutes = (deg - d) * 60.0
    m = int(minutes)
    s = (minutes - m) * 60.0
    return sign * d, m, s
