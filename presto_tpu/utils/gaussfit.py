"""Gaussian decomposition of pulse profiles (bin/pygaussfit.py's
fitting core, non-interactive): fit N wrapped Gaussians + a DC level
to a folded profile, report components in the .gaussians format that
get_TOAs-style template generation consumes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np
from scipy.optimize import least_squares


@dataclass
class GaussComponent:
    phase: float     # center, rotations
    fwhm: float      # rotations
    ampl: float      # peak amplitude


def gauss_profile(n: int, components: List[GaussComponent],
                  dc: float = 0.0) -> np.ndarray:
    x = (np.arange(n) + 0.5) / n
    out = np.full(n, dc, float)
    for c in components:
        sigma = c.fwhm / 2.35482
        d = x - c.phase
        d = d - np.round(d)
        out += c.ampl * np.exp(-0.5 * (d / sigma) ** 2)
    return out


def _theta_to_comps(theta):
    dc = theta[0]
    comps = [GaussComponent(phase=theta[i] % 1.0,
                            fwhm=abs(theta[i + 1]),
                            ampl=theta[i + 2])
             for i in range(1, len(theta), 3)]
    return dc, comps


def fit_gaussians(profile: np.ndarray, ngauss: int = 1,
                  init: Optional[List[GaussComponent]] = None):
    """Fit `ngauss` wrapped Gaussians + DC.  Components are seeded at
    the residual maxima (the interactive seeding of pygaussfit.py,
    automated).  Returns (components, dc, residual_rms)."""
    prof = np.asarray(profile, np.float64)
    n = prof.size
    theta = [float(np.median(prof))]
    if init:
        for c in init:
            theta += [c.phase, c.fwhm, c.ampl]
    else:
        resid = prof - np.median(prof)
        for _ in range(ngauss):
            k = int(np.argmax(resid))
            amp = float(resid[k])
            # crude width: half-max crossing distance
            half = amp / 2.0
            w = 1
            while w < n // 2 and resid[(k + w) % n] > half:
                w += 1
            fwhm = max(2.0 * w / n, 1.5 / n)
            theta += [(k + 0.5) / n, fwhm, amp]
            resid = resid - gauss_profile(
                n, [GaussComponent((k + 0.5) / n, fwhm, amp)])

    def residfn(th):
        dc, comps = _theta_to_comps(th)
        return gauss_profile(n, comps, dc) - prof

    sol = least_squares(residfn, theta, max_nfev=20000)
    dc, comps = _theta_to_comps(sol.x)
    comps.sort(key=lambda c: -abs(c.ampl))
    rms = float(np.sqrt(np.mean(sol.fun ** 2)))
    return comps, float(dc), rms


def write_gaussians(path: str, comps: List[GaussComponent],
                    dc: float, ref: str = "") -> None:
    """The .gaussians text format pygaussfit.py saves."""
    with open(path, "w") as f:
        f.write("# gauss components for %s\n" % (ref or "profile"))
        f.write("const = %.6g\n" % dc)
        for i, c in enumerate(comps, 1):
            f.write("phas%d = %.6f\n" % (i, c.phase))
            f.write("fwhm%d = %.6f\n" % (i, c.fwhm))
            f.write("ampl%d = %.6g\n" % (i, c.ampl))


def read_gaussians(path: str):
    dc = 0.0
    comps = {}
    with open(path) as f:
        for line in f:
            if "=" not in line or line.startswith("#"):
                continue
            key, val = [s.strip() for s in line.split("=", 1)]
            if key == "const":
                dc = float(val)
            elif key[:4] in ("phas", "fwhm", "ampl"):
                i = int(key[4:])
                comps.setdefault(i, {})[key[:4]] = float(val)
    out = [GaussComponent(phase=v["phas"], fwhm=v["fwhm"],
                          ampl=v["ampl"])
           for _, v in sorted(comps.items())]
    return out, dc
