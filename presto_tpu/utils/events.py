"""Event-based periodicity statistics (lib/python/events.py +
kuiper.py analog): Z^2_m, H-test, Rayleigh, and the Kuiper test, for
photon/event arrival-time folding (X-ray / gamma-ray style searches).
"""

from __future__ import annotations

import numpy as np


def fold_events(times: np.ndarray, f: float, fd: float = 0.0,
                fdd: float = 0.0, t0: float = 0.0) -> np.ndarray:
    """Event times (s) -> phases in [0, 1)."""
    t = np.asarray(times, np.float64) - t0
    ph = t * (f + t * (fd / 2.0 + t * fdd / 6.0))
    return np.mod(ph, 1.0)


def z2m(phases: np.ndarray, m: int = 2) -> float:
    """Z^2_m statistic (Buccheri et al. 1983): summed Fourier power of
    the first m harmonics of the event phase distribution; chi^2 with
    2m dof under uniformity."""
    ph = 2.0 * np.pi * np.asarray(phases, np.float64)
    n = ph.size
    if n == 0:
        return 0.0
    k = np.arange(1, m + 1)[:, None]
    c = np.cos(k * ph[None, :]).sum(axis=1)
    s = np.sin(k * ph[None, :]).sum(axis=1)
    return float(2.0 / n * np.sum(c ** 2 + s ** 2))


def z2m_prob(z2: float, m: int = 2) -> float:
    """False-alarm probability of a Z^2_m value (chi^2, 2m dof)."""
    from scipy.stats import chi2 as chi2dist
    return float(chi2dist.sf(z2, 2 * m))


def rayleigh(phases: np.ndarray) -> float:
    """Rayleigh statistic = Z^2_1."""
    return z2m(phases, 1)


def htest(phases: np.ndarray, maxharms: int = 20):
    """H-test (de Jager, Raubenheimer & Swanepoel 1989):
    H = max_m (Z^2_m - 4m + 4).  Returns (H, best_m, prob) with the
    de Jager & Buesching (2010) calibration P = exp(-0.4 H)."""
    ph = 2.0 * np.pi * np.asarray(phases, np.float64)
    n = ph.size
    if n == 0:
        return 0.0, 1, 1.0
    k = np.arange(1, maxharms + 1)[:, None]
    c = np.cos(k * ph[None, :]).sum(axis=1)
    s = np.sin(k * ph[None, :]).sum(axis=1)
    z_cum = 2.0 / n * np.cumsum(c ** 2 + s ** 2)
    m = np.arange(1, maxharms + 1)
    hs = z_cum - 4.0 * m + 4.0
    best = int(np.argmax(hs))
    H = float(hs[best])
    prob = float(np.exp(-0.4 * H)) if H > 0 else 1.0
    return H, best + 1, min(prob, 1.0)


def kuiper_statistic(phases: np.ndarray) -> float:
    """Kuiper V: rotation-invariant two-sided KS statistic of phases
    against the uniform distribution (lib/python/kuiper.py)."""
    x = np.sort(np.mod(np.asarray(phases, np.float64), 1.0))
    n = x.size
    if n == 0:
        return 0.0
    i = np.arange(1, n + 1)
    d_plus = np.max(i / n - x)
    d_minus = np.max(x - (i - 1) / n)
    return float(d_plus + d_minus)


def kuiper_prob(V: float, n: int) -> float:
    """Asymptotic false-alarm probability of Kuiper V for n events
    (Stephens 1970 series, as used by the reference's kuiper.py)."""
    if n <= 0 or V <= 0:
        return 1.0
    lam = (np.sqrt(n) + 0.155 + 0.24 / np.sqrt(n)) * V
    if lam < 0.4:
        return 1.0
    j = np.arange(1, 101)
    t = 4.0 * j ** 2 * lam ** 2
    p = np.sum((t - 1.0) * np.exp(-t / 2.0)) * 2.0
    return float(min(max(p, 0.0), 1.0))


def kuiper_uniform_test(phases: np.ndarray):
    """(V, prob) of the phases being uniform."""
    V = kuiper_statistic(phases)
    return V, kuiper_prob(V, len(np.atleast_1d(phases)))
