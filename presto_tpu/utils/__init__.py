from presto_tpu.utils import psr  # noqa: F401
