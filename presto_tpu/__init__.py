"""presto_tpu — a TPU-native pulsar search & analysis framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of PRESTO
(reference: /root/reference): RFI excision, dedispersion, FFT,
Fourier-domain acceleration search, phase-modulation (miniFFT) search,
single-pulse matched filtering, candidate sifting, and folding —
expressed as pure, jit-compiled, shardable tensor programs over
`jax.sharding.Mesh` device meshes.

Layering (bottom-up):
  utils/    — constants, unit conversions, smooth-length selection
  io/       — .inf sidecars, SIGPROC filterbank, PSRFITS, .dat/.fft, masks
  ops/      — device ops: dedispersion, packed real FFT, Fourier response
              kernels, correlation, statistics, folding, clipping
  models/   — synthetic signal generation (makedata parity), orbits
  search/   — accelsearch, single-pulse, phase-modulation, sifting, DDplan
  parallel/ — mesh construction, DM-sharded plans, sequence-sharded FFT
  apps/     — CLI entry points with PRESTO flag parity
"""

__version__ = "0.1.0"

from presto_tpu.utils import psr  # noqa: F401
