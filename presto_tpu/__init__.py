"""presto_tpu — a TPU-native pulsar search & analysis framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of PRESTO
(reference: /root/reference): RFI excision, dedispersion, FFT,
Fourier-domain acceleration search, phase-modulation (miniFFT) search,
single-pulse matched filtering, candidate sifting, and folding —
expressed as pure, jit-compiled, shardable tensor programs over
`jax.sharding.Mesh` device meshes.

Layering (bottom-up):
  utils/    — constants, unit conversions, smooth-length selection
  io/       — .inf sidecars, SIGPROC filterbank, PSRFITS, .dat/.fft, masks
  ops/      — device ops: dedispersion, packed real FFT, Fourier response
              kernels, correlation, statistics, folding, clipping
  models/   — synthetic signal generation (makedata parity), orbits
  search/   — accelsearch, single-pulse, phase-modulation, sifting, DDplan
  parallel/ — mesh construction, DM-sharded plans, sequence-sharded FFT
  apps/     — CLI entry points with PRESTO flag parity
"""

__version__ = "0.2.0"


def _enable_compilation_cache():
    """Persist XLA compilations across processes.

    The reference amortizes FFTW planning cost with a wisdom file
    (src/fftcalls.c:19 reads $PRESTO/lib/fftw_wisdom.txt); the XLA-era
    equivalent is the persistent compilation cache, which turns the
    ~40 s cold-start of a full accelsearch program into a sub-second
    cache load on every later process.  Opt out by setting
    PRESTO_TPU_CACHE_DIR to the empty string; JAX_COMPILATION_CACHE_DIR
    takes precedence if the user set it themselves.
    """
    import os

    if "JAX_COMPILATION_CACHE_DIR" in os.environ:
        return
    platforms = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    first = platforms.split(",")[0].strip() if platforms else ""
    if first in ("tpu", "axon"):
        pass                       # TPU explicitly requested: enable
    elif platforms == "":
        # Unset: the standard TPU-VM deployment auto-detects tpu, so
        # enable when a TPU runtime is installed; otherwise skip — CPU
        # compiles are fast and XLA:CPU AOT cache entries are machine-
        # feature-pinned (cross-host loads risk SIGILL).
        import importlib.util
        if not (importlib.util.find_spec("libtpu")
                or importlib.util.find_spec("libtpu_nightly")):
            return
    else:
        return                     # explicitly non-TPU (e.g. cpu)
    cache_dir = os.environ.get(
        "PRESTO_TPU_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "presto_tpu", "xla"),
    )
    if not cache_dir:
        return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # cache is an optimization; never block import


_enable_compilation_cache()

from presto_tpu.utils import psr  # noqa: F401
