"""Stacked cross-job batch execution (serve layer).

The micro-batching scheduler has always *coalesced* same-bucket jobs
(one compiled plan serves the batch warm), but until now the batch
still executed as a per-job Python loop — `Scheduler.batch_executor`
was an empty seam.  This module fills it: a coalesced batch of
same-bucket survey jobs runs its device-bound middle (rFFT -> [zap]
-> accelsearch -> single-pulse) as ONE stacked chain, the jobs' DM
fan-outs concatenated on the batch axis into a single
``[jobs x numdms, nsamp]`` device array (pipeline/survey.py
``run_survey_stacked``).  This is the continuous-batching shape of an
inference server — amortize one compiled plan over N requests by
stacking them — and the FDAS lesson of AstroAccelerate: batch
geometry is a measured per-device parameter, which is exactly what
the ``serve_batch_geometry`` tune family provides.

Contracts (docs/SERVING.md, "Stacked cross-job batches"):

  * **Byte-identity** — stacking only widens the batch axis of
    dispatches whose per-trial math is independent (the DM-sharded
    seam's pinned invariant), so every artifact a stacked batch
    writes is byte-identical to N independent per-job runs.
  * **Graceful degradation** — `StackIncompatible` (mixed configs,
    sharded seams, callable jobs) and ANY mid-chain failure propagate
    to the scheduler, whose existing degrade path redoes the batch
    per-job; the verify-not-trust resume contract makes partial head
    work safe to redo.
  * **Geometry is tuned, never trusted** — the sub-stack plan comes
    from the tuning DB's ``serve_batch_geometry`` entry (max stack
    size x pad-bucket chunk scheme) clamped by the same HBM group
    budget the accel slab plan uses, so a deep stack can never OOM
    the chain.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

from presto_tpu.serve.queue import Job, JobStatus

#: SurveyConfig fields that shape the stacked device chain or the
#: artifacts it writes.  Two jobs may share one stacked chain only
#: when every one of these matches — the scheduling bucket (nchan/
#: nsamp/dm_block/zmax/numharm) is necessary but NOT sufficient,
#: because e.g. sigma/flo/zaplist change candidate collection without
#: changing the bucket.
STACK_FIELDS = (
    "lodm", "hidm", "nsub", "rfi_time", "zmax", "numharm", "sigma",
    "flo", "zaplist", "accel_passes", "min_dm_hits", "low_dm_cutoff",
    "fold_top", "fold_sigma", "max_folds", "max_folds_per_pass",
    "sp_threshold", "sp_maxwidth", "singlepulse", "skip_rfifind",
    "bary", "verify_resume", "elastic", "tune", "durable_stages",
    "inflight_depth",
)

#: the accel slab plan's group budget (search/accel.py halves 6 GiB
#: for its 2-deep window); the stack clamp reuses the same figure so
#: a stacked chain's peak residency matches what the per-job chain
#: already proved safe
STACK_HBM_BUDGET = 3 * 2 ** 30

DEFAULT_MAX_STACK = 8
DEFAULT_SCHEME = "exact"


class StackIncompatible(RuntimeError):
    """This batch cannot run as one stacked chain; the scheduler's
    degradation path gives each job an individual shot."""


def stack_signature(cfg) -> tuple:
    """The stack-compatibility identity of a SurveyConfig: everything
    that shapes the merged device chain or its artifacts."""
    return tuple(repr(getattr(cfg, f, None)) for f in STACK_FIELDS)


def plan_stack_sizes(n: int, max_stack: int = DEFAULT_MAX_STACK,
                     scheme: str = DEFAULT_SCHEME) -> List[int]:
    """Split an n-job batch into sub-stack sizes.

    ``exact`` takes the largest allowed bite each time (fewest
    dispatches; every distinct occupancy is a distinct compiled
    shape).  ``pow2`` bites at power-of-two sizes (one extra dispatch
    per odd tail, but recurring occupancies reuse one compiled stacked
    program — the pad-bucket trade the ``serve_batch_geometry`` tune
    family scores).  Sizes always sum to n and never exceed
    max_stack."""
    n = max(int(n), 0)
    max_stack = max(int(max_stack), 1)
    sizes: List[int] = []
    left = n
    while left > 0:
        take = min(left, max_stack)
        if scheme == "pow2" and take > 1:
            take = 1 << (take.bit_length() - 1)   # largest pow2 <=
        sizes.append(take)
        left -= take
    return sizes


def resolve_stack_geometry(per_job_bytes: Optional[List[int]] = None,
                           obs=None) -> tuple:
    """(max_stack, scheme) for the next stacked batch: the tuning
    DB's ``serve_batch_geometry`` entry when tuning is active, else
    the defaults — then the HBM-budget clamp (the accel slab-plan
    group budget divided by the heaviest job's chain working set), so
    a deep stack degrades to more sub-stacks instead of an OOM."""
    max_stack, scheme = DEFAULT_MAX_STACK, DEFAULT_SCHEME
    from presto_tpu import tune
    if tune.enabled():
        cfg = tune.best("serve_batch_geometry", tune.GLOBAL_KEY,
                        obs=obs)
        if cfg:
            try:
                max_stack = int(cfg.get("max_stack", max_stack))
            except (TypeError, ValueError):
                pass
            scheme = str(cfg.get("scheme", scheme))
    if per_job_bytes:
        heaviest = max(int(b) for b in per_job_bytes)
        if heaviest > 0:
            fit = max(1, int(STACK_HBM_BUDGET // heaviest))
            max_stack = min(max_stack, fit)
    return max(1, max_stack), scheme


class StackedBatchExecutor:
    """The scheduler's cross-job `batch_executor`: callable(jobs) ->
    per-job result dicts, executing the whole same-bucket batch
    through one stacked device chain."""

    def __init__(self, service):
        self.service = service
        reg = service.obs.metrics
        self._c_batches = reg.counter(
            "serve_stacked_batches_total",
            "Cross-job stacked device batches executed")
        self._c_jobs = reg.counter(
            "serve_stacked_jobs_total",
            "Jobs executed through the stacked cross-job chain")
        self._h_occupancy = reg.histogram(
            "serve_batch_occupancy",
            "Jobs per executed micro-batch (stacked path)",
            buckets=(1, 2, 3, 4, 6, 8, 12, 16, 32))
        self._last_sizes: List[int] = []

    # -- geometry -------------------------------------------------------

    def _plan(self, per_job_bytes: List[int]) -> List[int]:
        max_stack, scheme = resolve_stack_geometry(
            per_job_bytes, obs=self.service.obs)
        self._last_sizes = plan_stack_sizes(len(per_job_bytes),
                                            max_stack, scheme)
        return self._last_sizes

    # -- compatibility --------------------------------------------------

    @staticmethod
    def check_stackable(jobs: List[Job]) -> None:
        """Raise StackIncompatible unless this batch may share one
        stacked chain.  Two stackable families exist: same-signature
        survey jobs (the stacked device chain) and same-bucket DAG
        fold jobs (the stacked drizzle, serve/dag.py) — never
        mixed."""
        if len(jobs) < 2:
            raise StackIncompatible("nothing to stack")
        if os.environ.get("PRESTO_TPU_STACKED", "1") == "0":
            raise StackIncompatible("PRESTO_TPU_STACKED=0 kill switch")
        kinds = {getattr(job, "kind", "survey") or "survey"
                 for job in jobs}
        if kinds == {"fold"}:
            if any(job.bucket != jobs[0].bucket for job in jobs[1:]):
                raise StackIncompatible("mixed fold stack buckets")
            return
        if kinds != {"survey"}:
            raise StackIncompatible(
                "only survey or fold batches stack (got %s)"
                % sorted(kinds))
        for job in jobs:
            if job.run is not None or job.cfg is None:
                raise StackIncompatible(
                    "callable jobs cannot be stacked")
            if getattr(job.cfg, "elastic", None):
                raise StackIncompatible(
                    "elastic surveys keep the staged/ledger contract")
        sig0 = stack_signature(jobs[0].cfg)
        for job in jobs[1:]:
            if job.bucket != jobs[0].bucket:
                raise StackIncompatible("mixed plan buckets")
            if stack_signature(job.cfg) != sig0:
                raise StackIncompatible(
                    "same bucket but different search configs")

    # -- execution ------------------------------------------------------

    def _fold_batch(self, jobs: List[Job]) -> List[dict]:
        """The fold arm: a coalesced same-bucket DAG fold batch runs
        as one batched drizzle dispatch set (serve/dag.py), byte-
        identical to per-job folds, degrading to the per-job path on
        any failure exactly like the survey arm."""
        from presto_tpu.serve.dag import run_folds_stacked
        injector = self.service.scheduler.cfg.fault_injector
        for job in jobs:
            job.status = JobStatus.RUNNING
            if not job.started:
                job.started = time.time()
            self.service.events.emit("execute", job=job.job_id,
                                     attempt=job.attempts + 1,
                                     stacked=True)
            if injector is not None:
                injector(job, job.attempts + 1)
        span = self.service.obs.span("serve:stacked-batch",
                                     jobs=len(jobs), kind="fold",
                                     bucket=repr(jobs[0].bucket))
        self._h_occupancy.observe(len(jobs))
        t0 = time.time()
        try:
            results = run_folds_stacked(self.service, jobs)
        except Exception as e:
            span.finish("error: %s" % type(e).__name__)
            raise
        span.finish()
        self._c_batches.inc()
        self._c_jobs.inc(len(jobs))
        if self.service.latency is not None:
            self.service.latency.record("job_exec",
                                        time.time() - t0)
        for job in jobs:
            job.attempts += 1
        return results

    def __call__(self, jobs: List[Job]) -> List[dict]:
        from presto_tpu.pipeline.survey import run_survey_stacked
        from presto_tpu.utils.timing import StageTimer
        self.check_stackable(jobs)
        if all(getattr(j, "kind", "survey") == "fold" for j in jobs):
            return self._fold_batch(jobs)
        injector = self.service.scheduler.cfg.fault_injector
        timers = []
        for job in jobs:
            job.status = JobStatus.RUNNING
            if not job.started:
                job.started = time.time()
            self.service.events.emit("execute", job=job.job_id,
                                     attempt=job.attempts + 1,
                                     stacked=True)
            if injector is not None:
                injector(job, job.attempts + 1)
            timers.append(StageTimer(stats=self.service.latency,
                                     obs=self.service.obs))
        span = self.service.obs.span("serve:stacked-batch",
                                     jobs=len(jobs),
                                     bucket=repr(jobs[0].bucket))
        self._h_occupancy.observe(len(jobs))
        t0 = time.time()
        try:
            results = run_survey_stacked(
                [(job.rawfiles, job.cfg, job.workdir, timer)
                 for job, timer in zip(jobs, timers)],
                stack_planner=self._plan)
        except Exception as e:
            span.finish("error: %s" % type(e).__name__)
            raise
        span.finish()
        self._c_batches.inc(len(self._last_sizes or [jobs]))
        self._c_jobs.inc(len(jobs))
        if self.service.latency is not None:
            self.service.latency.record("job_exec",
                                        time.time() - t0)
        out = []
        for job, res, timer in zip(jobs, results, timers):
            job.attempts += 1
            out.append({
                "workdir": res.workdir,
                "candfile": res.candfile,
                "n_datfiles": len(res.datfiles),
                "n_cands": (len(res.sifted)
                            if res.sifted is not None else 0),
                "folded": list(res.folded),
                "sp_events": res.sp_events,
                "stacked": len(jobs),
                "stage_seconds": {k: round(v, 4)
                                  for k, v in timer.stages.items()},
            })
        return out
