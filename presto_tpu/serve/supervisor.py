"""Fleet supervisor: the actuator that closes the control loop.

PR 14 derived every decision signal the control plane needs — the
durable per-tenant device-seconds ledger, multi-window burn-rate
alerts, and the advisory `GET /scale` wanted-replica count — but
nothing *acted* on them.  This module is the actuator: a control loop
that polls the router's `/scale` advisory and actually spawns and
drains real `presto-serve` replica processes.

Design points, each earned by an earlier PR's machinery:

  * **Hysteresis + cooldown.**  The advisory recomputes every router
    poll and flaps with the backlog; the supervisor only actuates
    after `scale_up_after` (resp. `scale_down_after`) *consecutive*
    polls agree, and never twice within `cooldown_s`.  Replacing a
    dead replica is repair, not scaling — it bypasses both gates.
  * **Cheap spin-up.**  Spawned replicas point at the fleet's
    persistent `PlanStore` tier, so a cold process serves any known
    bucket with zero new XLA compiles; scaling 1→N is dominated by
    interpreter start, not compilation.
  * **Drain is the existing graceful path.**  Scale-down sends
    SIGTERM: the replica stops leasing (503 on /readyz), finishes
    in-flight work, releases leftovers, and writes its heartbeat
    tombstone — the supervisor merely waits, escalating to SIGKILL
    only past `drain_timeout_s` (the lease reaper makes even that
    escalation lossless).
  * **Dead-replica replacement.**  A supervised replica that dies
    (process gone) or goes silent (ledger heartbeat stale while the
    process lives — the wedged-VM case) is replaced immediately; the
    ledger's epoch fence guarantees the replacement and the zombie
    cannot double-commit.
  * **Crash-only supervision.**  The replica registry persists as
    `<fleet>/supervisor.json` (atomic writes) BEFORE each spawn, so a
    supervisor crash at any instant leaves no orphan: a restarted
    supervisor adopts every still-live registered replica (and
    recovers even a mid-spawn child by its `-replica` name on the
    process table) instead of leaking it and spawning anew.  With no
    supervisor running at all, the fleet degrades to exactly the
    pre-supervisor advisory-only behavior — replicas keep leasing,
    nothing is lost.

Every decision (spawn / drain / hold / replace, with the advisory
inputs that drove it) is emitted on a durable event stream
(`<fleet>/supervisor_events.jsonl`) and wrapped in a `supervisor:*`
span, so a whole scaling episode is reconstructable from telemetry
alone — `presto-report -fleet` renders the timeline, and
tools/serve_loadgen.py's `-supervisor` verdict mode replays one
end-to-end (SUPERVISOR_r16.json).

See docs/SERVING.md ("Fleet supervisor") and docs/ROBUSTNESS.md for
the failure model.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from presto_tpu.io.atomic import atomic_write_text
from presto_tpu.serve.events import EventLog
from presto_tpu.serve.jobledger import JobLedger

REGISTRY_NAME = "supervisor.json"
EVENTS_NAME = "supervisor_events.jsonl"
LOG_DIR = "supervisor_logs"

REGISTRY_VERSION = 1

#: replica registry states
SPAWNING = "spawning"
UP = "up"
DRAINING = "draining"


@dataclass
class SupervisorConfig:
    """Knobs of the scaling control loop."""
    fleetdir: str
    router_url: str                   # the /scale advisory source
    poll_s: float = 1.0               # advisory poll cadence
    #: consecutive polls that must agree before actuating (hysteresis
    #: — the advisory recomputes per router poll and flaps with the
    #: backlog; asymmetric defaults scale up eagerly, down lazily)
    scale_up_after: int = 2
    scale_down_after: int = 4
    cooldown_s: float = 5.0           # min seconds between actuations
    min_replicas: int = 1
    max_replicas: int = 8
    drain_timeout_s: float = 30.0     # SIGTERM -> SIGKILL escalation
    spawn_timeout_s: float = 60.0     # first heartbeat deadline
    #: ledger-heartbeat staleness that marks a live process wedged
    heartbeat_timeout: float = 10.0
    replica_prefix: str = "sup"
    workdir: str = ""                 # default <fleet>/supervised
    #: heartbeat knobs handed to spawned replicas
    hb_interval: float = 0.5
    hb_timeout: float = 5.0
    #: extra presto-serve argv appended verbatim to every spawn
    replica_args: List[str] = field(default_factory=list)
    #: spot capacity as steady state: every `preempt_interval_s`, kill
    #: and replace this fraction of the replicas currently holding
    #: campaign-tenant leases (at least one while any holds one).
    #: 0.0 disables.  Deliberate SIGKILL — the lease reaper and epoch
    #: fence make the loss a latency cost, never a correctness one,
    #: and running it continuously keeps that path exercised rather
    #: than special
    preempt_fraction: float = 0.0
    preempt_interval_s: float = 10.0
    #: the backfill tenant whose lease-holders are preemptable
    preempt_tenant: str = "campaign"


def registry_path(fleetdir: str) -> str:
    return os.path.join(os.path.abspath(fleetdir), REGISTRY_NAME)


def events_path(fleetdir: str) -> str:
    return os.path.join(os.path.abspath(fleetdir), EVENTS_NAME)


def load_registry(fleetdir: str) -> dict:
    """The persisted replica registry ({} of replicas when absent or
    unreadable — a supervisor over a fresh fleet starts empty, never
    fails)."""
    try:
        with open(registry_path(fleetdir)) as f:
            doc = json.load(f)
        if int(doc.get("version", -1)) != REGISTRY_VERSION:
            return {"version": REGISTRY_VERSION, "seq": 0,
                    "replicas": {}}
        doc.setdefault("replicas", {})
        doc.setdefault("seq", 0)
        return doc
    except (OSError, ValueError):
        return {"version": REGISTRY_VERSION, "seq": 0, "replicas": {}}


class FleetSupervisor:
    """Spawn/drain actuator over one fleet directory.

    Process-table seams (`_popen`, `_alive`, `_signal`) are instance
    methods so tests drive the full decision machine against a fake
    process table; the real implementations spawn
    ``python -m presto_tpu.apps.serve`` subprocesses.
    """

    def __init__(self, cfg: SupervisorConfig, obs=None):
        from presto_tpu.obs import Observability, ObsConfig
        self.cfg = cfg
        self.obs = obs or Observability(
            ObsConfig(enabled=True, service="presto-supervise"))
        os.makedirs(cfg.fleetdir, exist_ok=True)
        if not cfg.workdir:
            cfg.workdir = os.path.join(cfg.fleetdir, "supervised")
        self.ledger = JobLedger(cfg.fleetdir, obs=self.obs)
        self.events = EventLog(path=events_path(cfg.fleetdir))
        self._reg = load_registry(cfg.fleetdir)
        self._procs: Dict[str, subprocess.Popen] = {}
        self._stop = threading.Event()
        self._loop_t: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # presto-lint: guards(_reg, _procs, _up_streak, _down_streak, _last_actuation, _last_preempt)
        self._up_streak = 0
        self._down_streak = 0
        self._last_actuation = None  # no cooldown before 1st action
        self.last_decision: Optional[dict] = None
        reg = self.obs.metrics
        self._g_replicas = reg.gauge(
            "supervisor_replicas",
            "Replicas currently supervised (spawning + up; draining "
            "ones are already leaving)")
        self._c_spawns = reg.counter(
            "supervisor_spawns_total",
            "Replica processes spawned by the scaling control loop")
        self._c_drains = reg.counter(
            "supervisor_drains_total",
            "Replica drains initiated by the scaling control loop "
            "(SIGTERM graceful path)")
        self._c_replacements = reg.counter(
            "supervisor_replacements_total",
            "Dead or heartbeat-silent replicas replaced outside the "
            "hysteresis/cooldown gates")
        self._c_holds = reg.counter(
            "supervisor_holds_total",
            "Actuations withheld by hysteresis or cooldown while the "
            "advisory disagreed with the current fleet size")
        self._c_preemptions = reg.counter(
            "campaign_preemptions_total",
            "Campaign-leased replicas deliberately killed and "
            "replaced by the supervisor's preempt-fraction pacing "
            "(spot capacity as steady state)")
        self._last_preempt: Optional[float] = None

    # ---- process-table seams (overridden by the fake-table tests) ----

    def _popen(self, name: str, argv: List[str]) -> int:  # presto-lint: holds(_lock)
        """Spawn one replica process; returns its pid.  stdout/stderr
        land in <fleet>/supervisor_logs/<name>.log so a failed spawn
        is diagnosable."""
        logdir = os.path.join(self.cfg.fleetdir, LOG_DIR)
        os.makedirs(logdir, exist_ok=True)
        # children must import presto_tpu even when the package is
        # run from a source tree rather than installed: carry the
        # package root on PYTHONPATH (cwd is the fleet dir)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = (pkg_root + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(
                                 os.pathsep)
        log = open(os.path.join(logdir, name + ".log"), "ab")
        try:
            proc = subprocess.Popen(
                argv, stdout=log, stderr=subprocess.STDOUT,
                cwd=self.cfg.fleetdir, env=env)
        finally:
            log.close()
        self._procs[name] = proc
        return proc.pid

    def _alive(self, name: str, pid: Optional[int]) -> bool:  # presto-lint: holds(_lock)
        proc = self._procs.get(name)
        if proc is not None:
            return proc.poll() is None
        if pid is None:
            return False
        try:
            os.kill(int(pid), 0)
            return True
        except (OSError, ValueError):
            return False

    # presto-lint: holds(_lock)
    def _signal(self, name: str, pid: Optional[int],
                sig: int) -> None:
        proc = self._procs.get(name)
        try:
            if proc is not None:
                proc.send_signal(sig)
            elif pid is not None:
                os.kill(int(pid), sig)
        except (OSError, ValueError):
            pass

    def _reap(self, name: str) -> None:  # presto-lint: holds(_lock)
        """Collect the exit status of an owned child (adopted pids
        have no Popen handle; init reaps them)."""
        proc = self._procs.pop(name, None)
        if proc is not None:
            try:
                proc.wait(timeout=0.1)
            except Exception:
                pass

    @staticmethod
    def find_pid_by_replica(name: str) -> Optional[int]:
        """Best-effort /proc sweep for a presto-serve process whose
        argv names this replica — the recovery path for a spawn the
        previous supervisor registered but crashed before recording
        the pid of."""
        try:
            pids = [p for p in os.listdir("/proc") if p.isdigit()]
        except OSError:
            return None
        for pid in pids:
            try:
                with open("/proc/%s/cmdline" % pid, "rb") as f:
                    argv = f.read().split(b"\0")
            except OSError:
                continue
            if (b"presto_tpu.apps.serve" in argv
                    and b"-replica" in argv and name.encode() in argv):
                return int(pid)
        return None

    # ---- registry persistence ----------------------------------------

    def _save_registry(self) -> None:  # presto-lint: holds(_lock)
        atomic_write_text(
            registry_path(self.cfg.fleetdir),
            json.dumps(self._reg, indent=1, sort_keys=True) + "\n")

    def replicas(self) -> Dict[str, dict]:
        with self._lock:
            return {n: dict(r)
                    for n, r in self._reg["replicas"].items()}

    def _count_serving(self) -> int:  # presto-lint: holds(_lock)
        """Replicas that count toward the fleet size the advisory is
        compared against: spawning + up.  Draining ones are already
        leaving — counting them would mask the need to spawn."""
        return sum(1 for r in self._reg["replicas"].values()
                   if r["state"] in (SPAWNING, UP))

    # ---- advisory ----------------------------------------------------

    def _fetch_advice(self) -> Optional[dict]:
        """GET /scale from the router (None when unreachable — the
        loop holds rather than acting on a dead signal)."""
        url = self.cfg.router_url.rstrip("/") + "/scale"
        try:
            with urllib.request.urlopen(url, timeout=5.0) as r:
                return json.loads(r.read())
        except Exception:
            return None

    # ---- actuation ---------------------------------------------------

    def _spawn_argv(self, name: str) -> List[str]:
        return ([sys.executable, "-m", "presto_tpu.apps.serve",
                 "-fleet", self.cfg.fleetdir,
                 "-replica", name,
                 "-workdir", os.path.join(self.cfg.workdir, name),
                 "-port", "0",
                 "-hb-interval", str(self.cfg.hb_interval),
                 "-hb-timeout", str(self.cfg.hb_timeout)]
                + list(self.cfg.replica_args))

    # presto-lint: holds(_lock)
    def _spawn_one(self, now: float, why: str,
                   advice: Optional[dict]) -> Optional[str]:
        """Register-then-spawn one replica (the registry row lands on
        disk BEFORE the fork, so a crash in between strands a *named*
        row the next supervisor can match to the process table — never
        an anonymous orphan)."""
        self._reg["seq"] = int(self._reg["seq"]) + 1
        name = "%s-%04d" % (self.cfg.replica_prefix, self._reg["seq"])
        self._reg["replicas"][name] = {
            "state": SPAWNING, "pid": None, "spawned": now,
            "deadline": now + self.cfg.spawn_timeout_s, "why": why,
        }
        self._save_registry()
        with self.obs.span("supervisor:spawn", replica=name) as span:
            try:
                pid = self._popen(name, self._spawn_argv(name))
            except Exception as e:
                del self._reg["replicas"][name]
                self._save_registry()
                span.set_attr("error", str(e))
                self.events.emit("supervisor-spawn-failed",
                                 replica=name, why=str(e))
                self.obs.event("supervisor-spawn-failed",
                               replica=name)
                return None
            self._reg["replicas"][name]["pid"] = pid
            self._save_registry()
            span.set_attr("pid", pid)
        self._c_spawns.inc()
        self.events.emit("supervisor-spawn", replica=name, pid=pid,
                         why=why, **self._advice_fields(advice))
        self.obs.event("supervisor-spawn", replica=name)
        return name

    # presto-lint: holds(_lock)
    def _drain_one(self, now: float, why: str,
                   advice: Optional[dict]) -> Optional[str]:
        """SIGTERM the youngest up replica: stop leasing, finish
        in-flight, tombstone — the existing graceful path."""
        up = [(r["spawned"], n)
              for n, r in self._reg["replicas"].items()
              if r["state"] == UP]
        if not up:
            return None
        name = max(up)[1]
        row = self._reg["replicas"][name]
        row["state"] = DRAINING
        row["drain_deadline"] = now + self.cfg.drain_timeout_s
        self._save_registry()
        with self.obs.span("supervisor:drain", replica=name):
            self._signal(name, row["pid"], signal.SIGTERM)
        self._c_drains.inc()
        self.events.emit("supervisor-drain", replica=name,
                         pid=row["pid"], why=why,
                         **self._advice_fields(advice))
        self.obs.event("supervisor-drain", replica=name)
        return name

    @staticmethod
    def _advice_fields(advice: Optional[dict]) -> dict:
        """The advisory inputs that drove a decision, flattened into
        the event payload so a scaling episode replays from the event
        stream alone."""
        if not advice:
            return {"wanted": None, "advice_reason": "unreachable"}
        return {"wanted": advice.get("wanted_replicas"),
                "advice_reason": advice.get("reason"),
                "inputs": advice.get("inputs", {})}

    # ---- lifecycle reconciliation ------------------------------------

    def _reconcile(self, now: float) -> None:  # presto-lint: holds(_lock)
        """One pass over the registry: confirm spawns (first ledger
        heartbeat), finish drains (process exit; SIGKILL past the
        deadline), and replace dead or heartbeat-silent replicas
        (repair bypasses hysteresis and cooldown)."""
        dirty = False
        for name in sorted(self._reg["replicas"]):
            row = self._reg["replicas"][name]
            alive = self._alive(name, row.get("pid"))
            hb = self.ledger.last_heartbeat(name)
            if row["state"] == SPAWNING:
                if hb is not None and hb >= row["spawned"]:
                    row["state"] = UP
                    dirty = True
                    self.events.emit("supervisor-up", replica=name,
                                     pid=row["pid"],
                                     warmup_s=round(now
                                                    - row["spawned"],
                                                    3))
                    self.obs.event("supervisor-up", replica=name)
                elif not alive or now > row["deadline"]:
                    if alive:
                        self._signal(name, row.get("pid"),
                                     signal.SIGKILL)
                    self._reap(name)
                    del self._reg["replicas"][name]
                    dirty = True
                    self.events.emit("supervisor-spawn-failed",
                                     replica=name, pid=row.get("pid"),
                                     why=("no heartbeat within %gs"
                                          % self.cfg.spawn_timeout_s
                                          if alive
                                          else "process exited"))
                    self.obs.event("supervisor-spawn-failed",
                                   replica=name)
            elif row["state"] == UP:
                stale = (hb is not None
                         and now - hb > self.cfg.heartbeat_timeout)
                if not alive or stale:
                    why = ("process died" if not alive
                           else "heartbeat stale %.1fs"
                           % (now - hb))
                    if alive:    # wedged: escalate straight to KILL
                        self._signal(name, row.get("pid"),
                                     signal.SIGKILL)
                    self._reap(name)
                    del self._reg["replicas"][name]
                    dirty = True
                    with self.obs.span("supervisor:replace",
                                       replica=name) as span:
                        span.set_attr("why", why)
                        new = self._spawn_one(now,
                                              "replace %s (%s)"
                                              % (name, why), None)
                    self._c_replacements.inc()
                    self.events.emit("supervisor-replace",
                                     replica=name,
                                     replacement=new, why=why)
                    self.obs.event("supervisor-replace",
                                   replica=name)
            elif row["state"] == DRAINING:
                if not alive:
                    self._reap(name)
                    del self._reg["replicas"][name]
                    dirty = True
                    self.events.emit("supervisor-drained",
                                     replica=name, pid=row.get("pid"))
                    self.obs.event("supervisor-drained",
                                   replica=name)
                elif now > row.get("drain_deadline", now):
                    self._signal(name, row.get("pid"),
                                 signal.SIGKILL)
                    row["drain_deadline"] = now + 5.0
                    dirty = True
                    self.events.emit("supervisor-drain-timeout",
                                     replica=name, pid=row.get("pid"))
                    self.obs.event("supervisor-drain-timeout",
                                   replica=name)
        if dirty:
            self._save_registry()

    def adopt(self, now: Optional[float] = None) -> List[str]:
        """Reconcile a restarted supervisor against the persisted
        registry: adopt every registered replica whose process still
        runs (matching a pid-less mid-spawn row to the process table
        by its `-replica` name), drop the rest — so a supervisor
        crash leaves no orphan and its restart spawns nothing it
        already owns."""
        now = time.time() if now is None else now
        adopted: List[str] = []
        with self._lock:
            for name in sorted(self._reg["replicas"]):
                row = self._reg["replicas"][name]
                pid = row.get("pid")
                if pid is None:
                    pid = self.find_pid_by_replica(name)
                    row["pid"] = pid
                if pid is not None and self._alive(name, pid):
                    if row["state"] == SPAWNING:
                        row["deadline"] = (now
                                           + self.cfg.spawn_timeout_s)
                    adopted.append(name)
                    self.events.emit("supervisor-adopt", replica=name,
                                     pid=pid, state=row["state"])
                    self.obs.event("supervisor-adopt", replica=name)
                else:
                    del self._reg["replicas"][name]
            self._save_registry()
        return adopted

    # ---- the decision step -------------------------------------------

    def step(self, now: Optional[float] = None) -> dict:
        """One control iteration: reconcile replica lifecycles, fetch
        the advisory, apply hysteresis + cooldown, actuate.  Returns
        the decision dict (also kept as `last_decision`)."""
        now = time.time() if now is None else now
        with self._lock:
            self._reconcile(now)
            self._preempt(now)
            advice = self._fetch_advice()
            current = self._count_serving()
            decision = self._decide(now, advice, current)
            self._g_replicas.set(self._count_serving())
        self.last_decision = decision
        return decision

    # presto-lint: holds(_lock)
    def _preempt(self, now: float) -> List[str]:
        """The preempt-fraction pacer: every `preempt_interval_s`,
        SIGKILL-and-replace a paced number of UP replicas currently
        holding campaign-tenant leases — spot capacity as a normal
        operating mode, not a chaos-test special case.  Deliberately
        the rudest path (no drain): the leases are reaped, the epoch
        fence rejects the dead replica's late commits, and the
        replacement rides the ordinary spawn path — exactly the
        machinery FLEET_CHAOS.json proves lossless.  Interactive
        tenants are untouched: only holders of `preempt_tenant`
        leases qualify."""
        cfg = self.cfg
        if cfg.preempt_fraction <= 0.0:
            return []
        if (self._last_preempt is not None
                and now - self._last_preempt < cfg.preempt_interval_s):
            return []
        try:
            owners = self.ledger.lease_owners(cfg.preempt_tenant)
        except Exception:
            return []
        holders = sorted(
            (n for n, r in self._reg["replicas"].items()
             if r["state"] == UP and owners.get(n)),
            key=lambda n: -owners[n])
        if not holders:
            return []
        n_kill = min(len(holders),
                     max(1, int(round(cfg.preempt_fraction
                                      * len(holders)))))
        preempted: List[str] = []
        self._last_preempt = now
        for name in holders[:n_kill]:
            row = self._reg["replicas"][name]
            with self.obs.span("campaign:preempt",
                               replica=name) as span:
                span.set_attr("leases", owners.get(name, 0))
                self._signal(name, row.get("pid"), signal.SIGKILL)
                self._reap(name)
                del self._reg["replicas"][name]
                new = self._spawn_one(
                    now, "preempt %s (campaign lane)" % name, None)
                span.set_attr("replacement", new)
            self._c_preemptions.inc()
            self.events.emit("campaign-preempt", replica=name,
                             replacement=new,
                             leases=owners.get(name, 0),
                             tenant=cfg.preempt_tenant)
            self.obs.event("campaign-preempt", replica=name)
            preempted.append(name)
        return preempted

    # presto-lint: holds(_lock)
    def _decide(self, now: float, advice: Optional[dict],
                current: int) -> dict:
        base = {"ts": now, "current": current,
                **self._advice_fields(advice)}
        if advice is None:
            self._up_streak = self._down_streak = 0
            return dict(base, action="hold", why="advisory-unreachable")
        wanted = min(max(int(advice.get("wanted_replicas", current)),
                         self.cfg.min_replicas),
                     self.cfg.max_replicas)
        base["wanted"] = wanted
        if wanted > current:
            self._up_streak += 1
            self._down_streak = 0
        elif wanted < current:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
            return dict(base, action="steady")
        cooldown_left = (0.0 if self._last_actuation is None
                         else (self._last_actuation
                               + self.cfg.cooldown_s) - now)
        if wanted > current and self._up_streak \
                >= self.cfg.scale_up_after and cooldown_left <= 0:
            with self.obs.span("supervisor:decide",
                               action="spawn") as span:
                span.set_attr("wanted", wanted)
                span.set_attr("current", current)
                names = [self._spawn_one(now, "scale-up", advice)
                         for _ in range(wanted - current)]
            self._last_actuation = now
            self._up_streak = 0
            return dict(base, action="spawn",
                        replicas=[n for n in names if n])
        if wanted < current and self._down_streak \
                >= self.cfg.scale_down_after and cooldown_left <= 0:
            with self.obs.span("supervisor:decide",
                               action="drain") as span:
                span.set_attr("wanted", wanted)
                span.set_attr("current", current)
                names = [self._drain_one(now, "scale-down", advice)
                         for _ in range(current - wanted)]
            self._last_actuation = now
            self._down_streak = 0
            return dict(base, action="drain",
                        replicas=[n for n in names if n])
        # hysteresis is the outer gate: a hold only blames the
        # cooldown once the streak would otherwise have actuated
        streak_met = (self._up_streak >= self.cfg.scale_up_after
                      if wanted > current
                      else self._down_streak
                      >= self.cfg.scale_down_after)
        why = ("cooldown %.1fs" % cooldown_left if streak_met
               else "hysteresis %d/%d"
               % (self._up_streak or self._down_streak,
                  self.cfg.scale_up_after if wanted > current
                  else self.cfg.scale_down_after))
        self._c_holds.inc()
        with self.obs.span("supervisor:decide", action="hold") as span:
            span.set_attr("wanted", wanted)
            span.set_attr("current", current)
            span.set_attr("why", why)
        out = dict(base, action="hold", why=why)
        self.events.emit("supervisor-hold", **out)
        self.obs.event("supervisor-hold")
        return out

    # ---- lifecycle ----------------------------------------------------

    def start(self) -> "FleetSupervisor":
        adopted = self.adopt()
        self.events.emit("supervisor-start", adopted=adopted,
                         min_replicas=self.cfg.min_replicas,
                         max_replicas=self.cfg.max_replicas,
                         cooldown_s=self.cfg.cooldown_s,
                         scale_up_after=self.cfg.scale_up_after,
                         scale_down_after=self.cfg.scale_down_after)
        self.obs.event("supervisor-start")
        self._stop.clear()
        self._loop_t = threading.Thread(
            target=self._loop, name="presto-supervisor",
            daemon=True)
        self._loop_t.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:
                self.obs.event("supervisor-step-error")
            self._stop.wait(self.cfg.poll_s)

    def stop(self) -> None:
        """Stop supervising, leave replicas RUNNING: supervisor death
        degrades the fleet to the advisory-only behavior, and the
        persisted registry lets the next supervisor adopt everything
        — stopping must never be the event that loses work."""
        self._stop.set()
        if self._loop_t is not None:
            self._loop_t.join(timeout=10.0)
        with self._lock:
            left = sorted(self._reg["replicas"])
        self.events.emit("supervisor-stop", replicas=left)
        self.obs.event("supervisor-stop")
        self.events.close()

    def drain_all(self, timeout: Optional[float] = None) -> None:
        """Tear the supervised fleet down (tool/test teardown — NOT
        the normal stop path): SIGTERM everything, SIGKILL past the
        deadline, clear the registry."""
        deadline = time.time() + (timeout
                                  or self.cfg.drain_timeout_s)
        with self._lock:
            rows = dict(self._reg["replicas"])
            for name, row in rows.items():
                self._signal(name, row.get("pid"), signal.SIGTERM)
            while time.time() < deadline and any(
                    self._alive(n, r.get("pid"))
                    for n, r in rows.items()):
                time.sleep(0.1)
            for name, row in rows.items():
                if self._alive(name, row.get("pid")):
                    self._signal(name, row.get("pid"),
                                 signal.SIGKILL)
                self._reap(name)
            self._reg["replicas"] = {}
            self._save_registry()
