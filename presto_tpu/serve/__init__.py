"""presto_tpu.serve — always-on, continuously-batching search service.

The batch driver (`pipeline/survey.py`) is artifact-per-stage and
process-per-run: every invocation pays XLA compilation for each
distinct trial shape it meets.  This package is the L8 serving layer
above it — the shape modern inference servers use — so a long-lived
process amortizes compilation across requests and keeps the device
mesh saturated:

  queue.py      bounded priority job queue with backpressure
  plancache.py  compiled-plan cache (pad-to-bucket shape quantization)
  scheduler.py  continuous micro-batching loop: same-bucket coalescing,
                per-job timeout, bounded retry with exponential
                backoff, graceful degradation to single-job execution
  server.py     SearchService + threaded HTTP front end
                (/submit /jobs/<id> /healthz /metrics /events)
  events.py     structured JSON event log for tracing

See docs/SERVING.md for the wire protocol, metrics schema, and
tuning knobs.
"""

from presto_tpu.serve.events import EventLog
from presto_tpu.serve.queue import (Job, JobQueue, QueueClosed,
                                    QueueFull, JobStatus)
from presto_tpu.serve.plancache import (PlanCache, PlanKey,
                                        SearcherProvider, bucket_key,
                                        bucket_quantize,
                                        quantize_nsamp)
from presto_tpu.serve.scheduler import (JobTimeout, Scheduler,
                                        SchedulerConfig)
from presto_tpu.serve.server import SearchService, start_http

__all__ = [
    "EventLog", "Job", "JobQueue", "JobStatus", "JobTimeout",
    "PlanCache", "PlanKey", "QueueClosed", "QueueFull", "Scheduler",
    "SchedulerConfig", "SearchService", "SearcherProvider",
    "bucket_key", "bucket_quantize", "quantize_nsamp",
    "start_http",
]
