"""presto_tpu.serve — always-on, continuously-batching search service.

The batch driver (`pipeline/survey.py`) is artifact-per-stage and
process-per-run: every invocation pays XLA compilation for each
distinct trial shape it meets.  This package is the L8 serving layer
above it — the shape modern inference servers use — so a long-lived
process amortizes compilation across requests and keeps the device
mesh saturated:

  queue.py      bounded priority job queue with backpressure
  plancache.py  compiled-plan cache (pad-to-bucket shape quantization)
                + the persistent compiled-plan tier (PlanStore: JAX
                compilation cache keyed by device fingerprint and a
                plan-recipe sidecar for cold-replica warm-up)
  scheduler.py  continuous micro-batching loop: same-bucket coalescing,
                per-job timeout, bounded retry with exponential
                backoff, graceful degradation to single-job execution
  server.py     SearchService + threaded HTTP front end
                (/submit /jobs/<id> /healthz /readyz /metrics /events)
  events.py     structured JSON event log for tracing

Fleet scale (N replicas, one shared on-disk job ledger):

  jobledger.py  durable job ledger (generic pipeline/leaseledger core:
                leases, heartbeats, epoch fencing, staged fence-checked
                commits) + tenant WRR fairness and quotas + job
                dependencies (blocked_on, fenced dynamic fan-out)
  fleet.py      FleetReplica: the lease-and-execute pump around one
                SearchService, with graceful drain and a chaos seam
  router.py     front-door admission (load shedding 429+Retry-After,
                typed tenant-quota rejections, /fleet topology view)
                + presto-router CLI
  dag.py        discovery DAGs: search -> sift -> fold-per-surviving-
                candidate -> timing as one submitted unit (POST /dag),
                with stacked same-geometry folds

See docs/SERVING.md for the wire protocol, metrics schema, fleet
topology, and tuning knobs.
"""

from presto_tpu.serve.events import EventLog
from presto_tpu.serve.queue import (Job, JobQueue, QueueClosed,
                                    QueueFull, JobStatus)
from presto_tpu.serve.plancache import (PlanCache, PlanKey, PlanStore,
                                        SearcherProvider,
                                        accel_plan_key, bucket_key,
                                        bucket_quantize,
                                        quantize_nsamp)
from presto_tpu.serve.scheduler import (JobTimeout, Scheduler,
                                        SchedulerConfig)
from presto_tpu.serve.server import SearchService, start_http
from presto_tpu.serve.jobledger import (JobLedger, JobLedgerError,
                                        StaleResultError,
                                        TenantQuotaExceeded)
from presto_tpu.serve.dag import (build_node_job, execute_node,
                                  plan_dag, run_folds_stacked)
from presto_tpu.serve.fleet import (FleetConfig, FleetReplica,
                                    artifact_digests)
from presto_tpu.serve.router import (FleetBusy, FleetRouter,
                                     NoReadyReplica, RouterConfig)

__all__ = [
    "EventLog", "FleetBusy", "FleetConfig", "FleetReplica",
    "FleetRouter", "Job", "JobLedger", "JobLedgerError", "JobQueue",
    "JobStatus", "JobTimeout", "NoReadyReplica", "PlanCache",
    "PlanKey", "PlanStore", "QueueClosed", "QueueFull",
    "RouterConfig", "Scheduler", "SchedulerConfig", "SearchService",
    "SearcherProvider", "StaleResultError", "TenantQuotaExceeded",
    "accel_plan_key", "artifact_digests", "bucket_key",
    "bucket_quantize", "build_node_job", "execute_node", "plan_dag",
    "quantize_nsamp", "run_folds_stacked", "start_http",
]
