"""Fleet front door: durable admission, load shedding, tenant quotas.

The router is deliberately *not* a proxy holding jobs in memory — the
single-process serve layer already showed why that loses: a crash
forfeits every queued job.  `POST /submit` here lands the job
directly in the shared on-disk ledger (`serve/jobledger.py`), and the
replicas *pull* work by leasing — so "fanning submissions across
replicas" is the lease protocol itself: a draining or cold replica
(503 on `/readyz`) simply stops leasing and traffic flows around it
with no routing table to go stale, and a replica crash strands
nothing the reaper cannot re-admit.

What the router adds on top of the ledger:

  * **Load shedding** — when fleet depth (pending + leased) crosses
    the high-water mark, `/submit` answers 429 with a `Retry-After`
    header: the fleet-scale twin of the in-process queue's bounded-
    depth backpressure (QueueFull -> 429).  A second, *priced* mark
    (`high_water_ds`) sheds on the backlog's expected device-seconds
    under the per-bucket execute cost model, so few huge jobs and
    many tiny jobs back the fleet up equivalently.
  * **Tenant quotas** — `JobLedger.admit` enforces per-tenant quotas
    counted in active jobs AND priced in expected device-seconds
    (`ds_quota`); the typed `TenantQuotaExceeded` maps to a 429
    whose body names the tenant, quota, and unit
    (`error: "quota-exceeded"`), and a `quota-exceeded` event is
    recorded — never a silent drop.  Weighted round-robin *fairness*
    between tenants is the ledger's lease policy (deficit WRR over
    the `tenant` job field, with SLO-class weight multipliers from
    `<fleet>/slo.json`).
  * **Fleet view** — `/fleet` aggregates the ledger (depth, epoch,
    tenant counts) with each registered replica's `/readyz` (polled;
    replicas register their HTTP address at ledger join), and the
    router runs the idempotent reaper so a fleet whose every replica
    died still re-admits leases the moment one returns.

Wire protocol (stdlib HTTP + JSON, like server.py):

  POST /submit            {"rawfiles": [...], "config": {...},
                           "tenant": "...", "priority": int}
                          -> 202 ledger job view
                          429 shed (Retry-After) / quota-exceeded
                          503 no ready replica registered
  POST /dag               {"rawfiles": [...], "config": {...},
                           "sift": {...}, "fold": {...},
                           "toa": {...}, "tenant": "..."}
                          -> 202 {dag_id, nodes} — one discovery DAG
                          (search -> sift -> folds -> timing)
                          admitted as ONE durable transaction
                          (serve/dag.py); same 429/503 semantics
  GET  /dag/<id>          aggregate DAG view (per-node states)
  POST /campaign          {"id": "...", "manifest": [<POST /dag
                           specs>], "wave_size": int, "tenant": ...,
                           "weight": float, "priority": int}
                          -> 202 campaign status.  Creation is
                          idempotent (re-POSTing an existing id
                          resumes it); the first wave is admitted
                          inline and the router's poll loop keeps
                          pulsing every campaign it has touched —
                          safely alongside an external
                          presto-campaign driver (serve/campaign.py
                          serializes pulses per campaign).  No shed
                          or ready-replica gate: a campaign IS the
                          backlog, bounded to wave_size outstanding
                          DAGs by its own ledger.
  GET  /campaign          campaign ids with state + counts
  GET  /campaign/<id>     full status + live ETA/cost projection
  GET  /jobs/<id>         ledger job view (404 unknown)
  GET  /jobs/<id>/result  committed result.json (409 until done)
  GET  /fleet             topology + readiness + tenant counts
  GET  /healthz           router liveness
  GET  /metrics           router-process metrics (JSON;
                          ?format=prometheus)
  GET  /fleet/metrics     FLEET-WIDE aggregation over the replicas'
                          atomic snapshots (obs/fleetagg.py):
                          counters summed, gauges per-replica,
                          histograms bucket-merged so fleet p50/p99
                          are real percentiles; JSON by default,
                          Prometheus via Accept/?format= exactly
                          like /metrics; snapshots older than 3x
                          their publish interval are flagged stale
  GET  /slo               per-tenant SLO state (error budget, multi-
                          window burn rates, alert state) evaluated
                          over the durable usage ledger (obs/slo.py)
  GET  /usage             per-tenant/per-bucket device-seconds
                          rollup from <fleet>/usage.jsonl
  GET  /scale             advisory {wanted_replicas, reason}: ledger
                          backlog priced in expected device-seconds
                          over per-replica measured capacity, plus
                          SLO-debt pressure — recorded in the
                          slo_wanted_replicas gauge and an
                          slo-scale-advice event on every change so
                          a supervisor can replay decisions from
                          telemetry alone
  GET  /events?n=100      router event tail

Load shedding quotes `Retry-After` from the fleet-aggregated
`job_e2e_seconds` drain estimate (backlog x mean execute seconds /
ready replicas) when replica snapshots are available, falling back
to the configured constant; the chosen value is recorded in the
`shed` event payload (docs/OBSERVABILITY.md, "Fleet observability").
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import urlparse, parse_qs

from presto_tpu.obs import fleetagg, slo
from presto_tpu.serve import campaign
from presto_tpu.serve.events import EventLog
from presto_tpu.serve.jobledger import (DEFAULT_TENANT, JobLedger,
                                        TenantQuotaExceeded)
from presto_tpu.serve.queue import QueueFull


class FleetBusy(QueueFull):
    """Fleet depth crossed the high-water mark: shed with 429 +
    Retry-After (the ledger-scale twin of QueueFull)."""

    def __init__(self, depth: int, high_water: int,
                 retry_after_s: float):
        self.depth = depth
        self.high_water = high_water
        self.retry_after_s = retry_after_s
        super().__init__("fleet depth %d at high-water mark %d"
                         % (depth, high_water))


class NoReadyReplica(RuntimeError):
    """No registered replica is currently ready (503: clients should
    retry; jobs already admitted keep draining when one returns)."""


@dataclass
class RouterConfig:
    fleetdir: str
    high_water: int = 256          # shed point over pending+leased
    #: shed point over the backlog's EXPECTED DEVICE-SECONDS (priced
    #: by the per-bucket execute cost model, fleet-median fallback);
    #: 0 disables — the count-based high_water stays the backstop
    high_water_ds: float = 0.0
    retry_after_s: float = 2.0
    heartbeat_timeout: float = 10.0
    poll_s: float = 2.0            # replica /readyz poll cadence
    require_ready: bool = True     # 503 /submit with no ready replica
    #: "name:weight[:quota[:ds_quota]]" tenant configs applied at
    #: start (empty quota field skips it: "gold:4::120" is weight 4,
    #: no job-count quota, 120 expected device-seconds)
    tenants: List[str] = field(default_factory=list)
    #: "tenant:objective[:latency_s]" SLO specs (obs/slo.py);
    #: persisted to <fleet>/slo.json so the fleet report and a
    #: future supervisor share the source of truth.  Empty: reuse a
    #: previously persisted spec file, if any.
    slo: List[str] = field(default_factory=list)
    #: "fast:slow:threshold[,...]" burn-window override applied to
    #: every -slo spec ("" keeps the 5m/1h + 30m/6h SRE defaults)
    slo_windows: str = ""
    #: /scale advisory knobs (obs/slo.ScaleConfig)
    scale_target_drain_s: float = 30.0
    scale_min_replicas: int = 1
    scale_max_replicas: int = 16


class FleetRouter:
    """Admission + observation front door over one fleet directory."""

    def __init__(self, cfg: RouterConfig, obs=None):
        from presto_tpu.obs import Observability, ObsConfig
        self.cfg = cfg
        self.obs = obs or Observability(
            ObsConfig(enabled=True, service="presto-router"))
        os.makedirs(cfg.fleetdir, exist_ok=True)
        self.ledger = JobLedger(cfg.fleetdir, obs=self.obs)
        self.events = EventLog()
        self._t0 = time.time()
        self._ready: Dict[str, Optional[dict]] = {}
        self._ready_lock = threading.Lock()
        self._stop = threading.Event()
        self._poll_t: Optional[threading.Thread] = None
        # fleet observability: the router's admission spans stream
        # into the shared obs dir (they are the ROOT spans of every
        # cross-process trace), and the poll loop refreshes a cached
        # fleet metric aggregation for Retry-After quoting
        if self.obs.enabled:
            self.obs.tracer.attach_jsonl(fleetagg.span_stream_path(
                cfg.fleetdir, "router-%d" % os.getpid()))
        self._agg: Optional[dict] = None
        for spec in cfg.tenants:
            parts = spec.split(":")
            self.ledger.set_tenant(
                parts[0],
                weight=(float(parts[1]) if len(parts) > 1
                        and parts[1] else 1.0),
                quota=(int(parts[2]) if len(parts) > 2
                       and parts[2] else None),
                ds_quota=(float(parts[3]) if len(parts) > 3
                          and parts[3] else None))
        # SLO observatory: declarative per-tenant specs, persisted as
        # <fleet>/slo.json (a restarted router with no -slo flags
        # reuses the persisted set); evaluation runs in the poll loop
        # and on demand from /slo, /usage, /scale
        windows = slo.parse_windows(cfg.slo_windows)
        if cfg.slo:
            self._slo_specs = [slo.parse_spec(s, windows=windows)
                               for s in cfg.slo]
            slo.save_specs(cfg.fleetdir, self._slo_specs)
        else:
            self._slo_specs = slo.load_specs(cfg.fleetdir)
        self._scale_cfg = slo.ScaleConfig(
            target_drain_s=cfg.scale_target_drain_s,
            min_replicas=cfg.scale_min_replicas,
            max_replicas=cfg.scale_max_replicas)
        # campaign drivers this router has touched (POST /campaign
        # or a status read): the poll loop pulses the running ones so
        # a campaign created through the front door advances without
        # a dedicated presto-campaign process.  In-memory only — a
        # restarted router re-adopts a campaign on the next POST or
        # status read (idempotent), and an external driver can run
        # concurrently (the per-campaign lockdir serializes pulses).
        self._campaigns: Dict[str, object] = {}
        self._campaigns_lock = threading.Lock()  # presto-lint: guards(_campaigns)
        self._slo_lock = threading.Lock()  # presto-lint: guards(_slo_view, _alerting, _last_wanted)
        self._slo_view: Optional[dict] = None
        self._alerting: set = set()     # (tenant, window) pairs live
        self._last_wanted: Optional[int] = None
        reg = self.obs.metrics
        self._c_submissions = reg.counter(
            "fleet_submissions_total",
            "Jobs durably admitted to the fleet ledger", ("tenant",))
        self._c_dags = reg.counter(
            "dag_submitted_total",
            "Job graphs durably admitted to the ledger")
        self._c_shed = reg.counter(
            "fleet_shed_total",
            "Submissions shed at the high-water mark (429)")
        self._c_quota = reg.counter(
            "fleet_quota_rejections_total",
            "Submissions rejected by tenant quota (typed 429)",
            ("tenant",))
        self._g_depth = reg.gauge(
            "fleet_depth", "Fleet depth (pending + leased jobs)")
        self._g_ready = reg.gauge(
            "fleet_replicas_ready", "Replicas currently ready")
        self._c_agg = reg.counter(
            "fleet_obs_aggregations_total",
            "Fleet metric aggregation passes (snapshot merges)")
        self._g_budget = reg.gauge(
            "slo_error_budget_remaining",
            "Remaining error-budget fraction per tenant (1 = whole "
            "budget left, 0 = spent)", ("tenant",))
        self._g_burn = reg.gauge(
            "slo_burn_rate",
            "Fast-window burn rate per tenant and alert window "
            "(1 = spending exactly the budgeted rate)",
            ("tenant", "window"))
        self._c_burn_alerts = reg.counter(
            "slo_burn_alerts_total",
            "Multi-window burn-rate alerts fired (rising edges) per "
            "tenant", ("tenant",))
        self._g_wanted = reg.gauge(
            "slo_wanted_replicas",
            "Advisory wanted-replica count from the /scale signal "
            "(backlog device-seconds + SLO-debt pressure)")

    # ---- lifecycle ----------------------------------------------------

    def start(self) -> "FleetRouter":
        self._stop.clear()
        self._poll_t = threading.Thread(
            target=self._poll_loop, name="presto-router-poll",
            daemon=True)
        self._poll_t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._poll_t is not None:
            self._poll_t.join(timeout=10.0)
        with self._campaigns_lock:
            drivers = list(self._campaigns.values())
            self._campaigns.clear()
        for drv in drivers:
            drv.close()
        self.events.close()
        self.obs.tracer.close()

    # ---- replica health -----------------------------------------------

    def _replica_addrs(self) -> Dict[str, Optional[str]]:
        state = self.ledger.read()
        return {host: h.get("addr")
                for host, h in sorted(state["hosts"].items())
                if h.get("alive", False)}

    @staticmethod
    def _get_readyz(addr: str, timeout: float = 2.0) \
            -> Optional[dict]:
        try:
            with urllib.request.urlopen(addr.rstrip("/") + "/readyz",
                                        timeout=timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:        # 503 still carries the readiness payload
                return json.loads(e.read())
            except Exception:
                return None
        except Exception:
            return None

    def poll_replicas(self) -> Dict[str, Optional[dict]]:
        """One health sweep: /readyz every registered live replica
        (None for unreachable ones) + the idempotent reap pass."""
        out: Dict[str, Optional[dict]] = {}
        for host, addr in self._replica_addrs().items():
            out[host] = self._get_readyz(addr) if addr else None
        with self._ready_lock:
            self._ready = out
        self._g_ready.set(sum(1 for r in out.values()
                              if r and r.get("ready")))
        self.ledger.reap(self.cfg.heartbeat_timeout)
        self._g_depth.set(self.ledger.depth())
        try:
            self._agg = fleetagg.aggregate(self.cfg.fleetdir)
            self._c_agg.inc()
        except Exception:
            self.obs.event("router-poll-error")
        try:
            self.evaluate_slo()
        except Exception:
            self.obs.event("router-poll-error")
        self._pulse_campaigns()
        return out

    def ready_replicas(self) -> List[str]:
        with self._ready_lock:
            return sorted(h for h, r in self._ready.items()
                          if r and r.get("ready"))

    def serving_replicas(self) -> List[str]:
        """Ready AND non-draining replicas — the capacity count the
        /scale advisory prices pressure against.  A draining replica
        still answers polls (it may be finishing in-flight work) but
        leases nothing new, so counting it toward capacity masks
        SLO-debt pressure exactly when the supervisor most needs the
        signal: mid-scale-down.  Both the readiness payload's own
        `draining` flag and the fleet lease state's are honored —
        an in-process replica drained directly (replica.drain())
        flips the lease state before the service flag."""
        with self._ready_lock:
            out = []
            for host, r in self._ready.items():
                if not (r and r.get("ready")):
                    continue
                if r.get("draining"):
                    continue
                if (r.get("lease") or {}).get("draining"):
                    continue
                out.append(host)
            return sorted(out)

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_replicas()
            except Exception:
                self.obs.event("router-poll-error")
            self._stop.wait(self.cfg.poll_s)

    # ---- admission ----------------------------------------------------

    @staticmethod
    def _bucket_hint(spec: dict) -> Optional[str]:
        """Best-effort plan-bucket hint recorded on the job row so
        `JobLedger.lease_batch` can hand a replica a whole same-bucket
        batch (the stacked executor's fleet feeder).  Failure — an
        unreadable header, an unknown config field — degrades to None
        (single-lease behavior), never to a rejected admission: the
        replica's own build_job still validates authoritatively."""
        try:
            from presto_tpu.pipeline.survey import SurveyConfig
            from presto_tpu.serve.plancache import bucket_key
            cfg = SurveyConfig(**dict(spec.get("config") or {}))
            return repr(bucket_key(list(spec["rawfiles"]), cfg))
        except Exception:
            return None

    # ---- admission control: Retry-After from fleet telemetry ----------

    @staticmethod
    def _trace_stamp(span) -> Optional[dict]:
        """The span's SpanContext as the wire dict stamped onto the
        admitted ledger row (None with observability disabled)."""
        ctx = span.context()
        return None if ctx is None else ctx.to_dict()

    def retry_after_estimate(self, depth: int):
        """(seconds, source): Retry-After quoted from the fleet-
        aggregated `job_e2e_seconds` drain estimate — mean device-
        execute seconds per job x backlog depth / ready replicas —
        when replica snapshots are available; the configured constant
        otherwise.  Never below the constant, capped at 600 s."""
        agg = self._agg
        if agg:
            roll = fleetagg.rollup(agg.get("merged") or {},
                                   "job_e2e_seconds", "phase")
            ph = roll.get("execute") or roll.get("total")
            if ph and ph.get("count"):
                mean = ph["sum"] / ph["count"]
                ready = max(1, len(self.ready_replicas()))
                est = depth * mean / ready
                return (max(self.cfg.retry_after_s,
                            min(est, 600.0)), "e2e-estimate")
        return self.cfg.retry_after_s, "constant"

    def _shed(self, tenant: str, depth: int,
              backlog_ds: Optional[float] = None) -> None:
        """429 + Retry-After at the high-water mark; the chosen value
        (and whether it came from the e2e estimate or the constant
        fallback) rides the `fleet_shed_total` event payload.
        ``backlog_ds`` names the priced backlog when the DEVICE-
        SECOND mark tripped (the cost-model shed path)."""
        retry_after_s, source = self.retry_after_estimate(depth)
        self._c_shed.inc()
        fields = dict(tenant=tenant, depth=depth,
                      high_water=self.cfg.high_water,
                      retry_after_s=round(retry_after_s, 3),
                      retry_after_source=source)
        if backlog_ds is not None:
            fields["backlog_device_seconds"] = round(backlog_ds, 3)
            fields["high_water_ds"] = self.cfg.high_water_ds
        self.events.emit("shed", **fields)
        raise FleetBusy(depth, self.cfg.high_water, retry_after_s)

    def _check_water(self, tenant: str, depth: int) -> None:
        """Both shed marks: job count (the backstop) and expected
        device-seconds (the priced gate — a backlog of few huge jobs
        sheds exactly like one of many tiny jobs)."""
        if depth >= self.cfg.high_water:
            self._shed(tenant, depth)
        if self.cfg.high_water_ds > 0.0:
            backlog_ds = self.ledger.backlog_device_seconds()
            if backlog_ds >= self.cfg.high_water_ds:
                self._shed(tenant, depth, backlog_ds)

    def submit(self, spec: dict) -> dict:
        """Durably admit one job.  Raises FleetBusy (shed),
        TenantQuotaExceeded (typed), NoReadyReplica (503).  The
        admission span's context is stamped onto the ledger row, so
        the leasing replica resumes THIS trace."""
        if not isinstance(spec, dict):
            raise ValueError("spec must be a JSON object")
        tenant = str(spec.get("tenant") or DEFAULT_TENANT)
        span = self.obs.span("fleet:submit", tenant=tenant)
        try:
            depth = self.ledger.depth()
            self._g_depth.set(depth)
            self._check_water(tenant, depth)
            if self.cfg.require_ready and not self.ready_replicas():
                raise NoReadyReplica(
                    "no ready replica registered in %s"
                    % self.cfg.fleetdir)
            try:
                view = self.ledger.admit(
                    spec, tenant=tenant,
                    job_id=spec.get("job_id"),
                    priority=int(spec.get("priority", 10)),
                    bucket=self._bucket_hint(spec),
                    trace=self._trace_stamp(span))
            except TenantQuotaExceeded as e:
                self._c_quota.labels(tenant=tenant).inc()
                self.events.emit("quota-exceeded", tenant=tenant,
                                 quota=e.quota, active=e.active)
                raise
        except Exception as e:
            span.finish("error: %s" % type(e).__name__)
            raise
        span.set_attr("job", view["job_id"])
        span.finish()
        self._c_submissions.labels(tenant=tenant).inc()
        self.events.emit("enqueue", job=view["job_id"],
                         tenant=tenant, depth=depth + 1)
        return view

    def submit_dag(self, spec: dict) -> dict:
        """Durably admit one discovery DAG (search -> sift ->
        fold-fan-out -> timing) as a single ledger transaction
        (serve/dag.plan_dag + JobLedger.admit_dag).  Shedding, the
        ready-replica gate, and tenant quotas apply exactly as for
        single submissions — the quota counts the whole graph."""
        if not isinstance(spec, dict):
            raise ValueError("spec must be a JSON object")
        from presto_tpu.serve.dag import plan_dag
        tenant = str(spec.get("tenant") or DEFAULT_TENANT)
        span = self.obs.span("fleet:dag-submit", tenant=tenant)
        try:
            depth = self.ledger.depth()
            self._g_depth.set(depth)
            self._check_water(tenant, depth)
            if self.cfg.require_ready and not self.ready_replicas():
                raise NoReadyReplica(
                    "no ready replica registered in %s"
                    % self.cfg.fleetdir)
            nodes = plan_dag(spec)
            try:
                # one trace for the whole graph: every node row
                # carries this span's context, and the sift's fenced
                # expand re-parents its fan-out under the sift span
                out = self.ledger.admit_dag(
                    nodes, tenant=tenant,
                    priority=int(spec.get("priority", 10)),
                    dag_id=spec.get("dag_id"),
                    trace=self._trace_stamp(span))
            except TenantQuotaExceeded as e:
                self._c_quota.labels(tenant=tenant).inc()
                self.events.emit("quota-exceeded", tenant=tenant,
                                 quota=e.quota, active=e.active)
                raise
        except Exception as e:
            span.finish("error: %s" % type(e).__name__)
            raise
        span.set_attr("dag", out["dag_id"])
        span.finish()
        self._c_submissions.labels(tenant=tenant).inc(len(nodes))
        self._c_dags.inc()
        self.events.emit("dag-submit", dag=out["dag_id"],
                         tenant=tenant, nodes=len(nodes))
        return dict(out, tenant=tenant)

    def dag_status(self, dag_id: str) -> Optional[dict]:
        return self.ledger.dag_view(dag_id)

    # ---- campaign engine ----------------------------------------------

    def _campaign_driver(self, campaign_id: str,
                         cfg_kw: Optional[dict] = None):
        """The cached per-campaign driver (created on first touch).
        Sharing the router's obs handle and job ledger means
        campaign telemetry rides the router's /metrics and span
        stream; sharing the ledger's stat-cache keeps status reads
        cheap."""
        from presto_tpu.serve.campaign import (CampaignConfig,
                                               CampaignDriver,
                                               _safe_id)
        cid = _safe_id(str(campaign_id))
        with self._campaigns_lock:
            drv = self._campaigns.get(cid)
            if drv is None:
                ccfg = CampaignConfig(fleetdir=self.cfg.fleetdir,
                                      campaign_id=cid,
                                      **dict(cfg_kw or {}))
                drv = CampaignDriver(ccfg, obs=self.obs,
                                     ledger=self.ledger)
                self._campaigns[cid] = drv
            return drv

    def submit_campaign(self, spec: dict) -> dict:
        """Durably create (or idempotently resume) a campaign from
        `{"id", "manifest", ...}` and run its first pulse — the
        manifest lands in `<fleet>/campaigns/<id>/campaign.json` and
        the first wave of discovery DAGs is admitted before the 202
        returns.  No shed/ready gate on purpose: the campaign ledger
        bounds outstanding work to wave_size DAGs, so an archive of
        any size never floods jobs.json the way a /submit firehose
        could."""
        if not isinstance(spec, dict):
            raise ValueError("spec must be a JSON object")
        manifest = spec.get("manifest")
        if not isinstance(manifest, list) or not manifest:
            raise ValueError(
                "manifest must be a non-empty list of observation "
                "specs (each the POST /dag wire schema)")
        kw = {}
        for key, cast in (("wave_size", int), ("tenant", str),
                          ("weight", float), ("priority", int),
                          ("yield_floor", float)):
            if spec.get(key) is not None:
                kw[key] = cast(spec[key])
        drv = self._campaign_driver(spec.get("id") or "campaign", kw)
        drv.create(manifest)
        return drv.pulse()

    def campaign_view(self, campaign_id: str) -> Optional[dict]:
        """`GET /campaign/<id>`: status + live ETA/cost projection
        (None for an unknown id — checked BEFORE a driver is built,
        so probing never creates an empty campaign directory).
        Reading a campaign adopts it into the poll loop's pulse set:
        a restarted router resumes driving a campaign the moment
        anyone asks about it."""
        from presto_tpu.serve.campaign import load_campaign
        if load_campaign(self.cfg.fleetdir, campaign_id) is None:
            return None
        return self._campaign_driver(campaign_id).status()

    def campaigns_view(self) -> dict:
        """`GET /campaign`: every campaign under the fleet with its
        state and per-state observation counts (ledger reads only —
        no drivers are built or adopted)."""
        from presto_tpu.serve.campaign import (CampaignDriver,
                                               list_campaigns,
                                               load_campaign)
        out = {}
        for cid in list_campaigns(self.cfg.fleetdir):
            doc = load_campaign(self.cfg.fleetdir, cid)
            if doc is None:
                continue
            out[cid] = {"state": doc.get("state"),
                        "observations": len(doc["observations"]),
                        "waves": int(doc.get("waves", 0)),
                        "counts": CampaignDriver._counts(doc)}
        return {"campaigns": out}

    def _pulse_campaigns(self) -> None:
        """One poll-loop pass over the adopted campaigns: pulse every
        one still running (settle landed DAGs, admit the next wave,
        refresh the backfill yield).  Terminal campaigns stay in the
        cache for cheap status reads but are not pulsed."""
        from presto_tpu.serve.campaign import load_campaign
        with self._campaigns_lock:
            drivers = list(self._campaigns.values())
        for drv in drivers:
            try:
                doc = load_campaign(self.cfg.fleetdir,
                                    drv.cfg.campaign_id)
                if doc is None or doc.get("state") != "running":
                    continue
                drv.pulse()
            except Exception:
                self.obs.event("router-poll-error")

    # ---- introspection ------------------------------------------------

    def status(self, job_id: str) -> Optional[dict]:
        return self.ledger.view(job_id)

    def result(self, job_id: str) -> Optional[dict]:
        view = self.ledger.view(job_id)
        if view is None:
            return None
        if view["state"] == "done":
            path = os.path.join(self.cfg.fleetdir, "jobs", job_id,
                                "result.json")
            try:
                with open(path) as f:
                    view["result_detail"] = json.load(f)
            except (OSError, ValueError):
                view["result_detail"] = None
        return view

    def wait(self, job_ids, timeout: float = 300.0,
             poll: float = 0.1) -> bool:
        """Block until every listed job is ledger-terminal."""
        if isinstance(job_ids, str):
            job_ids = [job_ids]
        deadline = time.time() + timeout
        while time.time() < deadline:
            views = [self.ledger.view(j) for j in job_ids]
            if all(v is not None and v["state"] in ("done", "failed")
                   for v in views):
                return True
            time.sleep(poll)
        return False

    def fleet_view(self) -> dict:
        with self._ready_lock:
            ready = dict(self._ready)
        counts = self.ledger.counts()
        return {
            "uptime_s": round(time.time() - self._t0, 3),
            "fleetdir": self.cfg.fleetdir,
            "epoch": self.ledger.epoch,
            "depth": self.ledger.depth(),
            "high_water": self.cfg.high_water,
            "jobs": counts,
            "tenants": {
                "config": self.ledger.tenants(),
                "jobs": self.ledger.tenant_counts(),
            },
            "replicas": {
                host: {"addr": addr,
                       "ready": bool(ready.get(host)
                                     and ready[host].get("ready")),
                       "readyz": ready.get(host)}
                for host, addr in self._replica_addrs().items()
            },
        }

    def metrics(self) -> dict:
        return {
            "uptime_s": round(time.time() - self._t0, 3),
            "depth": self.ledger.depth(),
            "high_water": self.cfg.high_water,
            "ready_replicas": len(self.ready_replicas()),
            "shed": int(self._c_shed.value),
            "quota_rejections": int(self._c_quota.total()),
            "submissions": int(self._c_submissions.total()),
            "jobs": self.ledger.counts(),
            "events": self.events.counts(),
        }

    # ---- fleet-wide metric aggregation --------------------------------

    def _aggregate(self) -> dict:
        """A fresh snapshot merge (request path; the poll loop keeps
        `self._agg` warm for Retry-After quoting between requests)."""
        agg = fleetagg.aggregate(self.cfg.fleetdir)
        self._agg = agg
        self._c_agg.inc()
        return agg

    def fleet_metrics(self) -> dict:
        """The `GET /fleet/metrics` JSON body: per-replica snapshot
        freshness, the merged registry (counters summed, gauges
        per-replica, histogram percentiles over the merged sample
        windows), and the per-phase `job_e2e_seconds` rollup the
        control-plane consumers read."""
        agg = self._aggregate()
        merged = agg["merged"]
        return {
            "fleetdir": self.cfg.fleetdir,
            "depth": self.ledger.depth(),
            "jobs": self.ledger.counts(),
            "replicas": agg["replicas"],
            # stale = merged anyway but out of date (older than 3x
            # its publish interval): the fleet view is partial
            "stale_replicas": agg.get("stale_replicas", []),
            "job_e2e": fleetagg.rollup(merged, "job_e2e_seconds",
                                       "phase"),
            "latency": fleetagg.rollup(merged, "latency_seconds",
                                       "name"),
            "metrics": fleetagg.to_json(merged),
        }

    def fleet_metrics_prometheus(self) -> str:
        """Prometheus text exposition of the merged fleet registry
        (the `Accept: text/plain` / `?format=prometheus` answer of
        `GET /fleet/metrics`)."""
        return fleetagg.render_prometheus(
            self._aggregate()["merged"])

    # ---- SLO observatory ----------------------------------------------

    def _backlog_buckets(self,
                         state: Optional[dict] = None) -> List:
        """One bucket hint per active (pending + leased) ledger job
        — what the /scale advisory prices in device-seconds."""
        state = state or self.ledger.read()
        return [row.get("bucket")
                for row in state.get("jobs", {}).values()
                if row.get("state") in ("pending", "leased")]

    def evaluate_slo(self, now: Optional[float] = None) -> dict:
        """One SLO observatory pass over the durable usage ledger:
        per-tenant budget/burn evaluation, gauge updates, rising-edge
        `slo-burn-alert` events, and the /scale advisory (gauge +
        `slo-scale-advice` event on every change, so a supervisor
        replays decisions from telemetry alone).  Runs in the poll
        loop and on demand from the /slo, /usage, /scale endpoints.
        """
        now = time.time() if now is None else now
        with self.obs.span("slo:evaluate") as span:
            rows = self.ledger.usage.rows()
            evals = {spec.tenant: slo.evaluate(spec, rows, now)
                     for spec in self._slo_specs}
            # backfill actuation: while any interactive tenant burns
            # error budget, shrink the campaign lane's live weight —
            # update_backfill_yield excludes the declared backfill
            # tenants from the burn census, writes <fleet>/
            # backfill.json atomically, and the lease policy's
            # stat-cache picks it up on the next lease (None when no
            # backfill lane is declared)
            backfill_yield = slo.update_backfill_yield(
                self.cfg.fleetdir, evals)
            alerts = []
            for tenant, ev in sorted(evals.items()):
                self._g_budget.labels(tenant=tenant).set(
                    ev["budget_remaining"])
                for w in ev["windows"]:
                    self._g_burn.labels(
                        tenant=tenant, window=w["window"]).set(
                            w["fast_burn"])
                    if w["alerting"]:
                        alerts.append((tenant, w["window"], w))
            # capacity clamps to ready NON-DRAINING replicas: a
            # draining one is leaving and must not mask pressure;
            # running campaigns' projected remaining-archive
            # device-seconds ride along so the advisory prices the
            # whole archive, not just the admitted wave
            campaign_s = campaign.fleet_remaining_device_seconds(
                self.cfg.fleetdir, rows, now=now)
            advice = slo.scale_advice(
                self._backlog_buckets(), rows, evals,
                len(self.serving_replicas()),
                cfg=self._scale_cfg, now=now,
                campaign_remaining_s=campaign_s)
            wanted = advice["wanted_replicas"]
            span.set_attr("tenants", len(evals))
            span.set_attr("wanted_replicas", wanted)
        live = {(t, w) for t, w, _ in alerts}
        with self._slo_lock:
            rising = [(t, w, ev) for t, w, ev in alerts
                      if (t, w) not in self._alerting]
            self._alerting = live
            previous = self._last_wanted
            changed = wanted != previous
            self._last_wanted = wanted
            view = {
                "ts": now,
                "specs": [s.to_dict() for s in self._slo_specs],
                "tenants": evals,
                "usage": slo.usage_rollup(rows),
                "scale": advice,
                "backfill_yield": backfill_yield,
            }
            self._slo_view = view
        for tenant, window, w in rising:
            self._c_burn_alerts.labels(tenant=tenant).inc()
            self.events.emit("slo-burn-alert", tenant=tenant,
                             window=window,
                             fast_burn=w["fast_burn"],
                             slow_burn=w["slow_burn"],
                             threshold=w["threshold"])
        self._g_wanted.set(wanted)
        if changed:
            self.events.emit("slo-scale-advice", wanted=wanted,
                             previous=previous,
                             reason=advice["reason"],
                             **advice["inputs"])
        return view

    def slo_view(self) -> dict:
        """The `GET /slo` body: per-tenant budget, burn, and alert
        state (freshly evaluated)."""
        view = self.evaluate_slo()
        return {"ts": view["ts"], "specs": view["specs"],
                "tenants": view["tenants"]}

    def usage_view(self) -> dict:
        """The `GET /usage` body: the device-seconds rollup."""
        view = self.evaluate_slo()
        return dict(view["usage"], ts=view["ts"])

    def scale_view(self) -> dict:
        """The `GET /scale` body: the advisory wanted-replica signal
        and its inputs."""
        view = self.evaluate_slo()
        return dict(view["scale"], ts=view["ts"])


# ----------------------------------------------------------------------
# HTTP front end
# ----------------------------------------------------------------------

class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def router(self) -> FleetRouter:
        return self.server.router      # type: ignore[attr-defined]

    def log_message(self, fmt, *args):
        self.router.events.emit("http", line=fmt % args)

    def _json(self, code: int, payload: dict,
              headers: Optional[dict] = None) -> None:
        body = json.dumps(payload, sort_keys=True).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _prometheus(self, text: str) -> None:
        body = text.encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        try:
            if url.path == "/healthz":
                self._json(200, {"ok": True, "role": "router"})
            elif url.path == "/fleet":
                self._json(200, self.router.fleet_view())
            elif url.path == "/metrics":
                fmt = parse_qs(url.query).get("format", [""])[0]
                accept = self.headers.get("Accept", "") or ""
                if fmt in ("prometheus", "text") \
                        or "text/plain" in accept:
                    self._prometheus(
                        self.router.obs.metrics.render_prometheus())
                else:
                    self._json(200, self.router.metrics())
            elif url.path == "/fleet/metrics":
                # fleet-wide aggregation over the replicas' atomic
                # snapshots: same content negotiation as /metrics
                fmt = parse_qs(url.query).get("format", [""])[0]
                accept = self.headers.get("Accept", "") or ""
                if fmt in ("prometheus", "text") \
                        or "text/plain" in accept:
                    self._prometheus(
                        self.router.fleet_metrics_prometheus())
                else:
                    self._json(200, self.router.fleet_metrics())
            elif url.path == "/slo":
                self._json(200, self.router.slo_view())
            elif url.path == "/usage":
                self._json(200, self.router.usage_view())
            elif url.path == "/scale":
                self._json(200, self.router.scale_view())
            elif url.path == "/events":
                n = int(parse_qs(url.query).get("n", ["100"])[0])
                self._json(200,
                           {"events": self.router.events.tail(n)})
            elif url.path == "/campaign":
                self._json(200, self.router.campaigns_view())
            elif len(parts) == 2 and parts[0] == "campaign":
                view = self.router.campaign_view(parts[1])
                if view is None:
                    self._json(404, {"error": "no such campaign"})
                else:
                    self._json(200, view)
            elif len(parts) == 2 and parts[0] == "dag":
                view = self.router.dag_status(parts[1])
                if view is None:
                    self._json(404, {"error": "no such dag"})
                else:
                    self._json(200, view)
            elif len(parts) == 2 and parts[0] == "jobs":
                view = self.router.status(parts[1])
                if view is None:
                    self._json(404, {"error": "no such job"})
                else:
                    self._json(200, view)
            elif (len(parts) == 3 and parts[0] == "jobs"
                  and parts[2] == "result"):
                view = self.router.result(parts[1])
                if view is None:
                    self._json(404, {"error": "no such job"})
                elif view["state"] not in ("done", "failed"):
                    self._json(409, {"error": "job not finished",
                                     "state": view["state"]})
                else:
                    self._json(200, view)
            else:
                self._json(404, {"error": "unknown endpoint"})
        except Exception as e:
            self._json(500, {"error": "%s: %s"
                             % (type(e).__name__, e)})

    def do_POST(self) -> None:
        path = urlparse(self.path).path
        if path not in ("/submit", "/dag", "/campaign"):
            self._json(404, {"error": "unknown endpoint"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            spec = json.loads(self.rfile.read(length) or b"{}")
            if path == "/campaign":
                self._json(202, self.router.submit_campaign(spec))
            elif path == "/dag":
                self._json(202, self.router.submit_dag(spec))
            else:
                self._json(202, self.router.submit(spec))
        except FleetBusy as e:
            # ceil, not int(): truncation under-quotes the drain
            # estimate (2.9s -> "2" tells clients to come back early)
            self._json(429, {"error": "shed", "detail": str(e),
                             "retry_after_s": e.retry_after_s},
                       headers={"Retry-After":
                                "%d" % max(1, math.ceil(
                                    e.retry_after_s))})
        except TenantQuotaExceeded as e:
            self._json(429, {"error": "quota-exceeded",
                             "tenant": e.tenant, "quota": e.quota,
                             "active": e.active,
                             "unit": getattr(e, "unit", "jobs")},
                       headers={"Retry-After": "1"})
        except NoReadyReplica as e:
            self._json(503, {"error": "no-ready-replica",
                             "detail": str(e)})
        except ValueError as e:
            self._json(400, {"error": str(e)})
        except Exception as e:
            self._json(500, {"error": "%s: %s"
                             % (type(e).__name__, e)})


class RouterHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, router: FleetRouter):
        super().__init__(addr, _RouterHandler)
        self.router = router


def start_http(router: FleetRouter, host: str = "127.0.0.1",
               port: int = 0) -> RouterHTTPServer:
    httpd = RouterHTTPServer((host, port), router)
    t = threading.Thread(target=httpd.serve_forever,
                         name="presto-router-http", daemon=True)
    t.start()
    return httpd


# ----------------------------------------------------------------------
# CLI: presto-router
# ----------------------------------------------------------------------

def build_parser():
    p = argparse.ArgumentParser(prog="presto-router")
    p.add_argument("-host", type=str, default="127.0.0.1")
    p.add_argument("-port", type=int, default=8786)
    p.add_argument("-fleetdir", type=str, required=True,
                   help="Shared fleet directory (the job ledger)")
    p.add_argument("-high-water", type=int, default=256,
                   help="Shed submissions (429 + Retry-After) once "
                        "pending+leased jobs reach this depth")
    p.add_argument("-high-water-ds", type=float, default=0.0,
                   help="Shed once the backlog's EXPECTED DEVICE-"
                        "SECONDS (per-bucket execute cost model, "
                        "fleet-median fallback) reach this; 0 "
                        "disables the priced gate")
    p.add_argument("-retry-after", type=float, default=2.0)
    p.add_argument("-hb-timeout", type=float, default=10.0,
                   help="Replica heartbeat TTL for the reap pass")
    p.add_argument("-poll", type=float, default=2.0,
                   help="Replica /readyz poll cadence, seconds")
    p.add_argument("-tenant", action="append", default=[],
                   metavar="NAME:WEIGHT[:QUOTA[:DS_QUOTA]]",
                   help="Tenant WRR weight, optional active-job "
                        "quota, and optional expected-device-second "
                        "quota over active work (repeatable; an "
                        "empty field skips it: gold:4::120)")
    p.add_argument("-slo", action="append", default=[],
                   metavar="TENANT:OBJECTIVE[:LATENCY_S]",
                   help="Per-tenant SLO spec (repeatable): "
                        "availability objective in (0,1) plus an "
                        "optional per-job e2e latency objective; "
                        "persisted to <fleet>/slo.json and "
                        "evaluated at /slo with multi-window burn-"
                        "rate alerts")
    p.add_argument("-slo-windows", type=str, default="",
                   metavar="FAST:SLOW:THRESHOLD[,...]",
                   help="Burn-alert window pairs in seconds "
                        "(default: the 300:3600:14.4 and "
                        "1800:21600:6 SRE pairs)")
    p.add_argument("-scale-drain", type=float, default=30.0,
                   help="/scale advisory: target seconds to drain "
                        "the backlog")
    p.add_argument("-scale-min", type=int, default=1)
    p.add_argument("-scale-max", type=int, default=16)
    p.add_argument("-allow-empty", action="store_true",
                   help="Admit submissions even with no ready "
                        "replica (they queue in the ledger)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cfg = RouterConfig(fleetdir=args.fleetdir,
                       high_water=args.high_water,
                       high_water_ds=args.high_water_ds,
                       retry_after_s=args.retry_after,
                       heartbeat_timeout=args.hb_timeout,
                       poll_s=args.poll,
                       require_ready=not args.allow_empty,
                       tenants=args.tenant,
                       slo=args.slo,
                       slo_windows=args.slo_windows,
                       scale_target_drain_s=args.scale_drain,
                       scale_min_replicas=args.scale_min,
                       scale_max_replicas=args.scale_max)
    router = FleetRouter(cfg).start()
    httpd = start_http(router, args.host, args.port)
    host, port = httpd.server_address[:2]
    print("presto-router: fleet %s on http://%s:%d "
          "(POST /submit, /dag, /campaign; GET /jobs/<id>, /fleet, "
          "/metrics, /slo, /usage, /scale, /campaign/<id>)"
          % (args.fleetdir, host, port))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("presto-router: shutting down")
    finally:
        httpd.shutdown()
        router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
