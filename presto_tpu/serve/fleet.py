"""Fleet replica: one presto-serve process leasing jobs from the
shared job ledger.

Topology (docs/SERVING.md, "Fleet-scale serving")::

    clients ──▶ router.py ──admit──▶ jobs.json (serve/jobledger)
                                        ▲  lease / commit / redo
                   ┌────────────────────┼────────────────────┐
              replica A            replica B            replica C
           (SearchService +     (SearchService +     (SearchService +
            FleetReplica)        FleetReplica)        FleetReplica)

Each replica runs the standard single-process service (queue, plan
cache, micro-batching scheduler) and this pump around it:

  * **lease** — claim pending jobs from the ledger (tenant-WRR order)
    up to `max_inflight`, build them into local queue jobs whose
    workdir is the job's *epoch-stamped attempt directory*
    (`<fleetdir>/jobs/<id>/a<epoch>`), so a zombie incarnation and
    its successor never write into the same tree;
  * **commit** — when the local job completes, stage `result.json`
    (result summary + artifact digests) and commit it through the
    ledger's fence-checked staged path: a replica the fleet declared
    dead gets `StaleResultError` and its late result is discarded —
    never landed twice;
  * **renew / reap** — heartbeat its own liveness, renew held leases
    at half-TTL, and run the (idempotent) reaper so any replica can
    re-admit a dead peer's leases;
  * **drain** — on SIGTERM: stop leasing, let in-flight work finish
    and commit, release what never started, park scheduler retries
    back into the ledger (`Scheduler.park` seam), and write a
    heartbeat *tombstone* so the reaper re-admits instantly instead
    of waiting out the TTL.

`kill()` is the chaos seam: it drops the replica exactly the way
SIGKILL does (heartbeats stop, leases stay claimed, any running
survey keeps running as a zombie) — tools/fleet_chaos.py and
tests/test_fleet.py drive it.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import socket
import tempfile
import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from presto_tpu.obs import fleetagg
from presto_tpu.serve.jobledger import JobLedger
from presto_tpu.serve.queue import (Job, JobStatus, QueueClosed,
                                    QueueFull)


def default_replica_name() -> str:
    return "%s-%d" % (socket.gethostname(), os.getpid())


#: attempt-dir artifact patterns whose bytes are deterministic given
#: the job spec (no embedded timings/paths) — the byte-equality
#: surface the chaos trials compare against a never-failed run
ARTIFACT_PATTERNS = ("*.dat", "*.fft", "*.singlepulse", "*_ACCEL_*",
                     "cands_sifted*")


def artifact_digests(workdir: str) -> Dict[str, dict]:
    """{relative artifact: {size, sha256}} for one attempt dir."""
    out: Dict[str, dict] = {}
    for pat in ARTIFACT_PATTERNS:
        for p in sorted(glob.glob(os.path.join(workdir, "**", pat),
                                  recursive=True)):
            h = hashlib.sha256()
            with open(p, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            rel = os.path.relpath(p, workdir)
            out[rel] = {"size": os.path.getsize(p),
                        "sha256": h.hexdigest()}
    return out


@dataclass
class FleetConfig:
    """Fleet-membership knobs for one replica."""
    fleetdir: str
    replica: str = ""              # default: <hostname>-<pid>
    lease_ttl: float = 30.0
    heartbeat_s: float = 1.0
    heartbeat_timeout: float = 10.0
    poll_s: float = 0.1
    max_inflight: int = 2          # leased jobs held at once
    prewarm: bool = True           # warm the plan cache before leasing
    #: same-bucket jobs leased per ledger transaction
    #: (JobLedger.lease_batch): a whole batch lands in the local
    #: queue together, coalesces into one micro-batch, and executes
    #: through the stacked executor as one device call.  Capped by
    #: the free max_inflight slots; 1 = classic single leasing.
    lease_batch: int = 4
    #: idle-capacity tuning (the ROADMAP fleet follow-up): when the
    #: ledger is empty and nothing is in flight, run ONE bounded
    #: presto-tune budget slice and merge-save into the fleet's
    #: shared tuning DB.  Off by default.
    tune_in_idle: bool = False
    idle_tune_families: str = "plancache_bucket"
    idle_tune_budget_s: float = 20.0
    idle_tune_interval: float = 300.0
    idle_tune_db: str = ""         # default <fleetdir>/tune.json
    #: fleet-observability snapshot cadence: the heartbeat loop
    #: publishes this replica's full metrics state into
    #: `<fleet>/obs/<replica>.json` every this many seconds (atomic,
    #: tombstoned on drain), feeding the router's `GET /fleet/metrics`
    #: aggregation (obs/fleetagg.py).  0 disables publishing.
    snapshot_s: float = 2.0


class FleetReplica:
    """The lease-and-execute pump wrapping one SearchService."""

    def __init__(self, service, cfg: FleetConfig,
                 addr: Optional[str] = None):
        self.service = service
        self.cfg = cfg
        self.replica = cfg.replica or default_replica_name()
        self.addr = addr
        os.makedirs(cfg.fleetdir, exist_ok=True)
        self.ledger = JobLedger(cfg.fleetdir, obs=service.obs)
        self.jobroot = os.path.join(os.path.abspath(cfg.fleetdir),
                                    "jobs")
        os.makedirs(self.jobroot, exist_ok=True)
        # fleet observability: this replica's spans stream into the
        # shared obs dir (one JSONL per process — what the fleet
        # report and tools/trace_merge.py join by trace id), and the
        # heartbeat loop publishes metric snapshots next to them
        self.obsdir = fleetagg.obs_dir(cfg.fleetdir)
        os.makedirs(self.obsdir, exist_ok=True)
        if service.obs.enabled:
            service.obs.tracer.attach_jsonl(
                fleetagg.span_stream_path(cfg.fleetdir,
                                          self.replica))
        self.epoch = 0
        self.draining = False
        self._killed = False
        self._stop = threading.Event()
        self._pump_t: Optional[threading.Thread] = None
        self._hb_t: Optional[threading.Thread] = None
        self._warmed = threading.Event()
        #: job_id -> (lease, local Job); shared between the pump
        #: thread, drain(), and the HTTP readiness handler
        self._inflight: Dict[str, Tuple[object, Job]] = {}
        self._inflight_lock = threading.Lock()  # presto-lint: guards(_inflight)
        #: chaos seam: kill the replica when the pump reaches this
        #: point ("job-leased" | "job-enqueued")
        self.kill_on: Optional[str] = None
        service.fleet = self
        service.scheduler.park = self._park
        reg = service.obs.metrics
        self._c_leased = reg.counter(
            "fleet_jobs_leased_total",
            "Jobs this replica leased from the fleet ledger")
        self._c_committed = reg.counter(
            "fleet_jobs_committed_total",
            "Job results committed through the ledger fence")
        self._c_redone = reg.counter(
            "fleet_jobs_redone_total",
            "Leased jobs handed back for another replica")
        self._c_failed = reg.counter(
            "fleet_jobs_failed_total",
            "Jobs terminally failed in the ledger by this replica")
        self._c_stale = reg.counter(
            "fleet_stale_results_total",
            "Late results the ledger fence rejected (zombie commits)")
        self._c_batchlease = reg.counter(
            "fleet_batch_leases_total",
            "Multi-job same-bucket batch leases claimed in one "
            "ledger transaction")
        self._c_idletune = reg.counter(
            "fleet_idle_tune_total",
            "Bounded tuning slices run in fleet idle capacity")
        self._c_snapshots = reg.counter(
            "fleet_obs_snapshots_total",
            "Metric snapshots published into the fleet obs dir")
        self._g_inflight = reg.gauge(
            "fleet_inflight", "Leased jobs currently held")
        self._g_epoch = reg.gauge(
            "fleet_epoch", "Fleet epoch this replica last observed")
        self._h_e2e = reg.histogram(
            "job_e2e_seconds",
            "End-to-end fleet job decomposition from ledger/event "
            "timestamps: admit->lease wait, device execute, commit, "
            "and total, per plan bucket", ("phase", "bucket"))

    # ---- lifecycle ----------------------------------------------------

    def start(self) -> "FleetReplica":
        self.epoch = self.ledger.join(self.replica, addr=self.addr)
        # a fresh incarnation cannot have in-flight work: anything
        # leased under this name is a dead predecessor's
        redone = self.ledger.readmit_owned(self.replica)
        if redone:
            self._c_redone.inc(len(redone))
        self.epoch = self.ledger.epoch
        self._g_epoch.set(self.epoch)
        self.ledger.heartbeat(self.replica, self.epoch)
        self._maybe_snapshot(force=True)
        self.service.events.emit("fleet-join", replica=self.replica,
                                 epoch=self.epoch,
                                 readmitted=len(redone))
        self._stop.clear()
        self._hb_t = threading.Thread(
            target=self._heartbeat_loop,
            name="presto-fleet-heartbeat", daemon=True)
        self._hb_t.start()
        self._pump_t = threading.Thread(
            target=self._pump, name="presto-fleet-pump", daemon=True)
        self._pump_t.start()
        return self

    def kill(self) -> None:
        """Chaos seam: die the way SIGKILL dies — heartbeats stop,
        leases stay claimed (the reaper must recover them), any
        running survey keeps running as a zombie whose late commit
        the fence must reject.  Like every real survey death, the
        flight recorder dumps first: the ring (whose last record is
        the `fleet-chaos-point` stamped BEFORE the kill fired) lands
        in `<fleet>/obs/<replica>/flightrec-*.json`, where the fleet
        report picks it up via the ledger's tombstone/reap records
        after the fleet declares this replica dead."""
        self.service.obs.dump_flight(
            fleetagg.replica_dump_dir(self.cfg.fleetdir,
                                      self.replica),
            reason="replica-killed")
        self._killed = True
        self._stop.set()

    def stop(self) -> None:
        self._stop.set()
        for t in (self._pump_t, self._hb_t):
            if t is not None:
                t.join(timeout=10.0)

    def drain(self, timeout: float = 60.0) -> dict:
        """Graceful departure: stop leasing, finish + commit in-flight
        work, hand back whatever never ran, tombstone the heartbeat.
        Returns {drained, released, parked} for the shutdown report."""
        self.draining = True
        self.service.draining = True
        self.service.events.emit("fleet-drain", replica=self.replica,
                                 inflight=self._inflight_size())
        deadline = time.time() + timeout
        drained = True
        while time.time() < deadline:
            if self._inflight_size() == 0:
                break
            time.sleep(self.cfg.poll_s)
        else:
            drained = False
        released = 0
        with self._inflight_lock:
            leftovers = dict(self._inflight)
            self._inflight.clear()
            self._g_inflight.set(0)
        for job_id, (lease, _job) in leftovers.items():
            # never finished here: back to pending for a live replica
            self.ledger.fail(lease, self.replica)
            self._c_redone.inc()
            released += 1
        self.stop()
        self.ledger.tombstone(self.replica)
        # final metric snapshot, tombstoned exactly like the
        # heartbeat: the aggregation keeps this replica's counters
        # (its work happened) but drops its point-in-time gauges
        self._maybe_snapshot(force=True, tombstone=True)
        self.service.events.emit("fleet-tombstone",
                                 replica=self.replica)
        parked = int(self.service.obs.metrics.get(
            "serve_jobs_parked_total").value) \
            if self.service.obs.metrics.get(
                "serve_jobs_parked_total") else 0
        return {"drained": drained, "released": released,
                "parked": parked}

    # ---- readiness ----------------------------------------------------

    def lease_state(self) -> dict:
        with self._inflight_lock:
            held = sorted(self._inflight)
        return {"replica": self.replica, "epoch": self.epoch,
                "held": held, "draining": bool(self.draining),
                "warmed": bool(self._warmed.is_set())}

    # ---- the pump -----------------------------------------------------

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.cfg.heartbeat_s):
            if self._killed or self.draining:
                return
            self.ledger.heartbeat(self.replica, self.epoch)
            self._maybe_snapshot()

    # ---- fleet-observability snapshots --------------------------------

    _last_snapshot = 0.0

    def _maybe_snapshot(self, force: bool = False,
                        tombstone: bool = False) -> None:
        """Publish this replica's full metrics state atomically into
        `<fleet>/obs/<replica>.json` (paced by snapshot_s; a failure
        is an event, never a dead heartbeat loop)."""
        if self.cfg.snapshot_s <= 0 or not self.service.obs.enabled:
            return
        now = time.time()
        if not force and now - self._last_snapshot \
                < self.cfg.snapshot_s:
            return
        self._last_snapshot = now
        try:
            fleetagg.publish_snapshot(self.cfg.fleetdir,
                                      self.replica,
                                      self.service.obs,
                                      tombstone=tombstone,
                                      interval=self.cfg.snapshot_s)
            self._c_snapshots.inc()
            self.service.obs.event("fleet-obs-snapshot",
                                   replica=self.replica,
                                   tombstone=tombstone)
        except Exception:
            self.service.obs.event("fleet-pump-error")

    def _chaos(self, point: str) -> bool:
        if self.kill_on == point:
            # recorded BEFORE the kill fires — the survey chaos
            # guarantee extended to the fleet seams (incl.
            # batch-leased and fold-fanout): the dump's last record
            # names the kill point
            self.service.obs.event("fleet-chaos-point", point=point)
            self.kill()
            return True
        return False

    def _pump(self) -> None:
        if self.cfg.prewarm:
            try:
                self.service.prewarm()
            finally:
                self._warmed.set()
        else:
            self._warmed.set()
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception:
                # a pump error must not kill the replica; the obs
                # flight recorder carries the traceback
                self.service.obs.event("fleet-pump-error")
            self._stop.wait(self.cfg.poll_s)

    _last_reap = 0.0

    def _tick(self) -> None:
        self._check_inflight()
        # the reaper is idempotent and any replica may run it, but it
        # is a ledger transaction — pace it well under the heartbeat
        # timeout instead of every poll
        now = time.time()
        if now - self._last_reap >= min(1.0,
                                        self.cfg.heartbeat_timeout
                                        / 4.0):
            self._last_reap = now
            report = self.ledger.reap(self.cfg.heartbeat_timeout)
            self.epoch = report.epoch
            self._g_epoch.set(self.epoch)
        leased_any = False
        while (not self.draining and not self._stop.is_set()
               and self._inflight_size() < self.cfg.max_inflight):
            want = min(max(int(self.cfg.lease_batch), 1),
                       self.cfg.max_inflight - self._inflight_size())
            if want > 1:
                # one fenced transaction claims a whole same-bucket
                # batch: the jobs coalesce into one local micro-batch
                # and execute through the stacked executor as one
                # device call (serve/batchexec.py)
                leases = self.ledger.lease_batch(
                    self.replica, self.cfg.lease_ttl, want)
            else:
                lease = self.ledger.lease(self.replica,
                                          self.cfg.lease_ttl)
                leases = [] if lease is None else [lease]
            if not leases:
                break
            leased_any = True
            self._c_leased.inc(len(leases))
            if len(leases) > 1:
                self._c_batchlease.inc()
            for lease in leases:
                self.service.events.emit("job-lease",
                                         job=lease.item_id,
                                         replica=self.replica,
                                         epoch=lease.epoch,
                                         batch=len(leases))
            if self._chaos("job-leased"):
                return
            if len(leases) > 1 and self._chaos("batch-leased"):
                # chaos seam: die holding a whole leased batch — the
                # reaper must re-admit every member exactly once
                return
            admitted = True
            for lease in leases:
                if not self._admit_local(lease):
                    admitted = False
            if not admitted:
                break
        if (not leased_any and self._inflight_size() == 0
                and self.cfg.tune_in_idle and not self.draining
                and not self._stop.is_set()):
            self._idle_tune()

    # ---- idle-capacity tuning ------------------------------------------

    _last_idle_tune = 0.0

    def _idle_tune(self) -> None:
        """One bounded presto-tune budget slice in idle capacity (the
        ROADMAP fleet follow-up, minimal cut): measurements merge-save
        into the fleet's shared tuning DB, so every replica's idle
        time compounds into better execution geometry for all of
        them.  Paced by idle_tune_interval; a failure is an event,
        never a dead pump."""
        now = time.time()
        if now - self._last_idle_tune < self.cfg.idle_tune_interval:
            return
        self._last_idle_tune = now
        try:
            from presto_tpu.apps.tune import run_sweeps
            from presto_tpu.tune.space import resolve
            names = [f.strip()
                     for f in self.cfg.idle_tune_families.split(",")
                     if f.strip()]
            families = resolve(names or None)
            db_path = self.cfg.idle_tune_db or os.path.join(
                os.path.abspath(self.cfg.fleetdir), "tune.json")
            summary = run_sweeps(families, db_path, smoke=True,
                                 budget=self.cfg.idle_tune_budget_s,
                                 k=1, timeout=10.0,
                                 obs=self.service.obs)
            self._c_idletune.inc()
            self.service.events.emit(
                "fleet-idle-tune", replica=self.replica,
                db_records=summary.get("db_records", 0),
                elapsed_s=summary.get("elapsed_s", 0.0),
                budget_exhausted=bool(
                    summary.get("budget_exhausted")))
        except Exception:
            self.service.obs.event("fleet-pump-error")

    def _attempt_dir(self, job_id: str, epoch: int) -> str:
        return os.path.join(self.jobroot, job_id, "a%04d" % epoch)

    def _committed_dir(self, job_id: str) -> str:
        """Absolute path of a DONE parent's committed attempt dir —
        resolved from the fence-landed result.json summary, so a
        child node only ever reads the winning epoch's tree, never a
        zombie's."""
        view = self.ledger.view(job_id)
        if (view is None or view["state"] != "done"
                or not view.get("result")):
            raise RuntimeError("dag parent %s is not committed"
                               % job_id)
        att = view["result"].get("attempt_dir") or "."
        return os.path.join(self.jobroot, job_id, att)

    def _resolve_parents(self, spec: dict) -> Dict[str, object]:
        """spec.parents ({role: job_id | [job_ids]}) resolved to the
        parents' committed attempt dirs (same shape)."""
        out: Dict[str, object] = {}
        for role, val in (spec.get("parents") or {}).items():
            if isinstance(val, (list, tuple)):
                out[role] = [self._committed_dir(v) for v in val]
            else:
                out[role] = self._committed_dir(val)
        return out

    def _admit_local(self, lease) -> bool:
        """Build the leased job into the local queue.  False when the
        local queue refused it (job handed back)."""
        job_id = lease.item_id
        spec = dict(lease.data.get("spec") or {})
        kind = str(spec.get("kind", "survey") or "survey")
        workdir = self._attempt_dir(job_id, lease.epoch)
        try:
            if kind != "survey":
                # DAG node: hand the executor its parents' committed
                # attempt dirs and the ledger row's stack bucket (so
                # same-geometry folds coalesce locally too)
                spec["parent_dirs"] = self._resolve_parents(spec)
                if lease.data.get("bucket"):
                    spec["bucket"] = lease.data["bucket"]
            job = self.service.build_job(spec, job_id=job_id,
                                         workdir=workdir)
            job.priority = int(lease.data.get("priority", 10))
            # resume the submission's trace (stamped at /submit by
            # the router, or at a parent's expand) and carry the
            # lease-grant timestamp for the job_e2e decomposition
            if lease.data.get("trace"):
                job.trace = dict(lease.data["trace"])
            job.leased_at = float(lease.data.get("leased_at")
                                  or 0.0)
            self.service.enqueue_job(job)
        except (QueueFull, QueueClosed):
            self.ledger.fail(lease, self.replica)
            self._c_redone.inc()
            return False
        except Exception as e:
            # unexecutable spec: terminal, not a redo loop
            self.ledger.fail_terminal(lease, self.replica,
                                      "%s: %s" % (type(e).__name__,
                                                  e))
            self._c_failed.inc()
            return True
        with self._inflight_lock:
            self._inflight[job_id] = (lease, job)
            self._g_inflight.set(len(self._inflight))
        self._chaos("job-enqueued")
        if kind == "fold":
            # chaos seam: die holding a leased fold mid-DAG
            self._chaos("mid-fold")
        if kind == "triage":
            # chaos seam: die holding a leased triage node mid-score
            # (the fan-out is never computed; a survivor re-leases
            # the node and scores identically — seeded model)
            self._chaos("mid-triage")
        return True

    def _check_inflight(self) -> None:
        now = time.time()
        with self._inflight_lock:
            items = list(self._inflight.items())
        for job_id, (lease, job) in items:
            if job.status == JobStatus.DONE:
                self._commit(lease, job)
                self._drop(job_id)
            elif job.status in (JobStatus.FAILED, JobStatus.TIMEOUT):
                try:
                    self.ledger.fail_terminal(
                        lease, self.replica, job.error,
                        usage={"phases": self._phases(lease, job,
                                                      now),
                               "replica": self.replica})
                    self._c_failed.inc()
                except self.ledger.STALE:
                    self._c_stale.inc()
                self._drop(job_id)
            elif job.status == JobStatus.PARKED:
                self._drop(job_id)      # _park already re-admitted it
            elif lease.expires - now < self.cfg.lease_ttl / 2.0:
                if self.ledger.renew(lease, self.replica,
                                     self.cfg.lease_ttl):
                    lease.expires = now + self.cfg.lease_ttl
                # a failed renew means the fleet fenced us off; keep
                # running — the commit fence settles it exactly once

    def _drop(self, job_id: str) -> None:
        with self._inflight_lock:
            self._inflight.pop(job_id, None)
            self._g_inflight.set(len(self._inflight))

    def _inflight_size(self) -> int:
        """Locked read of the in-flight count (the pump's lease
        budget and drain's progress test both race the executor's
        _drop without this — found by the lock-guard lint)."""
        with self._inflight_lock:
            return len(self._inflight)

    # ---- commit -------------------------------------------------------

    def _commit(self, lease, job: Job) -> bool:
        """Stage result.json and land it through the ledger fence.
        Returns False when the fence rejected us (zombie commit).

        A DAG node whose result carries a dynamic fan-out
        (``dag_children`` / ``dag_retarget`` — the sift node) commits
        through `JobLedger.complete_and_expand`: the result and the
        child rows land in ONE fenced transaction, so a zombie sift
        expands nothing and a crash can never strand a committed
        sift without its folds."""
        job_dir = os.path.join(self.jobroot, job.job_id)
        os.makedirs(job_dir, exist_ok=True)
        phases = self._phases(lease, job, time.time())
        result = {
            "job_id": job.job_id,
            "replica": self.replica,
            "epoch": int(lease.epoch),
            "attempt_dir": os.path.relpath(job.workdir, job_dir),
            "result": job.result,
            "artifacts": artifact_digests(job.workdir),
        }
        # staged, NOT atomic_open: result.json may only land through
        # the ledger fence (complete/complete_and_expand renames it
        # under the ledger lock after the epoch check) — but the
        # staged bytes are fsync'd here so the fenced rename promotes
        # a durable file, mirroring io/atomic's write discipline
        fd, tmp = tempfile.mkstemp(prefix=".result-", dir=job_dir)
        with os.fdopen(fd, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        final = os.path.join(job_dir, "result.json")
        summary = {"n_artifacts": len(result["artifacts"]),
                   "attempt_dir": result["attempt_dir"],
                   "replica": self.replica}
        children = retarget = None
        if isinstance(job.result, dict):
            children = job.result.get("dag_children")
            retarget = job.result.get("dag_retarget")
        if children or retarget:
            # inherit the graph's tenant/priority onto the fan-out,
            # and the DAG's trace: children parent under THIS node's
            # own span (the sift's folds nest under the sift) or,
            # failing that, the incoming trace context — either way
            # the whole expanded subtree stays in the DAG's one trace
            child_trace = (getattr(job, "span_ctx", None)
                           or lease.data.get("trace"))
            for _cid, fields in children or ():
                fields.setdefault("tenant",
                                  lease.data.get("tenant",
                                                 "default"))
                fields.setdefault("priority",
                                  int(lease.data.get("priority",
                                                     10)))
                if child_trace:
                    fields.setdefault("trace", dict(child_trace))
            if self._chaos("fold-fanout"):
                # chaos seam: die AFTER computing the fan-out but
                # BEFORE the commit transaction — the fan-out is
                # lost with the attempt; a successor redoes the sift
                # and expands identically (idempotence)
                return False
        usage = {"phases": phases,
                 "kind": str((lease.data.get("spec") or {})
                             .get("kind", "survey") or "survey"),
                 "replica": self.replica}
        try:
            if children or retarget:
                self.ledger.complete_and_expand(
                    lease, self.replica, {final: tmp},
                    extra={"result": summary}, children=children,
                    retarget=retarget, usage=usage)
            else:
                self.ledger.complete(lease, self.replica,
                                     {final: tmp},
                                     extra={"result": summary},
                                     usage=usage)
        except self.ledger.STALE:
            self._c_stale.inc()
            self.service.events.emit("stale-result-rejected",
                                     job=job.job_id,
                                     replica=self.replica,
                                     epoch=int(lease.epoch))
            return False
        self._c_committed.inc()
        self._observe_e2e(lease, phases)
        self.service.events.emit("job-done", job=job.job_id,
                                 replica=self.replica,
                                 epoch=int(lease.epoch))
        if children or retarget:
            self.service.events.emit("dag-expand", job=job.job_id,
                                     children=len(children or ()),
                                     replica=self.replica)
            # chaos seam: die right after the fan-out transaction
            # landed — the children exist; survivors lease them
            self._chaos("post-sift-commit")
        return True

    @staticmethod
    def _phases(lease, job: Job, now: float) -> Dict[str, float]:
        """One committed job's life decomposed from ledger/event
        timestamps: admit->lease wait, device execute, commit-prep,
        and total, in seconds — the per-bucket cost model the
        control-plane signals (predictive admission, drain-time
        Retry-After, the /scale advisory) consume.  Computed ONCE per
        commit and fed verbatim to both the usage ledger row and the
        `job_e2e_seconds` histogram, so per-tenant device-seconds
        sums reconcile exactly against the fleet metric aggregation.
        """
        sub = float(lease.data.get("submitted") or 0.0)
        leased = float(getattr(job, "leased_at", 0.0) or 0.0)
        phases: Dict[str, float] = {}
        if sub and leased:
            phases["lease_wait"] = max(leased - sub, 0.0)
        if job.started and job.finished:
            phases["execute"] = max(job.finished - job.started, 0.0)
        if job.finished:
            phases["commit"] = max(now - job.finished, 0.0)
        if sub:
            phases["total"] = max(now - sub, 0.0)
        return phases

    def _observe_e2e(self, lease, phases: Dict[str, float]) -> None:
        """Publish the phase decomposition into the
        `job_e2e_seconds{phase,bucket}` histogram (the snapshot/
        aggregation path to `GET /fleet/metrics`)."""
        bucket = str(lease.data.get("bucket") or "")
        for phase, seconds in phases.items():
            self._h_e2e.labels(phase=phase,
                               bucket=bucket).observe(seconds)

    # ---- shutdown parking ---------------------------------------------

    def _park(self, job: Job) -> bool:
        """Scheduler park seam: a retry that met the closed local
        queue goes back to the ledger as pending — requeueable by any
        replica — instead of stranding as a local failure."""
        with self._inflight_lock:
            entry = self._inflight.get(job.job_id)
        if entry is None:
            return False
        lease, _ = entry
        self.ledger.fail(lease, self.replica)
        self._c_redone.inc()
        self._drop(job.job_id)
        return True
