"""Structured JSON event log for the serving layer.

Every lifecycle transition a job makes (enqueue / schedule / compile /
execute / retry / degrade / complete / fail / timeout) emits one JSON
object, so a trace of the service is greppable the way the batch
driver's artifacts are replayable.  Events go to an in-memory ring
(the /events endpoint) and optionally to an append-only JSON-lines
file — one parseable line per event, never partial writes.
"""

from __future__ import annotations

import json
import threading
import time
from collections import Counter, deque
from typing import Dict, List, Optional


class EventLog:
    """Thread-safe event sink: bounded ring + optional file."""

    def __init__(self, path: Optional[str] = None, keep: int = 4096):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=keep)
        self._counts: Counter = Counter()
        self._seq = 0
        self._path = path
        self._fh = open(path, "a") if path else None

    def emit(self, kind: str, **fields) -> dict:
        """Record one event; returns the event dict (seq/ts stamped)."""
        with self._lock:
            self._seq += 1
            ev = {"seq": self._seq, "ts": time.time(), "kind": kind}
            ev.update(fields)
            self._ring.append(ev)
            self._counts[kind] += 1
            if self._fh is not None:
                self._fh.write(json.dumps(ev, sort_keys=True) + "\n")
                self._fh.flush()
        return ev

    def tail(self, n: int = 100) -> List[dict]:
        with self._lock:
            return list(self._ring)[-n:]

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
