"""Structured JSON event log for the serving layer.

Every lifecycle transition a job makes (enqueue / schedule / compile /
execute / retry / degrade / complete / fail / timeout) emits one JSON
object, so a trace of the service is greppable the way the batch
driver's artifacts are replayable.  Events go to an in-memory ring
(the /events endpoint) and optionally to an append-only JSON-lines
file — one parseable line per event, never partial writes.

Trigger-consumer hardening: every event carries a monotonic `seq`
cursor, `since(cursor)` resumes a reconnecting subscriber from where
it dropped (reporting how many events aged out of the ring if it was
gone too long — lost triggers are *detected*, never silent), and an
optional heartbeat thread emits a periodic `heartbeat` event so a
subscriber can distinguish "no triggers" from "dead service".
"""

from __future__ import annotations

import json
import threading
import time
from collections import Counter, deque
from typing import Dict, List, Optional, Tuple


class EventLog:
    """Thread-safe event sink: bounded ring + optional file."""

    def __init__(self, path: Optional[str] = None, keep: int = 4096):
        self._lock = threading.Lock()  # presto-lint: guards(_ring, _counts, _seq, _fh)
        self._ring: deque = deque(maxlen=keep)
        self._counts: Counter = Counter()
        self._seq = 0
        self._path = path
        self._fh = open(path, "a") if path else None
        self._hb_stop: Optional[threading.Event] = None
        self._hb_thread: Optional[threading.Thread] = None

    def emit(self, kind: str, **fields) -> dict:
        """Record one event; returns the event dict (seq/ts stamped)."""
        with self._lock:
            self._seq += 1
            ev = {"seq": self._seq, "ts": time.time(), "kind": kind}
            ev.update(fields)
            self._ring.append(ev)
            self._counts[kind] += 1
            if self._fh is not None:
                self._fh.write(json.dumps(ev, sort_keys=True) + "\n")
                self._fh.flush()
        return ev

    def tail(self, n: int = 100) -> List[dict]:
        with self._lock:
            return list(self._ring)[-n:]

    def cursor(self) -> int:
        """The latest event's seq (0 before any event): poll /events
        once, remember the cursor, resume with since(cursor)."""
        with self._lock:
            return self._seq

    def since(self, cursor: int,
              limit: int = 1000) -> Tuple[List[dict], int, int]:
        """Events with seq > cursor (oldest first, up to `limit`).

        Returns (events, lost, latest): `lost` counts events that aged
        out of the bounded ring before this resume — zero means the
        subscriber rejoined without losing or duplicating anything;
        nonzero is an explicit gap signal (re-sync from artifacts), not
        a silent skip.  `latest` is the newest seq at read time (the
        next cursor even when `limit` truncates the answer)."""
        cursor = max(int(cursor), 0)
        with self._lock:
            latest = self._seq
            if not self._ring:
                return [], max(latest - cursor, 0), latest
            oldest = self._ring[0]["seq"]
            lost = max(min(oldest - 1, latest) - cursor, 0)
            out = [ev for ev in self._ring if ev["seq"] > cursor]
        return out[:limit], lost, latest

    # -- heartbeat ----------------------------------------------------
    def start_heartbeat(self, interval_s: float) -> None:
        """Emit a `heartbeat` event every interval_s seconds (daemon
        thread; idempotent) so /events subscribers can detect a dead
        service instead of mistaking it for a quiet one."""
        if interval_s <= 0 or self._hb_thread is not None:
            return
        self._hb_stop = threading.Event()
        stop = self._hb_stop

        def beat():
            while not stop.wait(interval_s):
                self.emit("heartbeat", interval_s=interval_s)

        self._hb_thread = threading.Thread(
            target=beat, name="presto-serve-heartbeat", daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        if self._hb_stop is not None:
            self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
        self._hb_stop = None
        self._hb_thread = None

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def close(self) -> None:
        self.stop_heartbeat()
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
