"""Campaign engine: archive-scale reprocessing as ONE durable unit.

The serving tier can execute a discovery DAG exactly-once under
replica churn (PR 11/16), but the survey-archive workload — tens of
thousands of observations x search -> sift -> fold -> timing — had to
be hand-driven as a job firehose nobody could pause, resume, price,
or survive a bad night with.  This module is the tier above the job
ledger that closes that gap: a **campaign** is a manifest of
observations admitted as discovery DAGs in bounded **waves**, with
its own durable ledger, so the fleet processes an archive of any
size with `jobs.json` bounded and a crashed driver resuming from
disk alone.

Ledger (`<fleet>/campaigns/<id>/campaign.json`, atomic +
schema-versioned exactly like supervisor.json): one row per
observation with states

    pending -> admitting -> admitted -> done | failed

**Crash-only wave protocol** (the admit-mark-then-admit_dag dance):

  * the driver durably marks an observation ``admitting`` — with its
    *deterministic* dag id ``<campaign>.<obs>`` — BEFORE calling
    `JobLedger.admit_dag(dag_id=...)`;
  * on restart, an ``admitting`` row whose dag the job ledger does
    not know is simply re-admitted; one whose dag exists is marked
    ``admitted`` — and because `admit_dag` is all-or-nothing and
    raises ``duplicate job_id`` on any replay, a zombie driver's
    second admit can never create a second DAG (the duplicate error
    IS the idempotence signal: "the prior admit landed");
  * completion counting is **fence-checked by construction**: an
    observation settles only from `dag_view`'s terminal state, and a
    DAG node's state only ever becomes ``done`` through the job
    ledger's epoch fence — so a zombie replica (or driver) can never
    double-count.  Settling is idempotent: a terminal row is never
    rewritten.

**Backfill lane**: campaign traffic runs as a low-weight
deficit-WRR tenant (`JobLedger.set_tenant`) declared in
`<fleet>/backfill.json`; every pulse recomputes the live yield
factor from the interactive tenants' burn rates
(`obs/slo.update_backfill_yield`) so the campaign thins out exactly
when a gold tenant is burning error budget — and the supervisor's
``preempt_fraction`` mode (serve/supervisor.py) kills and replaces
campaign-leased replicas at a paced rate, making spot-like
preemption a continuously exercised steady state riding the proven
lease/epoch-fence/re-admit path.

**ETA + cost projection** (`project`): measured device-seconds of
settled observations (usage.jsonl, grouped by dag id) give a
per-observation cost that prices the remaining census; throughput
over the campaign's own elapsed time gives the ETA.  Both converge
to the measured totals as the campaign drains — `presto-report
-campaign` renders the convergence.

Every decision (wave-admit, yield, resume, settle, complete) lands
on a durable per-campaign `campaign_events.jsonl` plus `campaign:*`
spans and `campaign_*` metrics — obs-coverage check 17 pins the
vocabulary.  See docs/SERVING.md ("Campaign engine") and
docs/ROBUSTNESS.md for the failure model.
"""

from __future__ import annotations

import json
import math
import os
import re
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from presto_tpu.io.atomic import atomic_write_text
from presto_tpu.pipeline.leaseledger import _LockDir
from presto_tpu.serve.events import EventLog
from presto_tpu.serve.jobledger import JobLedger, JobLedgerError

CAMPAIGNS_DIR = "campaigns"
LEDGER_NAME = "campaign.json"
EVENTS_NAME = "campaign_events.jsonl"

CAMPAIGN_VERSION = 1

#: observation states in the campaign ledger
OBS_PENDING = "pending"
OBS_ADMITTING = "admitting"   # durably marked; admit_dag may have landed
OBS_ADMITTED = "admitted"     # the DAG exists in jobs.json
OBS_DONE = "done"
OBS_FAILED = "failed"

TERMINAL = (OBS_DONE, OBS_FAILED)

#: smoothing factor for the settle-throughput EWMAs (rate and
#: latency) that size waves — recent pulses dominate, but one noisy
#: settle burst cannot swing the budget by itself
EWMA_ALPHA = 0.3

_ID_RE = re.compile(r"[^A-Za-z0-9_.-]+")


def _safe_id(text: str) -> str:
    return _ID_RE.sub("-", str(text)).strip("-") or "campaign"


def campaigns_root(fleetdir: str) -> str:
    return os.path.join(os.path.abspath(fleetdir), CAMPAIGNS_DIR)


def campaign_dir(fleetdir: str, campaign_id: str) -> str:
    return os.path.join(campaigns_root(fleetdir), _safe_id(campaign_id))


def ledger_path(fleetdir: str, campaign_id: str) -> str:
    return os.path.join(campaign_dir(fleetdir, campaign_id),
                        LEDGER_NAME)


def events_path(fleetdir: str, campaign_id: str) -> str:
    return os.path.join(campaign_dir(fleetdir, campaign_id),
                        EVENTS_NAME)


def list_campaigns(fleetdir: str) -> List[str]:
    """Campaign ids with a readable ledger under this fleet."""
    root = campaigns_root(fleetdir)
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    return [n for n in names
            if os.path.exists(os.path.join(root, n, LEDGER_NAME))]


def load_campaign(fleetdir: str, campaign_id: str) -> Optional[dict]:
    """The persisted campaign ledger (None when absent, unreadable,
    or a foreign schema version — a reader never fails)."""
    try:
        with open(ledger_path(fleetdir, campaign_id)) as f:
            doc = json.load(f)
        if int(doc.get("version", -1)) != CAMPAIGN_VERSION:
            return None
        doc.setdefault("observations", {})
        return doc
    except (OSError, ValueError):
        return None


def fleet_remaining_device_seconds(fleetdir: str,
                                   usage_rows,
                                   now: Optional[float] = None
                                   ) -> float:
    """Every running campaign's projected remaining-archive
    device-seconds, summed — the term the `/scale` advisory folds
    into its backlog so a supervisor sees the whole archive, not just
    the currently-admitted wave (`CampaignDriver.project` is the
    per-campaign version; this is the fleet fold of the same math).

    Pure read: campaign ledgers + the usage rows the caller already
    holds.  A campaign with no settled observation yet is un-priced
    and contributes 0.0 (the admitted wave is still visible to the
    count-based backlog, so nothing is hidden — the projection just
    has no cost model until the first settle lands)."""
    total = 0.0
    for campaign_id in list_campaigns(fleetdir):
        doc = load_campaign(fleetdir, campaign_id)
        if doc is None or doc.get("state") != "running":
            continue
        dags = {str(r.get("dag_id") or ""): obs_id
                for obs_id, r in doc["observations"].items()}
        ds_by_obs: Dict[str, float] = {}
        for urow in usage_rows:
            obs_id = dags.get(str(urow.get("dag") or ""))
            if obs_id is None:
                continue
            ex = float((urow.get("phases") or {}).get("execute")
                       or 0.0)
            ds_by_obs[obs_id] = ds_by_obs.get(obs_id, 0.0) + ex
        settled = [o for o, r in doc["observations"].items()
                   if r["state"] in TERMINAL]
        if not settled:
            continue
        remaining = len(doc["observations"]) - len(settled)
        mean_obs = (sum(ds_by_obs.get(o, 0.0) for o in settled)
                    / len(settled))
        total += mean_obs * remaining
    return total


@dataclass
class CampaignConfig:
    """Knobs of one campaign (persisted into the ledger at create so
    a resumed driver needs nothing but the fleet dir + id)."""
    fleetdir: str
    campaign_id: str
    wave_size: int = 4            # outstanding-DAG ceiling; measured
                                  # settle throughput sizes waves
                                  # below it (see _wave_budget)
    tenant: str = "campaign"      # the backfill lane's tenant name
    weight: float = 0.1           # configured WRR weight (low: backfill)
    priority: int = 50            # worse than interactive default 10
    yield_floor: float = 0.05     # lowest live weight fraction


class SimulatedCrash(BaseException):
    """Injected driver death (BaseException so no handler in the
    driver can accidentally swallow it — mirrors the chaos tests'
    crash model elsewhere in the tree)."""


class CampaignDriver:
    """The campaign control loop over one fleet directory.

    Crash-only: every mutation is load -> mutate -> atomic save under
    a lockdir, every step is idempotent, and `resume()` rebuilds all
    driver state from the ledger alone — killing the driver at ANY
    instant and restarting it loses nothing and duplicates nothing.
    """

    def __init__(self, cfg: CampaignConfig, obs=None,
                 ledger: Optional[JobLedger] = None):
        from presto_tpu.obs import Observability, ObsConfig
        self.cfg = cfg
        self.cfg.campaign_id = _safe_id(cfg.campaign_id)
        self.obs = obs or Observability(
            ObsConfig(enabled=True, service="presto-campaign"))
        self.ledger = ledger or JobLedger(cfg.fleetdir, obs=self.obs)
        self.cdir = campaign_dir(cfg.fleetdir, cfg.campaign_id)
        os.makedirs(self.cdir, exist_ok=True)
        self.events = EventLog(
            path=events_path(cfg.fleetdir, cfg.campaign_id))
        self._lock = _LockDir(os.path.join(self.cdir, ".lock"),
                              timeout=10.0)
        reg = self.obs.metrics
        self._c_waves = reg.counter(
            "campaign_waves_total",
            "Admission waves the campaign driver opened")
        self._c_admitted = reg.counter(
            "campaign_admitted_total",
            "Observations durably admitted as discovery DAGs")
        self._c_settled = reg.counter(
            "campaign_settled_total",
            "Observations settled terminal, by outcome",
            ("state",))
        self._g_outstanding = reg.gauge(
            "campaign_outstanding",
            "Discovery DAGs currently outstanding (admitted, not "
            "yet terminal) — bounded by wave_size at any archive "
            "size")
        self._g_yield = reg.gauge(
            "campaign_yield_factor",
            "Live backfill yield factor (1.0 = full configured "
            "weight; shrinks while interactive tenants burn error "
            "budget)")

    # ---- chaos seam ---------------------------------------------------

    def _seam(self, point: str) -> None:
        """Crash-injection seam (no-op in production; the atomicity
        tests override this to raise SimulatedCrash at wave-admit /
        mid-wave / pre-count-commit)."""

    # ---- ledger persistence -------------------------------------------

    def _load(self) -> dict:  # presto-lint: holds(_lock)
        doc = load_campaign(self.cfg.fleetdir, self.cfg.campaign_id)
        if doc is None:
            raise JobLedgerError(
                "campaign %r has no ledger under %s (create it "
                "first)" % (self.cfg.campaign_id, self.cdir))
        return doc

    def _save(self, doc: dict) -> None:  # presto-lint: holds(_lock)
        atomic_write_text(
            ledger_path(self.cfg.fleetdir, self.cfg.campaign_id),
            json.dumps(doc, indent=1, sort_keys=True) + "\n")

    # ---- creation -----------------------------------------------------

    def create(self, manifest: List[dict],
               now: Optional[float] = None) -> dict:
        """Durably create the campaign from a manifest of observation
        specs (each the POST /dag wire schema: rawfiles + config +
        sift/fold/toa policies, validated through `dag.plan_dag`
        before anything persists).  Registers the backfill tenant
        (low WRR weight + the `backfill.json` declaration the lease
        policy yields through).  Idempotent: re-creating an existing
        campaign returns its ledger untouched — the resume path."""
        from presto_tpu.obs import slo
        from presto_tpu.serve.dag import plan_dag
        now = time.time() if now is None else now
        with self._lock():
            doc = load_campaign(self.cfg.fleetdir,
                                self.cfg.campaign_id)
            if doc is not None:
                return doc
            observations: Dict[str, dict] = {}
            for i, spec in enumerate(manifest):
                spec = dict(spec)
                obs_id = _safe_id(spec.pop("id", None)
                                  or "obs-%06d" % (i + 1))
                if obs_id in observations:
                    raise JobLedgerError(
                        "duplicate observation id %r in manifest"
                        % obs_id)
                plan_dag(spec)          # validate early, fail loudly
                observations[obs_id] = {
                    "spec": spec,
                    "state": OBS_PENDING,
                    "dag_id": "%s.%s" % (self.cfg.campaign_id,
                                         obs_id),
                }
            doc = {
                "version": CAMPAIGN_VERSION,
                "campaign_id": self.cfg.campaign_id,
                "created": now,
                "state": "running",
                "tenant": self.cfg.tenant,
                "priority": int(self.cfg.priority),
                "wave_size": max(int(self.cfg.wave_size), 1),
                "weight": float(self.cfg.weight),
                "yield_floor": float(self.cfg.yield_floor),
                "waves": 0,
                "last_yield": 1.0,
                "observations": observations,
            }
            with self.obs.span("campaign:create",
                               campaign=self.cfg.campaign_id) as span:
                span.set_attr("observations", len(observations))
                self.ledger.set_tenant(self.cfg.tenant,
                                       weight=self.cfg.weight)
                slo.save_backfill(self.cfg.fleetdir,
                                  [self.cfg.tenant],
                                  floor=self.cfg.yield_floor)
                self._save(doc)
        self.events.emit("campaign-create",
                         campaign=self.cfg.campaign_id,
                         observations=len(doc["observations"]),
                         wave_size=doc["wave_size"],
                         tenant=self.cfg.tenant,
                         weight=self.cfg.weight)
        self.obs.event("campaign-create",
                       campaign=self.cfg.campaign_id)
        return doc

    def resume(self, now: Optional[float] = None) -> dict:
        """Announce a driver (re)start over an existing ledger; all
        actual recovery happens inside the next `pulse` (re-admitting
        marked-but-unknown DAGs, settling landed ones) — restart IS
        the normal path, not a special case."""
        now = time.time() if now is None else now
        with self._lock():
            doc = self._load()
        counts = self._counts(doc)
        self.events.emit("campaign-resume",
                         campaign=self.cfg.campaign_id, **counts)
        self.obs.event("campaign-resume",
                       campaign=self.cfg.campaign_id)
        return doc

    # ---- the pulse ----------------------------------------------------

    @staticmethod
    def _counts(doc: dict) -> Dict[str, int]:
        counts = {s: 0 for s in (OBS_PENDING, OBS_ADMITTING,
                                 OBS_ADMITTED, OBS_DONE, OBS_FAILED)}
        for row in doc["observations"].values():
            counts[row["state"]] = counts.get(row["state"], 0) + 1
        return counts

    @staticmethod
    def _outstanding(doc: dict) -> int:
        return sum(1 for r in doc["observations"].values()
                   if r["state"] in (OBS_ADMITTING, OBS_ADMITTED))

    def _plan(self, spec: dict):
        from presto_tpu.serve.dag import plan_dag
        return plan_dag(spec)

    # presto-lint: holds(_lock)
    def _settle(self, doc: dict, now: float) -> List[str]:
        """Fence-checked completion counting: settle every
        outstanding observation whose DAG the job ledger reports
        terminal.  A node's state only becomes done through the
        epoch fence, so this count can never credit a zombie's late
        result; settling is write-once (a terminal row is skipped),
        so a racing second driver can never double-count."""
        settled: List[str] = []
        for obs_id in sorted(doc["observations"]):
            row = doc["observations"][obs_id]
            if row["state"] != OBS_ADMITTED:
                continue
            view = self.ledger.dag_view(row["dag_id"])
            if view is None or view["state"] not in TERMINAL:
                continue
            self._seam("pre-count-commit")
            row["state"] = (OBS_DONE if view["state"] == OBS_DONE
                            else OBS_FAILED)
            row["completed_at"] = now
            row["counts"] = dict(view.get("counts") or {})
            settled.append(obs_id)
        if settled:
            self._observe_settles(doc, settled, now)
            self._save(doc)
        return settled

    # presto-lint: holds(_lock)
    def _observe_settles(self, doc: dict, settled: List[str],
                         now: float) -> None:
        """Fold this pulse's settles into the throughput EWMAs that
        size waves: settle rate (obs/s between settle-bearing pulses)
        and admit→settle latency (s/obs).  Persisted in the campaign
        ledger by the caller's save, so a resumed driver sizes its
        first wave from the dead driver's measurements."""
        last = float(doc.get("last_settle_ts")
                     or doc.get("created", now))
        dt = max(now - last, 1e-6)
        rate_sample = len(settled) / dt
        lat_samples = [
            max(now - float(doc["observations"][o].get("admitted_at")
                            or now), 1e-6)
            for o in settled]
        lat_sample = sum(lat_samples) / len(lat_samples)
        prev_rate = doc.get("ewma_settle_rate")
        prev_lat = doc.get("ewma_settle_latency_s")
        doc["ewma_settle_rate"] = (
            rate_sample if prev_rate is None
            else EWMA_ALPHA * rate_sample
            + (1.0 - EWMA_ALPHA) * float(prev_rate))
        doc["ewma_settle_latency_s"] = (
            lat_sample if prev_lat is None
            else EWMA_ALPHA * lat_sample
            + (1.0 - EWMA_ALPHA) * float(prev_lat))
        doc["last_settle_ts"] = now

    @staticmethod
    def _wave_budget(doc: dict) -> int:
        """The measured wave bound: Little's-law concurrency (settle
        rate × admit→settle latency — the in-flight level the fleet
        actually sustains) rounded up, clamped to [1, wave_size].
        The configured ``wave_size`` constant is the ceiling and the
        pre-measurement default — until the first settle lands there
        is no throughput sample, so the bound starts at the constant
        and adapts from evidence only."""
        cap = max(int(doc["wave_size"]), 1)
        rate = float(doc.get("ewma_settle_rate") or 0.0)
        latency = float(doc.get("ewma_settle_latency_s") or 0.0)
        if rate <= 0.0 or latency <= 0.0:
            return cap
        return min(max(int(math.ceil(rate * latency)), 1), cap)

    # presto-lint: holds(_lock)
    def _admit_wave(self, doc: dict, now: float) -> List[str]:
        """Admit pending observations up to the wave bound.  Each one
        rides the admit-mark-then-admit_dag protocol: the ``admitting``
        mark (with the deterministic dag id) is durable BEFORE
        `admit_dag`, and a replayed admit's ``duplicate job_id`` error
        means the prior call landed — mark admitted, never re-admit."""
        admitted: List[str] = []
        # ``admitting`` rows (a crashed driver's in-flight marks)
        # already count as outstanding, so replaying them never
        # exceeds the wave bound — and they MUST replay even when the
        # budget is full, or a driver killed mid-wave would stall.
        # The bound itself is measured (settle-throughput EWMAs via
        # Little's law), with the wave_size constant as ceiling.
        budget = self._wave_budget(doc) - self._outstanding(doc)
        pending = [o for o in sorted(doc["observations"])
                   if doc["observations"][o]["state"] == OBS_PENDING]
        recovering = [o for o in sorted(doc["observations"])
                      if doc["observations"][o]["state"]
                      == OBS_ADMITTING]
        for obs_id in recovering + pending[:max(budget, 0)]:
            row = doc["observations"][obs_id]
            if row["state"] == OBS_PENDING:
                row["state"] = OBS_ADMITTING
                self._save(doc)          # the durable admit-mark
                self._seam("wave-admit")
            self._admit_one(doc, obs_id, row, now)
            admitted.append(obs_id)
            self._seam("mid-wave")
        return admitted

    # presto-lint: holds(_lock)
    def _admit_one(self, doc: dict, obs_id: str, row: dict,
                   now: float) -> None:
        with self.obs.span("campaign:admit",
                           campaign=self.cfg.campaign_id,
                           observation=obs_id) as span:
            try:
                self.ledger.admit_dag(
                    self._plan(row["spec"]), tenant=doc["tenant"],
                    priority=int(doc["priority"]),
                    dag_id=row["dag_id"], now=now)
            except JobLedgerError as e:
                if "duplicate job_id" not in str(e):
                    raise
                # the prior driver's admit landed before it died —
                # the duplicate error is the idempotence signal
                span.set_attr("replayed", True)
            row["state"] = OBS_ADMITTED
            row["admitted_at"] = now
            self._save(doc)
        self._c_admitted.inc()

    def _update_yield(self, doc: dict,
                      now: float) -> Optional[float]:
        """Recompute the live backfill yield from interactive burn
        and persist it (the lease policy stat-caches backfill.json,
        so the write is the actuation); emits campaign-yield only on
        change, so the event stream records every throttle decision
        without flooding."""
        from presto_tpu.obs import slo
        specs = [s for s in slo.load_specs(self.cfg.fleetdir)
                 if s.tenant != doc["tenant"]]
        rows = self.ledger.usage.rows()
        evals = {s.tenant: slo.evaluate(s, rows, now) for s in specs}
        factor = slo.update_backfill_yield(self.cfg.fleetdir, evals)
        if factor is None:
            return None
        self._g_yield.set(factor)
        if abs(factor - float(doc.get("last_yield", 1.0))) > 1e-9:
            doc["last_yield"] = factor
            self._save(doc)
            self.events.emit(
                "campaign-yield", campaign=self.cfg.campaign_id,
                factor=round(factor, 6),
                burning=sorted(t for t, ev in evals.items()
                               if ev.get("alert")))
            self.obs.event("campaign-yield",
                           campaign=self.cfg.campaign_id)
        return factor

    def pulse(self, now: Optional[float] = None) -> dict:
        """One driver iteration: settle landed DAGs (fence-checked),
        admit the next wave up to the bound, refresh the backfill
        yield, and mark the campaign complete when every observation
        is terminal.  Safe to call from a fresh driver at any time —
        recovery IS this same code path."""
        now = time.time() if now is None else now
        with self.obs.span("campaign:pulse",
                           campaign=self.cfg.campaign_id) as span:
            with self._lock():
                doc = self._load()
                settled = self._settle(doc, now)
                admitted = self._admit_wave(doc, now)
                if admitted:
                    doc["waves"] = int(doc.get("waves", 0)) + 1
                    self._save(doc)
                counts = self._counts(doc)
                outstanding = self._outstanding(doc)
                finished = (doc["state"] == "running"
                            and not outstanding
                            and counts[OBS_PENDING] == 0
                            and counts[OBS_ADMITTING] == 0)
                if finished:
                    doc["state"] = "done"
                    doc["completed"] = now
                    self._save(doc)
            span.set_attr("settled", len(settled))
            span.set_attr("admitted", len(admitted))
        for obs_id in settled:
            row = doc["observations"][obs_id]
            self._c_settled.labels(state=row["state"]).inc()
            fields = dict(campaign=self.cfg.campaign_id,
                          observation=obs_id, dag=row["dag_id"],
                          counts=row.get("counts", {}))
            if row["state"] == OBS_DONE:
                self.events.emit("campaign-obs-done", **fields)
                self.obs.event("campaign-obs-done",
                               campaign=self.cfg.campaign_id)
            else:
                self.events.emit("campaign-obs-failed", **fields)
                self.obs.event("campaign-obs-failed",
                               campaign=self.cfg.campaign_id)
        if admitted:
            self._c_waves.inc()
            self.events.emit("campaign-wave-admit",
                             campaign=self.cfg.campaign_id,
                             wave=int(doc.get("waves", 0)),
                             observations=admitted,
                             outstanding=self._outstanding(doc),
                             wave_budget=self._wave_budget(doc))
            self.obs.event("campaign-wave-admit",
                           campaign=self.cfg.campaign_id)
        self._update_yield(doc, now)
        self._g_outstanding.set(self._outstanding(doc))
        if doc["state"] == "done" and (settled or admitted
                                       or "completed" in doc
                                       and doc["completed"] == now):
            counts = self._counts(doc)
            self.events.emit("campaign-complete",
                             campaign=self.cfg.campaign_id,
                             done=counts[OBS_DONE],
                             failed=counts[OBS_FAILED],
                             waves=int(doc.get("waves", 0)))
            self.obs.event("campaign-complete",
                           campaign=self.cfg.campaign_id)
        return self.status(doc=doc, now=now)

    def run(self, poll_s: float = 0.5,
            timeout: Optional[float] = None) -> dict:
        """Pulse until the campaign is terminal (or the timeout
        expires); returns the final status."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            status = self.pulse()
            if status["state"] != "running":
                return status
            if deadline is not None and time.time() > deadline:
                return status
            time.sleep(poll_s)

    # ---- introspection ------------------------------------------------

    def status(self, doc: Optional[dict] = None,
               now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        doc = doc or load_campaign(self.cfg.fleetdir,
                                   self.cfg.campaign_id)
        if doc is None:
            return {"campaign_id": self.cfg.campaign_id,
                    "state": "absent"}
        counts = self._counts(doc)
        return {
            "campaign_id": doc["campaign_id"],
            "state": doc["state"],
            "tenant": doc["tenant"],
            "wave_size": doc["wave_size"],
            "wave_budget": self._wave_budget(doc),
            "ewma_settle_rate": doc.get("ewma_settle_rate"),
            "ewma_settle_latency_s": doc.get(
                "ewma_settle_latency_s"),
            "waves": int(doc.get("waves", 0)),
            "observations": len(doc["observations"]),
            "counts": counts,
            "outstanding": self._outstanding(doc),
            "yield": float(doc.get("last_yield", 1.0)),
            "projection": self.project(doc, now=now),
        }

    def project(self, doc: Optional[dict] = None,
                now: Optional[float] = None) -> dict:
        """Live ETA + cost projection from measured telemetry alone:
        settled observations' device-seconds (usage.jsonl rows
        grouped by this campaign's dag ids) price the remaining
        census, and settle throughput over the campaign's elapsed
        time gives the ETA.  Converges to the measured total as the
        archive drains — zero projected remainder when done."""
        now = time.time() if now is None else now
        doc = doc or load_campaign(self.cfg.fleetdir,
                                   self.cfg.campaign_id)
        if doc is None:
            return {}
        dags = {r["dag_id"]: obs_id
                for obs_id, r in doc["observations"].items()}
        ds_by_obs: Dict[str, float] = {}
        for urow in self.ledger.usage.rows():
            obs_id = dags.get(str(urow.get("dag") or ""))
            if obs_id is None:
                continue
            ex = float((urow.get("phases") or {}).get("execute")
                       or 0.0)
            ds_by_obs[obs_id] = ds_by_obs.get(obs_id, 0.0) + ex
        settled = [o for o, r in doc["observations"].items()
                   if r["state"] in TERMINAL]
        remaining = (len(doc["observations"]) - len(settled))
        ds_settled = sum(ds_by_obs.get(o, 0.0) for o in settled)
        mean_obs = (ds_settled / len(settled)) if settled else None
        remaining_ds = (mean_obs * remaining
                        if mean_obs is not None else None)
        elapsed = max(now - float(doc.get("created", now)), 1e-9)
        rate = len(settled) / elapsed        # observations per second
        eta_s = (remaining / rate) if rate > 0 and remaining else (
            0.0 if not remaining else None)
        total = (ds_settled + remaining_ds
                 if remaining_ds is not None else None)
        return {
            "settled": len(settled),
            "remaining": remaining,
            "device_seconds_settled": round(ds_settled, 6),
            "mean_obs_device_seconds": (
                None if mean_obs is None else round(mean_obs, 6)),
            "remaining_device_seconds": (
                None if remaining_ds is None
                else round(remaining_ds, 6)),
            "projected_total_device_seconds": (
                None if total is None else round(total, 6)),
            "throughput_obs_per_s": round(rate, 6),
            "eta_s": None if eta_s is None else round(eta_s, 3),
        }

    def close(self) -> None:
        self.events.close()
