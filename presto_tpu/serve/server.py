"""SearchService + threaded HTTP front end (serve layer).

`SearchService` is the composition root: it owns the bounded queue,
the plan cache, the event log, the latency accounting, and the
micro-batching scheduler, and executes each job as one restartable
`pipeline.survey.run_survey` in the job's own workdir — so every
serving result is byte-identical to what the batch driver would have
written, and a crashed service resumes from the artifacts.

The wire protocol is plain HTTP + JSON over stdlib `http.server`
(ThreadingHTTPServer; one thread per connection, the scheduler thread
does the device work):

  POST /submit            {"rawfiles": [...], "config": {...},
                           "priority": int}      -> 202 {job_id, ...}
                          429 when the queue applies backpressure
  GET  /jobs/<id>         job status snapshot
  GET  /jobs/<id>/result  terminal result payload (409 until terminal)
  GET  /healthz           liveness: queue + scheduler state
  GET  /readyz            readiness: draining / plan-cache warm
                          fraction / fleet lease state (503 while a
                          router should route around this replica)
  GET  /metrics           queue/scheduler/plan-cache/latency snapshot
  GET  /events?n=100      tail of the structured event log

See docs/SERVING.md for the full schema.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from dataclasses import fields as dataclass_fields
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import urlparse, parse_qs

from presto_tpu.serve.events import EventLog
from presto_tpu.serve.plancache import (PlanCache, PlanStore,
                                        SearcherProvider, bucket_key)
from presto_tpu.serve.queue import (Job, JobQueue, JobStatus,
                                    QueueClosed, QueueFull)
from presto_tpu.serve.scheduler import Scheduler, SchedulerConfig
from presto_tpu.utils.timing import LatencyStats, StageTimer


class BadRequest(ValueError):
    """Malformed submission (HTTP 400)."""


def _allowed_config_fields():
    """SurveyConfig fields settable over the wire: everything except
    object-valued hooks (plan_provider/sift_policy/fault_injector/obs
    are in-process only)."""
    from presto_tpu.pipeline.survey import SurveyConfig
    blocked = {"plan_provider", "sift_policy", "fault_injector",
               "obs"}
    return {f.name for f in dataclass_fields(SurveyConfig)
            if f.name not in blocked}


class SearchService:
    """The always-on search service (in-process API; server-agnostic).
    """

    def __init__(self, workroot: str, queue_depth: int = 64,
                 plan_capacity: int = 32,
                 scheduler_cfg: Optional[SchedulerConfig] = None,
                 events_path: Optional[str] = None, mesh=None,
                 max_retry_depth: Optional[int] = 8, obs=None,
                 obs_config=None, heartbeat_s: float = 0.0,
                 plan_store_dir: Optional[str] = None,
                 stacked: Optional[bool] = None):
        from presto_tpu.obs import Observability, ObsConfig
        os.makedirs(workroot, exist_ok=True)
        self.workroot = os.path.abspath(workroot)
        # a resident service is always observed (a server without
        # /metrics is blind); pass `obs`/`obs_config` to share or tune
        # the handle — e.g. a trace_dir for span export
        self.obs = obs or Observability(
            obs_config or ObsConfig(enabled=True,
                                    service="presto-serve"))
        self.events = EventLog(path=events_path)
        if heartbeat_s > 0:
            self.events.start_heartbeat(heartbeat_s)
        self.latency = LatencyStats(registry=self.obs.metrics)
        self.queue = JobQueue(maxdepth=queue_depth,
                              max_retry_depth=max_retry_depth)
        self.plans = PlanCache(capacity=plan_capacity,
                               events=self.events, obs=self.obs)
        # persistent compiled-plan tier: with a store dir configured,
        # JAX's compilation cache persists executables under the
        # device fingerprint and every plan built is recorded for
        # cold-replica prewarm (docs/SERVING.md, warm-start)
        self.plan_store: Optional[PlanStore] = None
        if plan_store_dir:
            self.plan_store = PlanStore(plan_store_dir, obs=self.obs)
            self.plan_store.enable()
        self.provider = SearcherProvider(self.plans, mesh=mesh,
                                         store=self.plan_store)
        self.scheduler = Scheduler(self.queue, self._execute_job,
                                   cfg=scheduler_cfg,
                                   events=self.events,
                                   latency=self.latency,
                                   obs=self.obs, plans=self.plans)
        # cross-job stacked batch execution (serve/batchexec.py):
        # the DEFAULT executor — a coalesced same-bucket batch runs
        # its device chain as one stacked dispatch set, degrading to
        # the per-job loop on any incompatibility or failure.  Off
        # when the subclass overrides job execution (the stub-executor
        # test services), via stacked=False, or PRESTO_TPU_STACKED=0.
        if stacked is None:
            stacked = (os.environ.get("PRESTO_TPU_STACKED", "1")
                       != "0"
                       and type(self)._execute_job
                       is SearchService._execute_job)
        self.stacked = bool(stacked)
        if self.stacked:
            from presto_tpu.serve.batchexec import StackedBatchExecutor
            self.scheduler.batch_executor = StackedBatchExecutor(self)
        self._jobs: Dict[str, Job] = {}
        self._jobs_lock = threading.Lock()  # presto-lint: guards(_jobs)
        self._ids = itertools.count(1)
        self._t0 = time.time()
        self.draining = False
        #: set by serve/fleet.FleetReplica when this service is a
        #: fleet member (readiness then reports the lease state)
        self.fleet = None

    # ---- lifecycle ----------------------------------------------------

    def start(self) -> "SearchService":
        self.scheduler.start()
        return self

    def stop(self) -> None:
        self.queue.close()
        self.scheduler.stop()
        self.events.close()
        self.obs.flush()
        self.obs.tracer.close()

    def shutdown(self, drain: bool = True,
                 timeout: float = 60.0) -> dict:
        """Graceful termination (the SIGTERM path): flip readiness off,
        drain in-flight and queued jobs, hand the fleet leases back
        (drained jobs commit; undrained ones are released for another
        replica), then stop.  Returns a small shutdown report."""
        self.draining = True
        report = {"drained": True, "parked": 0, "released": 0}
        if self.fleet is not None:
            # fleet drain owns the full sequence: stop leasing, wait
            # out in-flight work, release/park leftovers, tombstone
            report.update(self.fleet.drain(timeout=timeout))
        elif drain:
            report["drained"] = self.scheduler.drain(timeout=timeout)
        self.stop()
        return report

    # ---- plan warm-up --------------------------------------------------

    def prewarm(self, limit: Optional[int] = None) -> int:
        """Rebuild the persistent tier's recorded plans into the
        in-memory cache (no-op without a plan store)."""
        return self.provider.prewarm(limit=limit)

    def warm_fraction(self) -> float:
        """Persistently-known plans resident in memory (1.0 without a
        store: nothing to wait for)."""
        if self.plan_store is None:
            return 1.0
        return self.plan_store.warm_fraction(self.plans)

    # ---- job admission ------------------------------------------------

    def build_job(self, spec: dict, job_id: Optional[str] = None,
                  workdir: Optional[str] = None) -> Job:
        """Validate one submission spec into a Job (not yet queued).
        spec:

          rawfiles  [str, ...]  (required; must exist)
          config    {SurveyConfig field: value}   (optional)
          priority  int (optional; lower runs first)
          job_id    str (optional; must be unique)

        Raises BadRequest on malformed specs.  `job_id`/`workdir`
        override the spec (the fleet replica pins both to the ledger
        job id and its epoch-stamped attempt directory).

        Discovery-DAG node specs (`spec.kind` of sift/fold/toa) are
        validated by serve/dag.build_node_job instead — they carry no
        rawfiles; their inputs are parent nodes' committed attempt
        dirs."""
        from presto_tpu.pipeline.survey import SurveyConfig
        if not isinstance(spec, dict):
            raise BadRequest("spec must be a JSON object")
        if str(spec.get("kind", "survey") or "survey") != "survey":
            from presto_tpu.serve.dag import build_node_job
            return build_node_job(self, spec, job_id=job_id,
                                  workdir=workdir)
        rawfiles = spec.get("rawfiles")
        if not rawfiles or not isinstance(rawfiles, (list, tuple)):
            raise BadRequest("spec.rawfiles must be a non-empty list")
        rawfiles = [os.path.abspath(str(f)) for f in rawfiles]
        missing = [f for f in rawfiles if not os.path.exists(f)]
        if missing:
            raise BadRequest("rawfiles not found: %s" % missing)
        cfg_dict = spec.get("config") or {}
        allowed = _allowed_config_fields()
        unknown = set(cfg_dict) - allowed
        if unknown:
            raise BadRequest("unknown config fields: %s"
                             % sorted(unknown))
        cfg = SurveyConfig(**cfg_dict)
        cfg.plan_provider = self.provider
        cfg.obs = self.obs          # job telemetry -> service registry
        if "durable_stages" not in cfg_dict:
            # serve jobs default to the fused tier: stages hand device
            # arrays across the in-memory seam under the shared plan
            # cache, skipping the .dat/.fft disk round-trips.  A job
            # that fails and retries is flipped back to the durable
            # tier by the scheduler (resume-critical); clients can pin
            # either tier via config.durable_stages.
            cfg.durable_stages = False
        job_id = str(job_id or spec.get("job_id")
                     or "job-%06d" % next(self._ids))
        with self._jobs_lock:
            old = self._jobs.get(job_id)
            if old is not None and old.status not in JobStatus.SETTLED:
                raise BadRequest("duplicate job_id %r" % job_id)
        try:
            bucket = bucket_key(rawfiles, cfg)
        except Exception as e:
            raise BadRequest("unreadable observation header: %s" % e)
        return Job(job_id=job_id, rawfiles=rawfiles, cfg=cfg,
                   workdir=workdir or os.path.join(self.workroot,
                                                   job_id),
                   priority=int(spec.get("priority", 10)),
                   bucket=bucket, spec=dict(spec))

    def enqueue_job(self, job: Job) -> dict:
        """Admit a built Job into the local queue (may raise
        QueueFull / QueueClosed) and register it for /jobs lookup."""
        self.queue.submit(job)
        with self._jobs_lock:
            self._jobs[job.job_id] = job
        self.events.emit("enqueue", job=job.job_id,
                         bucket=repr(job.bucket),
                         priority=job.priority,
                         depth=len(self.queue))
        return job.view()

    def submit(self, spec: dict) -> dict:
        """Admit one search job (build + enqueue).  Raises BadRequest
        on malformed specs, QueueFull under backpressure.  Returns
        the job's status view."""
        if self.draining:
            raise QueueClosed("service is draining")
        return self.enqueue_job(self.build_job(spec))

    def submit_callable(self, fn, job_id: Optional[str] = None,
                        lane: str = "deadline", priority: int = 0,
                        bucket=None) -> Job:
        """Admit an in-process callable job (the streaming tick):
        `fn(job)` runs on the scheduler thread in lane order.  Deadline
        -lane callables bypass the depth bound — they are self-bounded
        by their submitter (at most one outstanding tick per stream),
        and shedding them behind a throughput backlog is exactly the
        SLO inversion the lane exists to prevent."""
        job = Job(job_id=job_id or "call-%06d" % next(self._ids),
                  rawfiles=[], cfg=None, workdir=self.workroot,
                  priority=priority, bucket=bucket, lane=lane, run=fn)
        self.queue.submit(job, force=(lane == "deadline"))
        self.events.emit("enqueue", job=job.job_id, lane=lane,
                         bucket=repr(bucket), priority=priority,
                         depth=len(self.queue))
        return job

    # ---- job execution (scheduler thread) -----------------------------

    def _execute_job(self, job: Job) -> dict:
        """Run one job as a restartable survey in its own workdir,
        feeding the shared per-stage latency percentiles.  DAG node
        jobs (sift/fold/toa) dispatch to their serve/dag executors."""
        if job.run is not None:
            return job.run(job) or {}
        if getattr(job, "kind", "survey") != "survey":
            from presto_tpu.serve.dag import execute_node
            return execute_node(self, job)
        from presto_tpu.pipeline.survey import run_survey
        timer = StageTimer(stats=self.latency, obs=self.obs)
        res = run_survey(job.rawfiles, job.cfg, workdir=job.workdir,
                         timer=timer)
        return {
            "workdir": res.workdir,
            "candfile": res.candfile,
            "n_datfiles": len(res.datfiles),
            "n_cands": (len(res.sifted) if res.sifted is not None
                        else 0),
            "folded": list(res.folded),
            "sp_events": res.sp_events,
            "stage_seconds": {k: round(v, 4)
                              for k, v in timer.stages.items()},
        }

    # ---- introspection ------------------------------------------------

    def get_job(self, job_id: str) -> Optional[Job]:
        with self._jobs_lock:
            return self._jobs.get(job_id)

    def status(self, job_id: str) -> Optional[dict]:
        job = self.get_job(job_id)
        return None if job is None else job.view()

    def result(self, job_id: str) -> Optional[dict]:
        job = self.get_job(job_id)
        if job is None:
            return None
        view = job.view()
        view["result"] = job.result
        return view

    def wait(self, job_ids, timeout: float = 300.0,
             poll: float = 0.05) -> bool:
        """Block until every listed job is terminal (True) or the
        timeout lapses (False).  In-process convenience for tests and
        the load generator."""
        if isinstance(job_ids, str):
            job_ids = [job_ids]
        deadline = time.time() + timeout
        while time.time() < deadline:
            jobs = [self.get_job(j) for j in job_ids]
            if all(j is not None and j.status in JobStatus.TERMINAL
                   for j in jobs):
                return True
            time.sleep(poll)
        return False

    def healthz(self) -> dict:
        """Liveness: is the process worth keeping alive?  True while
        the scheduler loop runs — even when draining or cold (those
        are *readiness* conditions; restarting a draining replica
        would lose the drain)."""
        return {
            "ok": bool(self.scheduler.alive),
            "uptime_s": round(time.time() - self._t0, 3),
            "queue_depth": len(self.queue),
            "scheduler_alive": self.scheduler.alive,
        }

    def readyz(self) -> dict:
        """Readiness: should a router send this replica work?  False
        while draining (shutdown in progress), dead, or cold (the
        persistent plan tier knows plans this process has not warmed
        yet) — the router keeps routing *around* it without killing
        it.  Reports the fleet lease state, plan-cache warm fraction,
        and queue depth so the router's decision is observable."""
        warm = self.warm_fraction()
        ready = bool(self.scheduler.alive) and not self.draining
        out = {
            "ready": ready,
            "draining": bool(self.draining),
            "scheduler_alive": bool(self.scheduler.alive),
            "plan_warm_fraction": round(warm, 4),
            "plan_store": (None if self.plan_store is None else {
                "supported": self.plan_store.supported,
                "known_plans": len(self.plan_store.known()),
                "xla_entries": self.plan_store.xla_entries(),
            }),
            "queue_depth": len(self.queue),
            "queue_capacity": self.queue.maxdepth,
            "lease": (None if self.fleet is None
                      else self.fleet.lease_state()),
        }
        return out

    def metrics(self) -> dict:
        """The pre-obs JSON metrics shape, unchanged for backward
        compat — every number now reads off the shared registry."""
        with self._jobs_lock:
            by_status: Dict[str, int] = {}
            for job in self._jobs.values():
                by_status[job.status] = by_status.get(job.status, 0) + 1
        from presto_tpu.obs import costmodel
        return {
            "uptime_s": round(time.time() - self._t0, 3),
            "queue": {"depth": len(self.queue),
                      "capacity": self.queue.maxdepth},
            "jobs": by_status,
            "scheduler": self.scheduler.stats(),
            "plans": self.plans.stats(),
            "latency": self.latency.snapshot(),
            "events": self.events.counts(),
            # per-kind silicon cost (obs/costmodel): {} until a
            # dispatch site harvested its unit cost; the labeled
            # kernel_* counters underneath ride the fleet snapshot
            # aggregation like every other registry series
            "kernel_costs": costmodel.snapshot(self.obs),
        }

    def metrics_prometheus(self) -> str:
        """Prometheus text exposition of the shared registry (the
        `Accept: text/plain` answer of GET /metrics).  Scrape-time
        gauges (queue depth, uptime, jobs by status) are refreshed
        here so the pull model sees current values."""
        reg = self.obs.metrics
        reg.gauge("serve_uptime_seconds",
                  "Service uptime").set(time.time() - self._t0)
        reg.gauge("serve_queue_depth",
                  "Queued jobs").set(len(self.queue))
        reg.gauge("serve_queue_capacity",
                  "Queue depth bound").set(self.queue.maxdepth)
        jobs_g = reg.gauge("serve_jobs", "Jobs by lifecycle status",
                           ("status",))
        with self._jobs_lock:
            by_status: Dict[str, int] = {}
            for job in self._jobs.values():
                by_status[job.status] = by_status.get(job.status, 0) + 1
        from presto_tpu.serve.queue import JobStatus as _JS
        for status in (_JS.QUEUED, _JS.SCHEDULED, _JS.RUNNING,
                       _JS.RETRY_WAIT, _JS.PARKED, _JS.DONE,
                       _JS.FAILED, _JS.TIMEOUT):
            jobs_g.labels(status=status).set(by_status.get(status, 0))
        return reg.render_prometheus()


# ----------------------------------------------------------------------
# HTTP front end
# ----------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def service(self) -> SearchService:
        return self.server.service        # type: ignore[attr-defined]

    def log_message(self, fmt, *args):    # route access logs to events
        self.service.events.emit("http", line=fmt % args)

    def _json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload, sort_keys=True).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, code: int, text: str,
              ctype: str = "text/plain; version=0.0.4") -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _wants_prometheus(self, url) -> bool:
        """Content negotiation for /metrics: Prometheus scrapers send
        `Accept: text/plain` (or the openmetrics type); humans and the
        pre-obs JSON consumers get the JSON shape.  `?format=` forces
        either way."""
        fmt = parse_qs(url.query).get("format", [""])[0]
        if fmt in ("prometheus", "text"):
            return True
        if fmt == "json":
            return False
        accept = self.headers.get("Accept", "") or ""
        return ("text/plain" in accept
                or "openmetrics-text" in accept)

    def do_GET(self) -> None:
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        try:
            if url.path == "/healthz":
                h = self.service.healthz()
                self._json(200 if h["ok"] else 503, h)
            elif url.path == "/readyz":
                r = self.service.readyz()
                self._json(200 if r["ready"] else 503, r)
            elif url.path == "/metrics":
                if self._wants_prometheus(url):
                    self._text(200, self.service.metrics_prometheus())
                else:
                    self._json(200, self.service.metrics())
            elif url.path == "/events":
                q = parse_qs(url.query)
                n = int(q.get("n", ["100"])[0])
                log = self.service.events
                if "since" in q:
                    # resume-from-cursor: a reconnecting trigger
                    # consumer passes its last seen seq and gets every
                    # later event exactly once; `lost` > 0 flags events
                    # that aged out of the ring while it was gone
                    evs, lost, latest = log.since(
                        int(q["since"][0]), limit=n)
                    self._json(200, {"events": evs, "lost": lost,
                                     "cursor": latest})
                else:
                    evs = log.tail(n)
                    self._json(200, {"events": evs,
                                     "cursor": log.cursor()})
            elif len(parts) == 2 and parts[0] == "jobs":
                view = self.service.status(parts[1])
                if view is None:
                    self._json(404, {"error": "no such job"})
                else:
                    self._json(200, view)
            elif (len(parts) == 3 and parts[0] == "jobs"
                  and parts[2] == "result"):
                view = self.service.result(parts[1])
                if view is None:
                    self._json(404, {"error": "no such job"})
                elif view["status"] not in JobStatus.TERMINAL:
                    self._json(409, {"error": "job not finished",
                                     "status": view["status"]})
                else:
                    self._json(200, view)
            else:
                self._json(404, {"error": "unknown endpoint"})
        except Exception as e:
            self._json(500, {"error": "%s: %s" % (type(e).__name__,
                                                  e)})

    def do_POST(self) -> None:
        if urlparse(self.path).path != "/submit":
            self._json(404, {"error": "unknown endpoint"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            spec = json.loads(self.rfile.read(length) or b"{}")
            self._json(202, self.service.submit(spec))
        except BadRequest as e:
            self._json(400, {"error": str(e)})
        except QueueFull as e:
            self._json(429, {"error": str(e)})
        except QueueClosed as e:
            self._json(503, {"error": str(e)})
        except json.JSONDecodeError as e:
            self._json(400, {"error": "bad JSON: %s" % e})
        except Exception as e:
            self._json(500, {"error": "%s: %s" % (type(e).__name__,
                                                  e)})


class ServeHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, service: SearchService):
        super().__init__(addr, _Handler)
        self.service = service


def start_http(service: SearchService, host: str = "127.0.0.1",
               port: int = 0) -> ServeHTTPServer:
    """Bind + serve in a daemon thread; returns the server (its
    .server_address carries the bound port — port=0 picks a free one,
    the test/loadgen pattern)."""
    httpd = ServeHTTPServer((host, port), service)
    t = threading.Thread(target=httpd.serve_forever,
                         name="presto-serve-http", daemon=True)
    t.start()
    return httpd
