"""Durable per-tenant usage ledger: `<fleet>/usage.jsonl`.

The fleet's decision signals (per-tenant SLO debt, the `/scale`
advisory, device-seconds admission) need an accounting record that
survives replica death and router restarts — a registry counter dies
with its process and a snapshot is only as old as its publisher.  So
every **fence-checked** terminal ledger transition appends one row
here (serve/jobledger.py calls `append` right after the commit
landed): the job's tenant, plan bucket, DAG id, terminal state, and
the admit→lease-wait→execute→commit phase decomposition in seconds.
The `execute` phase IS the device-seconds metering — the same float
the committing replica observes into `job_e2e_seconds{phase,bucket}`,
so per-tenant usage sums reconcile exactly against the fleet metric
aggregation.

Crash model (the append-only twin of `io/atomic`):

  * one row = one complete JSON line written in a SINGLE ``os.write``
    on an ``O_APPEND`` fd, fsync'd before the append returns —
    concurrent replicas interleave whole lines, never bytes (a tiny
    lockdir serializes writers across processes anyway);
  * a crash mid-append can at worst leave a torn FINAL line with no
    trailing newline.  Readers skip it (`rows` accepts only complete,
    parseable lines) and the next writer truncates it away before
    appending (`_repair`), so the ledger is always parseable and
    never contains a partial row;
  * double counting is fenced out: the append happens strictly
    AFTER the epoch-fence check inside the job ledger's commit
    transaction (and before the ledger state flips, so a job the
    fleet observes as terminal has always been metered) — a fenced
    zombie replica never reaches it.  The one residual case, a crash
    between the append and the ledger save, re-admits the job and
    the redo's row supersedes: `rows()` dedups by ``job_id``, last
    row wins.

`PRESTO_TPU_USAGE=0` disables metering entirely (the byte-equality
reference arm of tools/serve_loadgen.py -slo); artifacts are
identical either way — usage is bookkeeping about jobs, never part of
the data path.
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Dict, List, Optional

from presto_tpu.io.atomic import atomic_write_bytes
from presto_tpu.pipeline.leaseledger import _LockDir

USAGE_NAME = "usage.jsonl"


def usage_path(fleetdir: str) -> str:
    return os.path.join(os.path.abspath(fleetdir), USAGE_NAME)


class UsageLedger:
    """Append-only, crash-tolerant JSONL usage journal."""

    def __init__(self, fleetdir: str,
                 enabled: Optional[bool] = None):
        self.path = usage_path(fleetdir)
        if enabled is None:
            enabled = os.environ.get("PRESTO_TPU_USAGE", "1") != "0"
        self.enabled = bool(enabled)
        self._lock = _LockDir(self.path + ".lock", timeout=10.0)
        # offset-checkpointed read state: (inode, byte offset) of the
        # consumed complete-line prefix plus its parsed rows, so a
        # campaign-scale ledger is parsed O(new rows) per read, not
        # O(ledger).  A compaction (os.replace -> new inode) or a
        # truncation beneath the checkpoint resets to a full reread.
        self._ckpt: Optional[tuple] = None
        self._raw: List[dict] = []
        self._dedup_byid: Dict[str, int] = {}
        self._dedup_rows: List[dict] = []

    # -- writing --------------------------------------------------------

    @staticmethod
    def _write(fd: int, data: bytes) -> None:
        """The single-syscall append (seam: the chaos tests replace
        this with a torn write + SimulatedCrash)."""
        os.write(fd, data)

    def _repair(self, fd: int) -> int:
        """Truncate a torn final line (a predecessor died mid-append)
        so the file ends at a row boundary.  Returns bytes dropped."""
        size = os.fstat(fd).st_size
        if size == 0:
            return 0
        os.lseek(fd, size - 1, os.SEEK_SET)
        if os.read(fd, 1) == b"\n":
            return 0
        # walk back to the last complete row
        keep = 0
        os.lseek(fd, 0, os.SEEK_SET)
        data = os.read(fd, size)
        nl = data.rfind(b"\n")
        keep = nl + 1 if nl >= 0 else 0
        os.ftruncate(fd, keep)
        return size - keep

    def append(self, row: Dict) -> Optional[str]:
        """Durably append one usage row; returns the ledger path
        (None when metering is disabled)."""
        if not self.enabled:
            return None
        data = (json.dumps(row, sort_keys=True) + "\n").encode()
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        with self._lock():
            fd = os.open(self.path,
                         os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                self._repair(fd)
                self._write(fd, data)
                os.fsync(fd)
            finally:
                with contextlib.suppress(OSError):
                    os.close(fd)
        return self.path

    # -- compaction -----------------------------------------------------

    def compact(self) -> int:
        """Rewrite the ledger as its deduplicated row set (one line
        per surviving job_id, last row wins) via an atomic same-dir
        replace under the writer lock.  Superseded redo rows — the
        only rows dedup ever drops — are garbage a campaign-scale
        ledger accretes under churn; dropping them changes no reader's
        view (`rows()` is byte-for-byte the same before and after).
        Returns the number of rows dropped.  A torn final line is
        repaired first, exactly as a writer would, so torn-tail
        semantics are unchanged."""
        try:
            st = os.stat(self.path)
        except OSError:
            return 0
        if st.st_size == 0:
            return 0
        with self._lock():
            fd = os.open(self.path, os.O_RDWR, 0o644)
            try:
                self._repair(fd)
                os.lseek(fd, 0, os.SEEK_SET)
                data = os.read(fd, os.fstat(fd).st_size)
            finally:
                with contextlib.suppress(OSError):
                    os.close(fd)
            raw = self._parse(data)
            kept = self._dedup(raw)
            if len(kept) == len(raw):
                return 0
            out = b"".join(
                json.dumps(rec, sort_keys=True).encode() + b"\n"
                for rec in kept)
            atomic_write_bytes(self.path, out)
        self._reset_cache()
        return len(raw) - len(kept)

    # -- reading --------------------------------------------------------

    @staticmethod
    def _parse(data: bytes) -> List[dict]:
        out: List[dict] = []
        for line in data.split(b"\n"):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out

    @staticmethod
    def _dedup(raw: List[dict]) -> List[dict]:
        byid: Dict[str, int] = {}
        out: List[dict] = []
        for rec in raw:
            jid = rec.get("job_id")
            if jid is None:
                out.append(rec)
                continue
            if jid in byid:
                out[byid[jid]] = rec
            else:
                byid[jid] = len(out)
                out.append(rec)
        return out

    def _reset_cache(self) -> None:
        self._ckpt = None
        self._raw = []
        self._dedup_byid = {}
        self._dedup_rows = []

    def _absorb(self, fresh: List[dict]) -> None:
        """Fold newly-read rows into both caches (raw append order and
        the job_id-deduplicated view) — O(new rows)."""
        self._raw.extend(fresh)
        for rec in fresh:
            jid = rec.get("job_id")
            if jid is None:
                self._dedup_rows.append(rec)
                continue
            at = self._dedup_byid.get(jid)
            if at is None:
                self._dedup_byid[jid] = len(self._dedup_rows)
                self._dedup_rows.append(rec)
            else:
                self._dedup_rows[at] = rec

    def _refresh(self) -> None:
        """Advance the checkpoint over any bytes appended since the
        last read.  Only complete newline-terminated lines are ever
        consumed, so a torn tail is left for the next pass (and a
        writer's `_repair` truncation never reaches beneath the
        checkpoint — it cuts exactly at the last complete line)."""
        try:
            st = os.stat(self.path)
        except OSError:
            self._reset_cache()
            return
        ino, off = self._ckpt if self._ckpt else (None, 0)
        if ino != st.st_ino or st.st_size < off:
            # replaced (compacted) or rewritten: reread from byte 0
            self._reset_cache()
            off = 0
        if st.st_size == off:
            self._ckpt = (st.st_ino, off)
            return
        try:
            with open(self.path, "rb") as f:
                f.seek(off)
                data = f.read()
        except OSError:
            self._reset_cache()
            return
        nl = data.rfind(b"\n")
        if nl < 0:
            self._ckpt = (st.st_ino, off)
            return
        self._absorb(self._parse(data[:nl + 1]))
        self._ckpt = (st.st_ino, off + nl + 1)

    def raw_rows(self) -> List[dict]:
        """Every complete parseable row, in append order (torn or
        corrupt lines skipped, never fatal).  Incremental: repeat
        calls parse only bytes appended since the previous call."""
        self._refresh()
        return list(self._raw)

    def rows(self) -> List[dict]:
        """raw_rows deduplicated by job_id (last row wins — a redo
        after a crash-between-commit-and-append supersedes), append
        order preserved.  Incremental like raw_rows."""
        self._refresh()
        return list(self._dedup_rows)
