"""Continuous micro-batching scheduler (serve layer).

One daemon thread runs the serving loop:

  drain due retries -> pop a same-bucket batch -> execute

Execution semantics:

  * batch path — when a cross-job batch executor is configured it gets
    the whole batch (one stacked device call); any batch-level failure
    *degrades gracefully* to the single-job path instead of failing
    the batch's jobs wholesale.
  * single-job path — each job runs under a per-job wall-clock
    timeout; failures retry with exponential backoff up to
    max_retries, then surface as a failed/timeout job status.  A job
    failing never stops the loop.

Even without a cross-job executor the coalesced batch is what
amortizes compilation: every job in it shares the same plan bucket,
so the first job builds the executables and the rest ride the plan
cache (and XLA's process-lifetime jit cache) warm.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from dataclasses import dataclass
from typing import Callable, List, Optional

from presto_tpu.serve.queue import (Job, JobQueue, JobStatus,
                                    QueueClosed, RetryBudgetExceeded)


class JobTimeout(RuntimeError):
    """A job exceeded its per-job wall-clock budget."""


#: substrings that mark a RuntimeError as a device/executable failure
#: — the poisoned-plan signature (a reset TPU, a dead executable, an
#: exhausted HBM arena) where retrying into the same compiled plan
#: cannot succeed.  The retry path evicts the plan cache's affected
#: bindings first (ROADMAP: plan-cache invalidation on device error).
_DEVICE_ERROR_MARKERS = ("device", "executable", "xla", "tpu", "hbm",
                         "dead", "resource exhausted")


def is_device_error(exc: BaseException) -> bool:
    if not isinstance(exc, RuntimeError) or isinstance(exc, JobTimeout):
        return False
    msg = str(exc).lower()
    return any(m in msg for m in _DEVICE_ERROR_MARKERS)


def _trace_parent(job: Job):
    """The job's remote trace context (stamped by the router through
    the ledger) as an explicit span parent — None for local jobs,
    which keep the ordinary contextvar parenting."""
    from presto_tpu.obs.trace import SpanContext
    return SpanContext.from_dict(getattr(job, "trace", None))


@dataclass
class SchedulerConfig:
    max_batch: int = 8             # coalescing bound per iteration
    job_timeout_s: Optional[float] = None
    max_retries: int = 2           # retries after the first attempt
    backoff_base_s: float = 0.5    # delay = base * 2**(attempt-1)
    backoff_max_s: float = 30.0
    poll_s: float = 0.25           # loop tick while idle
    # Test seam (the injectpsr of the serving layer): called as
    # fault_injector(job, attempt) right before execution; anything it
    # raises is handled exactly like a stage failure.
    fault_injector: Optional[Callable] = None


class Scheduler:
    """Owns the serving loop thread; executes jobs via `executor`
    (callable(job) -> result dict) with optional cross-job
    `batch_executor` (callable(jobs) -> list of result dicts)."""

    def __init__(self, queue: JobQueue, executor: Callable,
                 cfg: Optional[SchedulerConfig] = None, events=None,
                 latency=None, batch_executor: Optional[Callable] = None,
                 obs=None, plans=None, park: Optional[Callable] = None):
        if obs is None:
            from presto_tpu.obs import Observability, ObsConfig
            obs = Observability(ObsConfig(enabled=True))
        self.queue = queue
        self.executor = executor
        self.batch_executor = batch_executor
        self.cfg = cfg or SchedulerConfig()
        self.events = events
        self.latency = latency
        self.obs = obs
        self.plans = plans          # PlanCache, for device-error evict
        # fleet seam: park(job) -> bool re-admits a retrying job into
        # the shared job ledger when the local queue is closed
        # (shutdown), so a scheduler retry during drain is handed to
        # another replica instead of stranded as a local failure
        self.park = park
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._retry_heap: list = []
        self._retry_seq = itertools.count()
        self._retry_lock = threading.Lock()  # presto-lint: guards(_retry_heap)
        self._pool: Optional[ThreadPoolExecutor] = None
        # lifecycle accounting lives on the metrics registry — the
        # stats() JSON block and the serve_* Prometheus series read
        # the same counters (one source of truth)
        reg = obs.metrics
        self._c_done = reg.counter("serve_jobs_done_total",
                                   "Jobs completed successfully")
        self._c_failed = reg.counter(
            "serve_jobs_failed_total",
            "Jobs terminally failed (incl. timeouts)")
        self._c_retried = reg.counter("serve_job_retries_total",
                                      "Job retry attempts scheduled")
        self._c_batches = reg.counter("serve_batches_total",
                                      "Micro-batches executed")
        self._c_batched = reg.counter("serve_batched_jobs_total",
                                      "Jobs executed inside batches")
        self._c_degrades = reg.counter(
            "serve_batch_degrades_total",
            "Batch failures degraded to single-job execution")
        self._c_deverr = reg.counter(
            "serve_device_errors_total",
            "Job failures classified as device/executable errors")
        self._c_lanes = reg.counter(
            "serve_lane_batches_total",
            "Micro-batches executed per scheduler lane", ("lane",))
        self._c_parked = reg.counter(
            "serve_jobs_parked_total",
            "Retrying jobs parked back into the fleet ledger at "
            "shutdown")
        self._g_retrywait = reg.gauge(
            "serve_retry_waiting", "Jobs on the retry backoff shelf")

    # ---- lifecycle ----------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Scheduler":
        if self.alive:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="presto-serve-scheduler",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        self._settle_retry_shelf()
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def drain(self, timeout: float = 60.0, poll: float = 0.05) -> bool:
        """Wait until the queue and retry shelf are empty (for tests /
        shutdown).  Returns False on timeout."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._retry_lock:
                pending_retries = len(self._retry_heap)
            if (len(self.queue) == 0 and pending_retries == 0
                    and not self._busy):
                return True
            time.sleep(poll)
        return False

    # ---- the loop -----------------------------------------------------

    _busy = False

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._admit_due_retries()
            try:
                batch = self.queue.pop_batch(self.cfg.max_batch,
                                             timeout=self.cfg.poll_s)
            except QueueClosed:
                break
            if not batch:
                continue
            self._busy = True
            try:
                self._run_batch(batch)
            except Exception:
                # belt-and-braces: _run_batch handles per-job errors;
                # anything escaping is a scheduler bug, but it must
                # not kill the always-on loop.
                if self.events is not None:
                    self.events.emit(
                        "scheduler-error",
                        error=traceback.format_exc(limit=5))
            finally:
                self._busy = False

    def _admit_due_retries(self) -> None:
        now = time.time()
        due: List[Job] = []
        with self._retry_lock:
            while self._retry_heap and self._retry_heap[0][0] <= now:
                _, _, job = heapq.heappop(self._retry_heap)
                due.append(job)
        with self._retry_lock:
            self._g_retrywait.set(len(self._retry_heap))
        for job in due:
            try:
                self.queue.requeue(job)
            except QueueClosed:
                self._park_or_fail(job, "queue closed during "
                                        "retry wait")
            except RetryBudgetExceeded as e:
                # poisoned job: terminate with the LAST execution
                # error preserved (the budget note rides along), and
                # emit the terminal `fail` event observers wait on.
                job.status = JobStatus.FAILED
                job.error = "%s [%s]" % (job.error or "retry", e)
                job.finished = time.time()
                self._c_failed.inc()
                if self.events is not None:
                    self.events.emit("fail", job=job.job_id,
                                     attempts=job.attempts,
                                     error=job.error, timeout=False,
                                     retry_depth_exceeded=True)

    # ---- shutdown parking ---------------------------------------------

    def _park_or_fail(self, job: Job, why: str) -> None:
        """A retry that can no longer re-enter the local queue
        (shutdown): hand it back to the fleet ledger when a park seam
        is wired (another replica re-admits it — the requeueable
        contract), else surface the old terminal failure rather than
        strand it silently in retry-wait."""
        if self.park is not None:
            try:
                parked = bool(self.park(job))
            except Exception:
                parked = False
            if parked:
                job.status = JobStatus.PARKED
                job.finished = time.time()
                self._c_parked.inc()
                if self.events is not None:
                    self.events.emit("park", job=job.job_id,
                                     attempts=job.attempts, why=why)
                return
        job.status = JobStatus.FAILED
        job.error = job.error or why
        job.finished = time.time()
        self._c_failed.inc()
        if self.events is not None:
            self.events.emit("fail", job=job.job_id,
                             attempts=job.attempts, error=why,
                             timeout=False)

    def _settle_retry_shelf(self) -> None:
        """Drain the backoff shelf at shutdown: every job still
        waiting out a retry delay is parked (fleet) or terminally
        failed (standalone) — never left in retry-wait forever."""
        with self._retry_lock:
            shelf = [job for _, _, job in self._retry_heap]
            self._retry_heap = []
            self._g_retrywait.set(0)
        for job in shelf:
            self._park_or_fail(job, "scheduler stopped during "
                                    "retry wait")

    # ---- batch execution ----------------------------------------------

    def _run_batch(self, batch: List[Job]) -> None:
        self._c_batches.inc()
        self._c_batched.inc(len(batch))
        self._c_lanes.labels(lane=batch[0].lane).inc()
        if self.events is not None:
            self.events.emit("schedule", jobs=[j.job_id for j in batch],
                             occupancy=len(batch),
                             lane=batch[0].lane,
                             bucket=repr(batch[0].bucket))
        if (self.batch_executor is not None and len(batch) > 1
                and all(j.run is None for j in batch)):
            # traced fleet jobs keep per-job spans even through the
            # stacked path (non-current siblings: they must not nest
            # into each other), so a stacked DAG fold still lands in
            # its DAG's cross-process trace
            spans = []
            if self.obs.enabled:
                for job in batch:
                    parent = _trace_parent(job)
                    if parent is None:
                        continue
                    sp = self.obs.tracer.span(
                        "serve-job", parent=parent, current=False,
                        job=job.job_id, stacked=True,
                        bucket=repr(job.bucket))
                    job.span_ctx = sp.context().to_dict()
                    spans.append(sp)
            try:
                results = self._with_timeout(
                    lambda: self.batch_executor(batch))
                for sp in spans:
                    sp.finish()
                for job, result in zip(batch, results):
                    self._finish_ok(job, result)
                return
            except Exception as e:
                for sp in spans:
                    sp.finish("error: %s" % type(e).__name__)
                # graceful degradation: the batch path failing means
                # each job gets an individual shot (and its own
                # retry/backoff budget), not a collective failure.
                self._c_degrades.inc()
                if self.events is not None:
                    self.events.emit(
                        "degrade", jobs=[j.job_id for j in batch],
                        error="%s: %s" % (type(e).__name__, e))
        for job in batch:
            self._run_single(job)

    def _run_single(self, job: Job) -> None:
        job.attempts += 1
        if job.attempts > 1 and \
                getattr(job.cfg, "durable_stages", None) is False:
            # a retry is by definition resume-critical: flip the
            # survey from the fused tier to durable stage artifacts so
            # THIS attempt journals its boundaries and a further
            # failure resumes from the last stage instead of the top
            job.cfg.durable_stages = True
        job.status = JobStatus.RUNNING
        if not job.started:
            job.started = time.time()
        if self.events is not None:
            self.events.emit("execute", job=job.job_id,
                             attempt=job.attempts)
        # a fleet job resumes the trace the router started at /submit
        # (explicit SpanContext across the process hop); survey/DAG
        # spans opened during execution nest under this via the
        # ordinary contextvar propagation
        span = self.obs.span("serve-job", parent=_trace_parent(job),
                             job=job.job_id,
                             attempt=job.attempts,
                             bucket=repr(job.bucket))
        ctx = span.context()
        if ctx is not None:
            job.span_ctx = ctx.to_dict()
        t0 = time.time()
        try:
            if self.cfg.fault_injector is not None:
                self.cfg.fault_injector(job, job.attempts)
            result = self._with_timeout(lambda: self.executor(job))
        except Exception as e:
            span.finish("error: %s" % type(e).__name__)
            self._handle_failure(job, e)
            return
        span.finish()
        if self.latency is not None:
            self.latency.record("job_exec", time.time() - t0)
        self._finish_ok(job, result)

    def _finish_ok(self, job: Job, result: Optional[dict]) -> None:
        job.result = result
        job.status = JobStatus.DONE
        job.error = ""
        job.finished = time.time()
        self._c_done.inc()
        if self.latency is not None and job.submitted:
            self.latency.record("job_total",
                                job.finished - job.submitted)
        if self.events is not None:
            self.events.emit("complete", job=job.job_id,
                             attempts=job.attempts,
                             seconds=round(job.finished
                                           - job.submitted, 3))

    def _handle_failure(self, job: Job, exc: Exception) -> None:
        timed_out = isinstance(exc, JobTimeout)
        job.error = "%s: %s" % (type(exc).__name__, exc)
        if is_device_error(exc):
            # poisoned-plan containment: a device/executable
            # RuntimeError means the cached executables bound to that
            # device may be dead — flush them BEFORE the retry, so the
            # retry re-warms fresh plans instead of re-entering the
            # poisoned one (observable as
            # plancache_evictions_total{reason="device_error"}).
            self._c_deverr.inc()
            if self.plans is not None:
                from presto_tpu.obs import jaxtel
                n = self.plans.evict_bucket(
                    device=jaxtel.current_device_id(),
                    reason="device_error")
                if self.events is not None:
                    self.events.emit("plan-evict", job=job.job_id,
                                     evicted=n, error=job.error)
        if job.attempts <= self.cfg.max_retries:
            delay = min(
                self.cfg.backoff_base_s * 2.0 ** (job.attempts - 1),
                self.cfg.backoff_max_s)
            job.status = JobStatus.RETRY_WAIT
            self._c_retried.inc()
            with self._retry_lock:
                heapq.heappush(
                    self._retry_heap,
                    (time.time() + delay, next(self._retry_seq), job))
                self._g_retrywait.set(len(self._retry_heap))
            if self.events is not None:
                self.events.emit("retry", job=job.job_id,
                                 attempt=job.attempts,
                                 delay_s=round(delay, 4),
                                 error=job.error)
            return
        job.status = (JobStatus.TIMEOUT if timed_out
                      else JobStatus.FAILED)
        job.finished = time.time()
        self._c_failed.inc()
        if self.events is not None:
            self.events.emit("fail", job=job.job_id,
                             attempts=job.attempts, error=job.error,
                             timeout=timed_out)

    # ---- timeout plumbing ---------------------------------------------

    def _with_timeout(self, fn: Callable):
        """Run fn() under the per-job wall-clock budget.  On timeout
        the worker thread is abandoned (Python offers no safe
        preemption) and a fresh worker serves subsequent jobs — the
        stuck thread ends with its work discarded."""
        if not self.cfg.job_timeout_s:
            return fn()
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="presto-serve-job")
        fut = self._pool.submit(fn)
        try:
            return fut.result(timeout=self.cfg.job_timeout_s)
        except FutureTimeout:
            stuck = self._pool
            self._pool = None          # zombie pool: never reused
            stuck.shutdown(wait=False)
            raise JobTimeout("exceeded %.3gs job budget"
                             % self.cfg.job_timeout_s) from None

    # ---- metrics ------------------------------------------------------

    def stats(self) -> dict:
        """The /metrics `scheduler` JSON block — read straight off the
        registry counters the Prometheus exposition also serves."""
        with self._retry_lock:
            waiting = len(self._retry_heap)
        batches = self._c_batches.value

        def _reg(name):
            fam = self.obs.metrics.get(name)
            return int(fam.value) if fam is not None else 0

        return {
            "alive": self.alive,
            "jobs_done": int(self._c_done.value),
            "jobs_failed": int(self._c_failed.value),
            "retries": int(self._c_retried.value),
            "retry_waiting": waiting,
            "batches": int(batches),
            "degrades": int(self._c_degrades.value),
            "batch_occupancy": (self._c_batched.value / batches
                                if batches else 0.0),
            # stacked cross-job execution (serve/batchexec.py
            # registers these on the same registry; 0 when the
            # executor is disabled)
            "stacked_batches": _reg("serve_stacked_batches_total"),
            "stacked_jobs": _reg("serve_stacked_jobs_total"),
        }
