"""Continuous micro-batching scheduler (serve layer).

One daemon thread runs the serving loop:

  drain due retries -> pop a same-bucket batch -> execute

Execution semantics:

  * batch path — when a cross-job batch executor is configured it gets
    the whole batch (one stacked device call); any batch-level failure
    *degrades gracefully* to the single-job path instead of failing
    the batch's jobs wholesale.
  * single-job path — each job runs under a per-job wall-clock
    timeout; failures retry with exponential backoff up to
    max_retries, then surface as a failed/timeout job status.  A job
    failing never stops the loop.

Even without a cross-job executor the coalesced batch is what
amortizes compilation: every job in it shares the same plan bucket,
so the first job builds the executables and the rest ride the plan
cache (and XLA's process-lifetime jit cache) warm.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from dataclasses import dataclass
from typing import Callable, List, Optional

from presto_tpu.serve.queue import (Job, JobQueue, JobStatus,
                                    QueueClosed, RetryBudgetExceeded)


class JobTimeout(RuntimeError):
    """A job exceeded its per-job wall-clock budget."""


@dataclass
class SchedulerConfig:
    max_batch: int = 8             # coalescing bound per iteration
    job_timeout_s: Optional[float] = None
    max_retries: int = 2           # retries after the first attempt
    backoff_base_s: float = 0.5    # delay = base * 2**(attempt-1)
    backoff_max_s: float = 30.0
    poll_s: float = 0.25           # loop tick while idle
    # Test seam (the injectpsr of the serving layer): called as
    # fault_injector(job, attempt) right before execution; anything it
    # raises is handled exactly like a stage failure.
    fault_injector: Optional[Callable] = None


class Scheduler:
    """Owns the serving loop thread; executes jobs via `executor`
    (callable(job) -> result dict) with optional cross-job
    `batch_executor` (callable(jobs) -> list of result dicts)."""

    def __init__(self, queue: JobQueue, executor: Callable,
                 cfg: Optional[SchedulerConfig] = None, events=None,
                 latency=None, batch_executor: Optional[Callable] = None):
        self.queue = queue
        self.executor = executor
        self.batch_executor = batch_executor
        self.cfg = cfg or SchedulerConfig()
        self.events = events
        self.latency = latency
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._retry_heap: list = []
        self._retry_seq = itertools.count()
        self._retry_lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None
        self._stats_lock = threading.Lock()
        self._done = 0
        self._failed = 0
        self._retried = 0
        self._batches = 0
        self._batched_jobs = 0
        self._degrades = 0

    # ---- lifecycle ----------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "Scheduler":
        if self.alive:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="presto-serve-scheduler",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def drain(self, timeout: float = 60.0, poll: float = 0.05) -> bool:
        """Wait until the queue and retry shelf are empty (for tests /
        shutdown).  Returns False on timeout."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._retry_lock:
                pending_retries = len(self._retry_heap)
            if (len(self.queue) == 0 and pending_retries == 0
                    and not self._busy):
                return True
            time.sleep(poll)
        return False

    # ---- the loop -----------------------------------------------------

    _busy = False

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._admit_due_retries()
            try:
                batch = self.queue.pop_batch(self.cfg.max_batch,
                                             timeout=self.cfg.poll_s)
            except QueueClosed:
                break
            if not batch:
                continue
            self._busy = True
            try:
                self._run_batch(batch)
            except Exception:
                # belt-and-braces: _run_batch handles per-job errors;
                # anything escaping is a scheduler bug, but it must
                # not kill the always-on loop.
                if self.events is not None:
                    self.events.emit(
                        "scheduler-error",
                        error=traceback.format_exc(limit=5))
            finally:
                self._busy = False

    def _admit_due_retries(self) -> None:
        now = time.time()
        due: List[Job] = []
        with self._retry_lock:
            while self._retry_heap and self._retry_heap[0][0] <= now:
                _, _, job = heapq.heappop(self._retry_heap)
                due.append(job)
        for job in due:
            try:
                self.queue.requeue(job)
            except QueueClosed:
                job.status = JobStatus.FAILED
                job.error = "queue closed during retry wait"
                job.finished = time.time()
            except RetryBudgetExceeded as e:
                # poisoned job: terminate with the LAST execution
                # error preserved (the budget note rides along), and
                # emit the terminal `fail` event observers wait on.
                job.status = JobStatus.FAILED
                job.error = "%s [%s]" % (job.error or "retry", e)
                job.finished = time.time()
                with self._stats_lock:
                    self._failed += 1
                if self.events is not None:
                    self.events.emit("fail", job=job.job_id,
                                     attempts=job.attempts,
                                     error=job.error, timeout=False,
                                     retry_depth_exceeded=True)

    # ---- batch execution ----------------------------------------------

    def _run_batch(self, batch: List[Job]) -> None:
        with self._stats_lock:
            self._batches += 1
            self._batched_jobs += len(batch)
        if self.events is not None:
            self.events.emit("schedule", jobs=[j.job_id for j in batch],
                             occupancy=len(batch),
                             bucket=repr(batch[0].bucket))
        if self.batch_executor is not None and len(batch) > 1:
            try:
                results = self._with_timeout(
                    lambda: self.batch_executor(batch))
                for job, result in zip(batch, results):
                    self._finish_ok(job, result)
                return
            except Exception as e:
                # graceful degradation: the batch path failing means
                # each job gets an individual shot (and its own
                # retry/backoff budget), not a collective failure.
                with self._stats_lock:
                    self._degrades += 1
                if self.events is not None:
                    self.events.emit(
                        "degrade", jobs=[j.job_id for j in batch],
                        error="%s: %s" % (type(e).__name__, e))
        for job in batch:
            self._run_single(job)

    def _run_single(self, job: Job) -> None:
        job.attempts += 1
        job.status = JobStatus.RUNNING
        if not job.started:
            job.started = time.time()
        if self.events is not None:
            self.events.emit("execute", job=job.job_id,
                             attempt=job.attempts)
        t0 = time.time()
        try:
            if self.cfg.fault_injector is not None:
                self.cfg.fault_injector(job, job.attempts)
            result = self._with_timeout(lambda: self.executor(job))
        except Exception as e:
            self._handle_failure(job, e)
            return
        if self.latency is not None:
            self.latency.record("job_exec", time.time() - t0)
        self._finish_ok(job, result)

    def _finish_ok(self, job: Job, result: Optional[dict]) -> None:
        job.result = result
        job.status = JobStatus.DONE
        job.error = ""
        job.finished = time.time()
        with self._stats_lock:
            self._done += 1
        if self.latency is not None and job.submitted:
            self.latency.record("job_total",
                                job.finished - job.submitted)
        if self.events is not None:
            self.events.emit("complete", job=job.job_id,
                             attempts=job.attempts,
                             seconds=round(job.finished
                                           - job.submitted, 3))

    def _handle_failure(self, job: Job, exc: Exception) -> None:
        timed_out = isinstance(exc, JobTimeout)
        job.error = "%s: %s" % (type(exc).__name__, exc)
        if job.attempts <= self.cfg.max_retries:
            delay = min(
                self.cfg.backoff_base_s * 2.0 ** (job.attempts - 1),
                self.cfg.backoff_max_s)
            job.status = JobStatus.RETRY_WAIT
            with self._stats_lock:
                self._retried += 1
            with self._retry_lock:
                heapq.heappush(
                    self._retry_heap,
                    (time.time() + delay, next(self._retry_seq), job))
            if self.events is not None:
                self.events.emit("retry", job=job.job_id,
                                 attempt=job.attempts,
                                 delay_s=round(delay, 4),
                                 error=job.error)
            return
        job.status = (JobStatus.TIMEOUT if timed_out
                      else JobStatus.FAILED)
        job.finished = time.time()
        with self._stats_lock:
            self._failed += 1
        if self.events is not None:
            self.events.emit("fail", job=job.job_id,
                             attempts=job.attempts, error=job.error,
                             timeout=timed_out)

    # ---- timeout plumbing ---------------------------------------------

    def _with_timeout(self, fn: Callable):
        """Run fn() under the per-job wall-clock budget.  On timeout
        the worker thread is abandoned (Python offers no safe
        preemption) and a fresh worker serves subsequent jobs — the
        stuck thread ends with its work discarded."""
        if not self.cfg.job_timeout_s:
            return fn()
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="presto-serve-job")
        fut = self._pool.submit(fn)
        try:
            return fut.result(timeout=self.cfg.job_timeout_s)
        except FutureTimeout:
            stuck = self._pool
            self._pool = None          # zombie pool: never reused
            stuck.shutdown(wait=False)
            raise JobTimeout("exceeded %.3gs job budget"
                             % self.cfg.job_timeout_s) from None

    # ---- metrics ------------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:
            with self._retry_lock:
                waiting = len(self._retry_heap)
            return {
                "alive": self.alive,
                "jobs_done": self._done,
                "jobs_failed": self._failed,
                "retries": self._retried,
                "retry_waiting": waiting,
                "batches": self._batches,
                "degrades": self._degrades,
                "batch_occupancy": (self._batched_jobs / self._batches
                                    if self._batches else 0.0),
            }
