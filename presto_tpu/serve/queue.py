"""Bounded priority job queue with backpressure (serve layer).

A job is one observation + one SurveyConfig-like spec.  The queue is
a heap ordered by (priority, arrival); depth is bounded so a burst of
submissions turns into explicit backpressure (QueueFull / HTTP 429)
instead of unbounded memory growth — the admission-control half of
continuous batching.  `pop_batch` is the other half: it hands the
scheduler the head job plus every queued job sharing its plan bucket,
so same-shaped beams ride one compiled executable.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple


class QueueFull(RuntimeError):
    """Submission rejected: the queue is at its bounded depth."""


class QueueClosed(RuntimeError):
    """The queue has been closed; no further pops/submissions."""


class RetryBudgetExceeded(RuntimeError):
    """A job was re-admitted more than max_retry_depth times: a
    poisoned job must terminate, not cycle the queue forever."""


class Lanes:
    """Scheduler lanes: two SLO classes sharing one process/device.

    DEADLINE jobs (the live-telescope trigger path) sort ahead of
    every THROUGHPUT job regardless of priority — a batch survey and a
    live feed share the scheduler without the feed waiting behind a
    queue of surveys.  There is no preemption: a deadline job still
    waits out the currently-executing job, so the deadline lane's SLO
    floor is the longest single throughput execution (see
    docs/STREAMING.md, lane semantics).
    """
    DEADLINE = "deadline"
    THROUGHPUT = "throughput"

    ORDER = {DEADLINE: 0, THROUGHPUT: 1}


class JobStatus:
    """Job lifecycle states (plain strings; JSON-friendly)."""
    QUEUED = "queued"
    SCHEDULED = "scheduled"
    RUNNING = "running"
    RETRY_WAIT = "retry-wait"
    PARKED = "parked"
    DONE = "done"
    FAILED = "failed"
    TIMEOUT = "timeout"

    TERMINAL = (DONE, FAILED, TIMEOUT)
    #: locally finished: terminal, or handed back to a fleet ledger
    #: for another replica to re-admit (the shutdown-park path)
    SETTLED = TERMINAL + (PARKED,)


@dataclass
class Job:
    """One search request: observation path(s) + survey spec."""
    job_id: str
    rawfiles: List[str]
    cfg: Any                       # pipeline.survey.SurveyConfig
    workdir: str
    priority: int = 10             # lower sorts first (within a lane)
    bucket: Any = None             # plancache.bucket_key() result
    spec: dict = field(default_factory=dict)   # raw submitted spec
    lane: str = Lanes.THROUGHPUT   # deadline | throughput (Lanes)
    #: discovery-DAG node kind: "survey" (the ordinary search job) or
    #: a dag node type ("sift" | "fold" | "toa", serve/dag.py) — the
    #: service dispatches execution on it, and the stacked batch
    #: executor stacks fold batches by it
    kind: str = "survey"
    #: in-process callable jobs (the streaming tick): when set, the
    #: service executes run(job) instead of a survey
    run: Optional[Callable] = None
    #: remote trace context (SpanContext wire dict) stamped by the
    #: router through the job ledger; the scheduler resumes it as the
    #: explicit parent of this job's `serve-job` span so one fleet
    #: submission renders as ONE cross-process trace
    trace: Optional[dict] = None
    #: this job's own span identity once execution started (set by
    #: the scheduler) — DAG fan-out children inherit it as THEIR
    #: trace parent, giving folds correct parenting under the sift
    span_ctx: Optional[dict] = None
    #: ledger lease-grant timestamp (fleet jobs; the admit->lease
    #: wait half of job_e2e_seconds)
    leased_at: float = 0.0
    status: str = JobStatus.QUEUED
    attempts: int = 0
    requeues: int = 0              # retry re-admissions so far
    error: str = ""
    submitted: float = 0.0
    started: float = 0.0
    finished: float = 0.0
    result: Optional[dict] = None

    def view(self) -> dict:
        """JSON-safe status snapshot (the /jobs/<id> payload)."""
        return {
            "job_id": self.job_id,
            "status": self.status,
            "lane": self.lane,
            "kind": self.kind,
            "priority": self.priority,
            "bucket": repr(self.bucket),
            "attempts": self.attempts,
            "requeues": self.requeues,
            "error": self.error,
            "submitted": self.submitted,
            "started": self.started,
            "finished": self.finished,
            "workdir": self.workdir,
        }


class JobQueue:
    """Thread-safe bounded priority queue with bucket coalescing."""

    def __init__(self, maxdepth: int = 64,
                 max_retry_depth: Optional[int] = 8):
        if maxdepth < 1:
            raise ValueError("maxdepth must be >= 1")
        self.maxdepth = maxdepth
        # retry re-admissions allowed per job (None = unbounded, the
        # pre-fix behavior); see requeue()
        self.max_retry_depth = max_retry_depth
        self._heap: List[Tuple[int, int, Job]] = []
        self._count = itertools.count()
        self._lock = threading.Lock()  # presto-lint: guards(_heap, _closed)
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._closed = False

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)

    depth = __len__

    def _key(self, job: Job) -> Tuple[int, int, int]:
        """Heap key: lane beats priority beats arrival — deadline-lane
        jobs always pop before throughput jobs."""
        return (Lanes.ORDER.get(job.lane, 1), job.priority,
                next(self._count))

    def submit(self, job: Job, block: bool = False,
               timeout: Optional[float] = None,
               force: bool = False) -> None:
        """Enqueue `job`.  Non-blocking by default: raises QueueFull at
        the depth bound (the server maps this to HTTP 429).  With
        block=True, waits up to `timeout` seconds for a slot.
        force=True bypasses the depth bound — reserved for the
        deadline lane's (self-bounded) stream ticks, which must not be
        shed behind a backlog of throughput submissions."""
        deadline = None if timeout is None else time.time() + timeout
        with self._lock:
            while True:
                if self._closed:
                    raise QueueClosed("queue is closed")
                if force or len(self._heap) < self.maxdepth:
                    break
                if not block:
                    raise QueueFull(
                        "queue depth %d reached" % self.maxdepth)
                remaining = (None if deadline is None
                             else deadline - time.time())
                if remaining is not None and remaining <= 0:
                    raise QueueFull(
                        "queue depth %d reached (timed out after "
                        "%.3gs)" % (self.maxdepth, timeout))
                self._not_full.wait(remaining)
            job.status = JobStatus.QUEUED
            if not job.submitted:
                job.submitted = time.time()
            heapq.heappush(self._heap, self._key(job) + (job,))
            self._not_empty.notify()

    def requeue(self, job: Job) -> None:
        """Re-admit a retrying job.  Retries bypass the depth bound —
        the job already held a slot when first admitted; bouncing it
        now would turn a transient failure into a drop.  They count
        against max_retry_depth instead: a job that keeps failing its
        way back in (poisoned input, permanently broken executor)
        raises RetryBudgetExceeded so the scheduler can terminate it
        with a final `fail` event rather than cycle it forever."""
        with self._lock:
            if self._closed:
                raise QueueClosed("queue is closed")
            if (self.max_retry_depth is not None
                    and job.requeues >= self.max_retry_depth):
                raise RetryBudgetExceeded(
                    "job %s re-admitted %d times (max_retry_depth=%d)"
                    % (job.job_id, job.requeues,
                       self.max_retry_depth))
            job.requeues += 1
            job.status = JobStatus.QUEUED
            heapq.heappush(self._heap, self._key(job) + (job,))
            self._not_empty.notify()

    def pop_batch(self, max_batch: int = 8,
                  timeout: Optional[float] = None) -> List[Job]:
        """Pop the head job plus up to max_batch-1 queued jobs sharing
        its bucket (arrival order preserved within the batch).  Jobs in
        other buckets keep their heap positions.  Returns [] on
        timeout, raises QueueClosed once closed and drained."""
        with self._lock:
            if not self._heap:
                if self._closed:
                    raise QueueClosed("queue is closed")
                self._not_empty.wait(timeout)
            if not self._heap:
                if self._closed:
                    raise QueueClosed("queue is closed")
                return []
            head = heapq.heappop(self._heap)[-1]
            batch = [head]
            if max_batch > 1:
                keep, take = [], []
                for entry in sorted(self._heap):
                    if (len(batch) + len(take) < max_batch
                            and entry[-1].bucket == head.bucket
                            and entry[-1].lane == head.lane):
                        take.append(entry)
                    else:
                        keep.append(entry)
                batch += [e[-1] for e in take]
                self._heap = keep
                heapq.heapify(self._heap)
            for j in batch:
                j.status = JobStatus.SCHEDULED
            self._not_full.notify(len(batch))
            return batch

    def close(self) -> None:
        """Close the queue: submitters fail fast, poppers drain then
        get QueueClosed."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()
