"""Shared on-disk job ledger for a fleet of presto-serve replicas.

One process, one queue, one crash losing everything is the failure
mode this closes: submissions land here — a durable, transactional
ledger on the shared filesystem — and N replicas *lease* jobs out of
it, so a replica crash loses nothing but time.  The lease /
heartbeat / epoch-fencing / staged-commit mechanics are the generic
`pipeline/leaseledger.LeaseLedger` (the elastic PR's recovery
primitives, factored out of `pipeline/shardledger.py`); this module
binds them to the serve-job vocabulary:

  * an item is a **job row** in `jobs.json`: the submitted spec
    (rawfiles + SurveyConfig fields), a tenant, a priority, and the
    usual lease columns;
  * `complete()` commits the job's `result.json` through the staged
    fence-checked path, so a zombie replica's late result never
    lands (`stale-result-rejected`);
  * jobs add a fence-checked terminal ``failed`` state
    (`fail_terminal`): a job whose retry budget is exhausted on a
    live replica must terminate, not cycle the fleet forever;
  * the lease scheduling policy is **weighted round-robin over
    tenants** (deficit-style: the pending tenant with the smallest
    served/weight ratio goes next), so one chatty tenant cannot
    starve the rest, and per-tenant **quotas** bound admission:
    `admit()` raises the typed `TenantQuotaExceeded` — a visible,
    typed rejection, never a silent drop.

Discovery DAGs (`serve/dag.py`) add **job dependencies** on top of
the same lease core: a job may be admitted ``blocked_on`` a list of
parent job ids and becomes leasable only once every parent's
fence-checked commit has landed — the parent's state only ever
becomes ``done`` through the epoch fence, so a zombie replica's late
result can never unblock a child.  `complete_and_expand` commits a
node AND creates its dynamically fanned-out children (the sift
node's per-candidate fold jobs) in ONE fenced transaction, so a
crash between "result landed" and "children exist" is impossible,
and a fenced-off zombie expands nothing.  Children of a terminally
failed parent cascade to ``failed`` (`dag-cascade-fail`) instead of
blocking the fleet forever.

The router (`serve/router.py`) is the admission front door; replicas
(`serve/fleet.py`) are the lease-and-execute loop.  See
docs/SERVING.md ("Fleet-scale serving" and "Discovery DAGs") for the
full protocol.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu.pipeline.leaseledger import (DONE, FAILED, LEASED,
                                             PENDING, ItemLease,
                                             LeaseLedger, LedgerError,
                                             StaleLeaseError)
from presto_tpu.serve.usage import UsageLedger

LEDGER_NAME = "jobs.json"

DEFAULT_TENANT = "default"


class JobLedgerError(LedgerError):
    """Base class for job-ledger protocol violations."""


class StaleResultError(StaleLeaseError, JobLedgerError):
    """A result commit attempted under a lease the fleet has fenced
    off — the zombie-replica case.  The staged result was discarded
    and the journaled one (if any) was never overwritten."""


class TenantQuotaExceeded(JobLedgerError):
    """Typed admission rejection: the tenant is at its quota —
    counted in active (pending + leased) jobs, or priced in expected
    device-seconds of active work (``unit="device-seconds"``, the
    measured-cost admission gate).  Mapped to HTTP 429 by the
    router; recorded as a `quota-exceeded` event, never a silent
    drop."""

    def __init__(self, tenant: str, quota, active,
                 unit: str = "jobs", cost: float = 0.0):
        self.tenant = tenant
        self.quota = quota
        self.active = active
        self.unit = unit
        self.cost = cost
        if unit == "jobs":
            msg = ("tenant %r is at its quota (%d active of %d "
                   "allowed)" % (tenant, active, quota))
        else:
            msg = ("tenant %r is at its device-second quota "
                   "(%.3f active + %.3f expected of %.3f allowed)"
                   % (tenant, active, cost, quota))
        super().__init__(msg)


class JobLedger(LeaseLedger):
    """Leased-job journal for one fleet directory."""

    LEDGER_NAME = LEDGER_NAME
    ITEMS_KEY = "jobs"
    ERROR = JobLedgerError
    STALE = StaleResultError
    EV_LEASE = "job-lease"
    EV_DONE = "job-done"
    EV_REDO = "job-redo"
    EV_STALE = "stale-result-rejected"
    EV_HOST_DEAD = "replica-dead"
    EV_EPOCH_BUMP = "fleet-epoch-bump"

    #: SLO-class lease-weight multiplier cap: a 99.9 % tenant beats a
    #: 50 % bronze 100:2 under contention, but no objective — however
    #: many nines — can starve the rest beyond this ratio
    CLASS_WEIGHT_CAP = 100.0

    # -- tenant configuration ------------------------------------------
    def set_tenant(self, tenant: str, weight: float = 1.0,
                   quota: Optional[int] = None,
                   ds_quota: Optional[float] = None) -> None:
        """Configure one tenant's WRR weight, active-job quota, and
        device-second quota (None = unbounded).  ``ds_quota`` bounds
        the *expected device-seconds* of the tenant's active
        (pending + leased) work, priced by the per-bucket execute
        cost model — the measured-cost admission gate that throttles
        one tenant's few huge jobs and another's many tiny jobs
        equivalently.  Unknown tenants default to weight 1, no
        quotas."""
        with self._lock():
            state = self._load()
            state.setdefault("tenants", {})[str(tenant)] = {
                "weight": max(float(weight), 1e-9),
                "quota": None if quota is None else int(quota),
                "ds_quota": (None if ds_quota is None
                             else float(ds_quota)),
            }
            self._save(state)

    def tenants(self) -> Dict[str, dict]:
        return dict(self._load().get("tenants", {}))

    # -- SLO-class lease weights ---------------------------------------
    def _class_weights(self) -> Dict[str, float]:
        """Per-tenant lease-weight multipliers derived from the SLO
        classes in `<fleet>/slo.json` (cached by file stat): a tenant
        with objective ``o`` multiplies its configured WRR weight by
        ``min(1/(1-o), CLASS_WEIGHT_CAP)``, so under contention a
        burning gold tenant's jobs are leased ahead of bronze
        backfill in proportion to how little error budget its class
        affords.  Tenants without a spec keep multiplier 1."""
        from presto_tpu.obs import slo
        try:
            st = os.stat(slo.spec_path(self.workdir))
            key = (st.st_mtime_ns, st.st_size)
        except OSError:
            key = None
        cached = getattr(self, "_class_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        weights: Dict[str, float] = {}
        if key is not None:
            for spec in slo.load_specs(self.workdir):
                mult = 1.0 / max(1.0 - float(spec.objective), 1e-9)
                weights[spec.tenant] = min(max(mult, 1.0),
                                           self.CLASS_WEIGHT_CAP)
        self._class_cache = (key, weights)
        return weights

    def _backfill_factors(self) -> Dict[str, float]:
        """Per-tenant lease-weight yield factors for the backfill
        lane, from `<fleet>/backfill.json` (cached by file stat, like
        `_class_weights`): tenants the campaign driver declared as
        backfill have their WRR weight multiplied by the live yield
        factor the SLO pass maintains — when an interactive tenant
        burns its error budget, backfill leases thin out in
        proportion, without touching the configured weights."""
        from presto_tpu.obs import slo
        try:
            st = os.stat(slo.backfill_path(self.workdir))
            key = (st.st_mtime_ns, st.st_size)
        except OSError:
            key = None
        cached = getattr(self, "_backfill_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        factors: Dict[str, float] = {}
        if key is not None:
            doc = slo.load_backfill(self.workdir)
            if doc is not None:
                y = min(max(float(doc.get("yield", 1.0)), 1e-9), 1.0)
                for t in doc.get("tenants") or ():
                    factors[str(t)] = y
        self._backfill_cache = (key, factors)
        return factors

    def _tenant_cfg(self, state: dict, tenant: str) -> dict:
        cfg = state.get("tenants", {}).get(tenant) or {}
        weight = max(float(cfg.get("weight", 1.0)), 1e-9)
        weight *= self._class_weights().get(tenant, 1.0)
        weight *= self._backfill_factors().get(tenant, 1.0)
        return {"weight": weight,
                "quota": cfg.get("quota"),
                "ds_quota": cfg.get("ds_quota")}

    # -- the measured-cost admission gate ------------------------------
    def cost_estimator(self):
        """``bucket -> expected device-seconds`` from the usage
        ledger's per-bucket execute cost model (fleet-median fallback
        for unknown buckets; obs/slo.cost_estimator), cached by the
        usage file's stat so admission stays O(active jobs), not
        O(history) per call."""
        from presto_tpu.obs import slo
        try:
            st = os.stat(self.usage.path)
            key = (st.st_mtime_ns, st.st_size)
        except OSError:
            key = None
        cached = getattr(self, "_cost_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        est = slo.cost_estimator(self.usage.rows())
        self._cost_cache = (key, est)
        return est

    def _charge_ds_quota(self, state: dict, tenant: str, cfg: dict,
                         new_buckets: Sequence) -> None:
        """Raise the typed device-second rejection when admitting
        ``new_buckets`` would push the tenant's expected active
        device-seconds past its ds_quota.  Called under the ledger
        lock, before any row is created."""
        if cfg.get("ds_quota") is None:
            return
        est = self.cost_estimator()
        active_ds = sum(
            est(j.get("bucket"))
            for j in self._items(state).values()
            if j.get("tenant") == tenant
            and j["state"] in (PENDING, LEASED))
        cost = sum(est(b) for b in new_buckets)
        if active_ds + cost > float(cfg["ds_quota"]):
            self._event("quota-exceeded", tenant=tenant,
                        quota=cfg["ds_quota"],
                        active=round(active_ds, 6),
                        cost=round(cost, 6),
                        unit="device-seconds")
            raise TenantQuotaExceeded(
                tenant, float(cfg["ds_quota"]),
                round(active_ds, 6), unit="device-seconds",
                cost=round(cost, 6))

    def backlog_device_seconds(self) -> float:
        """Expected device-seconds of the active (pending + leased)
        backlog under the cost model — the router's device-second
        shedding signal (the priced twin of `depth()`)."""
        est = self.cost_estimator()
        return sum(est(row.get("bucket"))
                   for row in self._load()[self.ITEMS_KEY].values()
                   if row["state"] in (PENDING, LEASED))

    # -- admission ------------------------------------------------------
    def admit(self, spec: dict, tenant: str = DEFAULT_TENANT,
              job_id: Optional[str] = None, priority: int = 10,
              now: Optional[float] = None,
              bucket: Optional[str] = None,
              blocked_on: Optional[Sequence[str]] = None,
              dag: Optional[str] = None,
              trace: Optional[dict] = None) -> dict:
        """Durably admit one job.  Enforces the tenant's quota over
        its *active* (pending + leased) jobs; raises the typed
        TenantQuotaExceeded past it.  Returns the job's ledger view.
        Duplicate explicit job_ids raise JobLedgerError.

        ``bucket`` is the job's plan-bucket hint (the repr of
        serve/plancache.bucket_key, computed by the router at
        admission): `lease_batch` stacks only jobs sharing it, so a
        replica can claim a whole same-bucket batch in one fenced
        transaction.  None disables batch leasing for this job —
        never a correctness loss, only a batching one.

        ``blocked_on`` names parent job ids: the job stays pending
        but UN-leasable until every parent's fence-checked commit
        lands (serve/dag.py).  ``dag`` tags the row with its graph id
        for `dag_view`.

        ``trace`` is the router's span context
        (`SpanContext.to_dict`): stamped onto the row so the leasing
        replica resumes the submission's trace — search on replica A
        and its folds on replica B render as ONE timeline.  Purely
        telemetry: never read by the execution path, absent rows
        simply start fresh traces."""
        now = time.time() if now is None else now
        tenant = str(tenant or DEFAULT_TENANT)
        with self._lock():
            state = self._load()
            jobs = self._items(state)
            cfg = self._tenant_cfg(state, tenant)
            active = sum(1 for j in jobs.values()
                         if j.get("tenant") == tenant
                         and j["state"] in (PENDING, LEASED))
            if cfg["quota"] is not None and active >= cfg["quota"]:
                self._event("quota-exceeded", tenant=tenant,
                            quota=cfg["quota"], active=active,
                            unit="jobs")
                raise TenantQuotaExceeded(tenant, int(cfg["quota"]),
                                          active)
            self._charge_ds_quota(state, tenant, cfg, [bucket])
            if job_id is None:
                seq = int(state.get("next_id", 1))
                state["next_id"] = seq + 1
                job_id = "fjob-%06d" % seq
            elif job_id in jobs:
                raise JobLedgerError("duplicate job_id %r" % job_id)
            row = {
                "spec": dict(spec),
                "tenant": tenant,
                "priority": int(priority),
                "submitted": now,
                "error": "",
                "bucket": bucket,
                "blocked_on": list(blocked_on or ()),
                "dag": dag,
            }
            if trace:
                row["trace"] = dict(trace)
            jobs[job_id] = self._new_row(row)
            self._save(state)
            return self._view(job_id, jobs[job_id])

    # -- discovery DAGs -------------------------------------------------
    def _registry(self):
        """The shared metrics registry (None without an obs handle);
        dag_* counters register with literal names so the obs_lint
        catalog check sees them."""
        return getattr(self.obs, "metrics", None)

    # -- durable usage metering (the SLO observatory's substrate) ------
    @property
    def usage(self) -> UsageLedger:
        """This fleet's crash-atomic `usage.jsonl` journal (lazy; a
        per-tenant device-seconds record that survives replica death
        and router restarts — serve/usage.py)."""
        led = getattr(self, "_usage", None)
        if led is None:
            led = self._usage = UsageLedger(self.workdir)
        return led

    def _usage_append(self, lease: ItemLease, usage: Optional[dict],
                      state: str, now: float) -> None:
        """Append one usage row for a terminal transition.  Called
        strictly AFTER the epoch-fence check accepted this replica's
        verdict (complete / complete_and_expand / fail_terminal), so
        a fenced zombie can never meter anything; crash-atomicity is
        the usage ledger's append contract.  The `execute` phase
        seconds also feed `slo_device_seconds_total{tenant,bucket}`
        so the snapshot/aggregation path carries the same number."""
        if usage is None or not self.usage.enabled:
            return
        row = dict(usage)
        row.setdefault("job_id", lease.item_id)
        row.setdefault("tenant", str(lease.data.get("tenant")
                                     or DEFAULT_TENANT))
        row.setdefault("bucket", lease.data.get("bucket"))
        row.setdefault("dag", lease.data.get("dag"))
        row["state"] = state
        row.setdefault("ts", now)
        self.usage.append(row)
        execute = float((row.get("phases") or {}).get("execute")
                        or 0.0)
        reg = self._registry()
        if reg is not None and state == DONE and execute > 0.0:
            reg.counter(
                "slo_device_seconds_total",
                "Device-execute seconds metered per tenant and plan "
                "bucket at each fence-checked commit (the usage "
                "ledger's counter twin)",
                ("tenant", "bucket")).labels(
                    tenant=row["tenant"],
                    bucket=str(row.get("bucket") or "")).inc(execute)

    def complete(self, lease, host: str, staged: Dict[str, str],
                 now: Optional[float] = None,
                 extra: Optional[dict] = None,
                 usage: Optional[dict] = None) -> Dict[str, dict]:
        """Fence-checked commit (the LeaseLedger.complete transaction)
        plus durable usage metering INSIDE it: the fence check runs
        first (a zombie raises STALE before ever reaching the append)
        and the usage row is durable before the ledger state flips to
        done — a job the fleet can observe as done has always been
        metered.  A crash between the append and the state save
        re-admits the job; the redo's row supersedes (usage reader
        dedups by job_id, last row wins)."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            row = self._items(state).get(lease.item_id)
            why = self._fence_why(row, lease, host)
            if why is not None:
                self._reject_stale(state, lease, host, staged, why)
            arts = self._commit_row(state, lease, host, staged, row,
                                    now, extra)
            self._usage_append(lease,
                               usage if usage is not None else {},
                               DONE, now)
            self._save(state)
        self._event(self.EV_DONE, item=lease.item_id, host=host,
                    artifacts=len(arts))
        return arts

    def admit_dag(self, nodes: Sequence[Tuple[str, dict,
                                              Optional[str],
                                              Sequence[str]]],
                  tenant: str = DEFAULT_TENANT, priority: int = 10,
                  dag_id: Optional[str] = None,
                  now: Optional[float] = None,
                  trace: Optional[dict] = None) -> dict:
        """Durably admit one job graph as ONE ledger transaction.

        ``nodes`` is a sequence of ``(rel_id, spec, bucket,
        parent_rel_ids)``; every rel_id becomes ``<dag_id>-<rel_id>``
        and the parent references (both ``blocked_on`` and the spec's
        ``parents``/``retarget`` fields, which replicas use to locate
        committed parent artifact dirs) are prefixed the same way, so
        a DagSpec is portable across submissions.  The tenant quota
        counts the whole graph: either every node is admitted or none
        is (TenantQuotaExceeded / JobLedgerError leave the ledger
        untouched).  Returns ``{"dag_id", "nodes": {rel: job_id}}``.
        """
        now = time.time() if now is None else now
        tenant = str(tenant or DEFAULT_TENANT)
        with self._lock():
            state = self._load()
            jobs = self._items(state)
            cfg = self._tenant_cfg(state, tenant)
            active = sum(1 for j in jobs.values()
                         if j.get("tenant") == tenant
                         and j["state"] in (PENDING, LEASED))
            if (cfg["quota"] is not None
                    and active + len(nodes) > cfg["quota"]):
                self._event("quota-exceeded", tenant=tenant,
                            quota=cfg["quota"], active=active,
                            unit="jobs")
                raise TenantQuotaExceeded(tenant, int(cfg["quota"]),
                                          active)
            self._charge_ds_quota(state, tenant, cfg,
                                  [b for _, _, b, _ in nodes])
            if dag_id is None:
                seq = int(state.get("next_dag", 1))
                state["next_dag"] = seq + 1
                dag_id = "dag-%06d" % seq

            def _full(rel: str) -> str:
                return "%s-%s" % (dag_id, rel)

            ids = {}
            for rel, _spec, _bucket, _parents in nodes:
                jid = _full(rel)
                if jid in jobs:
                    raise JobLedgerError("duplicate job_id %r" % jid)
                ids[rel] = jid
            for rel, spec, bucket, parents in nodes:
                spec = dict(spec, dag=dag_id)
                raw = spec.get("parents")
                if isinstance(raw, dict):
                    spec["parents"] = {
                        role: ([_full(v) for v in val]
                               if isinstance(val, (list, tuple))
                               else _full(val))
                        for role, val in raw.items()}
                if isinstance(spec.get("retarget"), str):
                    spec["retarget"] = _full(spec["retarget"])
                row = {
                    "spec": spec,
                    "tenant": tenant,
                    "priority": int(priority),
                    "submitted": now,
                    "error": "",
                    "bucket": bucket,
                    "blocked_on": [_full(p) for p in parents or ()],
                    "dag": dag_id,
                }
                if trace:
                    # every node starts under the DAG's trace; the
                    # sift expand re-parents its fold fan-out under
                    # the sift node's own span (fleet.py _commit)
                    row["trace"] = dict(trace)
                jobs[ids[rel]] = self._new_row(row)
            self._save(state)
        self._event("dag-submit", dag=dag_id, nodes=sorted(ids),
                    tenant=tenant)
        reg = self._registry()
        if reg is not None:
            reg.counter(
                "dag_submitted_total",
                "Job graphs durably admitted to the ledger").inc()
        return {"dag_id": dag_id, "nodes": dict(ids)}

    @staticmethod
    def _leasable(items: dict, row: dict) -> bool:
        """A pending row is leasable once every blocked_on parent has
        landed its fence-checked commit (state == done).  A parent's
        state only ever becomes done THROUGH the fence, so a zombie's
        late result can never make a child leasable."""
        for pid in row.get("blocked_on") or ():
            prow = items.get(pid)
            if prow is None or prow["state"] != DONE:
                return False
        return True

    def _cascade_failures(self, state: dict, now: float) -> List[str]:
        """Terminally fail pending jobs whose parents can never
        complete (a failed — or missing — parent): the DAG analog of
        fail_terminal, so a poisoned node's whole downstream subtree
        settles with a diagnosable error instead of blocking the
        fleet forever.  Transitive by fixpoint.  Called under the
        ledger lock from the lease scheduling policy."""
        items = self._items(state)
        failed: List[str] = []
        changed = True
        while changed:
            changed = False
            for jid in sorted(items):
                row = items[jid]
                if row["state"] != PENDING:
                    continue
                for pid in row.get("blocked_on") or ():
                    prow = items.get(pid)
                    if prow is None or prow["state"] == FAILED:
                        row["state"] = FAILED
                        row["error"] = (
                            "dag parent %s %s" % (
                                pid, "failed: %s"
                                % prow.get("error", "")
                                if prow is not None else "missing"))
                        row["completed_at"] = now
                        failed.append(jid)
                        changed = True
                        break
        for jid in failed:
            row = items[jid]
            if self.usage.enabled:
                # a cascade-failed node never executed, but it is
                # terminal: meter a zero-execute row so accounting
                # conserves (admitted == done + failed exactly) and
                # campaign ETA math cannot diverge on a failing
                # observation.  Re-appending after a crash before the
                # ledger save is harmless — rows() dedups by job_id.
                self.usage.append({
                    "job_id": jid,
                    "tenant": str(row.get("tenant")
                                  or DEFAULT_TENANT),
                    "bucket": row.get("bucket"),
                    "dag": row.get("dag"),
                    "state": FAILED,
                    "ts": now,
                    "phases": {},
                    "cascade": True,
                })
        for jid in failed:
            self._event("dag-cascade-fail", item=jid,
                        error=items[jid]["error"])
        reg = self._registry()
        if failed and reg is not None:
            reg.counter(
                "dag_cascade_failures_total",
                "DAG children terminally failed because a parent "
                "node failed").inc(len(failed))
        return failed

    def complete_and_expand(self, lease, host: str,
                            staged: Dict[str, str],
                            now: Optional[float] = None,
                            extra: Optional[dict] = None,
                            children: Optional[Sequence[Tuple[
                                str, dict]]] = None,
                            retarget: Optional[Dict[str, dict]]
                            = None,
                            usage: Optional[dict] = None) \
            -> Dict[str, dict]:
        """Fence-checked commit PLUS dynamic fan-out, atomically.

        The sift node's surviving-candidate list decides the fold
        fan-out; committing the list and creating the fold jobs must
        be one durable step — a crash between them would strand a
        done parent with no children, and a zombie must expand
        nothing.  So: under ONE ledger lock, fence-check (STALE
        raises exactly like complete(), staged files deleted, no row
        touched), land the staged result, create every child row
        idempotently (an id that already exists is left alone — the
        re-commit path), and retarget downstream nodes'
        ``blocked_on``/``parents`` (the timing node's fold fan-in).

        ``children``: [(job_id, row_fields)] where row_fields carries
        spec/tenant/priority/bucket/blocked_on/dag.  ``retarget``:
        {job_id: {"blocked_on": [...], "parents": {...merged into
        the row's spec...}}} applied only while the target is still
        pending."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            items = self._items(state)
            row = items.get(lease.item_id)
            why = self._fence_why(row, lease, host)
            if why is not None:
                self._reject_stale(state, lease, host, staged, why)
            arts = self._commit_row(state, lease, host, staged, row,
                                    now, extra)
            created = []
            for cid, fields in children or ():
                if cid in items:
                    continue            # idempotent re-expansion
                fields = dict(fields)
                fields.setdefault("submitted", now)
                fields.setdefault("error", "")
                items[cid] = self._new_row(fields)
                created.append(cid)
            for jid, change in (retarget or {}).items():
                trow = items.get(jid)
                if trow is None or trow["state"] != PENDING:
                    continue
                if "blocked_on" in change:
                    trow["blocked_on"] = list(change["blocked_on"])
                if "parents" in change:
                    spec = dict(trow.get("spec") or {})
                    parents = dict(spec.get("parents") or {})
                    parents.update(change["parents"])
                    spec["parents"] = parents
                    trow["spec"] = spec
            self._usage_append(lease,
                               usage if usage is not None else {},
                               DONE, now)
            self._save(state)
        self._event(self.EV_DONE, item=lease.item_id, host=host,
                    artifacts=len(arts))
        self._event("dag-expand", item=lease.item_id, host=host,
                    created=len(created),
                    retargeted=sorted(retarget or ()))
        reg = self._registry()
        if created and reg is not None:
            reg.counter(
                "dag_fanout_jobs_total",
                "Child jobs dynamically fanned out at a DAG node's "
                "fence-checked commit").inc(len(created))
        return arts

    def dag_view(self, dag_id: str) -> Optional[dict]:
        """Aggregate view of one job graph: every node's ledger view
        plus a graph-level state (failed > running > done)."""
        state = self._load()
        nodes = {jid: self._view(jid, row)
                 for jid, row in self._items(state).items()
                 if row.get("dag") == dag_id}
        if not nodes:
            return None
        states = {v["state"] for v in nodes.values()}
        if FAILED in states:
            agg = FAILED
        elif states == {DONE}:
            agg = DONE
        else:
            agg = "running"
        return {"dag_id": dag_id, "state": agg,
                "counts": {s: sum(1 for v in nodes.values()
                                  if v["state"] == s)
                           for s in sorted(states)},
                "nodes": nodes}

    # -- batch leasing --------------------------------------------------
    def lease_batch(self, host: str, ttl: float, k: int,
                    now: Optional[float] = None) -> List[ItemLease]:
        """Claim up to ``k`` same-bucket pending jobs for ``host`` in
        ONE fenced ledger transaction (the stacked batch executor's
        fleet feeder).  The first grant follows the ordinary deficit-
        WRR policy; the rest are restricted to pending jobs sharing
        the head's bucket hint, with the deficit selection re-applied
        over the tenants that still have matching jobs — every grant
        bumps its tenant's persisted ``served`` counter, so WRR
        fairness is preserved across the batch exactly as across k
        single leases.  Each returned lease carries the SAME epoch
        fence as a single lease: commits land per job, and a zombie's
        late batch commit is fenced per job.  Returns [] when nothing
        is pending; a head without a bucket hint returns just itself.
        """
        now = time.time() if now is None else now
        leases: List[ItemLease] = []
        with self._lock():
            state = self._load()
            h = state["hosts"].get(host)
            if h is not None and not h.get("alive", True):
                h["alive"] = True
                h["epoch"] = int(state["epoch"])
            iid = self._pick_pending(state, now)
            if iid is None:
                self._save(state)
                return []
            items = self._items(state)
            epoch = int(state["epoch"])

            def grant(jid):
                row = items[jid]
                row["state"] = LEASED
                row["owner"] = host
                row["lease_epoch"] = epoch
                row["lease_expires"] = now + ttl
                row["leased_at"] = now
                leases.append(self._make_lease(jid, row, epoch))

            grant(iid)
            hint = items[iid].get("bucket")
            served = state.setdefault("served", {})
            while hint is not None and len(leases) < max(int(k), 1):
                pend: Dict[str, List[str]] = {}
                for jid, row in items.items():
                    if (row["state"] == PENDING
                            and row.get("bucket") == hint
                            and self._leasable(items, row)):
                        pend.setdefault(
                            str(row.get("tenant", DEFAULT_TENANT)),
                            []).append(jid)
                if not pend:
                    break
                tenant = min(
                    pend,
                    key=lambda t: (float(served.get(t, 0))
                                   / self._tenant_cfg(state,
                                                      t)["weight"],
                                   t))
                jid = min(pend[tenant],
                          key=lambda j: (int(items[j].get("priority",
                                                          10)),
                                         float(items[j].get(
                                             "submitted", 0.0)), j))
                served[tenant] = int(served.get(tenant, 0)) + 1
                grant(jid)
            self._save(state)
        for lease in leases:
            self._event(self.EV_LEASE, item=lease.item_id, host=host,
                        epoch=lease.epoch, batch=len(leases))
        return leases

    # -- scheduling policy: weighted round-robin over tenants ----------
    def _pick_pending(self, state: dict,
                      now: float) -> Optional[str]:
        """Deficit-style WRR: among tenants with pending jobs, grant
        to the one with the smallest served/weight ratio (ties break
        by tenant name), then the oldest highest-priority job inside
        that tenant.  `served` counters persist in the ledger so the
        rotation is fleet-wide, not per-replica.

        DAG jobs whose parents have not all landed their fenced
        commits are pending but NOT grantable; children of a failed
        parent are cascaded to terminal failure first (both mutations
        persist with the grant — the caller saves state)."""
        self._cascade_failures(state, now)
        jobs = self._items(state)
        by_tenant: Dict[str, List[str]] = {}
        for jid, row in jobs.items():
            if (row["state"] == PENDING
                    and self._leasable(jobs, row)):
                by_tenant.setdefault(
                    str(row.get("tenant", DEFAULT_TENANT)),
                    []).append(jid)
        if not by_tenant:
            return None
        served = state.setdefault("served", {})
        tenant = min(
            by_tenant,
            key=lambda t: (float(served.get(t, 0))
                           / self._tenant_cfg(state, t)["weight"], t))
        jid = min(by_tenant[tenant],
                  key=lambda j: (int(jobs[j].get("priority", 10)),
                                 float(jobs[j].get("submitted", 0.0)),
                                 j))
        served[tenant] = int(served.get(tenant, 0)) + 1
        return jid

    # -- terminal failure ----------------------------------------------
    def fail_terminal(self, lease: ItemLease, host: str, error: str,
                      now: Optional[float] = None,
                      usage: Optional[dict] = None) -> None:
        """Fence-checked terminal failure: the replica exhausted the
        job's local retry budget (or the spec is unexecutable), so the
        job must stop cycling the fleet.  A fenced-off lease raises
        StaleResultError instead — the fleet already re-admitted the
        job, and this replica's verdict no longer counts."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            row = self._items(state).get(lease.item_id)
            why = self._fence_why(row, lease, host)
            if why is not None:
                self._reject_stale(state, lease, host, {}, why)
            row["state"] = FAILED
            row["owner"] = host
            row["lease_epoch"] = None
            row["lease_expires"] = None
            row["error"] = str(error)
            row["completed_epoch"] = int(state["epoch"])
            row["completed_at"] = now
            # settle the downstream subtree NOW (not at the next
            # lease attempt): a drained fleet must not leave a failed
            # node's children pending forever
            self._cascade_failures(state, now)
            # failures meter too (the availability half of an SLO is
            # exactly "terminal failures count against the budget")
            self._usage_append(lease,
                               usage if usage is not None else {},
                               FAILED, now)
            self._save(state)
        self._event("job-failed", item=lease.item_id, host=host,
                    error=str(error))

    # -- introspection --------------------------------------------------
    @staticmethod
    def _view(job_id: str, row: dict) -> dict:
        spec = row.get("spec") or {}
        return {
            "job_id": job_id,
            "state": row["state"],
            "tenant": row.get("tenant", DEFAULT_TENANT),
            "priority": int(row.get("priority", 10)),
            "owner": row.get("owner"),
            "redos": int(row.get("redos", 0)),
            "error": row.get("error", ""),
            "submitted": row.get("submitted", 0.0),
            "artifacts": dict(row.get("artifacts", {})),
            "result": row.get("result"),
            "kind": str(spec.get("kind", "survey") or "survey"),
            "blocked_on": list(row.get("blocked_on") or ()),
            "dag": row.get("dag"),
        }

    def view(self, job_id: str) -> Optional[dict]:
        row = self._load()[self.ITEMS_KEY].get(job_id)
        return None if row is None else self._view(job_id, row)

    def depth(self) -> int:
        """Active fleet depth (pending + leased) — the router's load-
        shedding signal, mirroring the in-process queue's bound."""
        counts = self.counts()
        return counts.get(PENDING, 0) + counts.get(LEASED, 0)

    def lease_owners(self, tenant: Optional[str] = None) \
            -> Dict[str, int]:
        """Replica -> count of currently leased jobs (optionally one
        tenant's only) — the supervisor's preempt-target census: a
        ``preempt_fraction`` supervisor kills replicas holding
        campaign-tenant leases, and the lease reaper + epoch fence
        make that lossless."""
        out: Dict[str, int] = {}
        for row in self._load()[self.ITEMS_KEY].values():
            if row["state"] != LEASED:
                continue
            if (tenant is not None
                    and str(row.get("tenant")) != str(tenant)):
                continue
            owner = row.get("owner")
            if owner:
                out[str(owner)] = out.get(str(owner), 0) + 1
        return out

    def tenant_counts(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for row in self._load()[self.ITEMS_KEY].values():
            t = str(row.get("tenant", DEFAULT_TENANT))
            st = out.setdefault(t, {PENDING: 0, LEASED: 0, DONE: 0,
                                    FAILED: 0})
            st[row["state"]] = st.get(row["state"], 0) + 1
        return out

    def all_terminal(self) -> bool:
        jobs = self._load()[self.ITEMS_KEY]
        return bool(jobs) and all(j["state"] in (DONE, FAILED)
                                  for j in jobs.values())
