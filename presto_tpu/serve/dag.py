"""Discovery DAGs: the full science loop as one submitted job graph.

PAPER.md's reference pipeline is seven stages, but the fleet served
only stages 1-4 and stopped at candidate lists — sift
(`ACCEL_sift.py`), fold/verify (`prepfold`), and timing
(`get_TOAs.py`) existed as hand-driven CLIs invisible to the serving
layer.  This module closes the gap: a `DagSpec`
(search -> sift -> fold-per-surviving-candidate -> timing) is
submitted to the router as ONE durable unit, and replicas lease *any
ready node*, so cheap fan-out work (folds) from one survey
interleaves with heavy searches from another across the fleet.

The graph machinery rides the exactly-once lease core
(serve/jobledger.py):

  * **Dependencies** — a node admitted ``blocked_on`` its parents
    becomes leasable only once every parent's *fence-checked* commit
    lands; a zombie replica's late result never unblocks a child
    (the parent's state only becomes ``done`` through the epoch
    fence).
  * **Dynamic fan-out** — the sift node's surviving-candidate list
    decides the fold set at runtime.  The replica commits the sift
    result AND creates the fold jobs (plus the timing node's fold
    fan-in retarget) in ONE ledger transaction
    (`JobLedger.complete_and_expand`): a crash between "result
    landed" and "children exist" is impossible, re-expansion is
    idempotent, and a fenced zombie expands nothing.
  * **Fold stacking** — same-geometry fold jobs share a ledger/queue
    bucket (`apps/prepfold.fold_stack_key`), so `lease_batch` claims
    a whole fold batch, the micro-batching queue coalesces it, and
    `StackedBatchExecutor` runs the folds as ONE batched drizzle
    dispatch (`apps/prepfold.fold_dat_cands`) where N per-job folds
    pay N — the same continuous-batching shape search jobs ride.

Node executors run inside the replica's `SearchService`
(`execute_node`), reading parent artifacts from the parents'
*committed* epoch-stamped attempt dirs (resolved by the replica at
lease time, so a zombie's tree is never read).  Artifact labels
embedded in fold/timing outputs are basenames, making every DAG
artifact byte-equal to the hand-driven CLI sequence
(`accelsearch -> ACCEL_sift -> prepfold -> get_TOAs`) — pinned by
tests/test_dag.py and DAG_r11.json.

See docs/SERVING.md ("Discovery DAGs") for the schema and failure
semantics.
"""

from __future__ import annotations

import glob
import os
from typing import List, Optional

from presto_tpu.serve.queue import Job, JobStatus

#: DAG node kinds (``survey`` is the ordinary search job; ``triage``
#: is the opt-in learned scorer between sift and fold —
#: presto_tpu/triage/, docs/TRIAGE.md)
NODE_KINDS = ("survey", "sift", "triage", "fold", "toa")


def _bucket_hint(rawfiles, config) -> Optional[str]:
    """Best-effort plan-bucket hint for the search node (the router's
    admission-time computation; failure degrades to None — single
    leasing, never a rejected admission)."""
    try:
        from presto_tpu.pipeline.survey import SurveyConfig
        from presto_tpu.serve.plancache import bucket_key
        return repr(bucket_key(list(rawfiles),
                               SurveyConfig(**dict(config or {}))))
    except Exception:
        return None


def _pass_zmaxes(config: dict) -> List[int]:
    """The accel-pass zmax list the search node will write ACCEL
    tables for — the sift node's glob set."""
    try:
        from presto_tpu.pipeline.survey import SurveyConfig
        cfg = SurveyConfig(**dict(config or {}))
        return [int(z) for (z, _nh, _sg, _flo) in cfg.all_passes]
    except Exception:
        return [int((config or {}).get("zmax", 0))]


def plan_dag(spec: dict):
    """Turn one wire-level DAG submission into the node list
    `JobLedger.admit_dag` takes: ``[(rel_id, node_spec, bucket,
    parent_rel_ids)]``.

    Wire schema (POST /dag)::

        {"rawfiles": [...],          # required
         "config":   {...},          # SurveyConfig fields (search)
         "sift":     {"min_dm_hits", "low_dm_cutoff"},
         "fold":     {"fold_top", "fold_sigma", "max_folds"},
         "triage":   true | {"budget", "budget_frac",
                             "weights", "borderline_frac"},
         "toa":      {"ntoa", "gauss_fwhm", "fmt"},
         "tenant":   "...", "priority": int}

    With ``triage`` set, a fifth node kind slots between sift and
    fold: search -> sift -> triage -> folds -> toa.  The sift node
    keeps writing the sifted list but hands its fan-out to the
    triage node, which scores the heuristic fold selection with the
    learned ranker (presto_tpu/triage/) and fans out only the
    surviving budget — the SAME `complete_and_expand` transaction,
    cascade-fail, and chaos seams the sift fan-out rides.  Truth
    sidecars (``<rawfile>_injected.json``, models/inject.py) found
    beside the rawfiles at submission are stamped into the node spec
    so injection recall rides real traffic.

    The search node is an ordinary survey job (it stacks with plain
    search traffic) with folding disabled — folds are DAG nodes —
    and durable stages forced on: fold nodes read the committed .dat
    trials from the search attempt dir."""
    rawfiles = spec.get("rawfiles")
    if not rawfiles or not isinstance(rawfiles, (list, tuple)):
        raise ValueError("dag spec.rawfiles must be a non-empty list")
    config = dict(spec.get("config") or {})
    config["fold_top"] = 0
    config.pop("fold_sigma", None)
    config["durable_stages"] = True
    search_spec = {"rawfiles": list(rawfiles), "config": config}
    sift_spec = {
        "kind": "sift",
        "parents": {"search": "search"},
        "retarget": "toa",
        "zmaxes": _pass_zmaxes(config),
        "sift": dict(spec.get("sift") or {}),
        "fold": dict(spec.get("fold") or {}),
    }
    toa_spec = {
        "kind": "toa",
        "parents": {"fold": []},
        "toa": dict(spec.get("toa") or {}),
    }
    tpol = spec.get("triage")
    if not tpol:
        return [
            ("search", search_spec, _bucket_hint(rawfiles, config),
             []),
            ("sift", sift_spec, None, ["search"]),
            ("toa", toa_spec, None, ["sift"]),
        ]
    tpol = dict(tpol) if isinstance(tpol, dict) else {}
    if "truth" not in tpol:
        from presto_tpu.triage.calibrate import find_truth_sidecars
        tpol["truth"] = find_truth_sidecars(list(rawfiles))
    # the sift node keeps its durable artifact but hands fan-out (and
    # the toa retarget) to the triage node
    sift_spec.pop("retarget", None)
    sift_spec["fanout"] = False
    triage_spec = {
        "kind": "triage",
        "parents": {"search": "search", "sift": "sift"},
        "retarget": "toa",
        "zmaxes": _pass_zmaxes(config),
        "sift": dict(spec.get("sift") or {}),
        "fold": dict(spec.get("fold") or {}),
        "triage": tpol,
    }
    return [
        ("search", search_spec, _bucket_hint(rawfiles, config), []),
        ("sift", sift_spec, None, ["search"]),
        ("triage", triage_spec, None, ["sift"]),
        ("toa", toa_spec, None, ["triage"]),
    ]


# ----------------------------------------------------------------------
# Node jobs in the local service
# ----------------------------------------------------------------------

def build_node_job(service, spec: dict, job_id: Optional[str] = None,
                   workdir: Optional[str] = None) -> Job:
    """Validate one DAG node spec into a local queue Job (the
    node-kind arm of SearchService.build_job).  The bucket is the
    ledger row's (injected by the replica at lease time) — fold jobs
    carry their stack signature so same-geometry folds coalesce;
    sift/toa nodes get a unique bucket so they never falsely
    coalesce."""
    from presto_tpu.serve.server import BadRequest
    kind = str(spec.get("kind") or "")
    if kind not in NODE_KINDS or kind == "survey":
        raise BadRequest("unknown dag node kind %r" % kind)
    job_id = str(job_id or spec.get("job_id")
                 or "%s-%06d" % (kind, next(service._ids)))
    with service._jobs_lock:
        old = service._jobs.get(job_id)
        if old is not None and old.status not in JobStatus.SETTLED:
            raise BadRequest("duplicate job_id %r" % job_id)
    bucket = spec.get("bucket") or "dag-node:%s" % job_id
    return Job(job_id=job_id, rawfiles=[], cfg=None,
               workdir=workdir or os.path.join(service.workroot,
                                               job_id),
               priority=int(spec.get("priority", 10)),
               bucket=bucket, spec=dict(spec), kind=kind)


def _parent_dirs(job: Job, role: str):
    dirs = (job.spec.get("parent_dirs") or {}).get(role)
    if dirs is None:
        raise ValueError(
            "dag node %s has no resolved %r parent dir (submitted "
            "outside a fleet replica without spec.parent_dirs?)"
            % (job.job_id, role))
    return dirs


def _nodes_done(service, kind: str, n: int = 1) -> None:
    service.obs.metrics.counter(
        "dag_nodes_done_total",
        "DAG nodes executed to completion, by kind",
        ("kind",)).labels(kind=kind).inc(n)


def execute_node(service, job: Job) -> dict:
    """Execute one leased DAG node on the scheduler thread (the
    node-kind arm of SearchService._execute_job)."""
    span = service.obs.span("serve:dag-node", job=job.job_id,
                            kind=job.kind, dag=job.spec.get("dag"))
    try:
        if job.kind == "sift":
            result = _execute_sift(service, job)
        elif job.kind == "triage":
            result = _execute_triage(service, job)
        elif job.kind == "fold":
            result = _execute_fold(service, job)
        elif job.kind == "toa":
            result = _execute_toa(service, job)
        else:
            raise ValueError("unknown dag node kind %r" % job.kind)
    except Exception as e:
        span.finish("error: %s" % type(e).__name__)
        raise
    span.finish()
    _nodes_done(service, job.kind)
    return result


# ---- sift: candidates in, fold fan-out + timing fan-in out -----------

def _sift_parent_candlist(job: Job, pdir: str):
    """(Candlist, zmaxes): the sifted survivors of the search
    parent's ACCEL tables — deterministic (sorted glob, sorted
    reads), so the sift node and a downstream triage node derive the
    IDENTICAL list from the same committed parent dir."""
    from presto_tpu.pipeline.sifting import sift_candidates
    spec = job.spec
    zmaxes = [int(z) for z in (spec.get("zmaxes") or [0])]
    accfiles = []
    for z in zmaxes:
        accfiles += glob.glob(os.path.join(pdir, "*_ACCEL_%d" % z))
    accfiles = sorted(set(accfiles))
    pol = spec.get("sift") or {}
    cl = sift_candidates(
        accfiles, numdms_min=int(pol.get("min_dm_hits", 2)),
        low_DM_cutoff=float(pol.get("low_dm_cutoff", 2.0)))
    return cl, zmaxes


def _heuristic_selection(job: Job, cl, zmaxes) -> tuple:
    """(selected, accounting): the shared fold-selection policy the
    batch survey uses, heuristic arm only — the safe superset a
    triage policy may truncate."""
    from presto_tpu.pipeline.sifting import select_fold_candidates
    fpol = job.spec.get("fold") or {}
    per_pass = fpol.get("max_folds_per_pass")
    accounting: dict = {}
    top = select_fold_candidates(
        cl, fold_top=int(fpol.get("fold_top", 3)),
        fold_sigma=fpol.get("fold_sigma"),
        max_folds=int(fpol.get("max_folds", 150)),
        max_folds_per_pass=tuple(per_pass) if per_pass else None,
        pass_zmaxes=zmaxes, accounting=accounting)
    return top, accounting


def _fold_fanout(job: Job, top, pdir: str) -> tuple:
    """(children, retarget): one fold child per selected candidate,
    bucketed by the exact stack signature fold_dat_cands will group
    by, plus the timing node's fan-in retarget.  Shared verbatim by
    the sift node (heuristic path) and the triage node (scored
    path), which is what keeps triage policy-not-data-path: a
    candidate selected by either node fans out the identical fold
    spec, so the fold artifacts are byte-equal."""
    from presto_tpu.apps.prepfold import (accel_cand_fold_params,
                                          fold_geometry,
                                          fold_stack_key)
    from presto_tpu.io.infodata import read_inf
    spec = job.spec
    dag_id = spec.get("dag") or job.job_id
    search_id = (spec.get("parents") or {}).get("search")
    children, fold_ids = [], []
    for i, c in enumerate(top):
        accpath = os.path.join(c.path or pdir, c.filename)
        datbase = accpath.split("_ACCEL_")[0]
        info = read_inf(datbase)
        f0, fd0, _fdd = accel_cand_fold_params(
            accpath + ".cand", c.candnum, info.N * info.dt)
        N, dt, proflen, subdiv = fold_geometry(datbase + ".dat",
                                               f0, fd0)
        fid = "%s-fold-%03d" % (dag_id, i + 1)
        fold_ids.append(fid)
        children.append([fid, {
            "spec": {
                "kind": "fold",
                "dag": dag_id,
                "parents": {"search": search_id},
                "fold": {
                    "accelfile": os.path.basename(accpath) + ".cand",
                    "candnum": int(c.candnum),
                    "dm": float(c.DM),
                    "datfile": os.path.basename(datbase) + ".dat",
                    "outname": "fold_cand%d" % (i + 1),
                },
            },
            "bucket": fold_stack_key(N, dt, proflen, 64, subdiv),
            "blocked_on": [job.job_id],
            "dag": dag_id,
        }])
    retarget = {}
    toa_id = spec.get("retarget")
    if toa_id:
        retarget[toa_id] = {"blocked_on": list(fold_ids),
                            "parents": {"fold": list(fold_ids)}}
    return children, retarget


def _execute_sift(service, job: Job) -> dict:
    """Sift the search node's ACCEL tables, write the sifted list,
    and COMPUTE the dynamic fan-out: one fold child per surviving
    candidate (under the shared fold-selection policy) plus the
    timing node's retarget.  The fan-out is *returned*, not applied —
    the replica hands it to `JobLedger.complete_and_expand`, so
    children exist exactly when the sift result's fenced commit
    lands.  With ``spec.fanout`` false (a triage DAG), the node
    stops at the durable sifted list — the triage node downstream
    owns the fan-out."""
    spec = job.spec
    pdir = _parent_dirs(job, "search")
    cl, zmaxes = _sift_parent_candlist(job, pdir)
    os.makedirs(job.workdir, exist_ok=True)
    candfile = os.path.join(job.workdir, "cands_sifted.txt")
    cl.to_file(candfile)
    nbad = sum(len(v) for v in cl.badcands.values())
    result = {
        "candfile": os.path.basename(candfile),
        "n_cands": len(cl),
        "n_rejected": nbad,
        "n_duplicates": len(cl.duplicates),
    }
    if spec.get("fanout", True) is False:
        result["folds"] = 0
        result["deferred_to_triage"] = True
        return result
    top, accounting = _heuristic_selection(job, cl, zmaxes)
    children, retarget = _fold_fanout(job, top, pdir)
    result.update({
        "folds": len(children),
        "n_untagged_dropped": accounting.get("untagged_dropped", 0),
        "dag_children": children,
        "dag_retarget": retarget,
    })
    return result


# ---- triage: score the heuristic selection, fold only the budget -----

def _execute_triage(service, job: Job) -> dict:
    """Score the heuristic fold selection with the learned ranker
    and fan out only the surviving budget (presto_tpu/triage/,
    docs/TRIAGE.md).

    Semantics are the sift node's, inherited wholesale: the fan-out
    is returned for `complete_and_expand` (atomic, idempotent,
    zombie-fenced), a failure cascades to the toa node, and the
    replica's fold-fanout / post-sift-commit chaos seams fire around
    the commit because they key on the result's children, not the
    node kind.  On ANY weights problem the selection degrades to the
    heuristic list unchanged — the byte-stable default — and says so
    (``triage-fallback`` event, ``mode`` in the result)."""
    from presto_tpu.triage.calibrate import load_truth, truth_matches
    from presto_tpu.triage.model import TriagePolicy
    spec = job.spec
    pdir = _parent_dirs(job, "search")
    span = service.obs.span("serve:triage-node", job=job.job_id,
                            dag=spec.get("dag"))
    try:
        cl, zmaxes = _sift_parent_candlist(job, pdir)
        heuristic, accounting = _heuristic_selection(job, cl, zmaxes)
        tpol = spec.get("triage") or {}
        policy = TriagePolicy(
            weights_path=tpol.get("weights") or None,
            budget=tpol.get("budget"),
            budget_frac=tpol.get("budget_frac"),
            borderline_frac=float(tpol.get("borderline_frac", 0.25)),
            datdir=pdir)
        selected, acct = policy.select(heuristic, obs=service.obs)
        scores = acct.pop("scores", None)

        truth = []
        for side in tpol.get("truth") or ():
            truth += load_truth(side)
        recall = None
        recovered = 0
        if truth:
            matched = {m for m in truth_matches(selected, truth)
                       if m is not None}
            recovered = len(matched)
            recall = len(matched) / len(truth)
            service.obs.metrics.gauge(
                "triage_recall",
                "Injected-pulsar recall of the triage fold "
                "selection, from truth sidecars riding the "
                "traffic").set(recall)
        service.obs.metrics.counter(
            "triage_candidates_scored_total",
            "Sift survivors scored by the triage "
            "ranker").inc(acct["scored"])
        service.obs.metrics.counter(
            "triage_folds_avoided_total",
            "Folds the triage budget cut from the heuristic "
            "selection").inc(acct["folds_avoided"])

        os.makedirs(job.workdir, exist_ok=True)
        _write_scores(job, heuristic, selected, scores, acct,
                      recall)
        children, retarget = _fold_fanout(job, selected, pdir)
        if acct["mode"] == "triage":
            service.events.emit(
                "triage-score", job=job.job_id, dag=spec.get("dag"),
                scored=acct["scored"], selected=acct["selected"],
                folds_avoided=acct["folds_avoided"],
                recall=recall)
        else:
            service.events.emit(
                "triage-fallback", job=job.job_id,
                dag=spec.get("dag"),
                load_error=acct.get("load_error"))
    except Exception as e:
        span.finish("error: %s" % type(e).__name__)
        raise
    span.finish()
    return {
        "mode": acct["mode"],
        "scored": acct["scored"],
        "heuristic_folds": len(heuristic),
        "folds": len(children),
        "folds_avoided": acct["folds_avoided"],
        "load_error": acct.get("load_error"),
        "recall": recall,
        "recovered": recovered,
        "injected": len(truth),
        "n_untagged_dropped": accounting.get("untagged_dropped", 0),
        "scorefile": "triage_scores.json",
        "dag_children": children,
        "dag_retarget": retarget,
    }


def _write_scores(job: Job, heuristic, selected, scores, acct,
                  recall) -> None:
    """The node's durable artifact: every scored candidate with its
    score and the selection verdict (atomic write; read by
    presto-report and the calibration loop)."""
    import json

    from presto_tpu.io.atomic import atomic_write_text
    chosen = {(c.filename, c.candnum) for c in selected}
    rows = []
    for i, c in enumerate(heuristic):
        rows.append({
            "filename": c.filename, "candnum": int(c.candnum),
            "sigma": float(c.sigma), "dm": float(c.DM),
            "f": float(c.f),
            "score": (float(scores[i]) if scores is not None
                      else None),
            "selected": (c.filename, c.candnum) in chosen,
        })
    atomic_write_text(
        os.path.join(job.workdir, "triage_scores.json"),
        json.dumps({"schema": 1, "mode": acct["mode"],
                    "budget": acct.get("budget"),
                    "recall": recall, "candidates": rows},
                   indent=1, sort_keys=True))


# ---- fold: one candidate, CLI-parity artifacts -----------------------

def _fold_spec(job: Job):
    from presto_tpu.apps.prepfold import DatFoldSpec
    pdir = _parent_dirs(job, "search")
    f = job.spec.get("fold") or {}
    os.makedirs(job.workdir, exist_ok=True)
    return DatFoldSpec(
        datfile=os.path.join(pdir, f["datfile"]),
        accelfile=os.path.join(pdir, f["accelfile"]),
        candnum=int(f.get("candnum", 1)),
        outbase=os.path.join(job.workdir,
                             f.get("outname", "fold_cand1")),
        dm=float(f.get("dm", 0.0)))


def _fold_result(res: dict) -> dict:
    return {
        "pfd": os.path.basename(res["pfd"]),
        "bestprof": os.path.basename(res["bestprof"]),
        "best_p": res["best_p"],
        "best_pd": res["best_pd"],
        "best_redchi": res["best_redchi"],
        "stacked": res["stacked"],
    }


def _execute_fold(service, job: Job) -> dict:
    from presto_tpu.apps.prepfold import fold_dat_cands
    res = fold_dat_cands([_fold_spec(job)], obs=service.obs)[0]
    return _fold_result(res)


def run_folds_stacked(service, jobs: List[Job]) -> List[dict]:
    """The StackedBatchExecutor's fold arm: a coalesced same-bucket
    fold batch runs as ONE batched drizzle dispatch set
    (apps/prepfold.fold_dat_cands groups by the stack signature the
    bucket already pinned), byte-identical to per-job folds."""
    from presto_tpu.apps.prepfold import fold_dat_cands
    specs = [_fold_spec(job) for job in jobs]
    results = fold_dat_cands(specs, obs=service.obs)
    service.obs.metrics.counter(
        "dag_folds_stacked_total",
        "Fold jobs executed through the stacked drizzle "
        "dispatch").inc(len(jobs))
    _nodes_done(service, "fold", len(jobs))
    return [_fold_result(r) for r in results]


# ---- toa: fold fan-in, one .tim ---------------------------------------

def _execute_toa(service, job: Job) -> dict:
    """Extract TOAs from every committed fold parent, in candidate
    order, through the CLI's own line formatter (get_toas.toa_lines)
    — the .tim is byte-equal to the hand-driven `get_TOAs -o`."""
    from presto_tpu.apps.get_toas import toa_lines
    from presto_tpu.io.atomic import atomic_open
    from presto_tpu.io.errors import PrestoIOError
    dirs = _parent_dirs(job, "fold")
    pfds = []
    for d in dirs:
        found = sorted(glob.glob(os.path.join(d, "*.pfd")))
        if not found:
            raise PrestoIOError("no .pfd in committed fold dir",
                                path=d, kind="missing")
        pfds.extend(found)
    pol = job.spec.get("toa") or {}
    lines = toa_lines(pfds, ntoa=int(pol.get("ntoa", 1)),
                      gauss_fwhm=float(pol.get("gauss_fwhm", 0.1)),
                      fmt=str(pol.get("fmt", "princeton")))
    os.makedirs(job.workdir, exist_ok=True)
    timf = os.path.join(job.workdir, "toas.tim")
    with atomic_open(timf, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return {"tim": os.path.basename(timf), "n_pfds": len(pfds),
            "n_toas": sum(1 for ln in lines
                          if ln and not ln.startswith("FORMAT"))}
