"""Federation: many independent fleets behind one front door.

One `FederationRouter` fronts N fleets — each its own fleet directory,
router, supervisor, and device fingerprint — and treats **whole-fleet
death as replica death one level up**:

  * **Fleet liveness ledger** (`FedLedger`): the `LeaseLedger` core
    re-bound a third time, after DM shards (`pipeline/shardledger.py`)
    and fleet jobs (`serve/jobledger.py`) — now the *hosts* are whole
    fleets and the *items* are federated placements.  The federation
    driver heartbeats each member fleet for as long as its router
    answers `/healthz`; a fleet that stops answering (dead or
    partitioned — the ledger cannot and need not distinguish) times
    out, is reaped, and its placements are re-admitted.  The epoch
    bump fences the dead fleet's incarnation: a **zombie fleet's late
    commit is rejected** by the same `_fence_why` discipline that
    rejects a zombie replica's, so nothing is lost and nothing lands
    twice at the federated level.
  * **Priced placement**: each admitted job/DAG is priced in expected
    device-seconds per fleet — the fleet's own per-bucket usage cost
    model first (`obs/slo.bucket_cost_model`), its fleet-median bucket
    cost next, then per-fingerprint `PERF_LEDGER` episodes (relative
    throughput across device generations), and finally a **uniform
    price** (`default_job_s`) when a fleet has neither history nor
    episodes.  A fleet holding the job's raw data gets a locality
    discount, so ties break toward not moving bytes.
  * **Spill-over**: a fleet whose `/scale` advisory wants more
    replicas than are ready — or that answered a push with a 429
    shed — sorts behind its unsaturated siblings, so load on a hot
    fleet spills to the next-cheapest one.
  * **Global views are one more fold**: `/fleet/metrics` merges the
    per-fleet `fleetagg` aggregations with the same associative
    `merge`, `/slo` merges per-fleet SLO window states with
    `slo.merge_states` before one `evaluate_state`, and `/usage`
    folds per-fleet rollups — so federated burn-rate math equals the
    single-fleet computation on the merged windows by construction
    (property-pinned in tests/test_federation.py).

Chaos seams: the failover pass fires `FED_KILL_POINTS` through the
standard `FaultInjector` hook, so `tools/fed_chaos.py` can kill the
federation driver at fleet-death / pre-readmit / post-readmit and
exercise the zombie-fleet commit window.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time
import urllib.error
import urllib.request
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlparse

from presto_tpu.io.atomic import atomic_write_text
from presto_tpu.obs import fleetagg, slo
from presto_tpu.pipeline.leaseledger import (LEASED, PENDING,
                                             LeaseLedger, LedgerError,
                                             StaleLeaseError)
from presto_tpu.serve.events import EventLog
from presto_tpu.serve.usage import UsageLedger

#: chaos kill points the failover driver fires through its
#: FaultInjector hook — the authoritative runtime copy (re-exported by
#: testing/chaos.py, pinned against obs/taxonomy.FED_KILL_POINTS by
#: obs_lint check 19)
FED_KILL_POINTS = ("fleet-dead", "pre-readmit", "post-readmit",
                   "zombie-fleet-commit")

#: terminal remote states a placement settles on
_TERMINAL = ("done", "failed")


class FederationError(LedgerError):
    """Federation ledger protocol violation."""


class FedStaleCommit(StaleLeaseError, FederationError):
    """A result arriving from a fleet whose placement lease the
    federation has fenced off — the zombie-fleet case."""


class NoFleetAvailable(RuntimeError):
    """No alive member fleet accepted the placement (503)."""


class FederationBusy(RuntimeError):
    """Every alive fleet is saturated (429 + Retry-After)."""

    def __init__(self, retry_after_s: float):
        self.retry_after_s = retry_after_s
        super().__init__("every member fleet is saturated "
                         "(retry in %.1fs)" % retry_after_s)


class FedLedger(LeaseLedger):
    """Fleet liveness + placement ledger (`<feddir>/fleets.json`).

    Hosts are member *fleets* (joined with their router URL,
    heartbeated by the federation's probe loop, reaped on silence);
    items are federated *placements* — one row per admitted job or
    DAG, leased to the fleet it was routed to and fence-checked on
    commit exactly like a replica's job lease."""

    LEDGER_NAME = "fleets.json"
    ITEMS_KEY = "placements"
    ERROR = FederationError
    STALE = FedStaleCommit
    EV_LEASE = "fed-place"
    EV_DONE = "fed-commit"
    EV_REDO = "fed-readmit"
    EV_STALE = "fed-stale-commit"
    EV_HOST_DEAD = "fed-fleet-dead"
    EV_EPOCH_BUMP = "fed-epoch-bump"

    def admit(self, item_id: str, kind: str, spec: dict,
              tenant: str, bucket: Optional[str]) -> int:
        """Idempotently admit one federated item (pre-placement);
        returns the not-done count (ensure_items contract)."""
        return self.ensure_items([(item_id, {
            "kind": kind, "spec": spec, "tenant": tenant,
            "bucket": bucket})])

    def place(self, item_id: str, fleet: str, ttl: float,
              now: Optional[float] = None):
        """Targeted lease: bind one pending placement to one alive
        member fleet (the routing decision, durably recorded before
        the job is pushed).  None when the item is no longer pending
        (already placed or terminal — the idempotent-resume case)."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            h = state["hosts"].get(fleet)
            if h is None or not h.get("alive", False):
                raise self.ERROR(
                    "fleet %r is not an alive federation member"
                    % fleet)
            row = self._items(state).get(item_id)
            if row is None:
                raise self.ERROR("unknown federated item %r"
                                 % item_id)
            if row["state"] != PENDING:
                return None
            row["state"] = LEASED
            row["owner"] = fleet
            row["lease_epoch"] = int(state["epoch"])
            row["lease_expires"] = now + ttl
            row["leased_at"] = now
            self._save(state)
            epoch = int(state["epoch"])
        self._event(self.EV_LEASE, item=item_id, host=fleet,
                    epoch=epoch)
        return self._make_lease(item_id, row, epoch)

    def fail_terminal(self, lease, fleet: str, why: str,
                      now: Optional[float] = None) -> None:
        """Fence-checked terminal failure: the remote fleet reported
        the job/DAG failed for good (retry budget exhausted there), so
        the federation must not bounce it between fleets forever."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            row = self._items(state).get(lease.item_id)
            bad = self._fence_why(row, lease, fleet)
            if bad is not None:
                self._reject_stale(state, lease, fleet, {}, bad)
            row["state"] = "failed"
            row["owner"] = fleet
            row["lease_epoch"] = None
            row["lease_expires"] = None
            row["failed_why"] = why
            row["completed_at"] = now
            self._save(state)
        self._event(self.EV_DONE, item=lease.item_id, host=fleet,
                    status="failed", why=why)

    def placements(self) -> Dict[str, dict]:
        return dict(self._items(self._load()))

    def adopt_leases(self) -> Dict[str, Tuple[str, object]]:
        """item_id -> (fleet, lease) for every currently leased
        placement — a restarted federation driver resumes polling the
        placements its dead incarnation made (the lease fields are in
        the durable row, so nothing depends on driver memory)."""
        out: Dict[str, Tuple[str, object]] = {}
        state = self._load()
        for iid, row in sorted(self._items(state).items()):
            if row["state"] == LEASED:
                out[iid] = (row["owner"], self._make_lease(
                    iid, row, int(row["lease_epoch"])))
        return out


# ----------------------------------------------------------------------
# configuration
# ----------------------------------------------------------------------

@dataclass
class FleetMember:
    """One federated fleet: its shared directory (for ledger/obs
    reads — the filesystem is the source of truth), its router URL
    (for pushes and liveness probes), an optional device fingerprint
    (the PERF_LEDGER pricing key), and the data roots it holds
    locally (the locality preference)."""
    name: str
    fleetdir: str
    url: str = ""
    fingerprint: Optional[str] = None
    data_roots: Tuple[str, ...] = ()


@dataclass
class FederationConfig:
    feddir: str
    fleets: List[FleetMember] = field(default_factory=list)
    poll_s: float = 1.0
    #: fleet heartbeat TTL: a member whose /healthz has not answered
    #: for this long is reaped (dead or partitioned — same remedy)
    heartbeat_ttl: float = 6.0
    #: placement lease TTL (renewed every pump pass while the owning
    #: fleet is alive; expiry alone also triggers re-admission)
    place_ttl: float = 600.0
    http_timeout: float = 4.0
    #: uniform price: expected device-seconds for a job on a fleet
    #: with no usage history and no PERF_LEDGER episodes — the
    #: documented fallback that keeps a cold federation routable
    default_job_s: float = 5.0
    #: price factor for a fleet holding the job's raw data locally
    locality_discount: float = 0.75
    #: PERF_LEDGER workload key used for per-fingerprint pricing
    perf_workload: str = "smoke"
    perf_ledger_path: Optional[str] = None
    #: give up re-placing an item after this many redos (a job that
    #: fails on every fleet is poisoned, not unlucky)
    max_redos: int = 6
    retry_after_s: float = 2.0
    fault_injector: Optional[object] = None


# ----------------------------------------------------------------------
# HTTP plumbing (stdlib only, like the fleet router)
# ----------------------------------------------------------------------

def _http_json(method: str, url: str, body: Optional[dict] = None,
               timeout: float = 4.0) -> Tuple[int, dict]:
    """(status, parsed JSON body) — HTTPError is a response, not an
    exception (the router speaks JSON at every status); URLError and
    timeouts propagate (the fleet is unreachable, which is the
    liveness signal)."""
    data = None
    if body is not None:
        data = json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {}
        return e.code, payload


# ----------------------------------------------------------------------
# the federated router
# ----------------------------------------------------------------------

class FederationRouter:
    """Admission + observation front door over N member fleets."""

    def __init__(self, cfg: FederationConfig, obs=None):
        from presto_tpu.obs import Observability, ObsConfig
        if not cfg.fleets:
            raise ValueError("a federation needs at least one fleet")
        self.cfg = cfg
        self.obs = obs or Observability(
            ObsConfig(enabled=True, service="presto-fed"))
        os.makedirs(cfg.feddir, exist_ok=True)
        self.fedledger = FedLedger(cfg.feddir, obs=self.obs)
        self.events = EventLog(
            path=os.path.join(cfg.feddir, "fed_events.jsonl"))
        self._injector = cfg.fault_injector
        self._members = {m.name: m for m in cfg.fleets}
        if len(self._members) != len(cfg.fleets):
            raise ValueError("duplicate fleet names in federation")
        self._usage = {m.name: UsageLedger(m.fleetdir)
                       for m in cfg.fleets}
        self._step_lock = threading.Lock()
        self._state_lock = threading.Lock()  # presto-lint: guards(_epochs, _advice, _shed_until, _placed)
        self._epochs: Dict[str, int] = {}
        self._advice: Dict[str, dict] = {}
        self._shed_until: Dict[str, float] = {}
        self._placed: Dict[str, List[Tuple[str, object]]] = {}
        self._stop = threading.Event()
        self._poll_t: Optional[threading.Thread] = None
        reg = self.obs.metrics
        self._g_alive = reg.gauge(
            "fed_fleets_alive", "Member fleets currently alive")
        self._g_epoch = reg.gauge(
            "fed_epoch", "Federation membership epoch (fence token)")
        self._c_sub = reg.counter(
            "fed_submissions_total",
            "Federated jobs/DAGs pushed to a member fleet",
            ("fleet",))
        self._c_spill = reg.counter(
            "fed_spills_total",
            "Placements routed past a saturated fleet to a sibling")
        self._c_readmit = reg.counter(
            "fed_readmits_total",
            "Placements re-admitted after fleet death or lease "
            "expiry")
        self._c_stale = reg.counter(
            "fed_stale_commits_total",
            "Zombie-fleet commits rejected by the epoch fence")
        self._c_commit = reg.counter(
            "fed_commits_total",
            "Federated results committed through the fence")
        for m in cfg.fleets:
            epoch = self.fedledger.join(m.name, addr=m.url)
            self.fedledger.heartbeat(m.name, epoch)
            with self._state_lock:
                self._epochs[m.name] = epoch
            self.events.emit("fed-fleet-join", fleet=m.name,
                             url=m.url, fleetdir=m.fleetdir,
                             fingerprint=m.fingerprint, epoch=epoch)
        with self._state_lock:
            self._placed.update(
                {iid: [pl] for iid, pl
                 in self.fedledger.adopt_leases().items()})
        self._g_epoch.set(self.fedledger.epoch)
        self._g_alive.set(len(self.alive_fleets()))

    # ---- chaos seam ---------------------------------------------------

    def _point(self, name: str) -> None:
        """Kill-point hook: the stamp is recorded BEFORE the injector
        may kill us, so a dead federation driver's event stream names
        its kill point (mirrors fleet.py's `_chaos`)."""
        if self._injector is None:
            return
        self.events.emit("fed-chaos-point", point=name)
        self._injector.point(name)

    # ---- membership / liveness ----------------------------------------

    def alive_fleets(self, now: Optional[float] = None) -> List[str]:
        return self.fedledger.alive_hosts(
            now, ttl=self.cfg.heartbeat_ttl)

    def probe(self, now: Optional[float] = None) -> Dict[str, bool]:
        """One liveness pass: GET each member router's /healthz; a
        healthy answer heartbeats the fleet (and refreshes its cached
        /scale advisory), silence lets its heartbeat age toward the
        reaper.  A previously-dead fleet that answers again re-joins
        at the current epoch — its fenced placements were already
        re-admitted, so it simply starts fresh."""
        now = time.time() if now is None else now
        results: Dict[str, bool] = {}
        ledger_state = self.fedledger.read()
        for name, m in sorted(self._members.items()):
            ok = False
            if m.url:
                try:
                    status, _ = _http_json(
                        "GET", m.url + "/healthz",
                        timeout=self.cfg.http_timeout)
                    ok = status == 200
                except OSError as e:
                    self.events.emit("fed-probe-error", fleet=name,
                                     error=str(e))
            results[name] = ok
            if not ok:
                continue
            host = ledger_state["hosts"].get(name)
            if host is not None and not host.get("alive", False):
                epoch = self.fedledger.join(name, addr=m.url,
                                            now=now)
                with self._state_lock:
                    self._epochs[name] = epoch
                self.events.emit("fed-fleet-join", fleet=name,
                                 url=m.url, fleetdir=m.fleetdir,
                                 fingerprint=m.fingerprint,
                                 epoch=epoch, rejoin=True)
            with self._state_lock:
                epoch = self._epochs.get(name, 0)
            self.fedledger.heartbeat(name, epoch, now=now)
            self._refresh_advice(m)
        self._g_alive.set(len(self.alive_fleets(now)))
        return results

    def _refresh_advice(self, m: FleetMember) -> None:
        try:
            status, advice = _http_json(
                "GET", m.url + "/scale",
                timeout=self.cfg.http_timeout)
        except OSError:
            return
        if status == 200:
            with self._state_lock:
                self._advice[m.name] = advice

    def tombstone_fleet(self, name: str,
                        now: Optional[float] = None) -> None:
        """Graceful member departure: the reaper re-admits its
        placements immediately instead of waiting out the TTL."""
        self.fedledger.tombstone(name, now=now)

    # ---- placement pricing --------------------------------------------

    def _perf_ledger(self):
        from presto_tpu.obs import perfledger
        path = (self.cfg.perf_ledger_path
                or perfledger.default_ledger_path())
        try:
            return perfledger.PerfLedger.load(path)
        except Exception:
            return None

    def _perf_speed(self, fingerprint: Optional[str]) \
            -> Optional[float]:
        """Geometric-mean throughput of a fingerprint's PERF_LEDGER
        episodes (direction='higher' metrics only) — the relative-
        speed signal that prices a fleet with no usage history of its
        own."""
        if not fingerprint:
            return None
        led = self._perf_ledger()
        if led is None:
            return None
        eps = led.select(fingerprint=fingerprint,
                         workload=self.cfg.perf_workload)
        if not eps:
            eps = led.select(fingerprint=fingerprint)
        vals = []
        for ep in eps[-3:]:
            for m in ep.get("metrics", {}).values():
                if (m.get("direction") == "higher"
                        and isinstance(m.get("median"),
                                       (int, float))
                        and m["median"] > 0.0):
                    vals.append(math.log(float(m["median"])))
        if not vals:
            return None
        return math.exp(sum(vals) / len(vals))

    def price_fleet(self, member: FleetMember,
                    bucket: Optional[str]) -> Tuple[float, str]:
        """(expected device-seconds, source) for one bucket on one
        fleet.  Pricing ladder: the fleet's own per-bucket usage cost
        model -> its fleet-median bucket cost -> per-fingerprint
        PERF_LEDGER episodes (federation-median throughput over this
        fingerprint's throughput, scaled onto default_job_s) -> the
        uniform default_job_s."""
        rows = self._usage[member.name].rows()
        means, _ = slo.bucket_cost_model(rows)
        b = str(bucket or "")
        if b in means:
            return means[b], "usage-bucket"
        if means:
            return (slo.fleet_median_cost(
                means, self.cfg.default_job_s), "usage-median")
        speed = self._perf_speed(member.fingerprint)
        if speed is not None:
            speeds = [s for s in
                      (self._perf_speed(m.fingerprint)
                       for m in self.cfg.fleets) if s is not None]
            ref = sorted(speeds)[len(speeds) // 2]
            return (self.cfg.default_job_s * ref / speed,
                    "perf-ledger")
        return self.cfg.default_job_s, "uniform"

    @staticmethod
    def _is_local(member: FleetMember, spec: dict) -> bool:
        raws = spec.get("rawfiles") or []
        if not member.data_roots or not raws:
            return False
        roots = [os.path.abspath(r) for r in member.data_roots]
        return all(any(os.path.abspath(str(f)).startswith(
            root + os.sep) or os.path.abspath(str(f)) == root
            for root in roots) for f in raws)

    def _saturated(self, name: str,
                   now: Optional[float] = None) -> bool:
        """A fleet is saturated while its last push shed (429,
        honored until Retry-After expires) or its /scale advisory
        wants more replicas than are ready — the same pressure signal
        a supervisor scales on, read as a routing signal here."""
        now = time.time() if now is None else now
        with self._state_lock:
            if now < self._shed_until.get(name, 0.0):
                return True
            advice = self._advice.get(name)
        if not advice:
            return False
        inputs = advice.get("inputs") or {}
        ready = int(inputs.get("ready_replicas") or 0)
        return int(advice.get("wanted_replicas") or 0) > ready

    def candidates(self, bucket: Optional[str], spec: dict,
                   now: Optional[float] = None) -> List[dict]:
        """Alive fleets ordered for placement: unsaturated before
        saturated, then by locality-discounted price, then by name
        (a stable tiebreak).  Every candidate carries its pricing
        provenance for the /fed view and the verdict artifacts."""
        now = time.time() if now is None else now
        alive = set(self.alive_fleets(now))
        out = []
        for name, m in sorted(self._members.items()):
            if name not in alive:
                continue
            price, source = self.price_fleet(m, bucket)
            local = self._is_local(m, spec)
            eff = price * (self.cfg.locality_discount if local
                           else 1.0)
            out.append({"fleet": name, "price_s": price,
                        "effective_s": eff, "source": source,
                        "local": local,
                        "saturated": self._saturated(name, now)})
        out.sort(key=lambda c: (c["saturated"], c["effective_s"],
                                c["fleet"]))
        return out

    # ---- admission ----------------------------------------------------

    @staticmethod
    def _bucket_hint(spec: dict) -> Optional[str]:
        from presto_tpu.serve.router import FleetRouter
        return FleetRouter._bucket_hint(spec)

    def submit(self, spec: dict) -> dict:
        """Durably admit one job to the federation and place it on
        the best-priced alive fleet (spilling past saturated ones).
        The federated job id doubles as the member fleet's job id, so
        a re-push after fleet death is idempotent downstream."""
        with self.obs.span("fed:submit") as span:
            return self._admit("job", spec, span)

    def submit_dag(self, spec: dict) -> dict:
        """Durably admit one discovery DAG.  Failover granularity is
        the whole graph: a dead fleet's unexpanded subtrees cannot be
        grafted node-by-node onto a survivor (the sift's fan-out is
        fleet-local), so the survivor re-admits the DAG under the
        same id and re-expands it there — the federated commit still
        lands exactly once through the fence."""
        with self.obs.span("fed:dag-submit") as span:
            return self._admit("dag", spec, span)

    def _admit(self, kind: str, spec: dict, span) -> dict:
        if not isinstance(spec, dict):
            raise ValueError("spec must be a JSON object")
        tenant = str(spec.get("tenant") or "default")
        span.set_attr("tenant", tenant)
        iid = str(spec.get("job_id") or spec.get("dag_id")
                  or "fed-%s" % uuid.uuid4().hex[:12])
        bucket = self._bucket_hint(spec)
        self.fedledger.admit(iid, kind, spec, tenant, bucket)
        self.events.emit("fed-admit", item=iid, item_kind=kind,
                         tenant=tenant, bucket=bucket)
        placement = self._place_and_push(iid, kind, spec, bucket)
        span.set_attr("item", iid)
        span.set_attr("fleet", placement["fleet"])
        return {"item": iid, "kind": kind, "tenant": tenant,
                "placement": placement}

    def _place_and_push(self, iid: str, kind: str, spec: dict,
                        bucket: Optional[str],
                        now: Optional[float] = None) -> dict:
        """Route one pending item: walk the priced candidate order,
        durably lease the placement, then push to the fleet's router.
        A 429 marks the fleet shed (spill), an unreachable fleet
        releases the lease and tries the next sibling; raises
        FederationBusy / NoFleetAvailable when the walk ends."""
        now = time.time() if now is None else now
        cands = self.candidates(bucket, spec, now)
        # the fleet a pure price ordering would pick — when it is
        # saturated and the walk lands elsewhere, that is a spill
        best = (min(cands, key=lambda c: (c["effective_s"],
                                          c["fleet"]))
                if cands else None)
        with self.obs.span("fed:place", item=iid) as span:
            any_shed = False
            for pos, cand in enumerate(cands):
                name = cand["fleet"]
                member = self._members[name]
                try:
                    lease = self.fedledger.place(
                        iid, name, ttl=self.cfg.place_ttl, now=now)
                except FederationError:
                    continue            # died between census and place
                if lease is None:
                    # no longer pending: placed by a concurrent pass
                    # or already terminal — idempotent resume
                    row = self.fedledger.placements().get(iid, {})
                    return {"fleet": row.get("owner"),
                            "state": row.get("state"),
                            "resumed": True}
                status, detail = self._push(member, iid, kind, spec)
                if status == "ok":
                    with self._state_lock:
                        self._placed.setdefault(iid, []).append(
                            (name, lease))
                    self._c_sub.labels(fleet=name).inc()
                    spilled_past = [c["fleet"] for c in cands[:pos]]
                    if (best is not None and best["fleet"] != name
                            and best["saturated"]
                            and best["fleet"] not in spilled_past):
                        spilled_past.insert(0, best["fleet"])
                    if spilled_past:
                        self._c_spill.inc()
                        self.events.emit(
                            "fed-spill", item=iid, to=name,
                            past=spilled_past,
                            why=("shed" if any_shed
                                 else "saturated"))
                    span.set_attr("fleet", name)
                    return dict(cand, state="leased")
                self.fedledger.fail(lease, name)
                if status == "shed":
                    any_shed = True
                    with self._state_lock:
                        self._shed_until[name] = now + float(
                            detail.get("retry_after_s")
                            or self.cfg.retry_after_s)
                else:
                    self.events.emit("fed-push-error", item=iid,
                                     fleet=name, detail=str(detail))
            if any_shed:
                raise FederationBusy(self.cfg.retry_after_s)
            raise NoFleetAvailable(
                "no alive member fleet accepted %r (%d candidates)"
                % (iid, len(cands)))

    def _push(self, member: FleetMember, iid: str, kind: str,
              spec: dict) -> Tuple[str, dict]:
        """Push one placement to its fleet's router.  'ok' covers the
        duplicate-id answer: the id was minted by the federation, so
        a duplicate means a previous incarnation's push landed — the
        idempotent-resume contract, same as the campaign engine's."""
        if not member.url:
            return "unreachable", {"error": "no router url"}
        body = dict(spec)
        path = "/submit" if kind == "job" else "/dag"
        body["job_id" if kind == "job" else "dag_id"] = iid
        try:
            status, payload = _http_json(
                "POST", member.url + path, body,
                timeout=self.cfg.http_timeout)
        except OSError as e:
            return "unreachable", {"error": str(e)}
        if status == 202:
            return "ok", payload
        if "duplicate" in str(payload.get("error", "")):
            return "ok", payload
        if status == 429:
            return "shed", payload
        return "rejected", payload

    # ---- the pump: placements -> terminal federated commits -----------

    def _remote_view(self, member: FleetMember, iid: str,
                     kind: str) -> Tuple[Optional[dict], str]:
        """(view, via): the placement's state on its fleet — over
        HTTP while the router answers, straight from the fleet
        directory's job ledger otherwise.  The ledger read is how a
        *dead* fleet's landed results are discovered (read-only: the
        federation never writes a member fleet's ledger)."""
        path = ("/jobs/" if kind == "job" else "/dag/") + iid
        if member.url:
            try:
                status, payload = _http_json(
                    "GET", member.url + path,
                    timeout=self.cfg.http_timeout)
                if status == 200:
                    return payload, "http"
                if status == 404:
                    return None, "http"
            except OSError:
                pass
        from presto_tpu.serve.jobledger import JobLedger
        led = JobLedger(member.fleetdir)
        view = (led.view(iid) if kind == "job"
                else led.dag_view(iid))
        return view, "ledger"

    def _commit(self, iid: str, fleet: str, lease, view: dict,
                now: float) -> bool:
        """Land one federated result through the fence: the remote
        terminal view is staged next to the final result path and
        committed under the fleets.json lock (fence-check -> rename
        -> journal).  A zombie fleet's late result dies here — the
        staged file is deleted, the journaled artifact untouched."""
        resdir = os.path.join(self.cfg.feddir, "results")
        os.makedirs(resdir, exist_ok=True)
        final = os.path.join(resdir, "%s.json" % iid)
        tmp = os.path.join(resdir, ".staged-%s.json" % iid)
        atomic_write_text(tmp, json.dumps(
            {"item": iid, "fleet": fleet, "view": view},
            indent=1, sort_keys=True) + "\n")
        ledger_state = self.fedledger.read()
        host = ledger_state["hosts"].get(fleet) or {}
        if not host.get("alive", False):
            # a result surfacing from a fleet the federation has
            # declared dead: the textbook zombie commit
            self._point("zombie-fleet-commit")
        try:
            self.fedledger.complete(
                lease, fleet, {final: tmp}, now=now,
                extra={"remote_state": view.get("state")})
            self._c_commit.inc()
            return True
        except FedStaleCommit:
            self._c_stale.inc()
            return False

    def pump(self, now: Optional[float] = None) -> dict:
        """One pass over live placements: renew leases of alive
        owners, poll each placement's remote state, commit terminal
        results through the fence (failed ones terminally,
        fence-checked too), and place anything pending (admitted but
        never routed, or re-admitted by the reaper)."""
        now = time.time() if now is None else now
        with self._state_lock:
            placed = {iid: list(pls)
                      for iid, pls in self._placed.items()}
        committed, stale = 0, 0
        for iid, pls in sorted(placed.items()):
            for fleet, lease in pls:
                member = self._members.get(fleet)
                if member is None:
                    continue
                row = self.fedledger.placements().get(iid)
                if row is None:
                    self._drop_placement(iid, fleet)
                    continue
                kind = str(row.get("kind") or "job")
                held = (row["state"] == LEASED
                        and row["owner"] == fleet
                        and int(row["lease_epoch"] or -1)
                        == int(lease.epoch))
                view, _via = self._remote_view(member, iid, kind)
                if view is None:
                    if held:
                        # pushed-then-crashed window (or a fleet
                        # that lost the push): re-push, same id
                        self._push(member, iid, kind,
                                   dict(row.get("spec") or {}))
                    elif row["state"] in ("done", "failed"):
                        # fenced-off placement whose fleet never saw
                        # the push: nothing can land late; forget it
                        self._drop_placement(iid, fleet)
                    continue
                if view.get("state") not in _TERMINAL:
                    if held:
                        self.fedledger.renew(
                            lease, fleet, self.cfg.place_ttl,
                            now=now)
                    continue
                # a terminal remote state commits through the fence
                # even when `held` is false — a fenced-off fleet's
                # late result MUST be rejected there (the zombie
                # path), never silently discarded before the fence
                if view.get("state") == "failed":
                    try:
                        self.fedledger.fail_terminal(
                            lease, fleet,
                            "remote %s failed" % kind, now=now)
                    except FedStaleCommit:
                        self._c_stale.inc()
                        stale += 1
                elif self._commit(iid, fleet, lease, view, now):
                    committed += 1
                else:
                    stale += 1
                self._drop_placement(iid, fleet)
        replaced = self._place_pending(now)
        return {"committed": committed, "stale": stale,
                "placed": replaced}

    def _drop_placement(self, iid: str, fleet: str) -> None:
        with self._state_lock:
            pls = self._placed.get(iid) or []
            pls = [(f, l) for f, l in pls if f != fleet]
            if pls:
                self._placed[iid] = pls
            else:
                self._placed.pop(iid, None)

    def _place_pending(self, now: float) -> int:
        """Route every pending placement (fresh admissions that never
        got a fleet, plus items the reaper re-admitted)."""
        n = 0
        for iid, row in sorted(
                self.fedledger.placements().items()):
            if row["state"] != PENDING:
                continue
            if int(row.get("redos", 0)) > self.cfg.max_redos:
                continue
            try:
                self._place_and_push(
                    iid, str(row.get("kind") or "job"),
                    dict(row.get("spec") or {}),
                    row.get("bucket"), now=now)
                n += 1
            except (FederationBusy, NoFleetAvailable):
                break
        return n

    # ---- failover: whole-fleet death as replica death -----------------

    def failover(self, now: Optional[float] = None) -> dict:
        """One failure-detection pass one level up: reap member
        fleets whose heartbeat went silent (dead or partitioned),
        re-admit their placements, and re-route them on survivors —
        through the same epoch fence that re-admits a dead replica's
        jobs, so the dead fleet's late commits are rejected and
        nothing is lost or landed twice."""
        now = time.time() if now is None else now
        with self.obs.span("fed:failover") as span:
            report = self.fedledger.reap(
                self.cfg.heartbeat_ttl, now=now)
            self._g_epoch.set(report.epoch)
            if report.dead_hosts:
                self._point("fleet-dead")
                self._g_alive.set(len(self.alive_fleets(now)))
            readmitted = []
            for iid in report.redone:
                row = self.fedledger.placements().get(iid)
                if row is None or row["state"] != PENDING:
                    continue
                if int(row.get("redos", 0)) > self.cfg.max_redos:
                    continue
                self._point("pre-readmit")
                self._c_readmit.inc()
                try:
                    self._place_and_push(
                        iid, str(row.get("kind") or "job"),
                        dict(row.get("spec") or {}),
                        row.get("bucket"), now=now)
                    readmitted.append(iid)
                    self._point("post-readmit")
                except (FederationBusy, NoFleetAvailable):
                    # stays pending; the next pump pass retries
                    break
            span.set_attr("dead", len(report.dead_hosts))
            span.set_attr("readmitted", len(readmitted))
        return {"dead_fleets": report.dead_hosts,
                "epoch": report.epoch, "bumped": report.bumped,
                "readmitted": readmitted}

    def step(self, now: Optional[float] = None) -> dict:
        """One driver pass (probe -> failover -> pump), serialized so
        the poll loop and an on-demand caller never interleave."""
        now = time.time() if now is None else now
        with self._step_lock:
            self.probe(now)
            fo = self.failover(now)
            pu = self.pump(now)
        return {"failover": fo, "pump": pu}

    # ---- lifecycle ----------------------------------------------------

    def start(self) -> "FederationRouter":
        self._stop.clear()
        self._poll_t = threading.Thread(
            target=self._poll_loop, name="presto-fed-poll",
            daemon=True)
        self._poll_t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._poll_t is not None:
            self._poll_t.join(timeout=10.0)
        self.events.close()
        self.obs.tracer.close()

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception as e:
                self.events.emit("fed-probe-error",
                                 error="step: %s" % e)
            self._stop.wait(self.cfg.poll_s)

    # ---- introspection / global folds ---------------------------------

    def status(self, item_id: str) -> Optional[dict]:
        row = self.fedledger.placements().get(item_id)
        if row is None:
            return None
        return {"item": item_id, "state": row["state"],
                "fleet": row.get("owner"),
                "kind": row.get("kind"),
                "redos": int(row.get("redos", 0))}

    def result(self, item_id: str) -> Optional[dict]:
        path = os.path.join(self.cfg.feddir, "results",
                            "%s.json" % item_id)
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def fleets_view(self, now: Optional[float] = None) -> dict:
        """GET /fed: the liveness ledger one level up — members with
        aliveness/epochs, placement counts, and the live candidate
        pricing table (empty-bucket pricing: what a cold job would
        pay on each fleet right now)."""
        now = time.time() if now is None else now
        state = self.fedledger.read()
        alive = set(self.alive_fleets(now))
        counts: Dict[str, int] = {}
        for row in state["placements"].values():
            counts[row["state"]] = counts.get(row["state"], 0) + 1
        return {
            "feddir": self.cfg.feddir,
            "epoch": int(state["epoch"]),
            "fleets": {
                name: {"alive": name in alive,
                       "url": m.url,
                       "fingerprint": m.fingerprint,
                       "saturated": self._saturated(name, now)}
                for name, m in sorted(self._members.items())},
            "placements": counts,
            "pricing": self.candidates(None, {}, now),
        }

    def fed_metrics(self, now: Optional[float] = None) -> dict:
        """GET /fleet/metrics: one more fleetagg fold — each member
        fleet's replica snapshots are merged per fleet, then the
        per-fleet merged states are merged again with the same
        associative `merge`, so the federated aggregate equals the
        single-registry aggregate over all snapshots."""
        now = time.time() if now is None else now
        merged: dict = {}
        per: Dict[str, dict] = {}
        for name, m in sorted(self._members.items()):
            agg = fleetagg.aggregate(m.fleetdir, now=now)
            per[name] = {"replicas": agg["replicas"],
                         "stale_replicas": agg["stale_replicas"]}
            merged = fleetagg.merge(merged, agg["merged"])
        return {"feddir": self.cfg.feddir, "fleets": per,
                "metrics": fleetagg.to_json(merged)}

    def slo_view(self, now: Optional[float] = None) -> dict:
        """GET /slo: federated burn rates — per-fleet SLO window
        states merged with `slo.merge_states` (associative +
        commutative) before ONE `evaluate_state`, so the federated
        burn math equals the single-fleet computation on the merged
        windows by construction."""
        now = time.time() if now is None else now
        specs: Dict[str, object] = {}
        for m in self.cfg.fleets:
            for spec in slo.load_specs(m.fleetdir):
                specs.setdefault(spec.tenant, spec)
        tenants = {}
        for tenant, spec in sorted(specs.items()):
            merged = None
            for m in self.cfg.fleets:
                st = slo.window_state(
                    spec, self._usage[m.name].rows(), now)
                merged = (st if merged is None
                          else slo.merge_states(merged, st))
            tenants[tenant] = slo.evaluate_state(spec, merged)
        return {"tenants": tenants,
                "fleets": sorted(self._members)}

    def usage_view(self) -> dict:
        """GET /usage: per-fleet rollups plus the federated rollup
        over the concatenated rows (device-second sums are
        associative, so the fold equals the flat rollup)."""
        per: Dict[str, dict] = {}
        all_rows: List[dict] = []
        for name in sorted(self._members):
            rows = self._usage[name].rows()
            per[name] = slo.usage_rollup(rows)
            all_rows.extend(rows)
        return {"fleets": per,
                "merged": slo.usage_rollup(all_rows)}

    def scale_view(self, now: Optional[float] = None) -> dict:
        """GET /scale: every member's cached advisory plus the
        saturation verdict the placer routes on."""
        now = time.time() if now is None else now
        with self._state_lock:
            advice = dict(self._advice)
        return {"fleets": {
            name: {"advice": advice.get(name),
                   "saturated": self._saturated(name, now)}
            for name in sorted(self._members)}}


# ----------------------------------------------------------------------
# HTTP front door
# ----------------------------------------------------------------------

class _FedHandler(BaseHTTPRequestHandler):
    server_version = "presto-fed/1"

    @property
    def fed(self) -> FederationRouter:
        return self.server.fed          # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _json(self, status: int, payload: dict,
              headers: Optional[dict] = None) -> None:
        body = json.dumps(payload, sort_keys=True).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:
        path = urlparse(self.path).path
        try:
            if path == "/healthz":
                self._json(200, {"ok": True,
                                 "fleets": self.fed.alive_fleets()})
            elif path == "/fed":
                self._json(200, self.fed.fleets_view())
            elif path == "/fleet/metrics":
                self._json(200, self.fed.fed_metrics())
            elif path == "/slo":
                self._json(200, self.fed.slo_view())
            elif path == "/usage":
                self._json(200, self.fed.usage_view())
            elif path == "/scale":
                self._json(200, self.fed.scale_view())
            elif path == "/events":
                self._json(200, {"events": self.fed.events.tail()})
            elif path.startswith("/jobs/"):
                rest = path[len("/jobs/"):]
                iid, _, tail = rest.partition("/")
                if tail == "result":
                    out = self.fed.result(iid)
                else:
                    out = self.fed.status(iid)
                if out is None:
                    self._json(404, {"error": "unknown item %r"
                                     % iid})
                else:
                    self._json(200, out)
            else:
                self._json(404, {"error": "unknown endpoint"})
        except Exception as e:
            self._json(500, {"error": "%s: %s"
                             % (type(e).__name__, e)})

    def do_POST(self) -> None:
        path = urlparse(self.path).path
        if path not in ("/submit", "/dag"):
            self._json(404, {"error": "unknown endpoint"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            spec = json.loads(self.rfile.read(length) or b"{}")
            if path == "/dag":
                self._json(202, self.fed.submit_dag(spec))
            else:
                self._json(202, self.fed.submit(spec))
        except FederationBusy as e:
            self._json(429, {"error": "federation-saturated",
                             "retry_after_s": e.retry_after_s},
                       headers={"Retry-After": "%d" % max(
                           1, math.ceil(e.retry_after_s))})
        except NoFleetAvailable as e:
            self._json(503, {"error": "no-fleet-available",
                             "detail": str(e)})
        except ValueError as e:
            self._json(400, {"error": str(e)})
        except Exception as e:
            self._json(500, {"error": "%s: %s"
                             % (type(e).__name__, e)})


class FedHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, fed: FederationRouter):
        super().__init__(addr, _FedHandler)
        self.fed = fed


def start_fed_http(fed: FederationRouter, host: str = "127.0.0.1",
                   port: int = 0) -> FedHTTPServer:
    httpd = FedHTTPServer((host, port), fed)
    t = threading.Thread(target=httpd.serve_forever,
                         name="presto-fed-http", daemon=True)
    t.start()
    return httpd


# ----------------------------------------------------------------------
# CLI: presto-fed
# ----------------------------------------------------------------------

def parse_fleet(text: str) -> FleetMember:
    """NAME:FLEETDIR[:URL] (URL may itself contain colons)."""
    parts = text.split(":", 2)
    if len(parts) < 2 or not parts[0] or not parts[1]:
        raise ValueError(
            "fleet spec must be NAME:FLEETDIR[:URL], got %r" % text)
    return FleetMember(name=parts[0], fleetdir=parts[1],
                       url=parts[2] if len(parts) > 2 else "")


def build_parser():
    p = argparse.ArgumentParser(prog="presto-fed")
    p.add_argument("-host", type=str, default="127.0.0.1")
    p.add_argument("-port", type=int, default=8787)
    p.add_argument("-feddir", type=str, required=True,
                   help="Federation directory (the fleets.json "
                        "liveness+placement ledger)")
    p.add_argument("-fleet", action="append", default=[],
                   metavar="NAME:FLEETDIR[:URL]", required=True,
                   help="Member fleet (repeatable): its shared fleet "
                        "directory and router URL")
    p.add_argument("-fingerprint", action="append", default=[],
                   metavar="NAME:FINGERPRINT",
                   help="Device fingerprint of one member (the "
                        "PERF_LEDGER pricing key; repeatable)")
    p.add_argument("-data", action="append", default=[],
                   metavar="NAME:ROOT",
                   help="Data root held locally by one member "
                        "(locality preference; repeatable)")
    p.add_argument("-poll", type=float, default=1.0)
    p.add_argument("-hb-ttl", type=float, default=6.0,
                   help="Fleet heartbeat TTL before the reaper "
                        "declares a silent fleet dead")
    p.add_argument("-default-job-s", type=float, default=5.0,
                   help="Uniform-fallback price (expected device-"
                        "seconds) for a fleet with no history")
    p.add_argument("-perf-ledger", type=str, default=None,
                   help="PERF_LEDGER.json path for fingerprint "
                        "pricing (default: the repo ledger)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    fleets = [parse_fleet(t) for t in args.fleet]
    by_name = {m.name: m for m in fleets}
    for spec, attr in ((args.fingerprint, "fingerprint"),
                       (args.data, "data_roots")):
        for text in spec:
            name, _, value = text.partition(":")
            if name not in by_name:
                raise SystemExit("unknown fleet %r in %r"
                                 % (name, text))
            if attr == "fingerprint":
                by_name[name].fingerprint = value
            else:
                by_name[name].data_roots = (
                    by_name[name].data_roots + (value,))
    cfg = FederationConfig(
        feddir=args.feddir, fleets=fleets, poll_s=args.poll,
        heartbeat_ttl=args.hb_ttl,
        default_job_s=args.default_job_s,
        perf_ledger_path=args.perf_ledger)
    fed = FederationRouter(cfg).start()
    httpd = start_fed_http(fed, args.host, args.port)
    host, port = httpd.server_address[:2]
    print("presto-fed: %d fleet(s) behind http://%s:%d "
          "(POST /submit, /dag; GET /fed, /fleet/metrics, /slo, "
          "/usage, /scale, /jobs/<id>)"
          % (len(fleets), host, port))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("presto-fed: shutting down")
    finally:
        httpd.shutdown()
        fed.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
