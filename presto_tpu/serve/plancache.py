"""Compiled-plan cache keyed on trial geometry (serve layer).

XLA compiles one executable per program shape; in the batch driver a
new process pays that cost for every run.  A resident service only
pays it once per *bucket*: plans are keyed on
(nchan, nsamp, dtype, DM-block shape, zmax, numharm) with the sample
count quantized pad-to-bucket (next power of two), so beams whose raw
lengths differ by a few percent land in the same bucket and reuse the
same jitted dedispersion/accelsearch executables — the plan-cache
shape modern inference servers use for sequence lengths.

Two cooperating layers:

  * `bucket_key(rawfile, cfg)` — the *scheduling* key: what the
    micro-batching loop coalesces on (same bucket -> same batch).
  * `PlanCache` + `SearcherProvider` — the *execution* cache: the
    survey's searcher construction (`_survey_searcher`) routes through
    `SurveyConfig.plan_provider`, so same-shaped trial groups across
    jobs share one AccelSearch instance (one kernel bank + one jit
    cache) instead of recompiling per job.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import warnings
from collections import OrderedDict
from dataclasses import asdict, dataclass
from typing import Any, Callable, Dict, Optional, Tuple


@dataclass(frozen=True)
class PlanKey:
    """Hashable plan identity.  `kind` separates plan families
    ("job" scheduling buckets vs "accel" searcher plans); `extra`
    carries family-specific fields (e.g. sigma/flo/T for accel)."""
    kind: str
    nchan: int
    nsamp: int
    dtype: str
    dm_block: Tuple
    zmax: int
    numharm: int
    extra: Tuple = ()


#: bucket-edge schemes: sub-pow2 mantissa steps each scheme admits.
#: "pow2" is the classic next-power-of-two; the finer schemes add
#: half/quarter points between octaves (fewer padded samples per job,
#: more distinct buckets = more compiles — the trade the tuning DB's
#: `plancache_bucket` family scores offline).
_BUCKET_SCHEMES = {
    "pow2": (1.0,),
    "pow2_half": (1.0, 1.5),
    "pow2_quarter": (1.0, 1.25, 1.5, 1.75),
}


def bucket_quantize(n: int, scheme: str = "pow2") -> int:
    """Smallest bucket edge >= n under `scheme`.  Unknown schemes
    fall back to pow2 (a tuned DB entry can degrade granularity,
    never produce an undersized bucket)."""
    n = max(int(n), 1)
    steps = _BUCKET_SCHEMES.get(scheme) or _BUCKET_SCHEMES["pow2"]
    p2 = 1 << (n - 1).bit_length()          # next pow2 >= n
    best = p2
    for m in steps:
        edge = int(m * (p2 >> 1))           # edges in (p2/2, p2]
        if edge >= n and edge < best:
            best = edge
    return best


def quantize_nsamp(n: int) -> int:
    """Pad-to-bucket sample-count quantization.

    Coarse on purpose — the goal is few buckets and many hits, not a
    tight fit; the survey's own choose_N padding happens downstream of
    this at the actual trial length.  Default is next power of two;
    when tuning is active (PRESTO_TPU_TUNE=1 / presto-tune) the
    bucket-edge scheme comes from the tuning DB's `plancache_bucket`
    entry, with pow2 as the fallback.  The bucket is a *scheduling*
    key (what the micro-batching loop coalesces on) — it never changes
    job outputs."""
    from presto_tpu import tune
    if tune.enabled():
        cfg = tune.best("plancache_bucket", tune.GLOBAL_KEY)
        if cfg:
            return bucket_quantize(n, str(cfg.get("scheme", "pow2")))
    from presto_tpu.utils.psr import next2_to_n
    return int(next2_to_n(max(int(n), 1)))


def dm_block_shape(cfg) -> Tuple:
    """The DM fan-out geometry of a SurveyConfig, as a hashable
    shape: (lodm, hidm, nsub) fully determine the DDplan methods for
    a given observation."""
    return (round(float(cfg.lodm), 3), round(float(cfg.hidm), 3),
            int(cfg.nsub))


def bucket_key(rawfiles, cfg) -> PlanKey:
    """Scheduling bucket for a job: observation geometry (from the raw
    header) + search geometry (from the config).  Jobs with equal
    buckets produce identically-shaped device programs, so the
    scheduler may coalesce them."""
    from presto_tpu.apps.common import open_raw
    paths = [rawfiles] if isinstance(rawfiles, str) else list(rawfiles)
    fb = open_raw(paths)
    hdr = fb.header
    nchan, nsamp, nbits = int(hdr.nchans), int(hdr.N), int(hdr.nbits)
    fb.close()
    return PlanKey(kind="job", nchan=nchan,
                   nsamp=quantize_nsamp(nsamp),
                   dtype="uint%d" % nbits if nbits < 32 else "float32",
                   dm_block=dm_block_shape(cfg),
                   zmax=int(cfg.zmax), numharm=int(cfg.numharm))


@dataclass
class CompiledPlan:
    """A cached executable bundle + bookkeeping.  `device` records the
    executable->device binding at build time (obs/jaxtel
    current_device_id), so a TPU reset can evict exactly the plans
    bound to the dead device instead of flushing the whole cache."""
    key: PlanKey
    obj: Any
    build_seconds: float
    built_at: float
    uses: int = 0
    device: Optional[str] = None

    def place(self, batch, mesh=None):
        """Mesh-aware placement of a stacked same-bucket batch: shard
        the leading (job/trial) axis across the mesh so one batched
        device call spans the chips (no-op passthrough without a
        mesh)."""
        if mesh is None:
            return batch
        import jax
        import jax.numpy as jnp
        from presto_tpu.parallel.mesh import batch_sharding
        arr = jnp.asarray(batch)
        return jax.device_put(
            arr, batch_sharding(mesh, ndim=arr.ndim))


class PlanCache:
    """Thread-safe LRU cache of compiled plans with hit/miss/eviction
    accounting on the shared metrics registry (the /metrics `plans`
    block and the `plancache_*` Prometheus series are the same
    counters)."""

    def __init__(self, capacity: int = 32, events=None, obs=None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if obs is None:
            from presto_tpu.obs import Observability, ObsConfig
            obs = Observability(ObsConfig(enabled=True))
        self.capacity = capacity
        self.obs = obs
        self._events = events
        self._lock = threading.Lock()  # presto-lint: guards(_plans, _compile_s)
        self._plans: "OrderedDict[PlanKey, CompiledPlan]" = \
            OrderedDict()
        self._compile_s = 0.0
        reg = obs.metrics
        self._c_hits = reg.counter("plancache_hits_total",
                                   "Plan-cache hits")
        self._c_misses = reg.counter("plancache_misses_total",
                                     "Plan-cache misses (compiles)")
        self._c_evict = reg.counter(
            "plancache_evictions_total", "Plan-cache evictions",
            ("reason",))
        self._g_size = reg.gauge("plancache_size",
                                 "Compiled plans resident")

    def get(self, key: PlanKey, builder: Callable[[], Any]) -> Any:
        """Return the cached plan for `key`, building (and counting a
        compile) on first use.  The builder runs outside the lock so a
        long XLA compile never blocks cache hits on other keys; two
        racing builders for one key keep the first-inserted plan."""
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None:
                self._plans.move_to_end(key)
                self._c_hits.inc()
                plan.uses += 1
                return plan.obj
            self._c_misses.inc()
        from presto_tpu.obs import jaxtel
        t0 = time.time()
        obj = builder()
        dt = time.time() - t0
        device = jaxtel.current_device_id()
        with self._lock:
            existing = self._plans.get(key)
            if existing is not None:        # lost the build race
                existing.uses += 1
                return existing.obj
            self._compile_s += dt
            self._plans[key] = CompiledPlan(
                key=key, obj=obj, build_seconds=dt, built_at=t0,
                uses=1, device=device)
            self._plans.move_to_end(key)
            while len(self._plans) > self.capacity:
                old_key, _ = self._plans.popitem(last=False)
                self._c_evict.labels(reason="capacity").inc()
                if self._events is not None:
                    self._events.emit("evict", plan=repr(old_key))
            self._g_size.set(len(self._plans))
        # the built plan rides along so obs/costmodel can harvest its
        # unit cost when it IS a compiled executable (AOT bundles);
        # AccelSearch-style plan objects are skipped silently
        jaxtel.note_compile(self.obs, kind=key.kind, seconds=dt,
                            key=key, device=device, compiled=obj)
        if self._events is not None:
            self._events.emit("compile", plan=repr(key), seconds=dt)
        return obj

    def evict_bucket(self, device: Optional[str] = None,
                     reason: str = "device_error") -> int:
        """Flush plans bound to `device` (None = every plan): the
        scheduler's retry path calls this on a device/executable
        RuntimeError so a retry re-warms a fresh executable instead of
        re-entering the poisoned one (ROADMAP: plan-cache invalidation
        on device error).  Returns the number evicted; each eviction
        counts under `plancache_evictions_total{reason=...}`."""
        with self._lock:
            doomed = [k for k, p in self._plans.items()
                      if device is None or p.device == device
                      or p.device is None]
            for k in doomed:
                del self._plans[k]
                self._c_evict.labels(reason=reason).inc()
            self._g_size.set(len(self._plans))
        for k in doomed:
            if self._events is not None:
                self._events.emit("plan-evict", plan=repr(k),
                                  reason=reason, device=device or "*")
        self.obs.event("plan-evict", n=len(doomed), reason=reason,
                       device=device or "*")
        return len(doomed)

    def contains(self, key: PlanKey) -> bool:
        with self._lock:
            return key in self._plans

    def stats(self) -> dict:
        hits = int(self._c_hits.value)
        misses = int(self._c_misses.value)
        total = hits + misses
        with self._lock:
            size = len(self._plans)
            compile_s = self._compile_s
        return {
            "size": size,
            "capacity": self.capacity,
            "hits": hits,
            "misses": misses,
            "evictions": int(self._c_evict.total()),
            "compile_s": round(compile_s, 3),
            "hit_rate": (hits / total) if total else 0.0,
        }


def accel_plan_key(acfg, T: float, numbins: int) -> PlanKey:
    """The execution-plan identity of one accel searcher.  T enters
    the key (it scales the z grid and candidate frequencies), so only
    genuinely identical trial geometries share a plan — required for
    byte-equality with the batch driver."""
    return PlanKey(kind="accel", nchan=0, nsamp=int(numbins),
                   dtype="float32", dm_block=(),
                   zmax=int(acfg.zmax), numharm=int(acfg.numharm),
                   extra=(float(acfg.sigma), float(acfg.flo),
                          round(float(T), 9)))


class SearcherProvider:
    """The `SurveyConfig.plan_provider` adapter: routes the survey's
    per-trial-group searcher construction through a PlanCache, so a
    resident service compiles each accel-plan geometry once.  With a
    PlanStore attached, every plan built is also *recorded* — its
    rebuild recipe lands in the persistent tier, so a cold replica
    can re-derive the whole working set before its first job."""

    def __init__(self, cache: PlanCache, mesh=None,
                 store: Optional["PlanStore"] = None):
        self.cache = cache
        self.mesh = mesh
        self.store = store

    def searcher(self, acfg, T: float, numbins: int):
        """Cached AccelSearch for (acfg, T, numbins)."""
        key = accel_plan_key(acfg, T, numbins)

        def _build():
            from presto_tpu.search.accel import AccelSearch
            s = AccelSearch(acfg, T=T, numbins=numbins)
            if self.store is not None:
                self.store.record(key, {
                    "kind": "accel", "acfg": asdict(acfg),
                    "T": float(T), "numbins": int(numbins)})
            return s

        return self.cache.get(key, _build)

    def prewarm(self, limit: Optional[int] = None) -> int:
        """Rebuild every plan the persistent tier knows for this
        device fingerprint into the in-memory cache (a no-op without
        a store).  With JAX's compilation cache enabled underneath,
        the XLA executables come off disk instead of recompiling —
        a freshly joined replica warms in seconds, not per-bucket
        compile time.  Returns the number of plans warmed."""
        if self.store is None:
            return 0
        from presto_tpu.search.accel import AccelConfig
        n = 0
        for recipe in self.store.known().values():
            if recipe.get("kind") != "accel":
                continue
            if limit is not None and n >= limit:
                break
            try:
                acfg = AccelConfig(**recipe["acfg"])
                self.searcher(acfg, float(recipe["T"]),
                              int(recipe["numbins"]))
                n += 1
            except Exception as e:     # a stale recipe must not
                warnings.warn(          # block replica start
                    "plan prewarm skipped a recorded plan: %s" % e,
                    RuntimeWarning, stacklevel=2)
        if self.store is not None:
            self.store.note_warm(self.cache)
        return n


# ----------------------------------------------------------------------
# persistent compiled-plan tier
# ----------------------------------------------------------------------

#: sidecar schema version (bumping it orphans old recipes, never
#: crashes a replica — loads are defensive like tune/db.py)
STORE_SCHEMA = 1


class PlanStore:
    """Persistent compiled-plan tier keyed by device fingerprint.

    Two cooperating layers close the cold-replica problem:

      * **JAX's compilation cache** (`enable()`): XLA executables are
        serialized under `<root>/<fingerprint>/xla/`, so rebuilding a
        known plan on a fresh replica deserializes instead of
        recompiling.  Where the backend cannot persist executables
        the store still works — the sidecar below bounds what must be
        rebuilt, and `supported` records the degradation.
      * **A plan-recipe sidecar** (`plankeys.json`): every plan the
        fleet ever built is recorded with enough to rebuild it
        (`SearcherProvider.prewarm`), merged atomically under a lock
        directory so concurrent replicas compose.

    The fingerprint is `tune/db.py`'s device fingerprint — the same
    cache-correctness boundary the tuning DB uses: an executable
    serialized on one chip generation / jaxlib never warms another.
    """

    def __init__(self, root: str, fingerprint: Optional[str] = None,
                 obs=None):
        from presto_tpu.tune.db import (device_fingerprint,
                                        fingerprint_key)
        if obs is None:
            from presto_tpu.obs import Observability, ObsConfig
            obs = Observability(ObsConfig(enabled=True))
        self.obs = obs
        self.fingerprint = fingerprint or fingerprint_key(
            device_fingerprint())
        fp_id = hashlib.sha1(
            self.fingerprint.encode()).hexdigest()[:16]
        self.root = os.path.abspath(root)
        self.dir = os.path.join(self.root, fp_id)
        self.xla_dir = os.path.join(self.dir, "xla")
        self.sidecar = os.path.join(self.dir, "plankeys.json")
        from presto_tpu.pipeline.leaseledger import _LockDir
        self._lock = _LockDir(self.sidecar + ".lock")
        self.supported: Optional[bool] = None
        self.enable_error: Optional[str] = None
        reg = obs.metrics
        self._g_warm = reg.gauge(
            "plancache_warm_fraction",
            "Fraction of persistently-known plans resident in the "
            "in-memory cache")
        self._c_prewarmed = reg.counter(
            "plancache_prewarmed_total",
            "Plans rebuilt from the persistent tier at replica start")
        self._g_known = reg.gauge(
            "plancache_store_plans",
            "Plans recorded in the persistent tier sidecar")

    # -- XLA compilation cache ----------------------------------------
    def enable(self) -> bool:
        """Point JAX's persistent compilation cache at this store's
        fingerprint directory (min-size/min-time thresholds dropped so
        every bucket executable persists).  Best-effort: a backend or
        jax version without support degrades to sidecar-only warm-up,
        recorded in `supported`/`enable_error`."""
        os.makedirs(self.xla_dir, exist_ok=True)
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir",
                              self.xla_dir)
            for knob, val in (
                    ("jax_persistent_cache_min_compile_time_secs", 0.0),
                    ("jax_persistent_cache_min_entry_size_bytes", -1)):
                try:
                    jax.config.update(knob, val)
                except Exception:
                    pass                # older jax: keep defaults
            self.supported = True
        except Exception as e:
            self.supported = False
            self.enable_error = "%s: %s" % (type(e).__name__, e)
            warnings.warn(
                "persistent compilation cache unavailable (%s) — "
                "cold replicas fall back to sidecar prewarm only"
                % self.enable_error, RuntimeWarning, stacklevel=2)
        return bool(self.supported)

    def xla_entries(self) -> int:
        """Serialized executables currently on disk (0 when the
        backend never persisted any)."""
        try:
            return sum(1 for n in os.listdir(self.xla_dir)
                       if not n.startswith("."))
        except OSError:
            return 0

    # -- recipe sidecar ------------------------------------------------
    def _load_sidecar(self) -> dict:
        try:
            with open(self.sidecar) as f:
                raw = json.load(f)
            if (isinstance(raw, dict)
                    and raw.get("schema") == STORE_SCHEMA
                    and isinstance(raw.get("plans"), dict)):
                return raw["plans"]
        except (OSError, ValueError):
            pass
        return {}

    def known(self) -> Dict[str, dict]:
        """{plan-key repr: rebuild recipe} recorded for this
        fingerprint."""
        plans = self._load_sidecar()
        self._g_known.set(len(plans))
        return plans

    def record(self, key: PlanKey, recipe: dict) -> None:
        """Merge one rebuild recipe into the sidecar (atomic
        read-modify-replace under the lock, so concurrent replicas
        compose instead of clobbering)."""
        from presto_tpu.io.atomic import atomic_write_text
        os.makedirs(self.dir, exist_ok=True)
        with self._lock():
            plans = self._load_sidecar()
            plans[repr(key)] = dict(recipe, recorded_at=time.time())
            atomic_write_text(self.sidecar, json.dumps(
                {"schema": STORE_SCHEMA, "plans": plans},
                indent=1, sort_keys=True))
        self._g_known.set(len(plans))

    # -- warm accounting ----------------------------------------------
    @staticmethod
    def _recipe_key(recipe: dict) -> Optional[PlanKey]:
        if recipe.get("kind") != "accel":
            return None
        try:
            from presto_tpu.search.accel import AccelConfig
            return accel_plan_key(AccelConfig(**recipe["acfg"]),
                                  float(recipe["T"]),
                                  int(recipe["numbins"]))
        except Exception:
            return None

    def warm_fraction(self, cache: PlanCache) -> float:
        """How much of the persistently-known working set is resident
        in `cache` — the readiness signal a router uses to keep
        traffic off a cold replica.  An empty store is vacuously warm
        (a brand-new fleet has nothing to wait for)."""
        keys = [k for k in (self._recipe_key(r)
                            for r in self.known().values())
                if k is not None]
        if not keys:
            frac = 1.0
        else:
            frac = (sum(1 for k in keys if cache.contains(k))
                    / float(len(keys)))
        self._g_warm.set(frac)
        return frac

    def note_warm(self, cache: PlanCache) -> None:
        self._c_prewarmed.inc(cache.stats()["size"])
        self.warm_fraction(cache)
