"""Compiled-plan cache keyed on trial geometry (serve layer).

XLA compiles one executable per program shape; in the batch driver a
new process pays that cost for every run.  A resident service only
pays it once per *bucket*: plans are keyed on
(nchan, nsamp, dtype, DM-block shape, zmax, numharm) with the sample
count quantized pad-to-bucket (next power of two), so beams whose raw
lengths differ by a few percent land in the same bucket and reuse the
same jitted dedispersion/accelsearch executables — the plan-cache
shape modern inference servers use for sequence lengths.

Two cooperating layers:

  * `bucket_key(rawfile, cfg)` — the *scheduling* key: what the
    micro-batching loop coalesces on (same bucket -> same batch).
  * `PlanCache` + `SearcherProvider` — the *execution* cache: the
    survey's searcher construction (`_survey_searcher`) routes through
    `SurveyConfig.plan_provider`, so same-shaped trial groups across
    jobs share one AccelSearch instance (one kernel bank + one jit
    cache) instead of recompiling per job.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple


@dataclass(frozen=True)
class PlanKey:
    """Hashable plan identity.  `kind` separates plan families
    ("job" scheduling buckets vs "accel" searcher plans); `extra`
    carries family-specific fields (e.g. sigma/flo/T for accel)."""
    kind: str
    nchan: int
    nsamp: int
    dtype: str
    dm_block: Tuple
    zmax: int
    numharm: int
    extra: Tuple = ()


#: bucket-edge schemes: sub-pow2 mantissa steps each scheme admits.
#: "pow2" is the classic next-power-of-two; the finer schemes add
#: half/quarter points between octaves (fewer padded samples per job,
#: more distinct buckets = more compiles — the trade the tuning DB's
#: `plancache_bucket` family scores offline).
_BUCKET_SCHEMES = {
    "pow2": (1.0,),
    "pow2_half": (1.0, 1.5),
    "pow2_quarter": (1.0, 1.25, 1.5, 1.75),
}


def bucket_quantize(n: int, scheme: str = "pow2") -> int:
    """Smallest bucket edge >= n under `scheme`.  Unknown schemes
    fall back to pow2 (a tuned DB entry can degrade granularity,
    never produce an undersized bucket)."""
    n = max(int(n), 1)
    steps = _BUCKET_SCHEMES.get(scheme) or _BUCKET_SCHEMES["pow2"]
    p2 = 1 << (n - 1).bit_length()          # next pow2 >= n
    best = p2
    for m in steps:
        edge = int(m * (p2 >> 1))           # edges in (p2/2, p2]
        if edge >= n and edge < best:
            best = edge
    return best


def quantize_nsamp(n: int) -> int:
    """Pad-to-bucket sample-count quantization.

    Coarse on purpose — the goal is few buckets and many hits, not a
    tight fit; the survey's own choose_N padding happens downstream of
    this at the actual trial length.  Default is next power of two;
    when tuning is active (PRESTO_TPU_TUNE=1 / presto-tune) the
    bucket-edge scheme comes from the tuning DB's `plancache_bucket`
    entry, with pow2 as the fallback.  The bucket is a *scheduling*
    key (what the micro-batching loop coalesces on) — it never changes
    job outputs."""
    from presto_tpu import tune
    if tune.enabled():
        cfg = tune.best("plancache_bucket", tune.GLOBAL_KEY)
        if cfg:
            return bucket_quantize(n, str(cfg.get("scheme", "pow2")))
    from presto_tpu.utils.psr import next2_to_n
    return int(next2_to_n(max(int(n), 1)))


def dm_block_shape(cfg) -> Tuple:
    """The DM fan-out geometry of a SurveyConfig, as a hashable
    shape: (lodm, hidm, nsub) fully determine the DDplan methods for
    a given observation."""
    return (round(float(cfg.lodm), 3), round(float(cfg.hidm), 3),
            int(cfg.nsub))


def bucket_key(rawfiles, cfg) -> PlanKey:
    """Scheduling bucket for a job: observation geometry (from the raw
    header) + search geometry (from the config).  Jobs with equal
    buckets produce identically-shaped device programs, so the
    scheduler may coalesce them."""
    from presto_tpu.apps.common import open_raw
    paths = [rawfiles] if isinstance(rawfiles, str) else list(rawfiles)
    fb = open_raw(paths)
    hdr = fb.header
    nchan, nsamp, nbits = int(hdr.nchans), int(hdr.N), int(hdr.nbits)
    fb.close()
    return PlanKey(kind="job", nchan=nchan,
                   nsamp=quantize_nsamp(nsamp),
                   dtype="uint%d" % nbits if nbits < 32 else "float32",
                   dm_block=dm_block_shape(cfg),
                   zmax=int(cfg.zmax), numharm=int(cfg.numharm))


@dataclass
class CompiledPlan:
    """A cached executable bundle + bookkeeping.  `device` records the
    executable->device binding at build time (obs/jaxtel
    current_device_id), so a TPU reset can evict exactly the plans
    bound to the dead device instead of flushing the whole cache."""
    key: PlanKey
    obj: Any
    build_seconds: float
    built_at: float
    uses: int = 0
    device: Optional[str] = None

    def place(self, batch, mesh=None):
        """Mesh-aware placement of a stacked same-bucket batch: shard
        the leading (job/trial) axis across the mesh so one batched
        device call spans the chips (no-op passthrough without a
        mesh)."""
        if mesh is None:
            return batch
        import jax
        import jax.numpy as jnp
        from presto_tpu.parallel.mesh import batch_sharding
        arr = jnp.asarray(batch)
        return jax.device_put(
            arr, batch_sharding(mesh, ndim=arr.ndim))


class PlanCache:
    """Thread-safe LRU cache of compiled plans with hit/miss/eviction
    accounting on the shared metrics registry (the /metrics `plans`
    block and the `plancache_*` Prometheus series are the same
    counters)."""

    def __init__(self, capacity: int = 32, events=None, obs=None):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if obs is None:
            from presto_tpu.obs import Observability, ObsConfig
            obs = Observability(ObsConfig(enabled=True))
        self.capacity = capacity
        self.obs = obs
        self._events = events
        self._lock = threading.Lock()
        self._plans: "OrderedDict[PlanKey, CompiledPlan]" = \
            OrderedDict()
        self._compile_s = 0.0
        reg = obs.metrics
        self._c_hits = reg.counter("plancache_hits_total",
                                   "Plan-cache hits")
        self._c_misses = reg.counter("plancache_misses_total",
                                     "Plan-cache misses (compiles)")
        self._c_evict = reg.counter(
            "plancache_evictions_total", "Plan-cache evictions",
            ("reason",))
        self._g_size = reg.gauge("plancache_size",
                                 "Compiled plans resident")

    def get(self, key: PlanKey, builder: Callable[[], Any]) -> Any:
        """Return the cached plan for `key`, building (and counting a
        compile) on first use.  The builder runs outside the lock so a
        long XLA compile never blocks cache hits on other keys; two
        racing builders for one key keep the first-inserted plan."""
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None:
                self._plans.move_to_end(key)
                self._c_hits.inc()
                plan.uses += 1
                return plan.obj
            self._c_misses.inc()
        from presto_tpu.obs import jaxtel
        t0 = time.time()
        obj = builder()
        dt = time.time() - t0
        device = jaxtel.current_device_id()
        with self._lock:
            existing = self._plans.get(key)
            if existing is not None:        # lost the build race
                existing.uses += 1
                return existing.obj
            self._compile_s += dt
            self._plans[key] = CompiledPlan(
                key=key, obj=obj, build_seconds=dt, built_at=t0,
                uses=1, device=device)
            self._plans.move_to_end(key)
            while len(self._plans) > self.capacity:
                old_key, _ = self._plans.popitem(last=False)
                self._c_evict.labels(reason="capacity").inc()
                if self._events is not None:
                    self._events.emit("evict", plan=repr(old_key))
            self._g_size.set(len(self._plans))
        jaxtel.note_compile(self.obs, kind=key.kind, seconds=dt,
                            key=key, device=device)
        if self._events is not None:
            self._events.emit("compile", plan=repr(key), seconds=dt)
        return obj

    def evict_bucket(self, device: Optional[str] = None,
                     reason: str = "device_error") -> int:
        """Flush plans bound to `device` (None = every plan): the
        scheduler's retry path calls this on a device/executable
        RuntimeError so a retry re-warms a fresh executable instead of
        re-entering the poisoned one (ROADMAP: plan-cache invalidation
        on device error).  Returns the number evicted; each eviction
        counts under `plancache_evictions_total{reason=...}`."""
        with self._lock:
            doomed = [k for k, p in self._plans.items()
                      if device is None or p.device == device
                      or p.device is None]
            for k in doomed:
                del self._plans[k]
                self._c_evict.labels(reason=reason).inc()
            self._g_size.set(len(self._plans))
        for k in doomed:
            if self._events is not None:
                self._events.emit("plan-evict", plan=repr(k),
                                  reason=reason, device=device or "*")
        self.obs.event("plan-evict", n=len(doomed), reason=reason,
                       device=device or "*")
        return len(doomed)

    def contains(self, key: PlanKey) -> bool:
        with self._lock:
            return key in self._plans

    def stats(self) -> dict:
        hits = int(self._c_hits.value)
        misses = int(self._c_misses.value)
        total = hits + misses
        with self._lock:
            size = len(self._plans)
            compile_s = self._compile_s
        return {
            "size": size,
            "capacity": self.capacity,
            "hits": hits,
            "misses": misses,
            "evictions": int(self._c_evict.total()),
            "compile_s": round(compile_s, 3),
            "hit_rate": (hits / total) if total else 0.0,
        }


class SearcherProvider:
    """The `SurveyConfig.plan_provider` adapter: routes the survey's
    per-trial-group searcher construction through a PlanCache, so a
    resident service compiles each accel-plan geometry once."""

    def __init__(self, cache: PlanCache, mesh=None):
        self.cache = cache
        self.mesh = mesh

    def searcher(self, acfg, T: float, numbins: int):
        """Cached AccelSearch for (acfg, T, numbins).  T enters the
        key (it scales the z grid and candidate frequencies), so only
        genuinely identical trial geometries share a plan — required
        for byte-equality with the batch driver."""
        key = PlanKey(kind="accel", nchan=0, nsamp=int(numbins),
                      dtype="float32", dm_block=(),
                      zmax=int(acfg.zmax), numharm=int(acfg.numharm),
                      extra=(float(acfg.sigma), float(acfg.flo),
                             round(float(T), 9)))

        def _build():
            from presto_tpu.search.accel import AccelSearch
            return AccelSearch(acfg, T=T, numbins=numbins)

        return self.cache.get(key, _build)
