"""Pulse timing: template matching (FFTFIT) and TOA extraction.

The reference implements this as the f2py-wrapped Fortran fftfit
(python/fftfit_src/*.f, Taylor 1992) driven by bin/get_TOAs.py; here it
is a NumPy/JAX-friendly reimplementation of the same algorithm.
"""

from presto_tpu.timing.fftfit import FFTFitResult, fftfit, gaussian_template
from presto_tpu.timing.toas import TOA, format_princeton, format_tempo2, \
    toas_from_pfd

__all__ = ["FFTFitResult", "fftfit", "gaussian_template", "TOA",
           "toas_from_pfd", "format_princeton", "format_tempo2"]
