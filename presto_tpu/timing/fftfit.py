"""FFTFIT — Fourier-domain template matching (Taylor 1992).

The reference wraps the original Fortran (python/fftfit_src/fftfit.f,
built via f2py per python/setup.py) and calls it from bin/get_TOAs.py to
measure the phase shift between a folded profile and a template.  This
is a from-scratch NumPy implementation of the same estimator:

model  p(j) = a + b * s(j - n*tau),  i.e. in the Fourier domain
       P_k  = b * S_k * exp(-2*pi*i*k*tau)   for harmonics k >= 1.

chi^2(b,tau) = sum_k |P_k - b S_k e^{-2 pi i k tau}|^2 / sigma^2 is
minimized exactly: the cross-spectrum IFFT gives the global coarse
peak, Brent polish gives sub-bin tau, and b follows in closed form.
Error estimates come from the curvature of chi^2 at the minimum with
the noise level sigma^2 estimated from the residual itself.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
from scipy.optimize import minimize_scalar


@dataclass
class FFTFitResult:
    shift: float    # phase shift in rotations, in [-0.5, 0.5)
    eshift: float   # 1-sigma uncertainty of shift (rotations)
    b: float        # template scale factor
    errb: float     # 1-sigma uncertainty of b
    offset: float   # DC offset a
    snr: float      # matched-filter S/N of the detection


def gaussian_template(n: int, fwhm: float, phase: float = 0.5
                      ) -> np.ndarray:
    """A wrapped Gaussian pulse template with the given FWHM (in
    rotations) centered at `phase` — the default template get_TOAs.py
    builds with -g (via psr_utils.gaussian_profile)."""
    sigma = fwhm / (2.0 * np.sqrt(2.0 * np.log(2.0)))
    x = (np.arange(n) + 0.5) / n
    d = x - phase
    d = d - np.round(d)            # wrap to [-0.5, 0.5)
    return np.exp(-0.5 * (d / sigma) ** 2)


def fftfit(profile: np.ndarray, template: np.ndarray) -> FFTFitResult:
    """Fit `profile` = a + b * template shifted by `shift` rotations.

    A positive shift means the profile's features arrive LATER (at
    higher phase) than the template's.
    """
    p = np.asarray(profile, np.float64)
    s = np.asarray(template, np.float64)
    n = p.size
    if s.size != n:
        raise ValueError("profile and template lengths differ")
    P = np.fft.rfft(p)
    S = np.fft.rfft(s)
    nh = n // 2
    k = np.arange(1, nh)           # harmonics 1..n/2-1 (skip DC+Nyquist)
    aP = np.abs(P[k])
    aS = np.abs(S[k])
    dphi = np.angle(P[k]) - np.angle(S[k])

    # coarse tau: peak of the cross-correlation, 16x zero-padded
    pad = 16
    X = np.zeros(n * pad // 2 + 1, np.complex128)
    X[1:nh] = P[k] * np.conj(S[k])
    cc = np.fft.irfft(X, n * pad)
    tau0 = np.argmax(cc) / (n * pad)

    two_pi_k = 2.0 * np.pi * k

    def merit(tau):
        return float(np.sum(aP * aS * np.cos(dphi + two_pi_k * tau)))

    half_bin = 1.0 / n
    res = minimize_scalar(lambda t: -merit(t),
                          bounds=(tau0 - half_bin, tau0 + half_bin),
                          method="bounded",
                          options={"xatol": 1e-12})
    tau = float(res.x)

    cosd = np.cos(dphi + two_pi_k * tau)
    sum_PS = float(np.sum(aP * aS * cosd))
    sum_SS = float(np.sum(aS ** 2))
    sum_PP = float(np.sum(aP ** 2))
    b = sum_PS / sum_SS

    # noise per harmonic from the chi^2 floor (Taylor 1992 eq. A10-ish)
    dof = max(len(k) - 2, 1)
    sigma2 = max(sum_PP - b * sum_PS, 0.0) / dof
    curv_tau = b * b * float(np.sum((two_pi_k ** 2) * aS ** 2))
    eshift = np.sqrt(sigma2 / curv_tau) if curv_tau > 0 else np.inf
    errb = np.sqrt(sigma2 / sum_SS) if sum_SS > 0 else np.inf
    snr = b * np.sqrt(sum_SS / sigma2) if sigma2 > 0 else np.inf

    shift = tau - np.round(tau)    # wrap to [-0.5, 0.5)
    offset = float((P[0].real - b * S[0].real) / n)
    return FFTFitResult(shift=float(shift), eshift=float(eshift),
                        b=float(b), errb=float(errb), offset=offset,
                        snr=float(snr))
