"""TOA extraction from folded profiles (bin/get_TOAs.py analog).

Flow (get_TOAs.py): read a .pfd, align subbands at the candidate DM,
sum sub-integrations into groups, FFTFIT each group profile against a
template, and convert the fitted phase shift into a topocentric TOA at
the group's mid-time using the fold's phase polynomial
(fold_p1/p2/p3 = f, fd, fdd — the same convention prepfold folds with).

TOA MJDs are kept as (int day, fractional day) pairs: a single float64
MJD only resolves ~1 us, below timing precision.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from presto_tpu.io.pfd import Pfd, pfd_subfreqs
from presto_tpu.ops.fold import combine_subbands, subband_fold_shifts
from presto_tpu.timing.fftfit import fftfit, gaussian_template

SECPERDAY = 86400.0



@dataclass
class TOA:
    mjdi: int          # integer MJD (topocentric, uncorrected)
    mjdf: float        # fractional day in [0, 1)
    err_us: float
    freq_mhz: float
    obs: str = "@"
    snr: float = 0.0
    shift: float = 0.0  # fitted phase shift, rotations

    @property
    def mjd(self) -> float:
        return self.mjdi + self.mjdf


def _fold_phase(t: float, f: float, fd: float, fdd: float) -> float:
    return t * (f + t * (fd / 2.0 + t * fdd / 6.0))


def _fold_freq(t: float, f: float, fd: float, fdd: float) -> float:
    return f + t * (fd + t * fdd / 2.0)


def toas_from_pfd(p: Pfd, template: Optional[np.ndarray] = None,
                  ntoa: int = 1, dm: Optional[float] = None,
                  fold_dm: Optional[float] = None,
                  gauss_fwhm: float = 0.1,
                  obs: str = "@") -> List[TOA]:
    """Extract `ntoa` TOAs from a .pfd's profile cube.

    template: profile template (defaults to a Gaussian of FWHM
    `gauss_fwhm` rotations centered at phase 0.5, as get_TOAs -g).
    dm/fold_dm: when both given and nsub > 1, subbands are re-aligned
    from fold_dm to dm before summing (pfd.dedisperse analog); when
    omitted the stored cube is assumed already aligned.
    """
    profs = np.asarray(p.profs, np.float64)     # [npart, nsub, proflen]
    npart, nsub, proflen = profs.shape
    f, fd, fdd = p.fold_p1, p.fold_p2, p.fold_p3
    if f <= 0:
        raise ValueError("pfd has no fold frequency (fold_p1)")

    # the fold cube is dedispersed referenced to the HIGHEST channel
    # (dedisp_delays/subband_fold_shifts zero the delay at the band
    # top), so TOAs are quoted at that frequency — get_TOAs.py keeps
    # the same frame via its sumsubdelays correction
    freq_ref = p.lofreq + (p.numchan - 1) * p.chan_wid
    if nsub > 1 and dm is not None and fold_dm is not None:
        subfreqs = pfd_subfreqs(p)
        shifts = subband_fold_shifts(subfreqs, dm, fold_dm, f, proflen,
                                     ref_freq=freq_ref)
        part_profs = np.asarray(combine_subbands(profs, shifts))
    else:
        part_profs = profs.sum(axis=1)          # [npart, proflen]

    if template is None:
        template = gaussian_template(proflen, gauss_fwhm)
    template = np.asarray(template, np.float64)

    numdata = p.stats[:, 0, 0].astype(np.float64)
    if not np.all(numdata > 0):
        numdata = np.full(npart, 1.0)
    starts_sec = np.concatenate([[0.0], np.cumsum(numdata)[:-1]]) * p.dt
    ends_sec = np.cumsum(numdata) * p.dt

    ntoa = max(1, min(ntoa, npart))
    per = npart // ntoa

    out: List[TOA] = []
    for g in range(ntoa):
        lo = g * per
        hi = npart if g == ntoa - 1 else (g + 1) * per
        prof = part_profs[lo:hi].sum(axis=0)
        t_mid = 0.5 * (starts_sec[lo] + ends_sec[hi - 1])
        fit = fftfit(prof, template)
        f_inst = _fold_freq(t_mid, f, fd, fdd)
        ph = _fold_phase(t_mid, f, fd, fdd)
        dph = (fit.shift - ph) % 1.0
        if dph >= 0.5:
            dph -= 1.0                           # nearest pulse to t_mid
        t_toa = t_mid + dph / f_inst
        mjdi = int(p.tepoch)
        mjdf = (p.tepoch - mjdi) + t_toa / SECPERDAY
        carry = np.floor(mjdf)
        mjdi += int(carry)
        mjdf -= carry
        out.append(TOA(mjdi=mjdi, mjdf=float(mjdf),
                       err_us=fit.eshift / f_inst * 1e6,
                       freq_mhz=freq_ref, obs=obs, snr=fit.snr,
                       shift=fit.shift))
    return out


def format_princeton(toa: TOA, name: str = "") -> str:
    """Princeton TOA format (psr_utils.write_princeton_toa layout):
    cols 1-1 obs code, 16-24 freq, 25-44 TOA (d.13f), 45-53 error."""
    frac = "%.13f" % toa.mjdf
    if frac.startswith("1"):                     # rounding carried over
        return format_princeton(
            TOA(toa.mjdi + 1, 0.0, toa.err_us, toa.freq_mhz, toa.obs,
                toa.snr, toa.shift), name)
    return "%1s %13s %8.3f %5d%s %8.2f" % (
        toa.obs, name[:13], toa.freq_mhz, toa.mjdi, frac[1:], toa.err_us)


def format_tempo2(toa: TOA, name: str = "unk") -> str:
    """tempo2 .tim line: name freq MJD error(us) site."""
    frac = "%.13f" % toa.mjdf
    if frac.startswith("1"):                     # rounding carried over
        return format_tempo2(
            TOA(toa.mjdi + 1, 0.0, toa.err_us, toa.freq_mhz, toa.obs,
                toa.snr, toa.shift), name)
    return "%s %.3f %5d.%s %.3f %s" % (
        name, toa.freq_mhz, toa.mjdi, frac[2:], toa.err_us, toa.obs)


def format_tim_lines(toas: Sequence[TOA], names,
                     fmt: str = "princeton") -> List[str]:
    """.tim lines for TOAs; `names` is one name or a per-TOA sequence.
    The single source of the .tim convention (CLI and write_tim)."""
    if isinstance(names, str):
        names = [names] * len(toas)
    lines = ["FORMAT 1"] if fmt == "tempo2" else []
    for t, nm in zip(toas, names):
        lines.append(format_tempo2(t, nm) if fmt == "tempo2"
                     else format_princeton(t, nm))
    return lines


def write_tim(path: str, toas: Sequence[TOA], name="unk",
              fmt: str = "princeton") -> None:
    with open(path, "w") as fh:
        fh.write("\n".join(format_tim_lines(toas, name, fmt)) + "\n")
