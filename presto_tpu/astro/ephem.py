"""Solar-system ephemerides: Earth w.r.t. the solar-system
barycenter, vectorized numpy.

Replaces the JPL DE200/DE405 ephemerides that the reference reaches
through TEMPO (src/barycenter.c:134 "EPHEM DE405").  The DEFAULT is
EpvEphemeris (bottom of file): the simplified VSOP2000 Earth solution
evaluated from ~2000 published Poisson-series coefficients shipped in
data/epv.npz — 4.6 km RMS vs JPL DE405 (sub-50-us Roemer), i.e. the
built-in path is km-grade with no external files.  A real JPL .bsp
kernel (astro/spk.py) remains the sub-us timing seam, and the
Keplerian AnalyticEphemeris below stays as the data-free fallback
(ephem="KEPLER").  AnalyticEphemeris construction:

  * Heliocentric positions of the eight planets (Earth-Moon barycenter
    for Earth) from Keplerian mean elements with secular rates
    (Standish's approximate elements, valid 1800-2050).
  * The Sun's offset from the solar-system barycenter from the mass-
    weighted planetary positions (dominated by Jupiter/Saturn; these
    orbits are nearly Keplerian so the offset is accurate to ~1e-5 AU).
  * The Earth's offset from the Earth-Moon barycenter from a truncated
    lunar theory (Meeus ch. 47 leading terms), weighted by
    1/(1+EMRAT); the truncation error enters Earth's position at the
    ~10 km * 0.012 level, i.e. negligible.
  * Velocities by central differencing (the series are smooth;
    dt=0.05 d gives ~1e-9 AU/day accuracy).

All vectors are equatorial J2000 (ICRS to within the frame tie),
units AU and AU/day, indexed by TDB Julian centuries from J2000.
"""

from __future__ import annotations

import numpy as np

AU_M = 1.495978707e11          # AU in meters
C_M_S = 299792458.0            # speed of light m/s
AU_LIGHT_S = AU_M / C_M_S      # 499.004783836... s
EMRAT = 81.30056               # Earth/Moon mass ratio
OBLIQUITY_J2000 = np.deg2rad(23.439291111)
GMSUN_C3 = 4.925490947e-6      # 2*GM_sun/c^3 / 2 -> GM_sun/c^3 seconds

# Keplerian elements at J2000 and per-Julian-century rates, mean
# ecliptic/equinox of J2000 (Standish, "Approximate positions of the
# major planets", 1800AD-2050AD table):
#   a [AU], e, I [deg], L [deg], varpi [deg], Omega [deg]
_ELEMENTS = {
    "mercury": ((0.38709927, 0.20563593, 7.00497902, 252.25032350,
                 77.45779628, 48.33076593),
                (0.00000037, 0.00001906, -0.00594749, 149472.67411175,
                 0.16047689, -0.12534081)),
    "venus": ((0.72333566, 0.00677672, 3.39467605, 181.97909950,
               131.60246718, 76.67984255),
              (0.00000390, -0.00004107, -0.00078890, 58517.81538729,
               0.00268329, -0.27769418)),
    "emb": ((1.00000261, 0.01671123, -0.00001531, 100.46457166,
             102.93768193, 0.0),
            (0.00000562, -0.00004392, -0.01294668, 35999.37244981,
             0.32327364, 0.0)),
    "mars": ((1.52371034, 0.09339410, 1.84969142, -4.55343205,
              -23.94362959, 49.55953891),
             (0.00001847, 0.00007882, -0.00813131, 19140.30268499,
              0.44441088, -0.29257343)),
    "jupiter": ((5.20288700, 0.04838624, 1.30439695, 34.39644051,
                 14.72847983, 100.47390909),
                (-0.00011607, -0.00013253, -0.00183714, 3034.74612775,
                 0.21252668, 0.20469106)),
    "saturn": ((9.53667594, 0.05386179, 2.48599187, 49.95424423,
                92.59887831, 113.66242448),
               (-0.00125060, -0.00050991, 0.00193609, 1222.49362201,
                -0.41897216, -0.28867794)),
    "uranus": ((19.18916464, 0.04725744, 0.77263783, 313.23810451,
                170.95427630, 74.01692503),
               (-0.00196176, -0.00004397, -0.00242939, 428.48202785,
                0.40805281, 0.04240589)),
    "neptune": ((30.06992276, 0.00859048, 1.77004347, -55.12002969,
                 44.96476227, 131.78422574),
                (0.00026291, 0.00005105, 0.00035372, 218.45945325,
                 -0.32241464, -0.00508664)),
}

# Sun/planet mass ratios (IAU/JPL values).
_MASS_RATIO = {
    "mercury": 6023682.0,
    "venus": 408523.72,
    "emb": 328900.56,
    "mars": 3098703.6,
    "jupiter": 1047.3486,
    "saturn": 3497.898,
    "uranus": 22902.98,
    "neptune": 19412.24,
}


def _kepler(M, e, tol=1e-12, maxiter=25):
    """Solve E - e sin E = M (radians), vectorized Newton iteration."""
    M = np.mod(M + np.pi, 2 * np.pi) - np.pi
    E = M + e * np.sin(M)
    for _ in range(maxiter):
        dE = (E - e * np.sin(E) - M) / (1.0 - e * np.cos(E))
        E = E - dE
        if np.max(np.abs(dE)) < tol:
            break
    return E


def planet_helio_ecl(T, name):
    """Heliocentric J2000-ecliptic position of a planet, AU.

    T: TDB Julian centuries from J2000 (array).  Returns (..., 3).
    """
    el, rate = _ELEMENTS[name]
    T = np.asarray(T, np.float64)
    a = el[0] + rate[0] * T
    e = el[1] + rate[1] * T
    I = np.deg2rad(el[2] + rate[2] * T)
    L = np.deg2rad(el[3] + rate[3] * T)
    varpi = np.deg2rad(el[4] + rate[4] * T)
    Om = np.deg2rad(el[5] + rate[5] * T)

    M = L - varpi
    w = varpi - Om
    E = _kepler(M, e)
    xp = a * (np.cos(E) - e)
    yp = a * np.sqrt(1.0 - e * e) * np.sin(E)

    cw, sw = np.cos(w), np.sin(w)
    cO, sO = np.cos(Om), np.sin(Om)
    cI, sI = np.cos(I), np.sin(I)
    x = (cw * cO - sw * sO * cI) * xp + (-sw * cO - cw * sO * cI) * yp
    y = (cw * sO + sw * cO * cI) * xp + (-sw * sO + cw * cO * cI) * yp
    z = (sw * sI) * xp + (cw * sI) * yp
    return np.stack([x, y, z], axis=-1)


def ssb_offset_ecl(T):
    """Position of the solar-system barycenter w.r.t. the Sun's center
    in the J2000 ecliptic frame, AU:  R = sum(m_p r_p) / M_total."""
    T = np.asarray(T, np.float64)
    num = np.zeros(T.shape + (3,))
    denom = 1.0
    for name, ratio in _MASS_RATIO.items():
        num = num + planet_helio_ecl(T, name) / ratio
        denom += 1.0 / ratio
    return num / denom


# --- Truncated lunar theory (Meeus ch. 47 leading terms) -------------
# Columns: (d, m, mp, f, coeff).  Longitude/latitude coeffs in 1e-6 deg,
# distance coeffs in 1e-3 km.  Terms with |coeff_lon| > 4000 or
# |coeff_r| > 8000 are kept; truncation error ~20 km in distance and
# ~10 arcsec in longitude, scaled into Earth's position by 1/82.3.
_LUN_LR = [
    # d  m  mp  f    lon(1e-6 deg)   r(1e-3 km)
    (0, 0, 1, 0, 6288774, -20905355),
    (2, 0, -1, 0, 1274027, -3699111),
    (2, 0, 0, 0, 658314, -2955968),
    (0, 0, 2, 0, 213618, -569925),
    (0, 1, 0, 0, -185116, 48888),
    (0, 0, 0, 2, -114332, -3149),
    (2, 0, -2, 0, 58793, 246158),
    (2, -1, -1, 0, 57066, -152138),
    (2, 0, 1, 0, 53322, -170733),
    (2, -1, 0, 0, 45758, -204586),
    (0, 1, -1, 0, -40923, -129620),
    (1, 0, 0, 0, -34720, 108743),
    (0, 1, 1, 0, -30383, 104755),
    (2, 0, 0, -2, 15327, 10321),
    (0, 0, 1, 2, -12528, 0),
    (0, 0, 1, -2, 10980, 79661),
    (4, 0, -1, 0, 10675, -34782),
    (0, 0, 3, 0, 10034, -23210),
    (4, 0, -2, 0, 8548, -21636),
    (2, 1, -1, 0, -7888, 24208),
    (2, 1, 0, 0, -6766, 30824),
    (1, 0, -1, 0, -5163, -8379),
    (1, 1, 0, 0, 4987, -16675),
    (2, -1, 1, 0, 4036, -12831),
]
_LUN_B = [
    # d  m  mp  f    lat(1e-6 deg)
    (0, 0, 0, 1, 5128122),
    (0, 0, 1, 1, 280602),
    (0, 0, 1, -1, 277693),
    (2, 0, 0, -1, 173237),
    (2, 0, -1, 1, 55413),
    (2, 0, -1, -1, 46271),
    (2, 0, 0, 1, 32573),
    (0, 0, 2, 1, 17198),
    (2, 0, 1, -1, 9266),
    (0, 0, 2, -1, 8822),
]


def moon_geo_ecl_date(T):
    """Geocentric Moon in the ecliptic *of date*: returns
    (lambda_deg, beta_deg, dist_km), vectorized."""
    T = np.asarray(T, np.float64)
    Lp = 218.3164477 + 481267.88123421 * T - 0.0015786 * T**2
    D = np.deg2rad(297.8501921 + 445267.1114034 * T - 0.0018819 * T**2)
    M = np.deg2rad(357.5291092 + 35999.0502909 * T - 0.0001536 * T**2)
    Mp = np.deg2rad(134.9633964 + 477198.8675055 * T + 0.0087414 * T**2)
    F = np.deg2rad(93.2720950 + 483202.0175233 * T - 0.0036539 * T**2)
    E = 1.0 - 0.002516 * T - 0.0000074 * T**2

    sl = np.zeros_like(T)
    sr = np.zeros_like(T)
    for d, m, mp, f, cl, cr in _LUN_LR:
        arg = d * D + m * M + mp * Mp + f * F
        ef = np.ones_like(T) if m == 0 else (E if abs(m) == 1 else E * E)
        sl = sl + cl * ef * np.sin(arg)
        sr = sr + cr * ef * np.cos(arg)
    sb = np.zeros_like(T)
    for d, m, mp, f, cb in _LUN_B:
        arg = d * D + m * M + mp * Mp + f * F
        ef = np.ones_like(T) if m == 0 else (E if abs(m) == 1 else E * E)
        sb = sb + cb * ef * np.sin(arg)

    lam = Lp + sl * 1e-6
    beta = sb * 1e-6
    dist = 385000.56 + sr * 1e-3
    return lam, beta, dist


def moon_geo_ecl_j2000(T):
    """Geocentric Moon in the J2000 ecliptic frame, AU."""
    lam, beta, dist = moon_geo_ecl_date(T)
    # Precess longitude from the ecliptic of date back to J2000 (the
    # dominant general-precession-in-longitude term; residual rotation
    # terms are < 1" / century and enter Earth's position at < 30 m).
    lam = np.deg2rad(lam - 1.3969713 * T)
    beta = np.deg2rad(beta)
    r = dist * 1000.0 / AU_M
    cb = np.cos(beta)
    return np.stack([r * cb * np.cos(lam),
                     r * cb * np.sin(lam),
                     r * np.sin(beta)], axis=-1)


def _ecl_to_equ(v):
    """Rotate J2000-ecliptic vectors to J2000-equatorial."""
    ce, se = np.cos(OBLIQUITY_J2000), np.sin(OBLIQUITY_J2000)
    x = v[..., 0]
    y = ce * v[..., 1] - se * v[..., 2]
    z = se * v[..., 1] + ce * v[..., 2]
    return np.stack([x, y, z], axis=-1)


def _earth_pos_ecl(T):
    """Earth (not EMB) w.r.t. SSB in the J2000 ecliptic frame, AU."""
    emb = planet_helio_ecl(T, "emb")
    moon = moon_geo_ecl_j2000(T)
    earth_helio = emb - moon / (1.0 + EMRAT)
    return earth_helio - ssb_offset_ecl(T)


class AnalyticEphemeris:
    """The built-in ephemeris; accepts TDB JD, returns J2000 equatorial
    AU / AU/day.  Stateless and vectorized."""

    name = "DEANALYTIC"

    def earth_posvel(self, jd_tdb):
        jd = np.asarray(jd_tdb, np.float64)
        T = (jd - 2451545.0) / 36525.0
        dt_days = 0.05
        dT = dt_days / 36525.0
        pos = _ecl_to_equ(_earth_pos_ecl(T))
        p_plus = _ecl_to_equ(_earth_pos_ecl(T + dT))
        p_minus = _ecl_to_equ(_earth_pos_ecl(T - dT))
        vel = (p_plus - p_minus) / (2.0 * dt_days)
        return pos, vel

    def sun_pos(self, jd_tdb):
        """Sun w.r.t. SSB, J2000 equatorial AU (for the Shapiro delay)."""
        jd = np.asarray(jd_tdb, np.float64)
        T = (jd - 2451545.0) / 36525.0
        return _ecl_to_equ(-ssb_offset_ecl(T))


class TabulatedEphemeris:
    """Precision seam: an ephemeris loaded from an .npz table with
    fields jd_tdb (N,), earth_pos (N,3) [AU], earth_vel (N,3) [AU/day],
    sun_pos (N,3) [AU] — e.g. exported from a JPL DE kernel elsewhere.
    Cubic Hermite interpolation on position using the tabulated
    velocities."""

    def __init__(self, path):
        dat = np.load(path)
        self.jd = dat["jd_tdb"]
        self.pos = dat["earth_pos"]
        self.vel = dat["earth_vel"]
        self.sunp = dat["sun_pos"]
        self.name = str(dat.get("name", "DETABLE"))

    def _hermite(self, jd, ya, yb, da, db, t, h):
        t2, t3 = t * t, t * t * t
        h00 = 2 * t3 - 3 * t2 + 1
        h10 = t3 - 2 * t2 + t
        h01 = -2 * t3 + 3 * t2
        h11 = t3 - t2
        return (h00[..., None] * ya + (h * h10)[..., None] * da
                + h01[..., None] * yb + (h * h11)[..., None] * db)

    def earth_posvel(self, jd_tdb):
        jd = np.atleast_1d(np.asarray(jd_tdb, np.float64))
        i = np.clip(np.searchsorted(self.jd, jd) - 1, 0, len(self.jd) - 2)
        h = self.jd[i + 1] - self.jd[i]
        t = (jd - self.jd[i]) / h
        pos = self._hermite(jd, self.pos[i], self.pos[i + 1],
                            self.vel[i], self.vel[i + 1], t, h)
        # derivative of the Hermite polynomial for velocity
        t2 = t * t
        d00 = (6 * t2 - 6 * t) / h
        d10 = 3 * t2 - 4 * t + 1
        d01 = (-6 * t2 + 6 * t) / h
        d11 = 3 * t2 - 2 * t
        vel = (d00[..., None] * self.pos[i] + d10[..., None] * self.vel[i]
               + d01[..., None] * self.pos[i + 1]
               + d11[..., None] * self.vel[i + 1])
        return pos, vel

    def sun_pos(self, jd_tdb):
        jd = np.atleast_1d(np.asarray(jd_tdb, np.float64))
        i = np.clip(np.searchsorted(self.jd, jd) - 1, 0, len(self.jd) - 2)
        h = (self.jd[i + 1] - self.jd[i])
        t = ((jd - self.jd[i]) / h)[..., None]
        return (1 - t) * self.sunp[i] + t * self.sunp[i + 1]


class EpvEphemeris:
    """The built-in KM-GRADE ephemeris: the simplified VSOP2000 Earth
    solution of X. Moisson & P. Bretagnon (2001, Celest. Mech. Dyn.
    Astron. 80, 205) — ~2000 published (amplitude, phase, frequency)
    Poisson-series coefficients, shipped in data/epv.npz
    (tools/make_epv_tables.py extracts them AS DATA from the tables
    the reference vendors in src/slalib/epv.f; no reference code is
    executed or translated).

    Model: each ecliptic component is
        P(t)  = Σ_{n=0..2} t^n Σ_j A cos(B + C t),   t = TDB Julian
    years from J2000, with the analytic frame tied to DE405/ICRS by a
    fixed published rotation.  Barycentric Earth = (Sun→Earth series)
    + (SSB→Sun series).  Stated accuracy vs JPL DE405 over 1900-2100:
    4.6 km RMS / 13.4 km max barycentric position, 1.4 mm/s RMS
    velocity — i.e. sub-50-µs absolute Roemer, timing-grade for
    everything short of µs pulsar timing (which uses a real JPL .bsp
    via astro/spk.py).
    """

    name = "EPV2000"

    # frame tie to DE405/ICRS (published empirical rotation)
    _AM = np.array([
        [1.0, +0.000000211284, -0.000000091603],
        [-0.000000230286, +0.917482137087, -0.397776982902],
        [0.0, +0.397776982902, +0.917482137087]])

    def __init__(self):
        import os
        path = os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
            "data", "epv.npz")
        dat = np.load(path)
        # per body ('e' Sun->Earth, 's' SSB->Sun), per power, per
        # component: [n, 3] (A, B, C)
        self._ser = {b: [[dat["%s%d%s" % (b.upper(), p, c)]
                          for c in "xyz"] for p in range(3)]
                     for b in ("e", "s")}

    def _eval(self, t, bodies):
        """Σ of the named series at t [Julian years from J2000]:
        (pos_ecl [.., 3] AU, vel_ecl [.., 3] AU/day).  t is flattened
        (callers reshape) so N-D epoch arrays work like the Keplerian
        model's."""
        t = np.atleast_1d(np.asarray(t, np.float64)).ravel()
        pos = np.zeros(t.shape + (3,))
        vel = np.zeros(t.shape + (3,))
        for b in bodies:
            for p in range(3):
                tp = t ** p
                for c in range(3):
                    A, B, C = self._ser[b][p][c].T
                    ph = B[:, None] + C[:, None] * t[None]
                    cp = np.cos(ph)
                    pos[..., c] += tp * (A[:, None] * cp).sum(0)
                    # d/dt of t^p A cos(B + C t)
                    dv = (A[:, None]
                          * (-C[:, None] * np.sin(ph))).sum(0) * tp
                    if p:
                        dv += (p * t ** (p - 1)
                               * (A[:, None] * cp).sum(0))
                    vel[..., c] += dv
        return pos, vel / 365.25

    def earth_posvel(self, jd_tdb):
        """Barycentric Earth (pos AU, vel AU/day), ICRS."""
        jd = np.asarray(jd_tdb, np.float64)
        t = (jd - 2451545.0) / 365.25
        pos, vel = self._eval(t, ("e", "s"))
        shape = np.shape(jd) + (3,)
        return (pos @ self._AM.T).reshape(shape), \
            (vel @ self._AM.T).reshape(shape)

    def sun_pos(self, jd_tdb):
        """Sun w.r.t. SSB, ICRS AU (for the Shapiro delay)."""
        jd = np.asarray(jd_tdb, np.float64)
        t = (jd - 2451545.0) / 365.25
        pos, _ = self._eval(t, ("s",))
        return (pos @ self._AM.T).reshape(np.shape(jd) + (3,))


_DEFAULT = None


def _default_ephemeris():
    """The shipped default: EPV2000 (km-grade); the Keplerian
    AnalyticEphemeris remains as the data-free fallback — with a loud
    warning, since the fallback is ~3 orders of magnitude less
    accurate and silent substitution would corrupt TOA provenance."""
    global _DEFAULT
    if _DEFAULT is None:
        try:
            _DEFAULT = EpvEphemeris()
        except (OSError, KeyError) as e:
            import warnings
            warnings.warn(
                "EPV2000 ephemeris tables (data/epv.npz) unavailable "
                "(%s): falling back to the Keplerian analytic model "
                "(~12,000 km Earth position error vs EPV's ~5 km)"
                % (e,), RuntimeWarning)
            _DEFAULT = AnalyticEphemeris()
    return _DEFAULT


def get_ephemeris(name="DEANALYTIC"):
    """Resolve an ephemeris spec.  Bare names ('DE200'/'DE405'/
    'DEANALYTIC'/'EPV2000') map to the built-in EPV2000 series (API
    parity with barycenter.c:134 — callers pass DE405 and get the
    km-grade built-in); a path ending in .npz loads a table, .bsp a
    JPL SPK kernel; 'KEPLER' forces the data-free analytic model."""
    if name is None:
        return _default_ephemeris()
    s = str(name)
    if s.upper() == "KEPLER":
        return AnalyticEphemeris()
    if s.upper() == "AUTO":
        # kernel-provisioning ladder (astro/kernels.py): a real JPL
        # DE file if available/fetchable, else the builtin EPV2000
        # kernel generated at first use — the .bsp route with zero
        # user setup (the reference's TEMPO+DE405 out-of-box parity)
        from presto_tpu.astro.kernels import resolve_kernel
        from presto_tpu.astro.spk import SPKEphemeris
        return SPKEphemeris(resolve_kernel()[0])
    if s.lower().endswith(".npz"):
        return TabulatedEphemeris(s)
    if s.lower().endswith(".bsp"):
        from presto_tpu.astro.spk import SPKEphemeris
        return SPKEphemeris(s)
    # Path-like names that are not a recognized ephemeris file must NOT
    # silently fall back to the analytic model — the user believes
    # their kernel is in use while barycentering runs at search grade.
    # (Bare names like 'DE405' always select the analytic model, even
    # if a same-named file happens to exist in the cwd.)
    import os
    if os.path.sep in s:
        raise ValueError(
            f"unrecognized ephemeris file {s!r}: expected a .bsp (JPL "
            f"SPK kernel) or .npz table; bare names like 'DE405' select "
            f"the built-in ephemeris")
    return _default_ephemeris()


def earth_posvel_ssb(jd_tdb, ephem="DEANALYTIC"):
    """Earth center w.r.t. SSB: (pos AU, vel AU/day), J2000 equatorial."""
    return get_ephemeris(ephem).earth_posvel(jd_tdb)
