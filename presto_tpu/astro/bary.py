"""Barycentering: topocentric UTC MJDs -> barycentric TDB MJDs + v/c.

API parity with the reference's barycenter() (src/barycenter.c:87-252),
which writes fake TOAs, shells out to TEMPO twice, and parses
resid2.tmp.  Here the whole chain is computed in-process:

  t_bary = TDB(t_topo) + Roemer/c + Shapiro(sun)        [infinite freq]
  voverc = -(v_obs . n_hat)/c

The v/c sign convention matches the reference: TEMPO reports the
barycentric frequency f_bary of a topocentric channel and PRESTO sets
voverc = f_bary/f_topo - 1 (barycenter.c:232-234), i.e. positive when
the observatory recedes from the pulsar, so that
doppler(f_topo, voverc) = f_topo*(1+voverc) = f_bary (doppler() in
barycenter.c:3-11).
"""

from __future__ import annotations

import re

import numpy as np

from presto_tpu.astro import time as ptime
from presto_tpu.astro import observatory as obsmod
from presto_tpu.astro.ephem import (AU_M, C_M_S, get_ephemeris)

SECPERDAY = 86400.0
# 2 GM_sun / c^3 in seconds (Shapiro-delay scale)
TWO_GMSUN_C3 = 9.8509819e-6


def parse_ra(ra):
    """'hh:mm:ss.ssss' (or hours as float) -> radians."""
    if isinstance(ra, (int, float)):
        return float(ra)
    parts = [p for p in re.split(r"[:\s]+", str(ra).strip()) if p]
    h = float(parts[0])
    m = float(parts[1]) if len(parts) > 1 else 0.0
    s = float(parts[2]) if len(parts) > 2 else 0.0
    return (abs(h) + m / 60.0 + s / 3600.0) * np.pi / 12.0


def parse_dec(dec):
    """'[+-]dd:mm:ss.ssss' (or degrees as float) -> radians."""
    if isinstance(dec, (int, float)):
        return float(dec)
    s_dec = str(dec).strip()
    sign = -1.0 if s_dec.lstrip().startswith("-") else 1.0
    parts = [p for p in re.split(r"[:\s]+", s_dec) if p]
    d = abs(float(parts[0]))
    m = float(parts[1]) if len(parts) > 1 else 0.0
    s = float(parts[2]) if len(parts) > 2 else 0.0
    return sign * (d + m / 60.0 + s / 3600.0) * np.pi / 180.0


def source_unit_vector(ra, dec):
    """J2000 unit vector toward (ra, dec) given as strings or radians."""
    a, d = parse_ra(ra), parse_dec(dec)
    return np.array([np.cos(d) * np.cos(a),
                     np.cos(d) * np.sin(a),
                     np.sin(d)])


def barycenter(topotimes, ra, dec, obs="GB", ephem="DE405"):
    """Correct topocentric UTC MJDs to barycentric TDB MJDs at infinite
    observing frequency, and return the site radial velocity in units
    of c at each epoch.

    Parameters mirror barycenter.c:87: ra 'hh:mm:ss.ss', dec
    '[+-]dd:mm:ss.ss', obs a 2-letter TEMPO code (observatory.py), and
    ephem a DE name (both DE200/DE405 resolve to the built-in analytic
    model; an .npz path loads a tabulated precision ephemeris).

    Returns (barytimes, voverc) as float64 arrays of the input shape.
    """
    topo = np.atleast_1d(np.asarray(topotimes, np.float64))
    nhat = source_unit_vector(ra, dec)
    eph = get_ephemeris(ephem)

    tdb = ptime.utc_to_tdb(topo)
    jd_tdb = tdb + 2400000.5

    epos, evel = eph.earth_posvel(jd_tdb)          # AU, AU/day
    opos, ovel = obsmod.obs_posvel_gcrs(topo, obs)  # m, m/s

    r_m = epos * AU_M + opos                        # site w.r.t. SSB, m
    v_m_s = evel * (AU_M / SECPERDAY) + ovel

    roemer_s = r_m @ nhat / C_M_S

    # Solar Shapiro delay: -2GM/c^3 ln(1 - cos(theta)), theta the
    # pulsar-Sun angular separation seen from the site.
    sun_m = eph.sun_pos(jd_tdb) * AU_M
    r_os = sun_m - r_m                              # site -> Sun
    rmag = np.linalg.norm(r_os, axis=-1)
    cos_theta = -(r_os @ nhat) / rmag               # cos(angle Sun vs psr)
    shapiro_s = -TWO_GMSUN_C3 * np.log(np.maximum(1.0 - cos_theta, 1e-12))

    bary = tdb + (roemer_s - shapiro_s) / SECPERDAY
    voverc = -(v_m_s @ nhat) / C_M_S

    if np.isscalar(topotimes) or np.ndim(topotimes) == 0:
        return float(bary[0]), float(voverc[0])
    return bary, voverc


def average_voverc(start_mjd, duration_s, ra, dec, obs="GB",
                   ephem="DE405", npts=100):
    """Mean/max/min v/c over an observation — the avgvoverc statistic
    prepdata/prepsubband print and use for Doppler-corrected DM delays
    (prepsubband.c:444-465)."""
    ts = start_mjd + np.linspace(0.0, duration_s / SECPERDAY, npts)
    _, voverc = barycenter(ts, ra, dec, obs, ephem)
    return float(np.mean(voverc)), float(np.max(voverc)), float(np.min(voverc))
