"""Barycentric resampling for the prep tools.

The reference barycenters a time series by keeping topocentric samples
and occasionally adding/removing single bins wherever the accumulated
(bary - topo) drift crosses a half-bin boundary (prepdata.c:469-505,
prepsubband.c:506-539: the `diffbins` schedule).  The output is then
uniformly sampled in barycentric time to within half a bin, with the
.inf epoch set to the barycentric MJD of the first sample.

This module reproduces that schedule exactly (same TDT=20 s sampling of
the TEMPO/ephemeris curve, same rounding and linear interpolation) but
applies it as a vectorized insert/delete pass over the finished series
instead of interleaving it with the write loop.
"""

from __future__ import annotations

import numpy as np

from presto_tpu.astro.bary import barycenter

SECPERDAY = 86400.0
TDT = 20.0  # seconds between barycentric-motion samples (prepdata.c:14)


def bary_grid(tlotoa_mjd, total_sec, ra, dec, obs="GB", ephem="DE405"):
    """Barycenter a TDT-spaced grid covering the observation.

    Mirrors prepdata.c:214 (numbarypts = T*1.1/TDT + 5.5 + 1) and
    :415 (ttoa[i] = tlotoa + TDT*i).  Returns (ttoa, btoa, voverc).
    """
    numbarypts = int(total_sec * 1.1 / TDT + 5.5) + 1
    ttoa = tlotoa_mjd + TDT * np.arange(numbarypts) / SECPERDAY
    btoa, voverc = barycenter(ttoa, ra, dec, obs, ephem)
    return ttoa, btoa, voverc


def diffbin_schedule(ttoa, btoa, dsdt):
    """Output-bin indices where one sample must be added (+) or
    removed (-) to stay aligned with barycentric time.

    Direct port of the drift-crossing scan in prepdata.c:469-505:
    express (btoa-ttoa) relative to the first point in units of the
    (downsampled) bin length, then linearly interpolate the time at
    which each successive half-integer level is crossed.
    """
    drift = ((btoa - ttoa) - (btoa[0] - ttoa[0])) * SECPERDAY / dsdt
    diffbins = []
    oldbin = 0
    for ii in range(1, len(drift)):
        currentbin = int(round(drift[ii]))
        if currentbin != oldbin:
            if currentbin > 0:
                calcpt = oldbin + 0.5
                lobin = (ii - 1) * TDT / dsdt
                hibin = ii * TDT / dsdt
            else:
                calcpt = oldbin - 0.5
                lobin = -((ii - 1) * TDT / dsdt)
                hibin = -(ii * TDT / dsdt)
            while abs(calcpt) < abs(drift[ii]):
                # linear interp of the crossing time between samples
                frac = (calcpt - drift[ii - 1]) / (drift[ii] - drift[ii - 1])
                diffbins.append(int(round(lobin + frac * (hibin - lobin))))
                calcpt += 1.0 if currentbin > 0 else -1.0
            oldbin = currentbin
    return np.asarray(diffbins, dtype=np.int64)


def apply_diffbins(series, diffbins, fill_mode="local_avg"):
    """Insert/remove single bins at the scheduled output positions.

    Positive entry b: insert one bin *at* output index |b| (the
    reference writes an extra padding bin there, value = local block
    average, prepdata.c:556-575).  Negative: drop the bin at |b|.
    Returns a new 1-D float32 array.
    """
    if diffbins.size == 0:
        return series
    # Single pass building output pieces: positions are output-bin
    # counters exactly as in the reference write loop (it compares
    # totwrote against *diffbinptr, prepdata.c:556-575), so walk them
    # in increasing |position| while advancing an input cursor.
    entries = sorted((int(b) for b in diffbins), key=abs)
    pieces = []
    in_pos = 0
    out_count = 0
    n = series.size
    for b in entries:
        target = abs(b)
        ncopy = min(target - out_count, n - in_pos)
        if ncopy > 0:
            pieces.append(series[in_pos:in_pos + ncopy])
            in_pos += ncopy
            out_count += ncopy
        if in_pos >= n:
            break
        if b >= 0:
            lo = max(in_pos - 500, 0)
            fill = (np.float32(np.mean(series[lo:in_pos + 500]))
                    if fill_mode == "local_avg" else np.float32(0))
            pieces.append(np.array([fill], dtype=np.float32))
            out_count += 1
        else:
            in_pos += 1  # drop one topocentric sample
    pieces.append(series[in_pos:])
    return np.concatenate(pieces).astype(np.float32, copy=False)


class BaryPlan:
    """Everything the prep tools need to barycenter one observation."""

    def __init__(self, tlotoa_mjd, total_sec, dsdt, ra, dec,
                 obs="GB", ephem="DE405"):
        self.ttoa, self.btoa, voverc = bary_grid(
            tlotoa_mjd, total_sec, ra, dec, obs, ephem)
        self.avgvoverc = float(np.mean(voverc))
        self.maxvoverc = float(np.max(voverc))
        self.minvoverc = float(np.min(voverc))
        self.blotoa = float(self.btoa[0])   # bary epoch of first sample
        self.diffbins = diffbin_schedule(self.ttoa, self.btoa, dsdt)

    def apply(self, series):
        return apply_diffbins(series, self.diffbins)
