"""Binary-pulsar orbital calculations driven by a .par file.

Parity target: lib/python/binary_psr.py (class binary_psr) — anomalies,
orbital position, radial velocity, Doppler period, TOA demodulation,
and Shapiro-delay predictions.  Built on the vectorized Kepler solver
in ops.orbit rather than the reference's fixed-point iteration.
"""

from __future__ import annotations

import numpy as np

from presto_tpu.io.parfile import Parfile
from presto_tpu.ops.orbit import SOL, keplers_eqn

TWOPI = 2.0 * np.pi
SECPERDAY = 86400.0
SECPERJULYR = 86400.0 * 365.25
DEGTORAD = np.pi / 180.0
Tsun = 4.925490947e-6      # GM_sun/c^3 (s)


def shapiro_R(m2: float) -> float:
    """Shapiro 'R' (range) parameter in seconds, companion mass in
    solar units (binary_psr.py:12-17)."""
    return Tsun * m2


def shapiro_S(m1: float, m2: float, x: float, pb: float) -> float:
    """Shapiro 'S' (shape = sin i) from masses (solar), x (lt-s), and
    pb (days) (binary_psr.py:20-28)."""
    return (x * (pb * SECPERDAY / TWOPI) ** (-2.0 / 3.0)
            * Tsun ** (-1.0 / 3.0) * (m1 + m2) ** (2.0 / 3.0) / m2)


def true_anomaly(E, ecc: float):
    """Eccentric -> true anomaly (psr_utils.true_anomaly)."""
    return 2.0 * np.arctan(np.sqrt((1.0 + ecc) / (1.0 - ecc))
                           * np.tan(E / 2.0))


class BinaryPsr:
    """Orbital calculations for a binary pulsar from its .par file."""

    def __init__(self, parfilenm: str):
        self.par = Parfile(parfilenm) if isinstance(parfilenm, str) \
            else parfilenm
        if not self.par.is_binary:
            raise ValueError("%s has no binary parameters"
                             % getattr(self.par, "FILE", "parfile"))
        self.PBsec = self.par.PB * SECPERDAY
        self.T0 = self.par.T0

    # -- anomalies --------------------------------------------------- #

    def calc_anoms(self, MJD):
        """(mean, eccentric, true) anomalies (radians) at barycentric
        MJD(s) (binary_psr.py:51-64)."""
        MJD = np.atleast_1d(np.asarray(MJD, dtype=np.float64))
        difft = (MJD - self.T0) * SECPERDAY
        since_peri = np.fmod(difft, self.PBsec)
        since_peri[since_peri < 0] += self.PBsec
        mean_anom = since_peri / self.PBsec * TWOPI
        ecc_anom = self.eccentric_anomaly(mean_anom)
        return mean_anom, ecc_anom, true_anomaly(ecc_anom, self.par.E)

    def eccentric_anomaly(self, mean_anomaly):
        """Solve Kepler's equation (binary_psr.py:78-93) via the shared
        vectorized solver in ops.orbit (fixed-point warmup + Newton)."""
        ma = np.fmod(np.asarray(mean_anomaly, dtype=np.float64), TWOPI)
        ma = np.where(ma < 0.0, ma + TWOPI, ma)
        return np.atleast_1d(keplers_eqn(ma / TWOPI * self.PBsec,
                                         self.PBsec, self.par.E,
                                         acc=5e-15))

    def most_recent_peri(self, MJD):
        """MJD(s) of the last periastron before MJD
        (binary_psr.py:66-76)."""
        MJD = np.atleast_1d(np.asarray(MJD, dtype=np.float64))
        days = np.fmod(MJD - self.T0, self.par.PB)
        days[days < 0] += self.par.PB
        return MJD - days

    def calc_omega(self, MJD):
        """Argument of periastron (radians) incl. OMDOT advance
        (binary_psr.py:95-107)."""
        MJD = np.atleast_1d(np.asarray(MJD, dtype=np.float64))
        om = getattr(self.par, "OM", 0.0)
        omdot = getattr(self.par, "OMDOT", 0.0)
        if omdot:
            difft = (MJD - self.T0) * SECPERDAY
            return (om + difft / SECPERJULYR * omdot) * DEGTORAD
        return np.full_like(MJD, om * DEGTORAD)

    # -- observables ------------------------------------------------- #

    def radial_velocity(self, MJD):
        """Pulsar radial velocity (km/s) at MJD(s)
        (binary_psr.py:109-120)."""
        _, ea, _ = self.calc_anoms(MJD)
        ws = self.calc_omega(MJD)
        e = self.par.E
        c1 = TWOPI * self.par.A1 / self.PBsec
        c2 = np.cos(ws) * np.sqrt(1 - e * e)
        cea = np.cos(ea)
        return (SOL / 1000.0) * c1 * (c2 * cea - np.sin(ws) * np.sin(ea)) \
            / (1.0 - e * cea)

    def doppler_period(self, MJD):
        """Observed spin period (s) at MJD(s) (binary_psr.py:122-128)."""
        vs = self.radial_velocity(MJD) * 1000.0
        return self.par.P0 * (1.0 + vs / SOL)

    def position(self, MJD, inc: float = 60.0, returnz: bool = False):
        """Orbital position in lt-s: x along the line of sight (+
        towards us), y in the sky plane (binary_psr.py:130-154)."""
        _, _, ta = self.calc_anoms(MJD)
        ws = self.calc_omega(MJD)
        orb_phs = ta + ws
        sini = np.sin(inc * DEGTORAD)
        e = self.par.E
        x = self.par.A1 / sini
        r = x * (1.0 - e * e) / (1.0 + e * np.cos(ta))
        xs = -r * np.sin(orb_phs) * sini
        ys = -r * np.cos(orb_phs)
        if returnz:
            return xs, ys, -r * np.sin(orb_phs) * np.cos(inc * DEGTORAD)
        return xs, ys

    def demodulate_TOAs(self, MJD):
        """Remove orbital modulation from arrival times via the
        Deeter, Boynton & Pravdo (1981) Newton iteration
        (binary_psr.py:176-197)."""
        MJD = np.atleast_1d(np.asarray(MJD, dtype=np.float64))
        ts = MJD.copy()
        for _ in range(100):
            xs = -self.position(ts, inc=90.0)[0] / SECPERDAY  # lt-days
            dxs = self.radial_velocity(ts) * 1000.0 / SOL
            dts = (ts + xs - MJD) / (1.0 + dxs)
            ts = ts - dts
            if np.max(np.abs(dts)) < 1e-10:
                break
        return ts

    def shapiro_delays(self, R: float, S: float, ecc_anoms):
        """Predicted Shapiro delay (us) at eccentric anomalies
        (binary_psr.py:199-215)."""
        canoms = np.cos(ecc_anoms)
        sanoms = np.sin(ecc_anoms)
        ecc = self.par.E
        omega = self.par.OM * DEGTORAD
        return -2.0e6 * R * np.log(
            1.0 - ecc * canoms
            - S * (np.sin(omega) * (canoms - ecc)
                   + np.sqrt(1.0 - ecc * ecc) * np.cos(omega) * sanoms))

    def shapiro_measurable(self, R: float, S: float, mean_anoms):
        """Measurable part of the Shapiro delay (us), Freire & Wex
        2010 eqn 28, low-eccentricity limit (binary_psr.py:218-235)."""
        Phi = mean_anoms + self.par.OM * DEGTORAD
        cbar = np.sqrt(1.0 - S * S)
        zeta = S / (1.0 + cbar)
        h3 = R * zeta ** 3
        sPhi = np.sin(Phi)
        return -2.0e6 * h3 * (
            np.log(1.0 + zeta * zeta - 2.0 * zeta * sPhi) / zeta ** 3
            + 2.0 * sPhi / zeta ** 2 - np.cos(2.0 * Phi) / zeta)


binary_psr = BinaryPsr   # reference-compatible alias
