"""Observatory geometry: ITRF coordinates, geodetic conversion, and
GCRS (J2000 equatorial) position/velocity of a site.

Replaces TEMPO's obsys.dat lookup (the reference passes 2-letter ITOA
codes through barycenter.c:106 and maps telescope names to codes in
misc_utils.c:185-252).  Site coordinates are public geodetic/ITRF
values; a few meters of error contribute < 10 ns of Roemer delay.
"""

from __future__ import annotations

import numpy as np

from presto_tpu.astro import time as ptime

WGS84_A = 6378137.0
WGS84_F = 1.0 / 298.257223563
EARTH_OMEGA = 7.2921150e-5  # rad/s


def geodetic_to_itrf(lat_deg, lon_deg, height_m):
    """Geodetic (WGS84) -> geocentric ITRF xyz in meters."""
    lat = np.deg2rad(lat_deg)
    lon = np.deg2rad(lon_deg)
    e2 = WGS84_F * (2.0 - WGS84_F)
    N = WGS84_A / np.sqrt(1.0 - e2 * np.sin(lat) ** 2)
    x = (N + height_m) * np.cos(lat) * np.cos(lon)
    y = (N + height_m) * np.cos(lat) * np.sin(lon)
    z = (N * (1.0 - e2) + height_m) * np.sin(lat)
    return np.array([x, y, z])


# code -> (nice name, ITRF xyz meters)
OBSERVATORIES = {
    "GB": ("GBT", np.array([882589.65, -4924872.32, 3943729.35])),
    "AO": ("Arecibo", np.array([2390490.0, -5564764.0, 1994727.0])),
    "VL": ("VLA", np.array([-1601192.0, -5041981.4, 3554871.4])),
    "PK": ("Parkes", np.array([-4554231.5, 2816759.1, -3454036.3])),
    "JB": ("Jodrell Bank", np.array([3822626.04, -154105.65, 5086486.04])),
    "G1": ("GB43m", geodetic_to_itrf(38.4248, -79.8359, 807.0)),
    "NC": ("Nancay", np.array([4324165.81, 165927.11, 4670132.83])),
    "EF": ("Effelsberg", np.array([4033949.5, 486989.4, 4900430.8])),
    "SR": ("Sardinia Radio Telescope",
           np.array([4865182.766, 791922.689, 4035137.174])),
    "WT": ("WSRT", np.array([3828445.659, 445223.600, 5064921.568])),
    "GM": ("GMRT", np.array([1656342.30, 5797947.77, 2073243.16])),
    "LF": ("LOFAR", np.array([3826577.462, 461022.624, 5064892.526])),
    "LW": ("LWA1", geodetic_to_itrf(34.0689, -107.6284, 2133.6)),
    "MW": ("MWA128T", geodetic_to_itrf(-26.70331, 116.67081, 377.8)),
    "MK": ("MeerKAT", np.array([5109360.133, 2006852.586, -3238948.127])),
    "K7": ("KAT-7", geodetic_to_itrf(-30.7214, 21.4108, 1038.0)),
    "CH": ("CHIME", geodetic_to_itrf(49.3208, -119.6236, 545.0)),
    "FA": ("FAST", geodetic_to_itrf(25.6529, 106.8566, 1110.0)),
    "EC": ("Geocenter", np.array([0.0, 0.0, 0.0])),
}

# Telescope-name -> code map, parity with misc_utils.c:185-252.
_NAME_TO_CODE = {
    "gbt": "GB", "arecibo": "AO", "vla": "VL", "parkes": "PK",
    "jodrell": "JB", "gb43m": "G1", "gb 140ft": "G1", "nrao20": "G1",
    "nancay": "NC", "effelsberg": "EF", "srt": "SR", "wsrt": "WT",
    "gmrt": "GM", "lofar": "LF", "lwa": "LW", "mwa": "MW",
    "meerkat": "MK", "k7": "K7", "kat-7": "K7", "chime": "CH",
    "fast": "FA", "jodrell bank": "JB", "sardinia radio telescope": "SR",
    "lwa1": "LW", "mwa128t": "MW", "geocenter": "EC",
}


# TEMPO one-character TOA site codes (tempo obsys.dat column; the
# reference's get_TOAs.py carries the same name->digit map)
_TEMPO1_SITE = {
    "GB": "1", "AO": "3", "VL": "6", "PK": "7", "JB": "8",
    "G1": "a", "NC": "f", "EF": "g", "WT": "i", "FA": "k",
    "MK": "m", "GM": "r", "LF": "t", "CH": "y", "EC": "@",
}


def tempo1_site_code(name) -> str:
    """Telescope name -> 1-char TEMPO TOA site code ('@' = barycenter
    for unknown/geocenter, matching the reference's fallback)."""
    code = _NAME_TO_CODE.get(str(name).strip().lower())
    return _TEMPO1_SITE.get(code, "@") if code else "@"


def telescope_to_tempocode(name):
    """Telescope name -> (2-letter code, nice name); unknown -> EC
    (same fallback as misc_utils.c:246-250)."""
    code = _NAME_TO_CODE.get(str(name).strip().lower())
    if code is None:
        return "EC", "Unknown"
    return code, OBSERVATORIES[code][0]


def _precession_matrix(mjd_tt):
    """IAU1976 precession: rotates J2000 vectors to mean-of-date."""
    T = (np.asarray(mjd_tt, np.float64) - ptime.MJD_J2000) / 36525.0
    as2rad = np.pi / (180.0 * 3600.0)
    zeta = (2306.2181 * T + 0.30188 * T**2 + 0.017998 * T**3) * as2rad
    z = (2306.2181 * T + 1.09468 * T**2 + 0.018203 * T**3) * as2rad
    theta = (2004.3109 * T - 0.42665 * T**2 - 0.041833 * T**3) * as2rad
    cz, sz = np.cos(-z), np.sin(-z)
    ct, st = np.cos(theta), np.sin(theta)
    cze, sze = np.cos(-zeta), np.sin(-zeta)
    # P = Rz(-z) Ry(theta) Rz(-zeta)
    Rz1 = np.array([[cze, sze, 0], [-sze, cze, 0], [0, 0, 1]])
    Ry = np.array([[ct, 0, -st], [0, 1, 0], [st, 0, ct]])
    Rz2 = np.array([[cz, sz, 0], [-sz, cz, 0], [0, 0, 1]])
    return Rz2 @ Ry @ Rz1


def _nutation_matrix(mjd_tt):
    """Truncated IAU1980 nutation: mean-of-date -> true-of-date."""
    dpsi, deps = ptime.nutation_angles(mjd_tt)
    eps = ptime.mean_obliquity(mjd_tt)
    ce, se = np.cos(eps), np.sin(eps)
    cet, set_ = np.cos(eps + deps), np.sin(eps + deps)
    cp, sp = np.cos(dpsi), np.sin(dpsi)
    Rx1 = np.array([[1, 0, 0], [0, ce, se], [0, -se, ce]])
    Rz = np.array([[cp, sp, 0], [-sp, cp, 0], [0, 0, 1]])
    Rx2 = np.array([[1, 0, 0], [0, cet, -set_], [0, set_, cet]])
    return Rx2 @ Rz @ Rx1


def obs_posvel_gcrs(mjd_utc, code):
    """Observatory position (m) and velocity (m/s) in the J2000
    equatorial frame for an array of UTC MJDs.

    Chain: ITRF --Rz(GAST)--> true-of-date --N^T P^T--> J2000.
    Polar motion (< 0.3" -> < 10 m) is neglected.
    """
    mjd = np.atleast_1d(np.asarray(mjd_utc, np.float64))
    xyz = OBSERVATORIES[code][1]
    tt = ptime.utc_to_tt(mjd)
    theta = ptime.gast(mjd, tt)

    ct, st = np.cos(theta), np.sin(theta)
    # r_TOD = Rz(+GAST) r_ITRF  (site celestial longitude = lon + GAST)
    r_tod = np.stack([ct * xyz[0] - st * xyz[1],
                      st * xyz[0] + ct * xyz[1],
                      np.full_like(ct, xyz[2])], axis=-1)
    # v_TOD = omega x r
    v_tod = np.stack([-EARTH_OMEGA * r_tod[..., 1],
                      EARTH_OMEGA * r_tod[..., 0],
                      np.zeros_like(ct)], axis=-1)

    # Precession/nutation vary slowly; evaluate at the midpoint of the
    # request and apply one rotation (error < 0.05" over a day).
    mid_tt = float(np.mean(tt))
    M = (_nutation_matrix(mid_tt) @ _precession_matrix(mid_tt)).T
    return r_tod @ M.T, v_tod @ M.T
