"""JPL SPK (.bsp) planetary-ephemeris kernel reader, pure NumPy.

The reference reaches DE405 through the external TEMPO process
(src/barycenter.c:134 "EPHEM DE405" + system() at :156); the rebuild's
analytic ephemeris (astro/ephem.py) is search-grade (~16,000 km worst,
see tests/test_bary_golden.py).  This module closes the timing-grade
gap the same way TEMPO does — with a real JPL ephemeris file the user
supplies (de405.bsp / de421.bsp / de440s.bsp...), read natively:

    ephem = SPKEphemeris("de405.bsp")
    pos, vel = ephem.earth_posvel(jd_tdb)      # AU, AU/day, ICRS

Format: NAIF DAF (Double-precision Array File) containers holding SPK
segments; planetary ephemerides use data types 2 (Chebyshev position,
velocity by differentiation) and 3 (Chebyshev position+velocity).
Layout follows the public NAIF SPK/DAF "Required Reading" documents.
No SPICE code involved; ~200 lines of struct parsing + a Chebyshev
evaluator.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, Tuple

import numpy as np

AU_KM = 1.4959787069098932e8              # IAU 2012 definition, km
DAY_S = 86400.0
J2000_JD = 2451545.0

# NAIF integer codes
SSB, SUN, EMB, EARTH, MOON = 0, 10, 3, 399, 301


@dataclass
class _Segment:
    target: int
    center: int
    frame: int
    data_type: int
    start_et: float
    end_et: float
    init: float
    intlen: float
    rsize: int
    n_records: int
    records: np.ndarray        # [n_records, rsize] float64


class SPK:
    """Parsed SPK kernel: segments indexed by (center, target)."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            data = f.read()
        self._raw = data
        locidw = data[:8].decode("ascii", "replace")
        if not locidw.startswith("DAF/SPK"):
            raise ValueError(f"not an SPK kernel: LOCIDW={locidw!r}")
        locfmt = data[88:96].decode("ascii", "replace")
        if locfmt.startswith("LTL"):
            self._end = "<"
        elif locfmt.startswith("BIG"):
            self._end = ">"
        else:
            raise ValueError(f"unsupported DAF binary format {locfmt!r}")
        nd, ni = struct.unpack(self._end + "ii", data[8:16])
        if (nd, ni) != (2, 6):
            raise ValueError(f"not an SPK summary format: ND={nd} NI={ni}")
        fward, = struct.unpack(self._end + "i", data[76:80])
        # all segments per (center, target) pair — merged kernels (e.g.
        # de430+de431 splices) carry several per pair over different
        # time spans; evaluation selects by epoch
        self.segments: Dict[Tuple[int, int], list] = {}
        self._read_summaries(fward)

    # -- DAF plumbing --------------------------------------------------

    def _record(self, recno: int) -> bytes:
        """1-indexed 1024-byte physical record."""
        off = (recno - 1) * 1024
        return self._raw[off:off + 1024]

    def _doubles(self, addr0: int, n: int) -> np.ndarray:
        """Read n float64 starting at 1-indexed DAF address (in doubles)."""
        off = (addr0 - 1) * 8
        return np.frombuffer(self._raw, dtype=self._end + "f8",
                             count=n, offset=off)

    def _read_summaries(self, recno: int):
        while recno:
            rec = self._record(recno)
            nxt, _prev, nsum = struct.unpack(self._end + "ddd", rec[:24])
            for i in range(int(nsum)):
                s = rec[24 + i * 40: 24 + (i + 1) * 40]   # SS=5 doubles
                start_et, end_et = struct.unpack(self._end + "dd", s[:16])
                tgt, ctr, frame, dtype, a0, a1 = struct.unpack(
                    self._end + "6i", s[16:40])
                if dtype not in (2, 3):
                    continue            # only planetary Chebyshev types
                self._add_segment(start_et, end_et, tgt, ctr, frame,
                                  dtype, a0, a1)
            recno = int(nxt)

    def _add_segment(self, start_et, end_et, tgt, ctr, frame, dtype,
                     a0, a1):
        init, intlen, rsize, n = self._doubles(a1 - 3, 4)
        rsize, n = int(rsize), int(n)
        recs = self._doubles(a0, rsize * n).reshape(n, rsize)
        self.segments.setdefault((ctr, tgt), []).append(_Segment(
            target=tgt, center=ctr, frame=frame, data_type=dtype,
            start_et=start_et, end_et=end_et, init=init, intlen=intlen,
            rsize=rsize, n_records=n, records=recs))

    # -- evaluation ----------------------------------------------------

    def posvel(self, center: int, target: int, et) -> Tuple[np.ndarray,
                                                            np.ndarray]:
        """(position km, velocity km/s) of target w.r.t. center at
        ephemeris time(s) et (TDB seconds past J2000).  Chains through
        the barycenters when no direct segment exists (e.g. SSB->Earth
        = SSB->EMB + EMB->Earth)."""
        et = np.atleast_1d(np.asarray(et, np.float64))
        key = (center, target)
        if key in self.segments:
            return self._eval_list(self.segments[key], et)
        if (target, center) in self.segments:
            p, v = self._eval_list(self.segments[(target, center)], et)
            return -p, -v
        # one-level chaining via any common intermediate body
        for (c1, t1), _seg in self.segments.items():
            if c1 == center and (t1, target) in self.segments:
                p1, v1 = self._eval_list(self.segments[(c1, t1)], et)
                p2, v2 = self._eval_list(self.segments[(t1, target)], et)
                return p1 + p2, v1 + v2
        raise KeyError(f"no segment path {center}->{target}; have "
                       f"{sorted(self.segments)}")

    def _eval_list(self, segs: list, et: np.ndarray):
        """Evaluate choosing the covering segment per epoch; epochs no
        segment covers RAISE — a clipped evaluation would silently
        extrapolate the edge Chebyshev polynomial, corrupting exactly
        the timing-grade corrections this reader exists to provide."""
        if len(segs) == 1:
            return self._eval(segs[0], et)
        pos = np.empty(et.shape + (3,))
        vel = np.empty(et.shape + (3,))
        done = np.zeros(et.shape, dtype=bool)
        for seg in segs:
            # same 1 s edge slack as _eval so a boundary epoch behaves
            # identically whether the kernel is spliced or monolithic
            m = (~done) & (et >= seg.start_et - 1.0) \
                & (et <= seg.end_et + 1.0)
            if np.any(m):
                pos[m], vel[m] = self._eval(seg, et[m])
                done |= m
        if not np.all(done):
            bad = et[~done]
            raise ValueError(
                f"epoch(s) outside kernel coverage: et={bad[:3]}... "
                f"(spans {[(s.start_et, s.end_et) for s in segs]})")
        return pos, vel

    def _eval(self, seg: _Segment, et: np.ndarray):
        # tolerance: one second of slack at the span edges for TT/TDB
        # round-off; beyond that, clipping would silently extrapolate
        if np.any((et < seg.start_et - 1.0) | (et > seg.end_et + 1.0)):
            bad = et[(et < seg.start_et - 1.0) | (et > seg.end_et + 1.0)]
            raise ValueError(
                f"epoch(s) outside SPK segment coverage "
                f"[{seg.start_et}, {seg.end_et}] s past J2000 TDB: "
                f"et={bad[:3]}{'...' if bad.size > 3 else ''} — check "
                f"the kernel's time span and that epochs are TDB")
        i = np.clip(((et - seg.init) // seg.intlen).astype(np.int64),
                    0, seg.n_records - 1)
        recs = seg.records[i]                       # [n, rsize]
        mid, radius = recs[:, 0], recs[:, 1]
        tau = (et - mid) / radius                   # in [-1, 1]
        if seg.data_type == 2:
            ncoef = (seg.rsize - 2) // 3
            coef = recs[:, 2:].reshape(-1, 3, ncoef)
            pos = _cheby(coef, tau)
            vel = _cheby_deriv(coef, tau) / radius[:, None]
        else:                                       # type 3: pos+vel
            ncoef = (seg.rsize - 2) // 6
            coef = recs[:, 2:].reshape(-1, 6, ncoef)
            pos = _cheby(coef[:, :3], tau)
            vel = _cheby(coef[:, 3:], tau)
        return pos, vel


def _cheby_terms(tau: np.ndarray, n: int) -> np.ndarray:
    """T_k(tau) for k < n: [len(tau), n] via the recurrence."""
    T = np.empty(tau.shape + (n,))
    T[..., 0] = 1.0
    if n > 1:
        T[..., 1] = tau
    for k in range(2, n):
        T[..., k] = 2.0 * tau * T[..., k - 1] - T[..., k - 2]
    return T


def _cheby(coef: np.ndarray, tau: np.ndarray) -> np.ndarray:
    """coef: [n, 3, ncoef]; tau: [n] -> [n, 3]."""
    T = _cheby_terms(tau, coef.shape[-1])
    return np.einsum("nck,nk->nc", coef, T)


def _cheby_deriv(coef: np.ndarray, tau: np.ndarray) -> np.ndarray:
    """d/dtau of the Chebyshev sum, via U-polynomials:
    T_k'(tau) = k * U_{k-1}(tau)."""
    n = coef.shape[-1]
    U = np.empty(tau.shape + (n,))
    U[..., 0] = 1.0
    if n > 1:
        U[..., 1] = 2.0 * tau
    for k in range(2, n):
        U[..., k] = 2.0 * tau * U[..., k - 1] - U[..., k - 2]
    k = np.arange(n, dtype=np.float64)
    dT = np.zeros(tau.shape + (n,))
    dT[..., 1:] = U[..., :-1] * k[1:]
    return np.einsum("nck,nk->nc", coef, dT)


class SPKEphemeris:
    """astro/ephem.py-compatible ephemeris backed by an SPK kernel.

    Matches AnalyticEphemeris's interface: earth_posvel(jd_tdb) ->
    (AU, AU/day) and sun_pos(jd_tdb) -> AU, all ICRS/J2000 equatorial
    (planetary bsp kernels are ICRF frame 1)."""

    def __init__(self, path: str):
        self.spk = SPK(path)
        self.name = path

    @staticmethod
    def _et(jd_tdb):
        return (np.asarray(jd_tdb, np.float64) - J2000_JD) * DAY_S

    def earth_posvel(self, jd_tdb):
        et = self._et(jd_tdb)
        p, v = self.spk.posvel(SSB, EARTH, et)
        return p / AU_KM, v * (DAY_S / AU_KM)

    def sun_pos(self, jd_tdb):
        et = self._et(jd_tdb)
        p, _ = self.spk.posvel(SSB, SUN, et)
        return p / AU_KM
