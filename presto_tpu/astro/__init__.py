"""Astronomy services layer (reference L4, SURVEY.md §1 L4).

The reference outsources barycentering and polyco generation to the
external TEMPO Fortran program via subprocess (src/barycenter.c:156,
src/polycos.c:44) and carries 197 SLALIB Fortran files for positional
astronomy.  This package replaces all of that with a self-contained,
vectorized numpy implementation:

  time.py        — UTC/TAI/TT/TDB scales, GMST/GAST
  ephem.py       — analytic solar-system ephemeris: Earth position and
                   velocity w.r.t. the solar-system barycenter
  observatory.py — observatory ITRF coordinates and GCRS posvel
  bary.py        — barycenter(): topocentric UTC MJDs -> barycentric
                   TDB MJDs + v/c  (API parity with barycenter.c:87)

Accuracy envelope (documented, by design): the analytic ephemeris is
built from Keplerian mean elements plus a truncated lunar series, so
absolute Roemer delays are good to ~50 ms while *differential* delays
across an observation (what search-mode dedispersion, folding, and
acceleration searches consume) are good to ~microseconds/hour.  For
timing-grade work a tabulated JPL ephemeris can be dropped in through
the same interface (ephem.TabulatedEphemeris).
"""

from presto_tpu.astro.bary import barycenter  # noqa: F401
from presto_tpu.astro.time import utc_to_tdb, gmst  # noqa: F401
from presto_tpu.astro.ephem import earth_posvel_ssb  # noqa: F401
