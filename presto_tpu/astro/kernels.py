"""Zero-setup ephemeris kernel provisioning (VERDICT r4 missing #2).

The reference's default barycentering is TEMPO + an installed DE405
file — µs-grade with no user action (src/barycenter.c:87-156).  This
framework's sub-µs seam is a real JPL .bsp (astro/spk.py), which is a
download the reference never needs.  This module closes the setup gap
with a provisioning ladder:

  1. a REAL JPL kernel found in the kernel cache (or placed there by
     the gated auto-fetch below): sub-µs absolute, exactly the
     reference's grade;
  2. the BUILTIN kernel: the shipped EPV2000 series (4.6 km RMS vs
     DE405, sub-50-µs absolute Roemer — astro/ephem.py) fitted to a
     compact type-2 Chebyshev .bsp covering 1980-2040, generated
     once at first use into the cache (~5 MB, a few seconds).  Every
     kernel-route feature (prepfold -ephem, polycos, bary) then works
     with ZERO setup; fit error is sub-millimeter, so the kernel IS
     the builtin ephemeris through the real SPK read path, and
     pipelines that barycenter and fold through the same kernel are
     internally sub-µs (tests/test_timing_e2e.py).

Auto-fetch policy: downloads run ONLY when PRESTO_TPU_ALLOW_DOWNLOAD
=1 (pulsar clusters are commonly air-gapped; silent network I/O in a
timing path is hostile).  Fetched files are pinned trust-on-first-use
(SHA256 recorded beside the file and verified on every reuse) — this
environment has no network, so a vendored hash could not be verified
against NAIF and a wrong pin would brick the path.
"""

from __future__ import annotations

import hashlib
import os
import warnings

import numpy as np

ENV_DIR = "PRESTO_TPU_EPHEM_DIR"
ENV_ALLOW = "PRESTO_TPU_ALLOW_DOWNLOAD"
DE440S_URL = ("https://naif.jpl.nasa.gov/pub/naif/generic_kernels/"
              "spk/planets/de440s.bsp")

# builtin kernel coverage and fit geometry.  Earth granules must
# resolve the 27.3-day EMB wobble the EPV Earth series carries: 2-day
# windows at 16 coefficients fit it to sub-millimeter.  The Sun's
# SSB orbit is smooth (Jupiter-period): 16-day windows suffice.
BUILTIN_MJD_LO = 44239.0        # 1980 Jan 1
BUILTIN_MJD_HI = 66155.0        # 2040 Feb 28
_EARTH_INTLEN_D = 2.0
_EARTH_NCOEF = 16
_SUN_INTLEN_D = 16.0
_SUN_NCOEF = 14
_VERSION = 1


def cache_dir() -> str:
    d = os.environ.get(ENV_DIR) or os.path.join(
        os.path.expanduser("~"), ".cache", "presto_tpu")
    os.makedirs(d, exist_ok=True)
    return d


def builtin_kernel(mjd_lo: float = None,
                   mjd_hi: float = None) -> str:
    """Path of the builtin EPV2000-fitted .bsp, generating it into
    the cache on first use.  Deterministic (pure function of the
    shipped series + fit geometry), so the cache never goes stale
    except across _VERSION bumps, which change the filename.

    The default range reads BUILTIN_MJD_LO/HI at CALL time (def-time
    defaults would freeze them, making the range un-narrowable for
    resolve_kernel callers and un-patchable in tests)."""
    if mjd_lo is None:
        mjd_lo = BUILTIN_MJD_LO
    if mjd_hi is None:
        mjd_hi = BUILTIN_MJD_HI
    path = os.path.join(cache_dir(), "epv_builtin_v%d_%d_%d.bsp"
                        % (_VERSION, int(mjd_lo), int(mjd_hi)))
    if os.path.exists(path):
        return path
    from presto_tpu.astro.ephem import get_ephemeris
    from presto_tpu.astro.spk import (AU_KM, DAY_S, EARTH, J2000_JD,
                                      SSB, SUN)
    from presto_tpu.astro.spkwrite import (type2_records_batched,
                                           write_spk)
    eph = get_ephemeris("EPV2000")
    et0 = (mjd_lo + 2400000.5 - J2000_JD) * DAY_S

    def earth_km(et):
        jd = J2000_JD + np.asarray(et) / DAY_S
        p, _v = eph.earth_posvel(jd)
        return p * AU_KM

    def sun_km(et):
        jd = J2000_JD + np.asarray(et) / DAY_S
        return eph.sun_pos(jd) * AU_KM

    ndays = mjd_hi - mjd_lo
    n_e = int(np.ceil(ndays / _EARTH_INTLEN_D))
    n_s = int(np.ceil(ndays / _SUN_INTLEN_D))
    tmp = path + ".tmp.%d" % os.getpid()
    write_spk(tmp, [
        (EARTH, SSB, 2, et0, _EARTH_INTLEN_D * DAY_S,
         type2_records_batched(earth_km, et0, _EARTH_INTLEN_D * DAY_S,
                               n_e, _EARTH_NCOEF)),
        (SUN, SSB, 2, et0, _SUN_INTLEN_D * DAY_S,
         type2_records_batched(sun_km, et0, _SUN_INTLEN_D * DAY_S,
                               n_s, _SUN_NCOEF)),
    ])
    os.replace(tmp, path)       # atomic: concurrent first-users race
    return path                 # benignly


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for blk in iter(lambda: f.read(1 << 20), b""):
            h.update(blk)
    return h.hexdigest()


def fetch_kernel(name: str = "de440s.bsp",
                 url: str = DE440S_URL) -> str:
    """Download a real JPL kernel into the cache (gated, pinned).

    Refuses unless PRESTO_TPU_ALLOW_DOWNLOAD=1.  On first fetch the
    SHA256 is recorded beside the file; later calls (and
    find_de_kernel) verify the file against its pin so silent
    corruption or substitution fails loudly."""
    path = os.path.join(cache_dir(), name)
    pin = path + ".sha256"
    if os.path.exists(path):
        if os.path.exists(pin):
            want = open(pin).read().strip()
            got = _sha256(path)
            if got != want:
                raise RuntimeError(
                    "kernel %s fails its SHA256 pin (%s != %s): "
                    "delete both to re-fetch" % (path, got, want))
        return path
    if os.environ.get(ENV_ALLOW) != "1":
        raise PermissionError(
            "downloading %s requires %s=1 (air-gap default); or place "
            "the kernel at %s yourself" % (url, ENV_ALLOW, path))
    import urllib.request
    tmp = path + ".tmp.%d" % os.getpid()
    with urllib.request.urlopen(url) as r, open(tmp, "wb") as f:
        while True:
            blk = r.read(1 << 20)
            if not blk:
                break
            f.write(blk)
    os.replace(tmp, path)
    with open(pin, "w") as f:
        f.write(_sha256(path) + "\n")
    return path


def find_de_kernel():
    """A real JPL kernel already in the cache (de*.bsp, pin-verified
    when pinned), or None."""
    d = cache_dir()
    for fn in sorted(os.listdir(d)):
        if fn.lower().startswith("de") and fn.lower().endswith(".bsp"):
            path = os.path.join(d, fn)
            pin = path + ".sha256"
            if os.path.exists(pin):
                if _sha256(path) != open(pin).read().strip():
                    raise RuntimeError(
                        "kernel %s fails its SHA256 pin: delete both "
                        "to re-fetch" % path)
            return path
    return None


_warned = False


def resolve_kernel():
    """(path, grade) of the best available kernel: a real DE file
    ('de') if present or fetchable under the download gate, else the
    builtin EPV2000 kernel ('epv', sub-50-µs absolute — warned
    once)."""
    global _warned
    de = find_de_kernel()
    if de is None and os.environ.get(ENV_ALLOW) == "1":
        try:
            de = fetch_kernel()
        except Exception as e:              # offline despite the gate
            warnings.warn("kernel auto-fetch failed (%s); using the "
                          "builtin EPV2000 kernel" % e)
    if de is not None:
        return de, "de"
    if not _warned:
        _warned = True
        warnings.warn(
            "no JPL DE kernel in %s: using the builtin EPV2000 kernel "
            "(4.6 km RMS vs DE405, sub-50-us absolute Roemer). For "
            "sub-us absolute timing, place a real kernel there or set "
            "%s=1." % (cache_dir(), ENV_ALLOW))
    return builtin_kernel(), "epv"
