"""Minimal NAIF DAF/SPK (.bsp) writer — type-2 Chebyshev segments.

The reference reaches JPL ephemerides through TEMPO's installed DE
files (src/barycenter.c:87-156); this framework reads real JPL .bsp
kernels natively (astro/spk.py).  This module is the WRITE side: it
fits Chebyshev position records to any of the framework's ephemeris
models and emits a spec-conformant single-summary-record DAF/SPK
file.  Uses:

  * astro/kernels.py generates the zero-setup builtin kernel (the
    EPV2000 series packaged as a .bsp so every kernel-route feature —
    prepfold -ephem, bary tools, polycos — runs with no user file);
  * tests synthesize small DE-grade kernels to validate the reader's
    DAF walk, segment chaining and Chebyshev evaluation
    (tests/spk_synth.py re-exports these helpers).

Record layout per SPK type 2: [mid, radius, X coefs, Y coefs, Z
coefs], evaluated at tau = (et - mid) / radius.
"""

from __future__ import annotations

import struct
from typing import Sequence, Tuple

import numpy as np

NCOEF = 12      # historical default for the test-sized kernels


def cheby_fit(fn, t0: float, t1: float, ncoef: int) -> np.ndarray:
    """Chebyshev coefficients of fn over [t0, t1] (3 components) —
    one window.  Returns [3, ncoef]."""
    k = np.arange(ncoef)
    x = np.cos(np.pi * (k + 0.5) / ncoef)          # Chebyshev nodes
    t = 0.5 * (t0 + t1) + 0.5 * (t1 - t0) * x
    y = fn(t)                                      # [ncoef, 3]
    T = np.cos(np.outer(np.arccos(x), k))          # [ncoef, ncoef]
    c = 2.0 / ncoef * T.T @ y                      # [ncoef, 3]
    c[0] *= 0.5
    return c.T                                     # [3, ncoef]


def type2_records(fn_km, et0: float, intlen: float, nrec: int,
                  ncoef: int = NCOEF) -> np.ndarray:
    """Type-2 (Chebyshev position) records fitting fn_km(et) -> km,
    one window at a time (small kernels; see type2_records_batched
    for the builtin-kernel scale)."""
    out = []
    for i in range(nrec):
        t0 = et0 + i * intlen
        mid, radius = t0 + 0.5 * intlen, 0.5 * intlen
        c = cheby_fit(lambda tau: fn_km(mid + tau * radius),
                      -1.0, 1.0, ncoef)
        out.append(np.concatenate([[mid, radius], c.ravel()]))
    return np.asarray(out)


def type2_records_batched(fn_km, et0: float, intlen: float, nrec: int,
                          ncoef: int,
                          chunk: int = 512) -> np.ndarray:
    """type2_records with the ephemeris evaluated on the whole
    (record, node) grid in vectorized chunks — the builtin kernel
    fits ~10^4 windows over a ~2000-term Poisson series, where a
    per-window Python loop costs minutes and chunked evaluation
    seconds (chunk bounds the [nterms, chunk*ncoef] broadcast)."""
    k = np.arange(ncoef)
    x = np.cos(np.pi * (k + 0.5) / ncoef)
    T = np.cos(np.outer(np.arccos(x), k))          # [node, term]
    mids = et0 + (np.arange(nrec) + 0.5) * intlen
    radius = 0.5 * intlen
    recs = np.empty((nrec, 2 + 3 * ncoef))
    recs[:, 0] = mids
    recs[:, 1] = radius
    for r0 in range(0, nrec, chunk):
        r1 = min(r0 + chunk, nrec)
        ts = mids[r0:r1, None] + radius * x[None, :]
        y = np.asarray(fn_km(ts.ravel())).reshape(r1 - r0, ncoef, 3)
        c = 2.0 / ncoef * np.einsum("kn,rkc->rnc", T, y)
        c[:, 0, :] *= 0.5
        # record layout: X block, then Y, then Z
        recs[r0:r1, 2:] = c.transpose(0, 2, 1).reshape(r1 - r0, -1)
    return recs


def write_spk(path: str,
              segments: Sequence[Tuple[int, int, int, float, float,
                                       np.ndarray]]) -> None:
    """Single-summary-record DAF/SPK writer.

    segments: list of (target, center, data_type, init, intlen,
    records[n, rsize]).  Enough structure for the reader's address
    arithmetic, summary walk, and both Chebyshev data types; the
    builtin kernel needs exactly this much (direct SSB->Earth and
    SSB->Sun segments)."""
    nd, ni = 2, 6
    # element data begins at record 4 (1:file, 2:summary, 3:names)
    arrays = []
    addr = (4 - 1) * 128 + 1                       # 1-indexed doubles
    summaries = []
    for (tgt, ctr, dtype, init, intlen, recs) in segments:
        n, rsize = recs.shape
        flat = np.concatenate([recs.ravel(),
                               [init, intlen, float(rsize), float(n)]])
        a0, a1 = addr, addr + len(flat) - 1
        et0 = init
        et1 = init + intlen * n
        summaries.append((et0, et1, tgt, ctr, 1, dtype, a0, a1))
        arrays.append(flat)
        addr = a1 + 1

    file_rec = bytearray(1024)
    file_rec[0:8] = b"DAF/SPK "
    file_rec[8:16] = struct.pack("<ii", nd, ni)
    file_rec[16:76] = b"presto_tpu kernel".ljust(60)
    file_rec[76:88] = struct.pack("<iii", 2, 2, addr)  # FWARD BWARD FREE
    file_rec[88:96] = b"LTL-IEEE"

    sum_rec = bytearray(1024)
    sum_rec[0:24] = struct.pack("<ddd", 0.0, 0.0, float(len(summaries)))
    for i, (et0, et1, tgt, ctr, frame, dtype, a0, a1) in \
            enumerate(summaries):
        off = 24 + i * 40
        sum_rec[off:off + 40] = struct.pack("<dd6i", et0, et1, tgt, ctr,
                                            frame, dtype, a0, a1)
    name_rec = b" " * 1024

    data = np.concatenate(arrays)
    with open(path, "wb") as f:
        f.write(bytes(file_rec))
        f.write(bytes(sum_rec))
        f.write(name_rec)
        f.write(data.astype("<f8").tobytes())
        f.write(b"\0" * ((-f.tell()) % 1024))
