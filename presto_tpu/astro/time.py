"""Time scales: UTC -> TAI -> TT -> TDB, and sidereal time.

Replaces the reference's reliance on TEMPO's clock chain
(src/barycenter.c:124 "CLK UTC(NIST)") with an explicit leap-second
table and the standard analytic TDB-TT series.  All functions are
vectorized over numpy arrays of MJDs (float64).
"""

from __future__ import annotations

import numpy as np

SECPERDAY = 86400.0
MJD_J2000 = 51544.5  # 2000 Jan 1.5 TT (JD 2451545.0)

# (mjd_utc_of_change, TAI-UTC seconds from that date on).  Complete
# through 2026: no leap second has been added after 2017-01-01.
_LEAP_TABLE = np.array([
    (41317.0, 10.0),  # 1972-01-01
    (41499.0, 11.0),  # 1972-07-01
    (41683.0, 12.0),  # 1973-01-01
    (42048.0, 13.0),  # 1974-01-01
    (42413.0, 14.0),  # 1975-01-01
    (42778.0, 15.0),  # 1976-01-01
    (43144.0, 16.0),  # 1977-01-01
    (43509.0, 17.0),  # 1978-01-01
    (43874.0, 18.0),  # 1979-01-01
    (44239.0, 19.0),  # 1980-01-01
    (44786.0, 20.0),  # 1981-07-01
    (45151.0, 21.0),  # 1982-07-01
    (45516.0, 22.0),  # 1983-07-01
    (46247.0, 23.0),  # 1985-07-01
    (47161.0, 24.0),  # 1988-01-01
    (47892.0, 25.0),  # 1990-01-01
    (48257.0, 26.0),  # 1991-01-01
    (48804.0, 27.0),  # 1992-07-01
    (49169.0, 28.0),  # 1993-07-01
    (49534.0, 29.0),  # 1994-07-01
    (50083.0, 30.0),  # 1996-01-01
    (50630.0, 31.0),  # 1997-07-01
    (51179.0, 32.0),  # 1999-01-01
    (53736.0, 33.0),  # 2006-01-01
    (54832.0, 34.0),  # 2009-01-01
    (56109.0, 35.0),  # 2012-07-01
    (57204.0, 36.0),  # 2015-07-01
    (57754.0, 37.0),  # 2017-01-01
])

TT_MINUS_TAI = 32.184


def tai_minus_utc(mjd_utc):
    """TAI-UTC in seconds for the given UTC MJD(s)."""
    mjd = np.asarray(mjd_utc, dtype=np.float64)
    idx = np.searchsorted(_LEAP_TABLE[:, 0], mjd, side="right") - 1
    idx = np.clip(idx, 0, len(_LEAP_TABLE) - 1)
    return _LEAP_TABLE[idx, 1]


def utc_to_tt(mjd_utc):
    """UTC MJD -> TT MJD."""
    return np.asarray(mjd_utc, np.float64) + \
        (tai_minus_utc(mjd_utc) + TT_MINUS_TAI) / SECPERDAY


def tdb_minus_tt(mjd_tt):
    """TDB-TT in seconds (truncated Fairhead & Bretagnon series).

    Dominant annual + planetary terms; good to ~30 us, which is well
    inside this module's documented envelope (TEMPO links the full
    series; the residual here is constant-ish over an observation).
    """
    T = (np.asarray(mjd_tt, np.float64) - MJD_J2000) / 36525.0
    # Mean anomaly of the Earth and the dominant Jupiter/Saturn terms.
    g = np.deg2rad(357.53 + 35999.050 * T)
    l_lj = np.deg2rad(246.11 + 32964.467 * T)   # L_earth - L_jupiter
    return (0.001657 * np.sin(g + 0.01671 * np.sin(g))
            + 0.000022 * np.sin(l_lj))


def utc_to_tdb(mjd_utc):
    """UTC MJD -> TDB MJD."""
    tt = utc_to_tt(mjd_utc)
    return tt + tdb_minus_tt(tt) / SECPERDAY


def gmst(mjd_ut1):
    """Greenwich mean sidereal time, radians in [0, 2pi).

    IAU 1982 polynomial expressed in the compact degree form.  UT1 is
    approximated by UTC (|dUT1| < 0.9 s -> < 2 us of Roemer error).
    """
    d = np.asarray(mjd_ut1, np.float64) - MJD_J2000
    T = d / 36525.0
    deg = (280.46061837 + 360.98564736629 * d
           + 0.000387933 * T * T - T * T * T / 38710000.0)
    return np.deg2rad(np.mod(deg, 360.0))


def nutation_angles(mjd_tt):
    """Truncated IAU1980 nutation: (dpsi, deps) in radians.

    Four largest terms (>0.2"), plenty for the equation of the
    equinoxes and the ~arcsecond-level frame rotation this package
    needs.
    """
    T = (np.asarray(mjd_tt, np.float64) - MJD_J2000) / 36525.0
    Om = np.deg2rad(125.04452 - 1934.136261 * T)
    Ls = np.deg2rad(280.4665 + 36000.7698 * T)
    Lm = np.deg2rad(218.3165 + 481267.8813 * T)
    dpsi = (-17.20 * np.sin(Om) - 1.32 * np.sin(2 * Ls)
            - 0.23 * np.sin(2 * Lm) + 0.21 * np.sin(2 * Om))
    deps = (9.20 * np.cos(Om) + 0.57 * np.cos(2 * Ls)
            + 0.10 * np.cos(2 * Lm) - 0.09 * np.cos(2 * Om))
    as2rad = np.pi / (180.0 * 3600.0)
    return dpsi * as2rad, deps * as2rad


def mean_obliquity(mjd_tt):
    """Mean obliquity of the ecliptic, radians (IAU 1980)."""
    T = (np.asarray(mjd_tt, np.float64) - MJD_J2000) / 36525.0
    eps = 23.439291111 - (46.8150 * T + 0.00059 * T * T
                          - 0.001813 * T * T * T) / 3600.0
    return np.deg2rad(eps)


def gast(mjd_ut1, mjd_tt=None):
    """Greenwich apparent sidereal time, radians."""
    if mjd_tt is None:
        mjd_tt = mjd_ut1
    dpsi, _ = nutation_angles(mjd_tt)
    return np.mod(gmst(mjd_ut1) + dpsi * np.cos(mean_obliquity(mjd_tt)),
                  2 * np.pi)


def mjd_to_calendar(mjd):
    """MJD -> (year, month, day, fractional day). Fliegel-Van Flandern."""
    jd = int(np.floor(mjd)) + 2400001  # JD at following midnight rounding
    frac = float(mjd) - np.floor(mjd)
    l = jd + 68569
    n = 4 * l // 146097
    l = l - (146097 * n + 3) // 4
    i = 4000 * (l + 1) // 1461001
    l = l - 1461 * i // 4 + 31
    j = 80 * l // 2447
    day = l - 2447 * j // 80
    l = j // 11
    month = j + 2 - 12 * l
    year = 100 * (n - 49) + i + l
    return int(year), int(month), int(day), frac


def calendar_to_mjd(year, month, day, frac=0.0):
    """(y, m, d[, frac]) -> MJD. Fliegel-Van Flandern (C-style
    truncating division, not Python floor division)."""
    # (month-14)/12 truncated toward zero: -1 for Jan/Feb, 0 otherwise.
    t = -1 if month <= 2 else 0
    jdn = (1461 * (year + 4800 + t)) // 4 \
        + (367 * (month - 2 - 12 * t)) // 12 \
        - (3 * ((year + 4900 + t) // 100)) // 4 \
        + day - 32075
    return jdn - 2400001 + frac
