"""Polycos: polynomial pulsar-phase predictors (TEMPO polyco.dat).

Parity targets:
  src/polycos.c — make_polycos (:44-190, shells out to 'tempo -z'),
    getpoly (:195-280, polyco.dat parser), phcalc (:282-320, phase +
    frequency evaluation at topocentric MJD);
  lib/python/polycos.py — polyco/polycos classes (rotation/phase/freq
    evaluation and span selection).

TPU-era redesign: **no TEMPO subprocess**.  Polycos are generated
directly from a .par file using the framework's own barycentering
(astro.bary) and binary-orbit (astro.binary) machinery: for each span
the exact topocentric->emission phase is evaluated on a sample grid
and least-squares fit with the standard TEMPO polynomial
  rotation(t) = RPHASE + DT*60*F0 + sum_k coeffs[k] * DT^k,
DT in minutes from TMID.  Absolute rotation counts are carried in
numpy longdouble (80-bit) so ~1e10 rotations keep sub-1e-6 phase
precision.  Files written are standard TEMPO polyco.dat format, so
reference tools (and prepfold -polycos here) interoperate.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from presto_tpu.io.parfile import Parfile
from presto_tpu.astro.bary import barycenter

SECPERDAY = 86400.0
# observing-freq dispersion delay constant (dispersion.c:30-39)
DM_CONST = 1.0 / 0.000241


# TEMPO single-char site codes used in polyco.dat (polycos.c:91-140,
# lib/python/polycos.py telescope_to_id)
TELESCOPE_TO_SITE = {
    "GBT": "1", "Arecibo": "3", "VLA": "6", "Parkes": "7",
    "Jodrell": "8", "GB43m": "a", "GB 140FT": "a", "NRAO20": "a",
    "Nancay": "f", "Effelsberg": "g", "LOFAR": "t", "WSRT": "i",
    "GMRT": "r", "CHIME": "y", "MeerKAT": "m", "KAT-7": "k",
    "Geocenter": "0", "Barycenter": "@",
}
# single-char site code -> 2-letter TEMPO obs code for our bary layer
SITE_TO_OBSCODE = {
    "1": "GB", "3": "AO", "6": "VL", "7": "PK", "8": "JB", "a": "G1",
    "f": "NC", "g": "EF", "t": "LF", "i": "WT", "r": "GM", "y": "CH",
    "m": "MK", "k": "K7", "0": "EC", "@": "EC",
}


@dataclass
class Polyco:
    """One polyco block: phase polynomial valid for `dataspan` minutes
    around TMID (lib/python/polycos.py:58-131)."""
    psr: str
    tmid_i: int                 # integer MJD
    tmid_f: float               # fractional MJD
    dm: float
    doppler: float              # v/c (stored *1e4 in the file)
    log10rms: float
    rphase: float               # fractional reference phase at TMID
    f0: float                   # reference spin freq (Hz) at TMID
    obs: str                    # TEMPO site char
    dataspan: int               # minutes
    numcoeff: int
    obsfreq: float              # MHz (0 or 1e6+ => infinite freq)
    coeffs: np.ndarray = field(default_factory=lambda: np.zeros(12))
    binphase: Optional[float] = None
    date: str = ""
    utc: str = ""

    @property
    def tmid(self) -> float:
        return self.tmid_i + self.tmid_f

    def _dt_min(self, mjdi, mjdf):
        """minutes from TMID, split-precision (polycos.py:113)."""
        return (((np.asarray(mjdi) - self.tmid_i)
                 + (np.asarray(mjdf) - self.tmid_f)) * 1440.0)

    def rotation(self, mjdi, mjdf):
        """Absolute (fractional) rotation count at topocentric MJD
        (polycos.py:107-119; phcalc polycos.c:282-320)."""
        DT = self._dt_min(mjdi, mjdf)
        phase = np.polynomial.polynomial.polyval(DT, self.coeffs)
        return phase + self.rphase + DT * 60.0 * self.f0

    def phase(self, mjdi, mjdf):
        """Predicted pulse phase in [0,1)."""
        return self.rotation(mjdi, mjdf) % 1.0

    def freq(self, mjdi, mjdf):
        """Apparent topocentric spin frequency (Hz)
        (polycos.py:121-130)."""
        DT = self._dt_min(mjdi, mjdf)
        dcoef = np.polynomial.polynomial.polyder(self.coeffs)
        return self.f0 + np.polynomial.polynomial.polyval(DT, dcoef) / 60.0


class Polycos:
    """A set of polyco blocks for one pulsar, with span selection
    (lib/python/polycos.py:133-199)."""

    def __init__(self, blocks: Sequence[Polyco]):
        if not blocks:
            raise ValueError("no polyco blocks")
        self.blocks = list(blocks)
        self.psr = blocks[0].psr
        self.dataspan = blocks[0].dataspan
        self.tmids = np.array([b.tmid for b in blocks])
        self.validrange = 0.5 * self.dataspan / 1440.0

    def __len__(self):
        return len(self.blocks)

    def select(self, mjdi, mjdf) -> int:
        """Index of the block whose TMID is closest; warns outside the
        valid range (select_polyco polycos.py:156-164)."""
        t = mjdi + mjdf
        good = int(np.argmin(np.abs(self.tmids - t)))
        if abs(self.tmids[good] - t) > self.validrange:
            import sys
            sys.stderr.write("Cannot find a valid polyco at %f!\n" % t)
        return good

    def get_phase(self, mjdi, mjdf) -> float:
        return float(self.blocks[self.select(mjdi, mjdf)].phase(mjdi, mjdf))

    def get_rotation(self, mjdi, mjdf) -> float:
        return float(self.blocks[self.select(mjdi, mjdf)]
                     .rotation(mjdi, mjdf))

    def get_freq(self, mjdi, mjdf) -> float:
        return float(self.blocks[self.select(mjdi, mjdf)].freq(mjdi, mjdf))

    def get_phs_and_freq(self, mjdi, mjdf) -> Tuple[float, float]:
        """phcalc equivalent (polycos.c:282-320): (phase [0,1), freq)."""
        b = self.blocks[self.select(mjdi, mjdf)]
        return float(b.phase(mjdi, mjdf)), float(b.freq(mjdi, mjdf))


# ------------------------------------------------------------------ #
# polyco.dat I/O

def _parse_block(lines: List[str], k: int) -> Tuple[Optional[Polyco], int]:
    while k < len(lines) and not lines[k].strip():
        k += 1
    if k >= len(lines):
        return None, k
    sl = lines[k].split()
    psr, date, utc = sl[0], sl[1], sl[2]
    tmid_i = int(sl[3].split(".")[0])
    tmid_f = float("0." + sl[3].split(".")[1]) if "." in sl[3] else 0.0
    dm = float(sl[4])
    if len(sl) >= 7:
        doppler = float(sl[5]) * 1e-4
        log10rms = float(sl[6])
    else:
        # doppler/rms columns fused like '-0.123-7' (polycos.py:75-79)
        tail = sl[-1]
        rms = "-" + tail.split("-")[-1]
        doppler = float(tail[:tail.find(rms)]) * 1e-4
        log10rms = float(rms)
    sl = lines[k + 1].split()
    rphase = float(sl[0])
    f0 = float(sl[1])
    obs = sl[2]
    dataspan = int(sl[3])
    numcoeff = int(sl[4])
    obsfreq = float(sl[5])
    binphase = float(sl[6]) if len(sl) >= 7 else None
    coeffs = np.zeros(numcoeff)
    k += 2
    n = 0
    while n < numcoeff:
        for tok in lines[k].split():
            coeffs[n] = float(tok.replace("D", "E").replace("d", "e"))
            n += 1
            if n == numcoeff:
                break
        k += 1
    return Polyco(psr=psr, tmid_i=tmid_i, tmid_f=tmid_f, dm=dm,
                  doppler=doppler, log10rms=log10rms, rphase=rphase,
                  f0=f0, obs=obs, dataspan=dataspan, numcoeff=numcoeff,
                  obsfreq=obsfreq, coeffs=coeffs, binphase=binphase,
                  date=date, utc=utc), k


def read_polycos(path: str, psrname: Optional[str] = None) -> Polycos:
    """Parse a TEMPO polyco.dat (getpoly polycos.c:195-280)."""
    with open(path) as f:
        lines = f.readlines()
    blocks, k = [], 0
    while True:
        b, k = _parse_block(lines, k)
        if b is None:
            break
        if psrname is None or b.psr.lstrip("JB").startswith(
                psrname.lstrip("JB")[:4]):
            blocks.append(b)
    return Polycos(blocks)


def write_polycos(pcs: Polycos, path: str) -> None:
    """Write standard TEMPO polyco.dat format."""
    with open(path, "w") as f:
        for b in pcs.blocks:
            ti, tf = b.tmid_i, round(b.tmid_f * 1e11)
            if tf >= 10 ** 11:        # .99999... rounded up a day
                ti, tf = ti + 1, 0
            tmid = "%05d.%011d" % (ti, tf)
            f.write("%-10s %9s%11s%20s%21.6f%7.3f%7.3f\n"
                    % (b.psr[:10], b.date or "DD-MMM-YY",
                       b.utc or "000000.00", tmid, b.dm,
                       b.doppler * 1e4, b.log10rms))
            bin_str = ("%7.4f" % b.binphase) if b.binphase is not None \
                else ""
            f.write("%20.6f%18.12f%5s%5d%5d%10.3f%s\n"
                    % (b.rphase, b.f0, b.obs, b.dataspan, b.numcoeff,
                       b.obsfreq, bin_str))
            for i in range(0, b.numcoeff, 3):
                row = b.coeffs[i:i + 3]
                f.write("".join("%25.17E" % c for c in row)
                        .replace("E", "D") + "\n")


# ------------------------------------------------------------------ #
# TEMPO-free polyco generation

def make_polycos(par: Union[str, Parfile], mjd_start: float,
                 duration_min: float, telescope: str = "GBT",
                 obsfreq: float = 0.0, span_min: int = 60,
                 numcoeff: int = 12, ephem: str = "DEANALYTIC",
                 outfile: Optional[str] = None,
                 barytime: bool = False) -> Polycos:
    """Generate polycos covering [mjd_start, mjd_start+duration] by
    fitting the framework's own topo->bary->emission phase model.

    Replaces make_polycos' 'tempo -z' subprocess (polycos.c:44-190):
    same polyco.dat contract, but the phase model is astro.bary
    barycentering + astro.binary orbit demodulation + the .par spin
    polynomial.  obsfreq (MHz) folds the dispersion delay at the band
    center into the prediction (0 => infinite frequency).

    barytime=True: the input timestamps are ALREADY barycentric MJDs
    (e.g. folding a prepdata-barycentered .dat) — skip the topo->bary
    Roemer/Shapiro conversion entirely (doppler=0), keeping only the
    DM delay and binary demodulation.  Telescope 'Barycenter' ('@')
    implies this too.
    """
    if isinstance(par, str):
        par = Parfile(par)
    site = TELESCOPE_TO_SITE.get(telescope, telescope
                                 if len(telescope) == 1 else "0")
    obscode = SITE_TO_OBSCODE.get(site, "EC")
    if site == "@" or telescope == "Barycenter":
        barytime = True
    psrname = par.name.lstrip("JB") or "PSR"
    dm = getattr(par, "DM", 0.0)
    pepoch = getattr(par, "PEPOCH", mjd_start)
    f0 = getattr(par, "F0")
    f1 = getattr(par, "F1", 0.0)
    f2 = getattr(par, "F2", 0.0)
    ra = getattr(par, "RAJ", "00:00:00")
    dec = getattr(par, "DECJ", "00:00:00")
    binary = None
    if par.is_binary:
        from presto_tpu.astro.binary import BinaryPsr
        binary = BinaryPsr(par)

    def emission_mjd(topo_mjd):
        """topo UTC MJD -> emission-frame MJD (bary - DM - orbit)."""
        if barytime:
            tb = np.atleast_1d(np.asarray(topo_mjd, dtype=np.float64))
        else:
            tb, _ = barycenter(topo_mjd, ra, dec, obs=obscode,
                               ephem=ephem)
            tb = np.atleast_1d(tb)
        if obsfreq > 0.0:
            tb = tb - dm * DM_CONST / (obsfreq * obsfreq) / SECPERDAY
        if binary is not None:
            tb = binary.demodulate_TOAs(tb)
        return tb

    def spin_phase(em_mjd):
        """Absolute rotation count since PEPOCH, longdouble."""
        dt = (np.asarray(em_mjd, dtype=np.longdouble)
              - np.longdouble(pepoch)) * np.longdouble(SECPERDAY)
        return (np.longdouble(f0) * dt
                + np.longdouble(0.5 * f1) * dt * dt
                + np.longdouble(f2 / 6.0) * dt * dt * dt)

    nspans = max(1, int(math.ceil(duration_min / span_min)))
    blocks = []
    for i in range(nspans):
        tmid = mjd_start + (i + 0.5) * span_min / 1440.0
        tmid_i = int(tmid)
        tmid_f = tmid - tmid_i
        # sample grid across the span (over-sampled 4x for the fit)
        npts = max(4 * numcoeff, 32)
        dts_min = np.linspace(-span_min / 2, span_min / 2, npts)
        topo = tmid + dts_min / 1440.0
        phs = spin_phase(emission_mjd(topo))
        phs_mid = spin_phase(emission_mjd(np.array([tmid])))[0]
        # apparent freq at tmid: d(phase)/dt via a short central diff
        eps_d = 1.0 / SECPERDAY
        p_lo = spin_phase(emission_mjd(np.array([tmid - eps_d])))[0]
        p_hi = spin_phase(emission_mjd(np.array([tmid + eps_d])))[0]
        f0_app = float((p_hi - p_lo) / 2.0)
        rphase = float(np.fmod(phs_mid, np.longdouble(1.0)))
        if rphase < 0:
            rphase += 1.0
        # residual after removing the linear TEMPO term, in float64
        resid = np.asarray(
            phs - phs_mid
            - np.longdouble(f0_app) * np.longdouble(60.0)
            * np.asarray(dts_min, dtype=np.longdouble),
            dtype=np.float64)
        coeffs = np.polynomial.polynomial.polyfit(dts_min, resid,
                                                  numcoeff - 1)
        fit = np.polynomial.polynomial.polyval(dts_min, coeffs)
        rms = float(np.sqrt(np.mean((resid - fit) ** 2)))
        log10rms = math.log10(max(rms, 1e-30))
        if barytime:
            voverc = 0.0
        else:
            _, voverc = barycenter(tmid, ra, dec, obs=obscode,
                                   ephem=ephem)
        binphase = None
        if binary is not None:
            ma, _, _ = binary.calc_anoms(tmid)
            binphase = float(ma[0] / (2 * np.pi))
        blocks.append(Polyco(
            psr=psrname, tmid_i=tmid_i, tmid_f=tmid_f, dm=dm,
            doppler=float(voverc), log10rms=log10rms, rphase=rphase,
            f0=f0_app, obs=site, dataspan=span_min, numcoeff=numcoeff,
            obsfreq=obsfreq, coeffs=coeffs, binphase=binphase))
    pcs = Polycos(blocks)
    if outfile:
        write_polycos(pcs, outfile)
    return pcs


def fit_fold_params(pcs: Polycos, mjd_start: float, T_sec: float,
                    npts: int = 128) -> Tuple[float, float, float, float]:
    """Fit topocentric (f, fd, fdd) for a constant-derivative fold over
    [mjd_start, mjd_start + T] from a polyco set.

    The reference's prepfold re-evaluates polyco phase block-by-block
    (prepfold.c:1347-1369); the folder here uses one cubic phase
    polynomial, so the polycos are collapsed to the best-fit
    (f, fd, fdd) at the start epoch.  Returns (f, fd, fdd, rms) where
    rms is the residual in rotations — callers should warn when it
    exceeds ~0.1/proflen (phase model too curvy for one polynomial).
    """
    ts = np.linspace(0.0, T_sec, npts)
    mjds = mjd_start + ts / SECPERDAY
    rot = np.array([pcs.get_rotation(int(m), m - int(m)) for m in mjds])
    rot = rot - rot[0]
    # guard against inter-block fractional-rphase jumps: integrate the
    # per-sample phase increments mod the expected f*dt.  The expected
    # step uses the LOCAL instantaneous frequency at each interval
    # midpoint (not the start-epoch f): for a binary, orbital Doppler
    # can drift f by more than 0.5 rotations per sample interval over
    # the start value, which would make a fixed-f re-wrap subtract
    # spurious integers from genuine phase steps
    mids = mjds[:-1] + 0.5 * np.diff(ts) / SECPERDAY
    f_mid = np.array([pcs.get_freq(int(m), m - int(m)) for m in mids])
    expect = f_mid * np.diff(ts)
    steps = np.diff(rot)
    steps = steps - np.round((steps - expect))   # re-wrap block joins
    rot = np.concatenate([[0.0], np.cumsum(steps)])
    c = np.polynomial.polynomial.polyfit(ts, rot, 3)
    resid = rot - np.polynomial.polynomial.polyval(ts, c)
    return (float(c[1]), float(2.0 * c[2]), float(6.0 * c[3]),
            float(np.sqrt(np.mean(resid ** 2))))
