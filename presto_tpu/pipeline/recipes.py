"""Survey recipes: complete, named end-to-end search policies.

The reference ships three battle-tested survey orchestrations
(bin/PALFA_presto_search.py, GBNCC_search.py, GBT350_drift_search.py)
whose value is the POLICY they encode — interval lengths, the lo/hi
acceleration-pass pair, sifting thresholds, fold selection, the
single-pulse settings, zaplist handling.  A recipe captures that
policy as data and expands to a ready SurveyConfig, so

    presto-pipeline --recipe palfa obs.fits

reproduces the PALFA flow end to end (and the policies are testable
on synthetic data, tests/test_survey_recipe.py).

Recipe values are taken from the reference drivers:
PALFA_presto_search.py:28-52, GBNCC_search.py:16-35,
GBT350_drift_search.py:16-35.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

from presto_tpu.pipeline.sifting import SiftPolicy
from presto_tpu.pipeline.survey import SurveyConfig


@dataclass(frozen=True)
class SurveyRecipe:
    name: str
    rfi_time: float                       # rfifind interval (s)
    # ((zmax, numharm, sigma, flo), ...): first is the primary pass;
    # flo is the per-pass low-frequency search limit in Hz
    # (lo_accel_flo=2.0 / hi_accel_flo=1.0, PALFA_presto_search.py:39-43)
    accel_passes: Tuple[Tuple[int, int, float, float], ...]
    sift: SiftPolicy
    fold_sigma: float                     # to_prepfold_sigma
    max_folds: int                        # max_cands_to_fold (combined)
    sp_threshold: float
    sp_maxwidth: float
    use_default_zaplist: bool = True
    nsub: int = 32
    # per-pass fold caps aligned with accel_passes, e.g. GBNCC's
    # 20-lo + 10-hi split (GBNCC_search.py:21-22); None -> one
    # combined max_folds cap (PALFA_presto_search.py:33)
    fold_caps_per_pass: Optional[Tuple[int, ...]] = None

    def to_config(self, lodm: float, hidm: float,
                  nsub: Optional[int] = None,
                  zaplist: Optional[str] = None) -> SurveyConfig:
        """Expand to a SurveyConfig for one DM range."""
        if zaplist is None and self.use_default_zaplist:
            from presto_tpu.utils.catalog import default_birds_path
            zaplist = default_birds_path()
        (zmax0, nh0, sg0, flo0), *rest = self.accel_passes
        return SurveyConfig(
            lodm=lodm, hidm=hidm, nsub=nsub or self.nsub,
            rfi_time=self.rfi_time,
            zmax=zmax0, numharm=nh0, sigma=sg0, flo=flo0,
            accel_passes=tuple(rest) or None,
            zaplist=zaplist,
            sift_policy=self.sift,
            fold_sigma=self.fold_sigma, max_folds=self.max_folds,
            max_folds_per_pass=self.fold_caps_per_pass,
            sp_threshold=self.sp_threshold,
            sp_maxwidth=self.sp_maxwidth)


# -- the shipped recipes ------------------------------------------------

# PALFA (Arecibo L-band Feed Array; PALFA_presto_search.py:28-52):
# ~2.1 s RFI intervals, a zmax=0/numharm=16 low pass + a zmax=50/
# numharm=8 high pass, sift at to_prepfold_sigma-1, fold everything
# above 6 sigma capped at 150, single-pulse to 0.1 s widths.
PALFA = SurveyRecipe(
    name="palfa",
    rfi_time=2 ** 15 * 0.000064,          # 2.097 s
    accel_passes=((0, 16, 2.0, 2.0), (50, 8, 3.0, 1.0)),
    sift=SiftPolicy(sigma_threshold=5.0, c_pow_threshold=100.0,
                    short_period=0.0005, long_period=15.0,
                    harm_pow_cutoff=8.0, r_err=1.1),
    fold_sigma=6.0, max_folds=150,
    sp_threshold=5.0, sp_maxwidth=0.1,
    nsub=32)

# GBNCC (GBT 350 MHz Northern Celestial Cap; GBNCC_search.py:16-35):
# same lo/hi accel pair and thresholds at GBT 350 MHz sampling, with
# the per-pass fold budget (20 lo-accel + 10 hi-accel,
# GBNCC_search.py:21-22,479-486).
GBNCC = SurveyRecipe(
    name="gbncc",
    rfi_time=25600 * 0.00008192,          # 2.097 s
    accel_passes=((0, 16, 2.0, 2.0), (50, 8, 3.0, 1.0)),
    sift=SiftPolicy(sigma_threshold=5.0, c_pow_threshold=100.0,
                    short_period=0.0005, long_period=15.0,
                    harm_pow_cutoff=8.0, r_err=1.1),
    fold_sigma=6.0, max_folds=30, fold_caps_per_pass=(20, 10),
    sp_threshold=5.0, sp_maxwidth=0.1,
    nsub=32)

# GBT350 drift survey (GBT350_drift_search.py:16-35): GBNCC's policy
# (same lo/hi passes, same 20+10 per-pass fold caps,
# GBT350_drift_search.py:21-22) applied per drift-scan pointing.
# Split a raw drift scan into overlapping pointings first with
# `python -m presto_tpu.apps.drift_prep` (the GBT350_drift_prep.py
# analog) or pass --driftprep to the pipeline app.
GBT350_DRIFT = replace(GBNCC, name="gbt350drift")

RECIPES = {r.name: r for r in (PALFA, GBNCC, GBT350_DRIFT)}


def get_recipe(name: str) -> SurveyRecipe:
    try:
        return RECIPES[name.lower()]
    except KeyError:
        raise ValueError("unknown survey recipe %r (have: %s)"
                         % (name, ", ".join(sorted(RECIPES))))
