"""Generic leased-item ledger: the lease / heartbeat / epoch-fencing
core shared by the elastic DM-shard ledger and the fleet job ledger.

PR 4 built these recovery primitives for DM shards
(`pipeline/shardledger.py`); the fleet-serving layer needs the exact
same machinery for *jobs* (`serve/jobledger.py`), so the mechanics
live here once:

  * **Items** are leased rows in one JSON ledger file.  Every public
    mutator is transactional: take the lock directory, reload the
    ledger from disk, apply, write the whole file back atomically —
    concurrent hosts always act on the latest accepted state and a
    kill mid-mutation loses nothing but that mutation.
  * **Heartbeats** are small per-host atomic files (1 Hz liveness
    never contends with the ledger lock).  A host may also write a
    *tombstone* heartbeat on graceful shutdown, so the reaper treats
    it as dead immediately instead of waiting out the TTL.
  * **Epoch fencing**: the ledger carries an epoch, bumped whenever
    membership changes.  Every lease records the epoch it was granted
    under; `complete()` is accepted only while the item is still
    leased to that owner under that epoch, so a zombie host — one
    declared dead whose process lingers — can never land a late
    write: its staged output files are deleted before they can
    replace a journaled artifact.
  * **Staged commits**: workers never write final artifact names
    directly.  They stage outputs next to the targets and hand the
    staged map to `complete()`, which performs fence-check -> rename
    -> size+CRC journal *under the ledger lock*.

Subclasses declare the domain vocabulary (ledger filename, JSON items
key, event-kind names — see `ShardLedger` and `JobLedger`) and may
override `_pick_pending` to change the lease scheduling policy (the
job ledger's weighted round-robin over tenants).

State machine per item::

    pending --lease--> leased --complete--> done
       ^                 |                   |
       |   (lease expiry, owner death,      | (artifact fails
       |    explicit fail)                  |  size+CRC verify)
       +---------------- reap --------------+

(`JobLedger` adds a fence-checked terminal `failed` state for jobs
whose retry budget is exhausted — a poisoned job must terminate, not
cycle the fleet forever.)
"""

from __future__ import annotations

import contextlib
import errno
import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu.io.atomic import atomic_write_text, file_checksum

HEARTBEAT_PREFIX = ".hb-"

PENDING, LEASED, DONE, FAILED = "pending", "leased", "done", "failed"


class LedgerError(Exception):
    """Base class for ledger protocol violations."""


class StaleLeaseError(LedgerError):
    """A write attempted under a lease the cluster has fenced off —
    the zombie-host case.  The staged outputs were discarded."""

    def __init__(self, item_id: str, host: str, epoch: int,
                 current_epoch: int, why: str):
        self.item_id = item_id
        self.host = host
        self.epoch = epoch
        self.current_epoch = current_epoch
        self.why = why
        super().__init__(
            "stale write rejected: %r by %r under epoch %d "
            "(cluster epoch %d): %s"
            % (item_id, host, epoch, current_epoch, why))


@dataclass
class ItemLease:
    """A granted item lease (what the worker computes against).
    `data` is a copy of the item's extra row fields (e.g. the shard's
    DM rows, or the job's submitted spec)."""
    item_id: str
    epoch: int                     # fence token for complete()
    expires: float
    data: dict = field(default_factory=dict)


@dataclass
class ReapReport:
    """What one reap pass changed."""
    dead_hosts: List[str] = field(default_factory=list)
    redone: List[str] = field(default_factory=list)
    epoch: int = 0
    bumped: bool = False


class _LockDir:
    """Tiny cross-process mutex: os.mkdir is atomic on POSIX.  A lock
    older than `stale` seconds is presumed abandoned by a killed
    process and broken — safe here because every mutation under the
    lock ends in an atomic whole-file replace, so a breaker can never
    observe a half-written ledger."""

    def __init__(self, path: str, timeout: float = 30.0,
                 stale: float = 30.0, poll: float = 0.02,
                 error=LedgerError):
        self.path = path
        self.timeout = timeout
        self.stale = stale
        self.poll = poll
        self.error = error

    @contextlib.contextmanager
    def __call__(self):
        deadline = time.time() + self.timeout
        while True:
            try:
                os.mkdir(self.path)
                break
            except OSError as e:
                if e.errno != errno.EEXIST:
                    raise
                try:
                    age = time.time() - os.path.getmtime(self.path)
                except OSError:
                    continue               # raced with the releaser
                if age > self.stale:
                    with contextlib.suppress(OSError):
                        os.rmdir(self.path)
                    continue
                if time.time() > deadline:
                    raise self.error(
                        "could not acquire ledger lock %s within %.1fs"
                        % (self.path, self.timeout))
                time.sleep(self.poll)
        try:
            yield
        finally:
            with contextlib.suppress(OSError):
                os.rmdir(self.path)


class LeaseLedger:
    """Leased-item journal for one shared working directory.

    Class attributes subclasses set:

      LEDGER_NAME   ledger filename inside the workdir
      ITEMS_KEY     JSON key the item table lives under (kept
                    distinct per domain so the on-disk schemas of the
                    shard and job ledgers stay self-describing)
      ERROR / STALE exception classes raised by this ledger
      EV_*          event-kind names for the flight recorder (None
                    disables that event)
    """

    LEDGER_NAME = "items.json"
    ITEMS_KEY = "items"
    ERROR = LedgerError
    STALE = StaleLeaseError
    EV_LEASE: Optional[str] = None
    EV_DONE: Optional[str] = None
    EV_REDO: Optional[str] = None
    EV_STALE: Optional[str] = None
    EV_HOST_DEAD: Optional[str] = None
    EV_EPOCH_BUMP: Optional[str] = None

    def __init__(self, workdir: str, name: Optional[str] = None,
                 obs=None):
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.path = os.path.join(self.workdir,
                                 name or self.LEDGER_NAME)
        self._lock = _LockDir(self.path + ".lock", error=self.ERROR)
        self.obs = obs

    # -- raw state ----------------------------------------------------
    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                state = json.load(f)
            if not isinstance(state, dict):
                raise ValueError("ledger is not an object")
        except (OSError, ValueError):
            state = {}
        state.setdefault("version", 1)
        state.setdefault("epoch", 0)
        state.setdefault(self.ITEMS_KEY, {})
        state.setdefault("hosts", {})
        return state

    def _save(self, state: dict) -> None:
        atomic_write_text(self.path, json.dumps(
            state, indent=1, sort_keys=True) + "\n")

    def read(self) -> dict:
        """Lock-free snapshot (monitoring / tests)."""
        return self._load()

    def _items(self, state: dict) -> dict:
        return state[self.ITEMS_KEY]

    @property
    def epoch(self) -> int:
        return int(self._load()["epoch"])

    # -- event plumbing ----------------------------------------------
    def _event(self, kind: Optional[str], **fields) -> None:
        if kind is None:
            return
        if self.obs is not None and getattr(self.obs, "enabled",
                                            False):
            self.obs.event(kind, **fields)

    # -- membership ---------------------------------------------------
    def join(self, host: str, addr: Optional[str] = None,
             now: Optional[float] = None) -> int:
        """Register (or re-register) a host; returns the epoch it
        joins under.  A host re-joining after being declared dead is
        admitted at the current epoch — its fenced leases were already
        re-admitted, so it simply starts fresh.  Joining also clears a
        previous incarnation's tombstone heartbeat."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            state["hosts"][host] = {"joined": now, "alive": True,
                                    "addr": addr,
                                    "epoch": int(state["epoch"])}
            self._save(state)
            epoch = int(state["epoch"])
        _ts, tombstoned = self._hb_record(host)
        if tombstoned:
            self.heartbeat(host, epoch, now=now)
        return epoch

    def heartbeat_path(self, host: str) -> str:
        return os.path.join(self.workdir, HEARTBEAT_PREFIX + host
                            + ".json")

    def heartbeat(self, host: str, epoch: int,
                  now: Optional[float] = None) -> None:
        """Cheap liveness signal: one small atomic file per host, no
        ledger lock taken."""
        now = time.time() if now is None else now
        atomic_write_text(self.heartbeat_path(host), json.dumps(
            {"host": host, "ts": now, "epoch": int(epoch)}) + "\n")

    def tombstone(self, host: str,
                  now: Optional[float] = None) -> None:
        """Final heartbeat of a gracefully-departing host: marks it
        dead *immediately* so the reaper re-admits anything it still
        holds without waiting out the heartbeat TTL."""
        now = time.time() if now is None else now
        atomic_write_text(self.heartbeat_path(host), json.dumps(
            {"host": host, "ts": now, "tombstone": True}) + "\n")

    def _hb_record(self, host: str) -> Tuple[Optional[float], bool]:
        """(last heartbeat ts, tombstoned?) for one host."""
        try:
            with open(self.heartbeat_path(host)) as f:
                rec = json.load(f)
            return float(rec["ts"]), bool(rec.get("tombstone"))
        except (OSError, ValueError, KeyError, TypeError):
            return None, False

    def last_heartbeat(self, host: str) -> Optional[float]:
        return self._hb_record(host)[0]

    def alive_hosts(self, now: Optional[float] = None,
                    ttl: float = 15.0) -> List[str]:
        now = time.time() if now is None else now
        state = self._load()
        out = []
        for host, h in sorted(state["hosts"].items()):
            if not h.get("alive", False):
                continue
            hb, tombstoned = self._hb_record(host)
            if tombstoned:
                continue
            seen = hb if hb is not None else float(h.get("joined", 0))
            if now - seen <= ttl:
                out.append(host)
        return out

    # -- item bookkeeping ---------------------------------------------
    @staticmethod
    def _new_row(extra: Optional[dict] = None) -> dict:
        row = {
            "state": PENDING,
            "owner": None,
            "lease_epoch": None,
            "lease_expires": None,
            "artifacts": {},
            "redos": 0,
        }
        if extra:
            row.update(extra)
        return row

    def ensure_items(self, specs: Sequence[Tuple[str, dict]],
                     meta: Optional[dict] = None) -> int:
        """Idempotently create item rows.  `specs` is a sequence of
        (item_id, extra-fields dict).  Existing rows keep their state
        (that is the resume contract); returns the not-done count."""
        with self._lock():
            state = self._load()
            if meta:
                state.setdefault("meta", {}).update(meta)
            items = self._items(state)
            for iid, extra in specs:
                items.setdefault(iid, self._new_row(extra))
            pending = sum(1 for s in items.values()
                          if s["state"] not in (DONE, FAILED))
            self._save(state)
            return pending

    def _pick_pending(self, state: dict,
                      now: float) -> Optional[str]:
        """The lease scheduling policy: the item id to grant next, or
        None.  Called under the ledger lock; may mutate `state`
        bookkeeping (it is saved with the grant).  Base policy: first
        pending id in sorted order."""
        for iid in sorted(self._items(state)):
            if self._items(state)[iid]["state"] == PENDING:
                return iid
        return None

    def _make_lease(self, item_id: str, row: dict, epoch: int):
        data = {k: v for k, v in row.items()
                if k not in ("state", "owner", "lease_epoch",
                             "lease_expires", "artifacts", "redos")}
        return ItemLease(item_id, epoch,
                         float(row["lease_expires"]), data)

    def lease(self, host: str, ttl: float,
              now: Optional[float] = None):
        """Claim the next pending item for `host` (per the scheduling
        policy); None when nothing is currently pending (all leased or
        terminal)."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            h = state["hosts"].get(host)
            if h is not None and not h.get("alive", True):
                # false-positive death (slow heartbeat): rejoin at the
                # current epoch and carry on
                h["alive"] = True
                h["epoch"] = int(state["epoch"])
            iid = self._pick_pending(state, now)
            if iid is None:
                self._save(state)
                return None
            row = self._items(state)[iid]
            row["state"] = LEASED
            row["owner"] = host
            row["lease_epoch"] = int(state["epoch"])
            row["lease_expires"] = now + ttl
            # grant timestamp: the admit->lease wait half of the
            # job_e2e_seconds decomposition (obs/fleetagg.py) and the
            # fleet report's critical-path attribution read this
            row["leased_at"] = now
            self._save(state)
            self._event(self.EV_LEASE, item=iid, host=host,
                        epoch=int(state["epoch"]))
            return self._make_lease(iid, row, int(state["epoch"]))

    def renew(self, lease, host: str, ttl: float,
              now: Optional[float] = None) -> bool:
        """Extend a held lease (long items).  False when the lease
        was fenced off meanwhile."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            row = self._items(state).get(lease.item_id)
            if (row is None or row["state"] != LEASED
                    or row["owner"] != host
                    or int(row["lease_epoch"]) != int(lease.epoch)):
                return False
            row["lease_expires"] = now + ttl
            self._save(state)
            return True

    @staticmethod
    def _fence_why(row: Optional[dict], lease, host: str) \
            -> Optional[str]:
        """The fence check: None when the commit may land, else the
        reason it must be rejected."""
        if row is None:
            return "unknown item"
        if row["state"] != LEASED:
            return "item is %s, not leased" % row["state"]
        if row["owner"] != host:
            return "lease owned by %r" % row["owner"]
        if int(row["lease_epoch"]) != int(lease.epoch):
            return "lease epoch %s superseded" % row["lease_epoch"]
        return None

    def _reject_stale(self, state: dict, lease, host: str,
                      staged: Dict[str, str], why: str):
        for tmp in staged.values():
            with contextlib.suppress(OSError):
                os.remove(tmp)
        self._event(self.EV_STALE, item=lease.item_id, host=host,
                    epoch=int(lease.epoch),
                    cluster_epoch=int(state["epoch"]), why=why)
        raise self.STALE(lease.item_id, host, int(lease.epoch),
                         int(state["epoch"]), why)

    def _commit_row(self, state: dict, lease, host: str,
                    staged: Dict[str, str], row: dict, now: float,
                    extra: Optional[dict] = None) -> Dict[str, dict]:
        """The commit body shared by complete() and subclass commit
        transactions (JobLedger.complete_and_expand): rename each
        staged file onto its final path, journal size+CRC, and flip
        the row to done.  Must run under the ledger lock, AFTER the
        fence check; the caller saves the state."""
        arts: Dict[str, dict] = {}
        for final, tmp in sorted(staged.items()):
            os.replace(tmp, final)
            rel = os.path.relpath(os.path.abspath(final),
                                  self.workdir)
            arts[rel] = {"size": os.path.getsize(final),
                         "checksum": file_checksum(final)}
        row["state"] = DONE
        row["owner"] = host
        row["lease_epoch"] = None
        row["lease_expires"] = None
        row["artifacts"] = arts
        row["completed_epoch"] = int(state["epoch"])
        row["completed_at"] = now
        if extra:
            row.update(extra)
        return arts

    def complete(self, lease, host: str, staged: Dict[str, str],
                 now: Optional[float] = None,
                 extra: Optional[dict] = None) -> Dict[str, dict]:
        """Commit a computed item: fence-check, rename each staged
        file onto its final path, journal size+CRC — all under the
        ledger lock.  `staged` maps final absolute path -> staged
        temp path; `extra` fields are merged into the accepted row
        (e.g. the job's result summary).  Raises the STALE error
        (after deleting the staged files) when the lease was fenced
        off; a journaled artifact is then never overwritten."""
        now = time.time() if now is None else now
        with self._lock():
            state = self._load()
            row = self._items(state).get(lease.item_id)
            why = self._fence_why(row, lease, host)
            if why is not None:
                self._reject_stale(state, lease, host, staged, why)
            arts = self._commit_row(state, lease, host, staged, row,
                                    now, extra)
            self._save(state)
            self._event(self.EV_DONE, item=lease.item_id, host=host,
                        artifacts=len(arts))
            return arts

    def fail(self, lease, host: str) -> None:
        """Voluntarily release a held lease back to pending (compute
        error on this host; let another host try)."""
        with self._lock():
            state = self._load()
            row = self._items(state).get(lease.item_id)
            if (row is not None and row["state"] == LEASED
                    and row["owner"] == host
                    and int(row["lease_epoch"]) == int(lease.epoch)):
                self._readmit(row)
                self._save(state)
                self._event(self.EV_REDO, item=lease.item_id,
                            why="released", host=host)

    def readmit_owned(self, host: str) -> List[str]:
        """Re-admit every lease held by `host` — called by a
        *restarting* host on join (a fresh incarnation cannot have
        in-flight work, so any lease under its name is a dead one).
        Bumps the epoch when anything was re-admitted, fencing off the
        dead incarnation's possible late writes."""
        redone = []
        with self._lock():
            state = self._load()
            items = self._items(state)
            for iid in sorted(items):
                row = items[iid]
                if row["state"] == LEASED and row["owner"] == host:
                    self._readmit(row)
                    redone.append(iid)
            if redone:
                state["epoch"] = int(state["epoch"]) + 1
            self._save(state)
        for iid in redone:
            self._event(self.EV_REDO, item=iid, why="owner-restart",
                        host=host)
        return redone

    @staticmethod
    def _readmit(row: dict) -> None:
        row["state"] = PENDING
        row["owner"] = None
        row["lease_epoch"] = None
        row["lease_expires"] = None
        row["redos"] = int(row.get("redos", 0)) + 1

    # -- failure detection / redo -------------------------------------
    def _dead_by_heartbeat(self, state: dict, now: float,
                           ttl: float) -> List[str]:
        """Alive-marked hosts whose heartbeat is stale or tombstoned."""
        out = []
        for host, h in sorted(state["hosts"].items()):
            if not h.get("alive", False):
                continue
            hb, tombstoned = self._hb_record(host)
            seen = hb if hb is not None else float(h.get("joined", 0))
            if tombstoned or now - seen > ttl:
                out.append(host)
        return out

    def reap(self, heartbeat_ttl: float,
             now: Optional[float] = None) -> ReapReport:
        """One failure-detection pass: mark hosts with stale (or
        tombstoned) heartbeats dead, re-admit their leases plus any
        lease past expiry, bump the epoch when anything changed.  Safe
        to call from every host (idempotent under the lock)."""
        now = time.time() if now is None else now
        report = ReapReport()
        with self._lock():
            state = self._load()
            for host in self._dead_by_heartbeat(state, now,
                                                heartbeat_ttl):
                state["hosts"][host]["alive"] = False
                report.dead_hosts.append(host)
            dead = {host for host, h in state["hosts"].items()
                    if not h.get("alive", False)}
            items = self._items(state)
            for iid in sorted(items):
                row = items[iid]
                if row["state"] != LEASED:
                    continue
                expired = (row["lease_expires"] is not None
                           and now > float(row["lease_expires"]))
                if row["owner"] in dead or expired:
                    self._readmit(row)
                    report.redone.append(iid)
            if report.dead_hosts or report.redone:
                state["epoch"] = int(state["epoch"]) + 1
                report.bumped = True
            report.epoch = int(state["epoch"])
            self._save(state)
        for host in report.dead_hosts:
            self._event(self.EV_HOST_DEAD, host=host,
                        epoch=report.epoch)
        for iid in report.redone:
            self._event(self.EV_REDO, item=iid, why="reaped",
                        epoch=report.epoch)
        if report.bumped:
            self._event(self.EV_EPOCH_BUMP, epoch=report.epoch,
                        dead=report.dead_hosts, redone=report.redone)
        return report

    def verify_done(self) -> List[str]:
        """Verify-not-trust for completed items: any done item whose
        journaled artifacts are missing, resized, or checksum-stale on
        disk is re-admitted (its stale files are deleted so nothing
        can resurrect them).  Returns the re-admitted item ids."""
        redone = []
        with self._lock():
            state = self._load()
            items = self._items(state)
            for iid in sorted(items):
                row = items[iid]
                if row["state"] != DONE:
                    continue
                ok = True
                for rel, ent in row.get("artifacts", {}).items():
                    p = os.path.join(self.workdir, rel)
                    if (not os.path.exists(p)
                            or os.path.getsize(p) != ent.get("size")
                            or file_checksum(p) != ent.get(
                                "checksum")):
                        ok = False
                        break
                if ok:
                    continue
                for rel in row.get("artifacts", {}):
                    with contextlib.suppress(OSError):
                        os.remove(os.path.join(self.workdir, rel))
                row["artifacts"] = {}
                self._readmit(row)
                redone.append(iid)
            self._save(state)
        for iid in redone:
            self._event(self.EV_REDO, item=iid, why="verify-failed")
        return redone

    # -- progress -----------------------------------------------------
    def counts(self) -> Dict[str, int]:
        state = self._load()
        out = {PENDING: 0, LEASED: 0, DONE: 0}
        for row in self._items(state).values():
            out[row["state"]] = out.get(row["state"], 0) + 1
        return out

    def all_done(self) -> bool:
        state = self._load()
        items = self._items(state)
        return bool(items) and all(s["state"] == DONE
                                   for s in items.values())

    def redo_set(self, heartbeat_ttl: float,
                 now: Optional[float] = None) -> List[str]:
        """The items a reap pass *would* re-admit right now (dead
        owners or expired leases) — computed without mutating."""
        now = time.time() if now is None else now
        state = self._load()
        dead = set(self._dead_by_heartbeat(state, now, heartbeat_ttl))
        dead |= {host for host, h in state["hosts"].items()
                 if not h.get("alive", False)}
        out = []
        items = self._items(state)
        for iid in sorted(items):
            row = items[iid]
            if row["state"] != LEASED:
                continue
            expired = (row["lease_expires"] is not None
                       and now > float(row["lease_expires"]))
            if row["owner"] in dead or expired:
                out.append(iid)
        return out
