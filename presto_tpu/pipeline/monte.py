"""Monte-Carlo binary-pulsar detection-efficiency campaign.

The reference validates its three binary-search methods with offline
Monte-Carlo studies (python/binresponses/monte_short.py,
monte_ffdot.py, monte_sideb.py): simulate orbits, run each method,
record the detection fraction as a function of orbital period over
observation length.  Those campaigns established the published
sensitivity claims (README.md:86-94).

This module is the same experiment as a scalable harness: the regimes
  Pb >> Tobs  -> acceleration (F-Fdot) search wins
  Pb << Tobs  -> phase-modulation (minifft / sideband) search wins
are measured per trial with randomized orbital phase.  Trial counts
are configurable so the default run is seconds-scale (the full
reference campaigns are overnight jobs; same code path, bigger N).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from presto_tpu.io.atomic import atomic_open
from presto_tpu.models.synth import pulse_shape
from presto_tpu.ops.orbit import OrbitParams, orbit_delays


@dataclass
class MonteConfig:
    N: int = 1 << 19             # samples per trial
    dt: float = 1e-2             # seconds (T ~ 5240 s: orbits must
                                 # clear the search's MINORBP = 300 s)
    f_psr: float = 20.0          # pulsar spin frequency (Hz)
    amp: float = 0.2             # pulse amplitude (noise sigma = 1)
    width: float = 0.1           # gaussian pulse fractional width
    asini_lts: float = 0.2       # projected semi-major axis (lt-s);
                                 # modulation index 2*pi*f*x ~ 25 rad
    ecc: float = 0.0
    pb_over_t: tuple = (0.1, 0.3, 3.0, 10.0)   # orbital regimes
    ntrials: int = 8
    sigma_cut: float = 5.0       # detection threshold
    seed: int = 42

    @property
    def tobs(self) -> float:
        return self.N * self.dt


def _make_trial(cfg: MonteConfig, pb: float, rng) -> np.ndarray:
    """One binary-pulsar time series with random orbital phase."""
    t = (np.arange(cfg.N) + 0.5) * cfg.dt
    orb = OrbitParams(p=pb, x=cfg.asini_lts, e=cfg.ecc,
                      w=float(rng.uniform(0, 360)),
                      t=float(rng.uniform(0, pb)))
    tb = t - np.asarray(orbit_delays(t, orb))
    ph = cfg.f_psr * tb
    x = cfg.amp * pulse_shape(ph, "gauss", cfg.width)
    return (x + rng.normal(0.0, 1.0, cfg.N)).astype(np.float32)


def _make_accel(cfg: MonteConfig, numbins: int):
    """One AccelSearch per campaign — its kernel bank and compiled
    functions are reused across every trial (same shapes)."""
    from presto_tpu.search.accel import AccelConfig, AccelSearch
    acfg = AccelConfig(zmax=50, numharm=4, sigma=cfg.sigma_cut,
                       uselen=1820)
    return AccelSearch(acfg, T=cfg.tobs, numbins=numbins)


def _detect_ffdot(cfg: MonteConfig, searcher, pairs: np.ndarray
                  ) -> bool:
    """Acceleration-search detection: any candidate within 2 Hz of
    the spin frequency (or a harmonic) above the sigma cut."""
    for c in searcher.search(pairs):
        f = c.r / cfg.tobs
        for k in range(1, 5):
            if abs(f / k - cfg.f_psr) < 2.0:
                return True
    return False


def _detect_phasemod(cfg: MonteConfig, pairs: np.ndarray,
                     maxfft: int) -> bool:
    """Phase-modulation (minifft) detection: a rawbin candidate whose
    modulation frequency sits at the pulsar spin frequency."""
    from presto_tpu.search.phasemod import (PhaseModConfig,
                                            search_phasemod)
    pcfg = PhaseModConfig(minfft=max(maxfft // 8, 64), maxfft=maxfft)
    amps = pairs[..., 0] + 1j * pairs[..., 1]
    cands = search_phasemod(amps.astype(np.complex64), N=float(cfg.N),
                            dt=cfg.dt, cfg=pcfg)
    for c in cands:
        # same threshold as the ffdot column: the campaign compares
        # the two methods at one nominal cut
        if c.mini_sigma < cfg.sigma_cut or c.psr_p <= 0:
            continue
        if abs(1.0 / c.psr_p - cfg.f_psr) < 4.0:
            return True
    return False


def run_campaign(cfg: MonteConfig,
                 methods: Optional[List[str]] = None,
                 progress: bool = False) -> Dict:
    """Returns {pb_over_t: {method: detection_fraction}} (+ metadata).
    """
    import jax.numpy as jnp
    from presto_tpu.ops import fftpack

    methods = methods or ["ffdot", "short", "long"]
    rng = np.random.default_rng(cfg.seed)
    out: Dict = {"config": {k: getattr(cfg, k) for k in
                            ("N", "dt", "f_psr", "amp", "asini_lts",
                             "ecc", "ntrials", "sigma_cut")},
                 "results": {}}
    searcher = _make_accel(cfg, cfg.N // 2) if "ffdot" in methods \
        else None
    for ratio in cfg.pb_over_t:
        pb = ratio * cfg.tobs
        hits = {m: 0 for m in methods}
        for trial in range(cfg.ntrials):
            x = _make_trial(cfg, pb, rng)
            pairs = np.asarray(fftpack.realfft_packed_pairs(
                jnp.asarray(x - x.mean())))
            if searcher is not None and _detect_ffdot(cfg, searcher,
                                                      pairs):
                hits["ffdot"] += 1
            if "short" in methods and _detect_phasemod(
                    cfg, pairs, maxfft=1024):
                hits["short"] += 1
            if "long" in methods and _detect_phasemod(
                    cfg, pairs, maxfft=8192):
                hits["long"] += 1
            if progress:
                print("  pb/T=%.2g trial %d/%d: %s" %
                      (ratio, trial + 1, cfg.ntrials,
                       {m: hits[m] for m in methods}))
        out["results"][str(ratio)] = {
            m: hits[m] / cfg.ntrials for m in methods}
    return out


def format_table(res: Dict) -> str:
    methods = sorted(next(iter(res["results"].values())).keys())
    lines = ["Pb/Tobs   " + "".join("%10s" % m for m in methods)]
    for ratio, fr in res["results"].items():
        lines.append("%-8s  " % ratio +
                     "".join("%10.2f" % fr[m] for m in methods))
    return "\n".join(lines)


def save_json(res: Dict, path: str) -> None:
    # a campaign is hours of trials; a kill mid-dump must leave the
    # previous complete results, not a truncated JSON a rerun trusts
    with atomic_open(path, "w") as f:
        json.dump(res, f, indent=1)
